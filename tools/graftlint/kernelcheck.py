"""Kernel-domain static analysis: GL09 limb value-range abstract
interpretation, GL10 Montgomery-domain typestate, GL11 twin/padding
discipline.

The hot kernels (``harmony_tpu/ops/{fp,fp_pallas,towers,curve,
pairing}.py``) do 381-bit field arithmetic in 32x12-bit int32 limbs.
Every optimization on the roadmap (Karatsuba limb convolution,
MXU-int8 reduction, Karabina compression, precomputed-line Miller)
changes the magnitude of intermediate limb values, and a silent int32
overflow produces a wrong-but-plausible pairing.  This pass makes the
bound a machine-checked precondition:

GL09 — an **interval abstract interpreter** over the jnp/np expression
dataflow.  Each array value carries a proven element bound [lo, hi]
propagated through ``+ - * >> & | where stack concatenate pad einsum/
matmul``-style reductions, the carry-lookahead helpers, ``lax.scan``
(unrolled when the trip count is provably the limb count, widened
fixpoint otherwise) and ``lax.fori_loop``/``while`` (join fixpoint
with power-of-two widening).  Any intermediate whose bound can leave
the module dtype's lanes (int32 by default, parameterized via the
module contract so the int8-plane MXU path is checkable) is flagged.

GL10 — a **Montgomery-domain typestate** rides on the same values:
every field element has an R-degree (value = x * R^d mod p): standard
d=0, Montgomery d=1, the R^2 conversion constant d=2, and "neutral"
for masks/zero/multiples of p.  ``mont_mul`` is the one primitive that
changes degree (d_out = d_a + d_b - 1); add/sub/select require equal
degrees.  Mixing degrees, raw ``*`` products of domain values outside
a primitive, and returns whose degree contradicts the declared
contract are flagged.

GL11 — **twin/padding discipline** for device-dispatched kernels:
every kernel a ``jax.jit`` dispatch site references must have a
bigint twin (same name in the declared twin module), a parity test
under tests/ referencing it, and a provable infinity-sentinel guard
(the kernel transitively reaches an ``is_zero``-style finiteness
check or a reviewed ``padding-safe`` function).

Contracts are declared in-code::

    # graftlint: kernel-module dtype=int32; twin=harmony_tpu/ops/twin.py
    ...
    # graftlint: kernel bounds=(limb, limb) -> limb; domain=(mont, mont) -> mont
    def add(a, b): ...

    ONE_MONT = jnp.asarray(...)  # graftlint: kernel domain=mont

Spec tokens: ``limb`` (canonical digits [0, 2^12-1]), ``bit`` ([0,1]),
``<N``/``<=N`` (explicit bound, N may be ``2**30``), ``any``,
``fieldops`` (a curve.FieldOps-shaped op table).  Domain tokens:
``mont std r2 neutral same any`` plus the whole-signature form
``domain=mul`` marking the Montgomery primitive (degree algebra at
call sites, internal domain checks off).

Like GL05-GL08, findings carry the witness derivation in
``Finding.detail`` (display-only, never fingerprinted) and respect
the baseline/pin workflow.  The pass is assume-guarantee: every
annotated function is verified once against its own contract assuming
its callees' contracts; unannotated helpers are inlined with the
caller's abstract arguments.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field, replace

from .interproc import Program, SiteFinding
from .rules import dotted_name, _enclosing_map

LIMB_BITS = 12
LIMB_MASK = (1 << LIMB_BITS) - 1
N_LIMBS = 32

_DTYPES = {
    "int8": (-(1 << 7), (1 << 7) - 1),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "int32": (-(1 << 31), (1 << 31) - 1),
    "int64": (-(1 << 63), (1 << 63) - 1),
}

# fixpoint knobs: join iterations before widening kicks in, and the
# hard cap after which a non-stabilizing loop carry is flagged
_WIDEN_AFTER = 6
_LOOP_CAP = 48
_UNROLL_CAP = 4096
_INLINE_DEPTH = 24

# ---------------------------------------------------------------------------
# abstract values


DOM_TOP = ("top",)
DOM_NEUTRAL = ("neutral",)


def deg(k: int) -> tuple:
    return ("deg", k)


@dataclass(frozen=True)
class AV:
    """Abstract array value: element interval + Montgomery R-degree.

    ``lo``/``hi`` of None mean unbounded in that direction.  ``prov``
    is a short human derivation note (display-only, excluded from
    equality so fixpoint tests converge)."""

    lo: int | None = None
    hi: int | None = None
    dom: tuple = DOM_TOP
    limbaxis: bool = False     # last axis is the 32-limb axis
    scanlen: int | None = None  # provable lax.scan trip count
    prov: str = field(default="", compare=False)

    @property
    def bounded(self) -> bool:
        return self.lo is not None and self.hi is not None

    def desc(self) -> str:
        lo = "-inf" if self.lo is None else str(self.lo)
        hi = "+inf" if self.hi is None else str(self.hi)
        return f"[{lo}, {hi}]"


TOPV = AV()


@dataclass(frozen=True)
class Conc:
    """A concretely-known host (python) value — int, str, tuple, ..."""
    value: object


UNKNOWN = Conc(object())  # a host value we cannot fold


@dataclass(frozen=True)
class ModRef:
    relpath: str


@dataclass(frozen=True)
class FuncRef:
    relpath: str
    name: str


class Closure:
    """A nested def / lambda with its defining environment."""

    def __init__(self, node, env, relpath):
        self.node = node
        self.env = env
        self.relpath = relpath


class FieldOpsVal:
    """Abstract curve.FieldOps op table: canonical mont ops."""


FIELDOPS = FieldOpsVal()


class AbsTuple(tuple):
    """Abstract tuple/list of abstract values."""


def is_known_conc(v) -> bool:
    return isinstance(v, Conc) and v is not UNKNOWN and v.value is not \
        UNKNOWN.value


def _dom_join(a: tuple, b: tuple) -> tuple:
    if a == b:
        return a
    if a == DOM_NEUTRAL:
        return b
    if b == DOM_NEUTRAL:
        return a
    return DOM_TOP


def _dom_mixes(a: tuple, b: tuple) -> bool:
    """True when two NON-neutral concrete domains disagree — the GL10
    add/sub/select mixing condition."""
    return (a not in (DOM_TOP, DOM_NEUTRAL)
            and b not in (DOM_TOP, DOM_NEUTRAL) and a != b)


def _dom_name(d: tuple) -> str:
    if d == DOM_TOP:
        return "unknown"
    if d == DOM_NEUTRAL:
        return "neutral"
    if d[0] == "deg":
        return {0: "std", 1: "mont", 2: "r2"}.get(d[1], f"R^{d[1]}")
    return f"poly({d[1]})"


def av_join(a, b):
    """Join two abstract values (any kind)."""
    if isinstance(a, AV) or isinstance(b, AV):
        a = to_av(a)
        b = to_av(b)
        lo = None if a.lo is None or b.lo is None else min(a.lo, b.lo)
        hi = None if a.hi is None or b.hi is None else max(a.hi, b.hi)
        return AV(lo, hi, _dom_join(a.dom, b.dom),
                  a.limbaxis and b.limbaxis, None,
                  prov=a.prov or b.prov)
    if isinstance(a, AbsTuple) and isinstance(b, AbsTuple) \
            and len(a) == len(b):
        return AbsTuple(av_join(x, y) for x, y in zip(a, b))
    if is_known_conc(a) and is_known_conc(b) and a.value == b.value \
            and type(a.value) is type(b.value):
        return a
    if isinstance(a, (ModRef, FuncRef, Closure, FieldOpsVal)) and a is b:
        return a
    if isinstance(a, Conc) and isinstance(b, Conc) \
            and isinstance(a.value, (int, bool)) \
            and isinstance(b.value, (int, bool)):
        # diverging host ints (loop counters): promote to unknown host
        return UNKNOWN
    if a is b:
        return a
    return TOPV


def to_av(v) -> AV:
    """View any abstract thing as an array interval (for arithmetic)."""
    if isinstance(v, AV):
        return v
    if is_known_conc(v) and isinstance(v.value, bool):
        return AV(int(v.value), int(v.value), DOM_NEUTRAL)
    if is_known_conc(v) and isinstance(v.value, int):
        return AV(v.value, v.value, DOM_NEUTRAL)
    if isinstance(v, AbsTuple):
        out = None
        for e in v:
            out = to_av(e) if out is None else av_join(out, to_av(e))
        return out if out is not None else TOPV
    return TOPV


def widen(prev: AV, new: AV) -> AV:
    """Power-of-two interval widening to force loop convergence."""
    lo, hi = new.lo, new.hi
    if prev.lo is not None and (lo is None or lo < prev.lo):
        lo = None if lo is None or lo < -(1 << 70) else -_pow2ceil(-lo)
    if prev.hi is not None and (hi is None or hi > prev.hi):
        hi = None if hi is None or hi > (1 << 70) else _pow2ceil(hi + 1) - 1
    return replace(new, lo=lo, hi=hi)


def _pow2ceil(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def widen_any(prev, new):
    if isinstance(prev, AV) and isinstance(new, AV):
        return widen(prev, new)
    if isinstance(prev, AbsTuple) and isinstance(new, AbsTuple) \
            and len(prev) == len(new):
        return AbsTuple(widen_any(p, n) for p, n in zip(prev, new))
    return new


# ---------------------------------------------------------------------------
# contract annotations

_ANNO_RE = re.compile(r"#\s*graftlint:\s*(kernel-module|kernel)\b(.*)$")


@dataclass
class Spec:
    """One parameter/return bound spec."""
    lo: int | None = None
    hi: int | None = None
    limbaxis: bool = False
    fieldops: bool = False
    anyv: bool = False

    def check(self, av) -> str | None:
        """Return a violation description, or None when av satisfies."""
        if self.anyv or self.fieldops:
            return None
        a = to_av(av)
        if not a.bounded:
            return f"unprovable bound {a.desc()}"
        if (self.lo is not None and a.lo < self.lo) or \
                (self.hi is not None and a.hi > self.hi):
            return f"proven {a.desc()} exceeds declared [{self.lo}, {self.hi}]"
        return None

    def seed(self, dom: tuple) -> object:
        if self.fieldops:
            return FIELDOPS
        if self.anyv:
            return AV(None, None, dom)
        return AV(self.lo, self.hi, dom, limbaxis=self.limbaxis)


def _parse_num(tok: str) -> int:
    node = ast.parse(tok, mode="eval").body
    for sub in ast.walk(node):
        if not isinstance(sub, (ast.BinOp, ast.UnaryOp, ast.Constant,
                                ast.Pow, ast.Mult, ast.Add, ast.Sub,
                                ast.LShift, ast.USub, ast.operator,
                                ast.unaryop)):
            raise ValueError(f"bad bound expression {tok!r}")
    return int(eval(compile(ast.Expression(node), "<spec>", "eval")))  # noqa: S307


def parse_spec(tok: str) -> Spec:
    tok = tok.strip()
    if tok == "limb":
        return Spec(0, LIMB_MASK, limbaxis=True)
    if tok == "bit":
        return Spec(0, 1)
    if tok in ("any", "*"):
        return Spec(anyv=True)
    if tok == "fieldops":
        return Spec(fieldops=True)
    if tok.startswith("<="):
        return Spec(0, _parse_num(tok[2:]))
    if tok.startswith("<"):
        return Spec(0, _parse_num(tok[1:]) - 1)
    raise ValueError(f"unknown bound spec {tok!r}")


_DOM_TOKENS = {
    "mont": deg(1), "std": deg(0), "r2": deg(2),
    "neutral": DOM_NEUTRAL, "any": DOM_TOP, "same": ("sym", "S"),
}


def _split_specs(txt: str) -> tuple[list[str], str | None]:
    """'(a, b) -> c' | 'a -> c' | 'a'  ->  ([params], ret|None)."""
    txt = txt.strip()
    ret = None
    if "->" in txt:
        txt, ret = txt.split("->", 1)
        ret = ret.strip()
        txt = txt.strip()
    if txt.startswith("(") and txt.endswith(")"):
        txt = txt[1:-1]
    parts = [p.strip() for p in txt.split(",") if p.strip()] if txt else []
    return parts, ret


def _parse_ret(ret: str, parser):
    ret = ret.strip()
    if ret.startswith("(") and ret.endswith(")"):
        return AbsTuple(parser(p.strip())
                        for p in ret[1:-1].split(",") if p.strip())
    return parser(ret)


@dataclass
class Contract:
    params: list[Spec] = field(default_factory=list)
    ret: object = None                    # Spec | AbsTuple[Spec] | None
    doms: list[tuple] = field(default_factory=list)
    retdom: object = None                 # dom tuple | AbsTuple | None
    primitive: bool = False               # domain=mul: the mont primitive
    padding_safe: bool = False
    trusted: bool = False                 # assume-only: body not verified
    has_bounds: bool = False
    has_domain: bool = False


@dataclass
class ModuleAnno:
    is_kernel_module: bool = False
    dtype: str = "int32"
    twin: str | None = None
    tests: str | None = None
    dispatch: list[str] | None = None


def parse_contract(text: str) -> Contract:
    c = Contract()
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause == "padding-safe":
            c.padding_safe = True
        elif clause == "trusted":
            c.trusted = True
        elif clause.startswith("bounds="):
            parts, ret = _split_specs(clause[len("bounds="):])
            c.params = [parse_spec(p) for p in parts]
            c.has_bounds = True
            if ret is not None:
                c.ret = _parse_ret(ret, parse_spec)
            elif not parts:
                c.ret = None
            elif len(parts) == 1 and ret is None and "->" not in clause:
                # value annotation: 'bounds=limb' on an assignment
                c.ret = c.params[0]
                c.params = []
        elif clause.startswith("domain="):
            body = clause[len("domain="):].strip()
            if body == "mul":
                c.primitive = True
                c.has_domain = True
                continue
            parts, ret = _split_specs(body)
            c.doms = [_DOM_TOKENS[p] for p in parts]
            c.has_domain = True
            if ret is not None:
                c.retdom = _parse_ret(
                    ret, lambda t: _DOM_TOKENS[t.strip()])
            elif len(parts) == 1 and "->" not in body:
                c.retdom = c.doms[0]
                c.doms = []
    return c


def parse_module_anno(text: str) -> ModuleAnno:
    m = ModuleAnno(is_kernel_module=True)
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("dtype="):
            m.dtype = clause[len("dtype="):].strip()
        elif clause.startswith("twin="):
            m.twin = clause[len("twin="):].strip()
        elif clause.startswith("tests="):
            m.tests = clause[len("tests="):].strip()
        elif clause.startswith("dispatch="):
            m.dispatch = [t.strip() for t in
                          clause[len("dispatch="):].split(",") if t.strip()]
    return m


def collect_annotations(source: str):
    """(module_anno | None, {line: (contract_text, standalone)}).
    ``standalone`` marks a comment-only line (an annotation for the
    def/assign BELOW it); trailing comments annotate their own line."""
    import io
    import tokenize

    mod = None
    lines: dict[int, tuple[str, bool]] = {}
    src_lines = source.splitlines()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _ANNO_RE.search(tok.string)
            if not m:
                continue
            if m.group(1) == "kernel-module":
                mod = parse_module_anno(m.group(2))
            else:
                row, col = tok.start
                standalone = row <= len(src_lines) and \
                    not src_lines[row - 1][:col].strip()
                lines[row] = (m.group(2).strip(), standalone)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return mod, lines


def _def_contract_line(node, annos: dict) -> int | None:
    """The annotation line feeding a def/assign: trailing on the node's
    first line, or a standalone annotation line directly above the def
    OR above its decorator stack (both placements are legal)."""
    if node.lineno in annos:
        return node.lineno
    starts = [node.lineno]
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            and node.decorator_list:
        starts.append(min(d.lineno for d in node.decorator_list))
    for start in starts:
        above = annos.get(start - 1)
        if above is not None and above[1]:
            return start - 1
    return None


# ---------------------------------------------------------------------------
# the fieldops op table (curve.FieldOps abstract methods)

_LIMB_SPEC = Spec(0, LIMB_MASK, limbaxis=True)
_BIT_SPEC = Spec(0, 1)
_ANY_SPEC = Spec(anyv=True)

# method -> (param specs, param doms, ret spec, ret dom); 'join' ret
# means join of args (stack), None params means unchecked varargs
_FIELD_METHODS = {
    "mul": ([_LIMB_SPEC, _LIMB_SPEC], "mul", _LIMB_SPEC, None),
    "sqr": ([_LIMB_SPEC], "mul", _LIMB_SPEC, None),
    "add": ([_LIMB_SPEC, _LIMB_SPEC], "same", _LIMB_SPEC, "same"),
    "sub": ([_LIMB_SPEC, _LIMB_SPEC], "same", _LIMB_SPEC, "same"),
    "neg": ([_LIMB_SPEC], "same", _LIMB_SPEC, "same"),
    "dbl_": ([_LIMB_SPEC], "same", _LIMB_SPEC, "same"),
    "inv": ([_LIMB_SPEC], "same", _LIMB_SPEC, "same"),
    "is_zero": ([_ANY_SPEC], None, _BIT_SPEC, DOM_NEUTRAL),
    "select": ([_ANY_SPEC, _LIMB_SPEC, _LIMB_SPEC], "sel",
               _LIMB_SPEC, "same"),
    "one": (None, None, _LIMB_SPEC, deg(1)),
    "zero": (None, None, Spec(0, 0), DOM_NEUTRAL),
    "stack": (None, None, "join", None),
}


class _Analysis:
    """One whole-program kernelcheck run."""

    def __init__(self, prog: Program):
        self.prog = prog
        self.module_annos: dict[str, ModuleAnno] = {}
        self.line_annos: dict[str, dict[int, str]] = {}
        self.contracts: dict[tuple, Contract] = {}  # (relpath, name)
        self.envs: dict[str, dict] = {}
        self._building: set[str] = set()
        self.findings: list[SiteFinding] = []
        self._flagged: set[tuple] = set()  # (relpath, id(node), rule)
        self._memo: dict = {}
        self._enclosing: dict[str, dict] = {}
        self._parity_texts: dict[str, list] = {}
        self._cur_rel: str | None = None
        self._dtype: tuple[int, int] = _DTYPES["int32"]
        self._domain_checks = True
        self._depth = 0

    # -- indexing -----------------------------------------------------------

    def index(self):
        for rel, mi in self.prog.modules.items():
            mod, lines = collect_annotations(mi.source)
            if mod:
                self.module_annos[rel] = mod
            self.line_annos[rel] = lines
            for node in mi.tree.body:
                self._index_def(rel, node, lines)
                if isinstance(node, ast.ClassDef):
                    for item in node.body:
                        self._index_def(rel, item, lines,
                                        prefix=node.name + ".")

    def _index_def(self, rel, node, lines, prefix=""):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        ln = _def_contract_line(node, lines)
        if ln is None:
            return
        try:
            c = parse_contract(lines[ln][0])
        except (ValueError, KeyError) as e:
            self.findings.append(SiteFinding(
                rel, "GL09", ln, 0,
                f"unparseable kernel contract: {e}", prefix + node.name))
            return
        self.contracts[(rel, prefix + node.name)] = c

    def enclosing(self, rel: str) -> dict:
        if rel not in self._enclosing:
            self._enclosing[rel] = _enclosing_map(self.prog.modules[rel].tree)
        return self._enclosing[rel]

    # -- findings -----------------------------------------------------------

    def emit(self, rule: str, node, message: str, detail: str = "",
             ctx: str | None = None):
        rel = self._cur_rel
        key = (rel, id(node), rule)
        if key in self._flagged:
            return
        self._flagged.add(key)
        if ctx is None:
            ctx = self.enclosing(rel).get(id(node), "<module>")
            if ctx == "<module>" and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ctx = node.name
        self.findings.append(SiteFinding(
            rel, rule, getattr(node, "lineno", 1),
            getattr(node, "col_offset", 0), message, ctx, detail))

    def check_overflow(self, node, av: AV, what: str):
        lo, hi = self._dtype
        if av.lo is not None and av.hi is not None and \
                (av.lo < lo or av.hi > hi):
            self.emit(
                "GL09", node,
                f"proven limb bound {av.desc()} can exceed the module "
                f"dtype lanes [{lo}, {hi}]",
                detail=f"{what}: {av.prov}" if av.prov else what)

    # -- module environments ------------------------------------------------

    def module_env(self, rel: str) -> dict:
        if rel in self.envs:
            return self.envs[rel]
        if rel in self._building or rel not in self.prog.modules:
            return {}
        self._building.add(rel)
        env: dict = {}
        self.envs[rel] = env
        mi = self.prog.modules[rel]
        prev_rel, prev_dtype = self._cur_rel, self._dtype
        self._cur_rel = rel
        anno = self.module_annos.get(rel)
        self._dtype = _DTYPES.get(anno.dtype if anno else "int32",
                                  _DTYPES["int32"])
        interp = Interp(self, rel, env, check=bool(anno))
        try:
            interp.exec_block(mi.tree.body)
        except _AnalysisError as e:
            self.findings.append(SiteFinding(
                rel, "GL09", e.line, 0,
                f"kernelcheck could not analyze module top level: "
                f"{e.msg}", "<module>"))
        finally:
            self._cur_rel, self._dtype = prev_rel, prev_dtype
            self._building.discard(rel)
        return env

    # -- verification roots -------------------------------------------------

    def run(self):
        self.index()
        kernel_mods = sorted(
            rel for rel, a in self.module_annos.items()
            if a.is_kernel_module)
        for rel in kernel_mods:
            self.module_env(rel)
        for rel in kernel_mods:
            mi = self.prog.modules[rel]
            anno = self.module_annos[rel]
            for node in mi.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        (rel, node.name) in self.contracts:
                    self.verify_function(rel, node, anno)
        self.gl11()
        return self.findings

    def verify_function(self, rel: str, node, anno: ModuleAnno):
        c = self.contracts[(rel, node.name)]
        if not c.has_bounds or c.trusted:
            return  # value/padding-safe annotations, or host helpers
            # whose contract is asserted rather than derived (documented
            # in docs/ANALYSIS.md; their outputs are test-pinned)
        prev_rel, prev_dtype = self._cur_rel, self._dtype
        prev_dc = self._domain_checks
        self._cur_rel = rel
        self._dtype = _DTYPES.get(anno.dtype, _DTYPES["int32"])
        self._domain_checks = not c.primitive
        try:
            env = dict(self.module_env(rel))
            args = node.args
            names = [a.arg for a in (args.posonlyargs + args.args)]
            doms = list(c.doms)
            if c.primitive:
                doms = [deg(1)] * len(c.params)
            for i, pname in enumerate(names):
                spec = c.params[i] if i < len(c.params) else _ANY_SPEC
                d = doms[i] if i < len(doms) else DOM_TOP
                env[pname] = spec.seed(d)
            for a in args.kwonlyargs:
                env.setdefault(a.arg, TOPV)
            interp = Interp(self, rel, env, check=True)
            try:
                ret = interp.exec_func_body(node)
            except (_AnalysisError, RecursionError) as e:
                self.emit("GL09", node,
                          f"kernelcheck could not analyze "
                          f"{node.name}: {e}")
                return
            self._check_return(node, c, ret)
        finally:
            self._cur_rel, self._dtype = prev_rel, prev_dtype
            self._domain_checks = prev_dc

    def _check_return(self, node, c: Contract, ret):
        if is_known_conc(ret) and ret.value is None:
            # an out-ref kernel (pallas style): the declared return spec
            # bounds the output ref, checked at every store into it
            return
        if c.ret is not None:
            self._check_ret_spec(node, c.ret, ret, "return")
        if c.retdom is not None and not c.primitive:
            self._check_ret_dom(node, c.retdom, ret)

    def _check_ret_spec(self, node, spec, ret, what):
        if isinstance(spec, AbsTuple):
            vals = ret if isinstance(ret, AbsTuple) else \
                AbsTuple([ret] * len(spec))
            for i, s in enumerate(spec):
                v = vals[i] if i < len(vals) else TOPV
                self._check_ret_spec(node, s, v, f"{what}[{i}]")
            return
        bad = spec.check(ret)
        if bad:
            self.emit("GL09", node,
                      f"{what} violates the declared contract: {bad}",
                      detail=to_av(ret).prov)

    def _check_ret_dom(self, node, retdom, ret):
        if isinstance(retdom, AbsTuple):
            vals = ret if isinstance(ret, AbsTuple) else \
                AbsTuple([ret] * len(retdom))
            for d, v in zip(retdom, vals):
                self._check_ret_dom(node, d, v)
            return
        if retdom in (DOM_TOP, DOM_NEUTRAL):
            return
        have = to_av(ret).dom
        if have in (DOM_NEUTRAL,):
            return
        if have != retdom:
            self.emit("GL10", node,
                      f"returns {_dom_name(have)}-domain value where the "
                      f"contract declares {_dom_name(retdom)}")

    # -- GL11 ---------------------------------------------------------------

    def gl11(self):
        for rel in sorted(self.module_annos):
            anno = self.module_annos[rel]
            if anno.twin is None:
                continue
            self._gl11_module(rel, anno)

    def _dispatched(self, rel: str, anno: ModuleAnno) -> list:
        """Kernel def nodes device dispatch references (jax.jit(mod.f)),
        the dispatch= override, or — when neither names any — every
        public top-level def (single-file fixture mode)."""
        mi = self.prog.modules[rel]
        defs = {n.name: n for n in mi.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if anno.dispatch is not None:
            return [defs[n] for n in anno.dispatch if n in defs]
        names: set[str] = set()
        for orel, omi in self.prog.modules.items():
            for node in ast.walk(omi.tree):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) not in (
                        "jax.jit", "jit", "jax.pmap", "pjit"):
                    continue
                for arg in node.args[:1]:
                    d = dotted_name(arg)
                    if not d:
                        continue
                    parts = d.split(".")
                    if len(parts) == 2 and omi.mod_imports.get(
                            parts[0]) == rel:
                        names.add(parts[1])
                    elif len(parts) == 1 and omi.name_imports.get(
                            parts[0], ("", ""))[0] == rel:
                        names.add(omi.name_imports[parts[0]][1])
        if names:
            return [defs[n] for n in sorted(names) if n in defs]
        return [defs[n] for n in sorted(defs) if not n.startswith("_")]

    def _gl11_module(self, rel: str, anno: ModuleAnno):
        self._cur_rel = rel
        twin_mi = self.prog.modules.get(anno.twin)
        twin_defs = set()
        if twin_mi is not None:
            twin_defs = {
                n.name for n in twin_mi.tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        guard_reach = self._padding_closure()
        for node in self._dispatched(rel, anno):
            name = node.name
            twin_name = name if anno.twin != rel else name + "_twin"
            if twin_name not in twin_defs:
                self.emit(
                    "GL11", node,
                    f"device-dispatched kernel {name} has no twin "
                    f"{twin_name} in {anno.twin}",
                    detail="twin module not in lint scope"
                    if twin_mi is None else "")
            if not self._has_parity_test(name, anno):
                self.emit(
                    "GL11", node,
                    f"device-dispatched kernel {name} has no parity "
                    "test referencing it under tests/")
            fid = f"{rel}::{name}"
            if not guard_reach.get(fid, False):
                self.emit(
                    "GL11", node,
                    f"device-dispatched kernel {name} never reaches an "
                    "infinity-sentinel guard (is_zero / padding-safe) "
                    "for its padding lanes")

    def _padding_closure(self) -> dict[str, bool]:
        """fid -> transitively reaches an is_zero-style guard or a
        padding-safe-annotated function."""
        direct: dict[str, bool] = {}
        for fid, fi in self.prog.funcs.items():
            c = self.contracts.get((fi.relpath, fi.qualname))
            safe = bool(c and c.padding_safe)
            if not safe:
                for node in ast.walk(fi.node):
                    if isinstance(node, ast.Call):
                        d = dotted_name(node.func) or ""
                        leaf = d.split(".")[-1]
                        if leaf.endswith("is_zero") or leaf == "infinity":
                            safe = True
                            break
            direct[fid] = safe
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fid in sorted(self.prog.call_edges):
                if direct.get(fid):
                    continue
                for callee in self.prog.call_edges[fid]:
                    if direct.get(callee):
                        direct[fid] = True
                        changed = True
                        break
        return direct

    def _has_parity_test(self, name: str, anno: ModuleAnno) -> bool:
        """A parity test = a tests/*.py that names the kernel (word-
        boundary) AND names the twin module's stem (word-boundary) —
        'reference'/'prefer' substrings don't count.  The text cache is
        per-run (``self``): a long-lived process re-reads tests/ every
        analysis, matching the engine cache's invalidation key."""
        if anno.tests == "skip":
            return True
        from .engine import REPO_ROOT

        root = REPO_ROOT / (anno.tests or "tests")
        if not root.is_dir():
            return False
        key = str(root)
        if key not in self._parity_texts:
            texts = []
            for p in sorted(root.glob("*.py")):
                try:
                    texts.append(p.read_text(encoding="utf-8"))
                except OSError:
                    continue
            self._parity_texts[key] = texts
        stem = (anno.twin or "twin").rsplit("/", 1)[-1]
        stem = stem[:-3] if stem.endswith(".py") else stem
        name_pat = re.compile(r"\b" + re.escape(name) + r"\b")
        twin_pat = re.compile(r"\b" + re.escape(stem) + r"\b")
        for text in self._parity_texts[key]:
            if name_pat.search(text) and twin_pat.search(text):
                return True
        return False


class _AnalysisError(Exception):
    def __init__(self, msg: str, line: int = 1):
        self.msg = msg
        self.line = line
        super().__init__(msg)


# ---------------------------------------------------------------------------
# the abstract interpreter


def _memokey(v):
    try:
        hash(v)
        return v
    except TypeError:
        return id(v)


class _Dead(Exception):
    """Control left the current path (return/raise)."""


class Interp:
    """Executes one scope (module top level or a function body) over
    the abstract domain."""

    def __init__(self, an: _Analysis, rel: str, env: dict,
                 check: bool):
        self.an = an
        self.rel = rel
        self.env = env
        self.check = check  # GL09/GL10 checks armed (kernel modules)
        self._returns = None

    # -- statements ---------------------------------------------------------

    def exec_func_body(self, node):
        try:
            self.exec_block(node.body)
        except _Dead:
            pass
        return self._returns if self._returns is not None else Conc(None)

    def exec_block(self, stmts):
        for s in stmts:
            self.exec_stmt(s)

    def exec_stmt(self, node):
        m = getattr(self, "_s_" + type(node).__name__, None)
        if m is not None:
            m(node)
        # unknown statement kinds are ignored (assert, global, ...)

    def _s_Expr(self, node):
        self.eval(node.value)

    def _s_Assign(self, node):
        val = self.eval(node.value)
        val = self._apply_line_anno(node, val)
        for tgt in node.targets:
            self._bind(tgt, val, node)

    def _s_AnnAssign(self, node):
        if node.value is not None:
            self._bind(node.target,
                       self._apply_line_anno(node, self.eval(node.value)),
                       node)

    def _s_AugAssign(self, node):
        cur = self.eval(node.target) if isinstance(
            node.target, ast.Name) else UNKNOWN
        val = self._binop(node, cur, node.op, self.eval(node.value))
        self._bind(node.target, val, node)

    def _apply_line_anno(self, node, val):
        """``X = ...  # graftlint: kernel bounds=limb; domain=mont``
        (trailing, or a standalone annotation line right above)."""
        annos = self.an.line_annos.get(self.rel, {})
        ln = _def_contract_line(node, annos)
        if ln is None:
            return val
        try:
            c = parse_contract(annos[ln][0])
        except (ValueError, KeyError) as e:
            self.an.emit("GL09", node,
                         f"unparseable kernel contract: {e}")
            return val
        av = to_av(val)
        if isinstance(c.ret, Spec) and not c.ret.anyv:
            av = replace(av, lo=c.ret.lo, hi=c.ret.hi,
                         limbaxis=c.ret.limbaxis or av.limbaxis)
        if c.retdom is not None and isinstance(c.retdom, tuple):
            av = replace(av, dom=c.retdom)
        return av

    def _bind(self, tgt, val, node):
        if isinstance(tgt, ast.Name):
            self.env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            elts = tgt.elts
            vals = None
            if isinstance(val, AbsTuple) and len(val) == len(elts):
                vals = list(val)
            elif is_known_conc(val) and isinstance(
                    val.value, (tuple, list)) and \
                    len(val.value) == len(elts):
                vals = [Conc(v) for v in val.value]
            for i, e in enumerate(elts):
                self._bind(e, vals[i] if vals else TOPV, node)
        elif isinstance(tgt, ast.Subscript):
            # store through a ref (pallas out_ref): check against the
            # declared bound of the ref it stores into
            if isinstance(tgt.value, ast.Name):
                ref = self.env.get(tgt.value.id)
                if isinstance(ref, AV) and ref.bounded and self.check:
                    a = to_av(val)
                    if not a.bounded or a.lo < ref.lo or a.hi > ref.hi:
                        self.an.emit(
                            "GL09", node,
                            f"store into {tgt.value.id} of "
                            f"{a.desc()} exceeds its declared bound "
                            f"{ref.desc()}", detail=a.prov)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, TOPV, node)

    def _s_Return(self, node):
        val = self.eval(node.value) if node.value is not None \
            else Conc(None)
        self._returns = val if self._returns is None \
            else av_join(self._returns, val)
        raise _Dead()

    def _s_Raise(self, node):
        raise _Dead()

    def _s_If(self, node):
        test = self.eval(node.test)
        if is_known_conc(test):
            branch = node.body if test.value else node.orelse
            self.exec_block(branch)
            return
        self._join_branches([node.body, node.orelse])

    def _join_branches(self, branches):
        pre = dict(self.env)
        outs = []
        for body in branches:
            self.env.clear()
            self.env.update(pre)
            try:
                self.exec_block(body)
                outs.append(dict(self.env))
            except _Dead:
                pass  # no fallthrough from this branch
        self.env.clear()
        if not outs:
            self.env.update(pre)
            raise _Dead()
        merged = outs[0]
        for other in outs[1:]:
            keys = set(merged) | set(other)
            merged = {
                k: av_join(merged.get(k, pre.get(k, TOPV)),
                           other.get(k, pre.get(k, TOPV)))
                for k in keys
            }
        self.env.update(merged)

    def _s_With(self, node):
        for item in node.items:
            self.eval(item.context_expr)
        self.exec_block(node.body)

    def _s_Try(self, node):
        pre = dict(self.env)
        try:
            self.exec_block(node.body)
        except _Dead:
            pass
        body_env = dict(self.env)
        for h in node.handlers:
            self.env.clear()
            self.env.update(pre)
            try:
                self.exec_block(h.body)
            except _Dead:
                continue
            keys = set(body_env) | set(self.env)
            body_env = {
                k: av_join(body_env.get(k, pre.get(k, TOPV)),
                           self.env.get(k, pre.get(k, TOPV)))
                for k in keys
            }
        self.env.clear()
        self.env.update(body_env)
        self.exec_block(node.finalbody)

    def _s_FunctionDef(self, node):
        self.env[node.name] = Closure(node, self.env, self.rel)

    _s_AsyncFunctionDef = _s_FunctionDef

    def _s_ClassDef(self, node):
        self.env[node.name] = UNKNOWN

    def _s_Delete(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name):
                self.env.pop(t.id, None)

    def _s_Import(self, node):
        for a in node.names:
            target = self.an.prog._module_path_of(self.rel, a.name, 0)
            name = a.asname or a.name.split(".")[0]
            self.env[name] = ModRef(target) if target else UNKNOWN

    def _s_ImportFrom(self, node):
        prog = self.an.prog
        modpath = prog._module_path_of(
            self.rel, node.module or "", node.level)
        for a in node.names:
            local = a.asname or a.name
            sub = prog._module_path_of(
                self.rel,
                ".".join(p for p in (node.module, a.name) if p),
                node.level)
            if sub is not None:
                self.env[local] = ModRef(sub)
            elif modpath is not None:
                self.env[local] = self._mod_attr(modpath, a.name)
            else:
                self.env[local] = UNKNOWN

    def _mod_attr(self, relpath: str, name: str):
        menv = self.an.module_env(relpath)
        if name in menv:
            return menv[name]
        mi = self.an.prog.modules.get(relpath)
        if mi is not None and name in mi.functions:
            return FuncRef(relpath, name)
        return UNKNOWN

    # -- loops --------------------------------------------------------------

    def _s_For(self, node):
        it = self.eval(node.iter)
        items = None
        if is_known_conc(it) and isinstance(
                it.value, (range, list, tuple, str)):
            items = [Conc(v) if not isinstance(v, (AV, AbsTuple, Conc))
                     else v for v in it.value]
        elif isinstance(it, AbsTuple):
            items = list(it)
        if items is not None and len(items) <= _UNROLL_CAP:
            for v in items:
                self._bind(node.target, v, node)
                self.exec_block(node.body)
            self.exec_block(node.orelse)
            return
        elem = self._elem_of(it)
        self._fix_loop(node, lambda: (self._bind(node.target, elem, node),
                                      self.exec_block(node.body)))
        self.exec_block(node.orelse)

    def _s_While(self, node):
        # concrete spin first: a loop over host ints runs for real
        for _ in range(_UNROLL_CAP):
            test = self.eval(node.test)
            if not is_known_conc(test):
                break
            if not test.value:
                self.exec_block(node.orelse)
                return
            self.exec_block(node.body)
        else:
            self.an.emit("GL09", node,
                         "concrete loop exceeded the unroll cap")
            return
        self._fix_loop(node, lambda: self.exec_block(node.body))
        self.exec_block(node.orelse)

    def _fix_loop(self, node, run_body):
        """Join-fixpoint over a loop body with interval widening."""
        for i in range(_LOOP_CAP):
            pre = dict(self.env)
            try:
                run_body()
            except _Dead:
                pass
            keys = set(pre) | set(self.env)
            nxt = {}
            stable = True
            for k in keys:
                a = pre.get(k, TOPV)
                b = self.env.get(k, pre.get(k, TOPV))
                j = av_join(a, b)
                if i >= _WIDEN_AFTER:
                    j = widen_any(a, j)
                if j != a:
                    stable = False
                nxt[k] = j
            self.env.clear()
            self.env.update(nxt)
            if stable:
                return
        self.an.emit("GL09", node,
                     "loop state does not stabilize under widening "
                     "(no provable bound)")

    def _elem_of(self, it):
        if isinstance(it, AV):
            return replace(it, scanlen=None)
        if isinstance(it, AbsTuple):
            return AbsTuple(self._elem_of(e) for e in it)
        if is_known_conc(it) and isinstance(
                it.value, (range, list, tuple, str)):
            out = None
            for v in it.value:
                c = v if isinstance(v, (AV, AbsTuple, Conc)) else Conc(v)
                out = c if out is None else av_join(out, c)
            return out if out is not None else UNKNOWN
        return TOPV if isinstance(it, AV) else UNKNOWN

    # -- expressions --------------------------------------------------------

    def eval(self, node):
        m = getattr(self, "_e_" + type(node).__name__, None)
        if m is None:
            return UNKNOWN
        return m(node)

    def _e_Constant(self, node):
        return Conc(node.value)

    def _e_Name(self, node):
        if node.id in self.env:
            return self.env[node.id]
        return UNKNOWN

    def _e_Attribute(self, node):
        base = self.eval(node.value)
        if isinstance(base, ModRef):
            return self._mod_attr(base.relpath, node.attr)
        if isinstance(base, AV):
            if node.attr == "T":
                return replace(base, limbaxis=False, scanlen=None)
            return UNKNOWN
        if isinstance(base, FieldOpsVal):
            return ("fieldmeth", node.attr)
        return UNKNOWN

    def _e_Tuple(self, node):
        return self._seq(node.elts)

    _e_List = _e_Tuple

    def _seq(self, elts):
        out = []
        for e in elts:
            if isinstance(e, ast.Starred):
                inner = self.eval(e.value)
                if isinstance(inner, AbsTuple):
                    out.extend(inner)
                elif is_known_conc(inner) and isinstance(
                        inner.value, (tuple, list)):
                    out.extend(Conc(v) for v in inner.value)
                else:
                    out.append(UNKNOWN)
            else:
                out.append(self.eval(e))
        return AbsTuple(out)

    def _e_IfExp(self, node):
        test = self.eval(node.test)
        if is_known_conc(test):
            return self.eval(node.body if test.value else node.orelse)
        return av_join(self.eval(node.body), self.eval(node.orelse))

    def _e_BoolOp(self, node):
        vals = [self.eval(v) for v in node.values]
        if all(is_known_conc(v) for v in vals):
            out = vals[0].value
            for v in vals[1:]:
                out = (out and v.value) if isinstance(node.op, ast.And) \
                    else (out or v.value)
            return Conc(out)
        if any(isinstance(v, AV) for v in vals):
            return AV(0, 1, DOM_NEUTRAL)
        return UNKNOWN

    def _e_Compare(self, node):
        left = self.eval(node.left)
        rights = [self.eval(c) for c in node.comparators]
        if is_known_conc(left) and all(is_known_conc(r) for r in rights):
            try:
                vals = [left.value] + [r.value for r in rights]
                ok = True
                for (a, b), op in zip(zip(vals, vals[1:]), node.ops):
                    ok = ok and _conc_compare(a, b, op)
                return Conc(bool(ok))
            except (TypeError, ValueError):
                return UNKNOWN
        return AV(0, 1, DOM_NEUTRAL)

    def _e_UnaryOp(self, node):
        v = self.eval(node.operand)
        if is_known_conc(v):
            try:
                if isinstance(node.op, ast.USub):
                    return Conc(-v.value)
                if isinstance(node.op, ast.Not):
                    return Conc(not v.value)
                if isinstance(node.op, ast.Invert):
                    return Conc(~v.value)
                return v
            except TypeError:
                return UNKNOWN
        a = to_av(v)
        if isinstance(node.op, ast.USub) and a.bounded:
            return AV(-a.hi, -a.lo, a.dom, prov=a.prov)
        if isinstance(node.op, (ast.Not, ast.Invert)) and \
                isinstance(v, AV):
            return AV(0, 1, DOM_NEUTRAL) if a.bounded and \
                0 <= a.lo and a.hi <= 1 else TOPV
        return TOPV if isinstance(v, AV) else UNKNOWN

    def _e_BinOp(self, node):
        return self._binop(node, self.eval(node.left), node.op,
                           self.eval(node.right))

    def _e_Subscript(self, node):
        base = self.eval(node.value)
        idx = self._eval_index(node.slice)
        if isinstance(base, AbsTuple):
            if is_known_conc(idx) and isinstance(idx.value, int):
                i = idx.value
                return base[i] if -len(base) <= i < len(base) else TOPV
            if is_known_conc(idx) and isinstance(idx.value, slice):
                return AbsTuple(base[idx.value])
            out = None
            for e in base:
                out = e if out is None else av_join(out, e)
            return out if out is not None else TOPV
        if is_known_conc(base):
            if is_known_conc(idx):
                try:
                    return Conc(base.value[idx.value])
                except (TypeError, KeyError, IndexError):
                    return UNKNOWN
            return UNKNOWN
        if isinstance(base, AV):
            # pure indexing/slicing never raises an element bound
            return replace(base, limbaxis=False, scanlen=None)
        return UNKNOWN

    def _eval_index(self, node):
        if isinstance(node, ast.Slice):
            parts = [self.eval(p) if p is not None else Conc(None)
                     for p in (node.lower, node.upper, node.step)]
            if all(is_known_conc(p) for p in parts):
                return Conc(slice(*(p.value for p in parts)))
            return UNKNOWN
        if isinstance(node, ast.Tuple):
            return UNKNOWN  # multi-axis index: bounds unchanged anyway
        return self.eval(node)

    def _e_ListComp(self, node):
        return self._comp(node)

    def _e_GeneratorExp(self, node):
        return self._comp(node)

    def _comp(self, node):
        if len(node.generators) != 1:
            return UNKNOWN
        gen = node.generators[0]
        it = self.eval(gen.iter)
        saved = dict(self.env)
        try:
            if is_known_conc(it) and isinstance(
                    it.value, (range, list, tuple, str)) and \
                    len(it.value) <= _UNROLL_CAP:
                out = []
                for v in it.value:
                    self._bind(gen.target,
                               v if isinstance(v, (AV, AbsTuple, Conc))
                               else Conc(v), node)
                    conds = [self.eval(c) for c in gen.ifs]
                    if any(is_known_conc(c) and not c.value
                           for c in conds):
                        continue
                    out.append(self.eval(node.elt))
                return AbsTuple(out)
            if isinstance(it, AbsTuple) and len(it) <= _UNROLL_CAP:
                out = []
                for v in it:
                    self._bind(gen.target, v, node)
                    out.append(self.eval(node.elt))
                return AbsTuple(out)
            self._bind(gen.target, self._elem_of(it), node)
            return AbsTuple([self.eval(node.elt)])
        finally:
            self.env.clear()
            self.env.update(saved)

    def _e_Lambda(self, node):
        return Closure(node, self.env, self.rel)

    def _e_JoinedStr(self, node):
        return UNKNOWN

    def _e_Starred(self, node):
        return self.eval(node.value)

    # -- arithmetic ---------------------------------------------------------

    def _binop(self, node, left, op, right):
        if is_known_conc(left) and is_known_conc(right):
            try:
                return Conc(_conc_binop(left.value, op, right.value))
            except (TypeError, ValueError, ZeroDivisionError,
                    OverflowError):
                return UNKNOWN
        if not isinstance(left, AV) and not isinstance(right, AV):
            return UNKNOWN
        a, b = to_av(left), to_av(right)
        out = self._interval_op(a, op, b)
        out = self._domain_op(node, a, op, b, out)
        if not isinstance(op, ast.MatMult):
            # elementwise ops keep the limb axis (broadcast included)
            out = replace(out, limbaxis=a.limbaxis or b.limbaxis)
        if self.check:
            self.an.check_overflow(
                node, out,
                f"{_opname(op)} of {a.desc()} and {b.desc()}")
        return out

    def _interval_op(self, a: AV, op, b: AV) -> AV:
        la, ha, lb, hb = a.lo, a.hi, b.lo, b.hi
        prov = ""
        if isinstance(op, ast.Add):
            lo = None if la is None or lb is None else la + lb
            hi = None if ha is None or hb is None else ha + hb
            prov = f"{a.desc()}+{b.desc()}"
        elif isinstance(op, ast.Sub):
            lo = None if la is None or hb is None else la - hb
            hi = None if ha is None or lb is None else ha - lb
            prov = f"{a.desc()}-{b.desc()}"
        elif isinstance(op, ast.Mult):
            if a.bounded and b.bounded:
                prods = [la * lb, la * hb, ha * lb, ha * hb]
                lo, hi = min(prods), max(prods)
            else:
                lo = hi = None
            prov = f"{a.desc()}*{b.desc()}"
        elif isinstance(op, ast.RShift):
            if b.bounded and lb == hb and lb >= 0:
                lo = None if la is None else la >> lb
                hi = None if ha is None else ha >> lb
            else:
                lo, hi = (0, ha) if la is not None and la >= 0 \
                    else (None, None)
            prov = f"{a.desc()}>>{lb if lb == hb else '?'}"
        elif isinstance(op, ast.LShift):
            if b.bounded and lb == hb and lb >= 0 and a.bounded:
                lo, hi = la << lb, ha << lb
            else:
                lo = hi = None
            prov = f"{a.desc()}<<{lb if lb == hb else '?'}"
        elif isinstance(op, ast.BitAnd):
            # masking with a nonneg value lands in [0, mask] regardless
            # of sign (int32 two's complement)
            cands = [x for x in (ha if la is not None and la >= 0
                                 else None,
                                 hb if lb is not None and lb >= 0
                                 else None) if x is not None]
            if hb is not None and lb == hb and hb >= 0:
                lo, hi = 0, hb
            elif ha is not None and la == ha and ha >= 0:
                lo, hi = 0, ha
            elif cands:
                lo, hi = 0, min(cands)
            else:
                lo = hi = None
            prov = f"{a.desc()}&{b.desc()}"
        elif isinstance(op, ast.BitOr):
            if a.bounded and b.bounded and la >= 0 and lb >= 0:
                lo, hi = 0, _pow2ceil(max(ha, hb) + 1) - 1
            else:
                lo = hi = None
            prov = f"{a.desc()}|{b.desc()}"
        elif isinstance(op, ast.BitXor):
            if a.bounded and b.bounded and la >= 0 and lb >= 0:
                lo, hi = 0, _pow2ceil(max(ha, hb) + 1) - 1
            else:
                lo = hi = None
            prov = f"{a.desc()}^{b.desc()}"
        elif isinstance(op, ast.FloorDiv):
            if a.bounded and b.bounded and lb == hb and lb > 0:
                lo, hi = la // lb, ha // lb
            else:
                lo = hi = None
            prov = f"{a.desc()}//{b.desc()}"
        elif isinstance(op, ast.Mod):
            if b.bounded and lb == hb and lb > 0:
                lo, hi = 0, hb - 1
            else:
                lo = hi = None
            prov = f"{a.desc()}%{b.desc()}"
        else:  # Div, Pow, MatMult, ...
            if isinstance(op, ast.MatMult):
                return self._reduction_product(a, b)
            lo = hi = None
            prov = _opname(op)
        return AV(lo, hi, DOM_TOP, prov=prov)

    def _reduction_product(self, a: AV, b: AV,
                           limb_contraction: bool | None = None) -> AV:
        """matmul/einsum-style contraction: elementwise product times
        the contraction length.  Provable ONLY when the contracted
        axis is the limb axis of the left operand (matmul contracts
        a's LAST axis; einsum passes ``limb_contraction`` from its
        parsed spec) — any other contraction length is unproven and
        fails at the next contract, never silently certified."""
        prod = self._interval_op(a, ast.Mult(), b)
        if limb_contraction is None:
            limb_contraction = a.limbaxis  # matmul: contracts a[..., -1]
        if prod.bounded and limb_contraction:
            return AV(min(prod.lo * N_LIMBS, 0), prod.hi * N_LIMBS,
                      DOM_TOP,
                      prov=f"{prod.prov} summed over {N_LIMBS} limbs")
        return AV(None, None, DOM_TOP, prov=prod.prov + " summed over "
                  "an unproven contraction length")

    def _domain_op(self, node, a: AV, op, b: AV, out: AV) -> AV:
        dc = self.check and self.an._domain_checks
        if isinstance(op, (ast.Add, ast.Sub)):
            if dc and _dom_mixes(a.dom, b.dom):
                self.an.emit(
                    "GL10", node,
                    f"{_opname(op)} mixes Montgomery domains "
                    f"{_dom_name(a.dom)} and {_dom_name(b.dom)}")
            return replace(out, dom=_dom_join(a.dom, b.dom))
        if isinstance(op, ast.Mult):
            if a.dom == DOM_NEUTRAL:
                return replace(out, dom=b.dom)
            if b.dom == DOM_NEUTRAL:
                return replace(out, dom=a.dom)
            if dc and a.dom[0] == "deg" and b.dom[0] == "deg":
                self.an.emit(
                    "GL10", node,
                    f"raw * product of {_dom_name(a.dom)}-domain and "
                    f"{_dom_name(b.dom)}-domain values outside the "
                    "mont_mul primitive")
            return replace(out, dom=DOM_TOP)
        if isinstance(op, (ast.RShift, ast.LShift, ast.BitAnd,
                           ast.BitOr, ast.BitXor, ast.Mod,
                           ast.FloorDiv)):
            # carry plumbing keeps the field element's domain
            keep = a.dom if isinstance(op, (ast.RShift, ast.LShift)) \
                else _dom_join(a.dom if a.dom != DOM_TOP else b.dom,
                               b.dom if b.dom != DOM_TOP else a.dom)
            return replace(out, dom=keep if keep != DOM_TOP
                           else _dom_join(a.dom, b.dom))
        return out

    # -- calls --------------------------------------------------------------

    def _eval_args(self, arg_nodes):
        out = []
        for a in arg_nodes:
            if isinstance(a, ast.Starred):
                inner = self.eval(a.value)
                if isinstance(inner, AbsTuple):
                    out.extend(inner)
                elif is_known_conc(inner) and isinstance(
                        inner.value, (tuple, list)):
                    out.extend(Conc(v) for v in inner.value)
                else:
                    out.append(UNKNOWN)
            else:
                out.append(self.eval(a))
        return out

    def _e_Call(self, node):
        dotted = dotted_name(node.func)
        key = _intrinsic_key(dotted)
        if key is not None:
            args = self._eval_args(node.args)
            kwargs = {k.arg: self.eval(k.value)
                      for k in node.keywords if k.arg}
            return _INTRINSICS[key](self, node, args, kwargs)
        args = self._eval_args(node.args)
        kwargs = {k.arg: self.eval(k.value)
                  for k in node.keywords if k.arg}
        if isinstance(node.func, ast.Name) and \
                node.func.id not in self.env:
            return self._builtin(node, node.func.id, args, kwargs)
        if isinstance(node.func, ast.Attribute):
            base = self.eval(node.func.value)
            if isinstance(base, AV):
                return self._av_method(node, base, node.func.attr, args)
            if isinstance(base, FieldOpsVal):
                return self._field_call(node, node.func.attr, args)
            if isinstance(base, ModRef):
                fn = self._mod_attr(base.relpath, node.func.attr)
                return self.call_value(fn, node, args, kwargs)
            return UNKNOWN
        fn = self.eval(node.func)
        return self.call_value(fn, node, args, kwargs)

    def call_value(self, fn, node, args, kwargs=None):
        kwargs = kwargs or {}
        if isinstance(fn, Closure):
            c = self.an.contracts.get((fn.relpath, fn.node.name)) \
                if isinstance(fn.node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)) else None
            if c is not None and c.has_bounds:
                return self._contract_call(
                    fn.relpath, fn.node.name, c, node, args)
            return self._inline(fn.node, fn.env, fn.relpath, node,
                                args, kwargs, memo=False)
        if isinstance(fn, FuncRef):
            c = self.an.contracts.get((fn.relpath, fn.name))
            if c is not None and c.has_bounds:
                return self._contract_call(fn.relpath, fn.name, c,
                                           node, args)
            fid = f"{fn.relpath}::{fn.name}"
            fi = self.an.prog.funcs.get(fid)
            if fi is None:
                return UNKNOWN
            env = self.an.module_env(fn.relpath)
            return self._inline(fi.node, env, fn.relpath, node, args,
                                kwargs, memo=True)
        if isinstance(fn, tuple) and len(fn) == 2 and \
                fn[0] == "fieldmeth":
            return self._field_call(node, fn[1], args)
        if isinstance(fn, _PallasProg):
            return fn.result(self)
        if isinstance(fn, _Partial):
            return self.call_value(fn.fn, node,
                                   list(fn.args) + list(args),
                                   {**fn.kwargs, **kwargs})
        if any(isinstance(a, AV) for a in args):
            return TOPV
        return UNKNOWN

    def _builtin(self, node, name, args, kwargs):
        if name in ("range", "len", "int", "bin", "hex", "min", "max",
                    "abs", "sum", "bool", "str", "float", "enumerate",
                    "zip", "list", "tuple", "sorted", "reversed",
                    "round", "ord", "chr", "divmod"):
            if all(is_known_conc(a) for a in args) and not kwargs:
                import builtins

                try:
                    v = getattr(builtins, name)(
                        *(a.value for a in args))
                    if name in ("enumerate", "zip", "reversed"):
                        v = list(v)
                    return Conc(v)
                except (TypeError, ValueError, OverflowError):
                    return UNKNOWN
            if name in ("list", "tuple") and args and \
                    isinstance(args[0], AbsTuple):
                return args[0]
            if name in ("len",) and args and \
                    isinstance(args[0], AbsTuple):
                return Conc(len(args[0]))
        return UNKNOWN

    def _av_method(self, node, base, meth, args):
        if meth in ("astype", "copy", "view", "clip", "block_until_ready"):
            return base
        if meth in ("reshape", "transpose", "swapaxes", "ravel",
                    "flatten", "squeeze"):
            return replace(base, limbaxis=False, scanlen=None)
        if meth == "sum":
            return self._reduce_sum(node, base)
        if meth in ("max", "min"):
            return replace(base, limbaxis=False, scanlen=None)
        if meth in ("item", "tolist"):
            return UNKNOWN
        return TOPV

    def _reduce_sum(self, node, x):
        a = to_av(x)
        if a.bounded and a.limbaxis:
            out = AV(a.lo * N_LIMBS if a.lo < 0 else 0,
                     a.hi * N_LIMBS, a.dom,
                     prov=f"sum of {N_LIMBS} limbs each {a.desc()}")
            if self.check:
                self.an.check_overflow(node, out, "limb-axis sum")
            return out
        return AV(None, None, a.dom,
                  prov=f"sum over an unproven length of {a.desc()}")

    def _field_call(self, node, meth, args):
        info = _FIELD_METHODS.get(meth)
        if info is None:
            return TOPV
        specs, domkind, ret, retdom = info
        if ret == "join":
            out = None
            for e in (args[0] if args and isinstance(args[0], AbsTuple)
                      else args):
                out = e if out is None else av_join(out, e)
            return out if out is not None else TOPV
        if specs is not None and self.check:
            for i, spec in enumerate(specs):
                if i >= len(args):
                    break
                bad = spec.check(args[i])
                if bad:
                    self.an.emit(
                        "GL09", node,
                        f"argument {i} of field op .{meth}(): {bad}",
                        detail=to_av(args[i]).prov)
        dom = retdom if isinstance(retdom, tuple) else DOM_TOP
        if domkind == "mul":
            degs = [to_av(a).dom for a in args]
            if all(d[0] == "deg" for d in degs):
                d = sum(x[1] for x in degs) * (2 if len(degs) == 1
                                               else 1) - 1
                dom = deg(d)
                self._check_deg(node, d, meth)
        elif domkind in ("same", "sel"):
            pick = args[1:] if domkind == "sel" else args
            dom = self._unify(node, [to_av(a).dom for a in pick],
                              f"field op .{meth}()")
        av = AV(ret.lo, ret.hi, dom, limbaxis=ret.limbaxis)
        return av

    def _check_deg(self, node, d, what):
        if self.check and self.an._domain_checks and d not in (0, 1, 2):
            self.an.emit("GL10", node,
                         f"{what} yields Montgomery degree R^{d} "
                         "(outside std/mont/r2 — a missing to_mont/"
                         "from_mont conversion)")

    def _unify(self, node, doms, what) -> tuple:
        uni = None
        all_neutral = True
        for d in doms:
            if d == DOM_NEUTRAL:
                continue
            all_neutral = False
            if d == DOM_TOP:
                continue
            if uni is None:
                uni = d
            elif uni != d:
                if self.check and self.an._domain_checks:
                    self.an.emit(
                        "GL10", node,
                        f"{what} mixes Montgomery domains "
                        f"{_dom_name(uni)} and {_dom_name(d)}")
                return DOM_TOP
        if all_neutral:
            return DOM_NEUTRAL
        return uni if uni is not None else DOM_TOP

    def _contract_call(self, rel, name, c, node, args):
        if c.has_bounds and self.check:
            for i, spec in enumerate(c.params):
                if i >= len(args):
                    break
                bad = spec.check(args[i])
                if bad:
                    self.an.emit(
                        "GL09", node,
                        f"argument {i} of {name}(): {bad}",
                        detail=to_av(args[i]).prov)
        retdom = self._call_retdom(node, name, c, args)
        return self._ret_from_spec(c.ret, retdom, name)

    def _call_retdom(self, node, name, c, args):
        if c.primitive:
            degs = [to_av(a).dom for a in args[:2]]
            if len(degs) == 2 and all(d[0] == "deg" for d in degs):
                d = degs[0][1] + degs[1][1] - 1
                self._check_deg(node, d, f"{name}()")
                return deg(d)
            return DOM_TOP
        doms = c.doms
        sym_doms = [to_av(a).dom for i, a in enumerate(args)
                    if i < len(doms) and doms[i] == ("sym", "S")]
        if self.check and self.an._domain_checks:
            for i, spec_dom in enumerate(doms):
                if i >= len(args) or spec_dom in (
                        DOM_TOP, DOM_NEUTRAL) or spec_dom[0] == "sym":
                    continue
                have = to_av(args[i]).dom
                if have[0] == "deg" and have != spec_dom:
                    self.an.emit(
                        "GL10", node,
                        f"argument {i} of {name}() is "
                        f"{_dom_name(have)}-domain where the contract "
                        f"declares {_dom_name(spec_dom)}")
        unified = self._unify(node, sym_doms, f"{name}()") \
            if sym_doms else DOM_TOP
        return self._resolve_retdom(c.retdom, unified)

    def _resolve_retdom(self, retdom, unified):
        if retdom is None:
            return DOM_TOP
        if isinstance(retdom, AbsTuple):
            return AbsTuple(self._resolve_retdom(d, unified)
                            for d in retdom)
        if retdom == ("sym", "S"):
            return unified
        return retdom

    def _ret_from_spec(self, ret, retdom, name):
        if ret is None:
            return AV(None, None,
                      retdom if isinstance(retdom, tuple) else DOM_TOP)
        if isinstance(ret, AbsTuple):
            doms = retdom if isinstance(retdom, AbsTuple) \
                else AbsTuple([retdom] * len(ret))
            return AbsTuple(self._ret_from_spec(s, d, name)
                            for s, d in zip(ret, doms))
        dom = retdom if isinstance(retdom, tuple) else DOM_TOP
        if ret.fieldops:
            return FIELDOPS
        return AV(ret.lo, ret.hi, dom, limbaxis=ret.limbaxis,
                  prov=f"contract of {name}")

    def _inline(self, fnode, defenv, defrel, node, args, kwargs,
                memo):
        an = self.an
        if an._depth >= _INLINE_DEPTH:
            return TOPV
        key = None
        if memo:
            key = (defrel, id(fnode),
                   tuple(_memokey(a) for a in args),
                   tuple(sorted((k, _memokey(v))
                                for k, v in kwargs.items())))
            if key in an._memo:
                got = an._memo[key]
                return TOPV if got is _INPROGRESS else got
            an._memo[key] = _INPROGRESS
        env = dict(defenv)
        a = fnode.args
        pos = list(a.posonlyargs) + list(a.args)
        bound = set()
        for i, p in enumerate(pos):
            if i < len(args):
                env[p.arg] = args[i]
                bound.add(p.arg)
        for k, v in kwargs.items():
            env[k] = v
            bound.add(k)
        if a.vararg:
            env[a.vararg.arg] = AbsTuple(args[len(pos):])
        if a.kwarg:
            env[a.kwarg.arg] = UNKNOWN
        prev_rel = an._cur_rel
        an._cur_rel = defrel
        an._depth += 1
        child = Interp(an, defrel, env, check=defrel in an.module_annos)
        try:
            ndef = len(a.defaults)
            for j, d in enumerate(a.defaults):
                p = pos[len(pos) - ndef + j]
                if p.arg not in bound:
                    env[p.arg] = child.eval(d)
            for p, d in zip(a.kwonlyargs, a.kw_defaults):
                if p.arg not in bound:
                    env[p.arg] = child.eval(d) if d is not None \
                        else UNKNOWN
            if isinstance(fnode, ast.Lambda):
                ret = child.eval(fnode.body)
            else:
                ret = child.exec_func_body(fnode)
        finally:
            an._cur_rel = prev_rel
            an._depth -= 1
        if memo and key is not None:
            an._memo[key] = ret
        return ret

    # -- lax loop primitives ------------------------------------------------

    def _lax_scan(self, node, args, kwargs):
        if len(args) < 3:
            return TOPV
        f, init, xs = args[0], args[1], args[2]
        xelem = self._elem_of(xs)
        n = xs.scanlen if isinstance(xs, AV) else None
        if n:
            carry = init
            for _ in range(min(n, _UNROLL_CAP)):
                r = self.call_value(f, node, [carry, xelem])
                carry = r[0] if isinstance(r, AbsTuple) and len(r) == 2 \
                    else TOPV
            return AbsTuple([carry, TOPV])
        carry = init
        for i in range(_LOOP_CAP):
            r = self.call_value(f, node, [carry, xelem])
            c2 = r[0] if isinstance(r, AbsTuple) and len(r) == 2 \
                else TOPV
            j = av_join(carry, c2)
            if i >= _WIDEN_AFTER:
                j = widen_any(carry, j)
            if j == carry:
                return AbsTuple([carry, TOPV])
            carry = j
        self.an.emit("GL09", node,
                     "lax.scan carry does not stabilize under widening "
                     "(no provable bound)")
        return AbsTuple([TOPV, TOPV])

    def _lax_fori(self, node, args, kwargs):
        if len(args) < 4:
            return TOPV
        lo, hi, body, init = args[0], args[1], args[2], args[3]
        if is_known_conc(lo) and is_known_conc(hi) and \
                isinstance(lo.value, int) and isinstance(hi.value, int):
            n = hi.value - lo.value
            if 0 <= n <= _UNROLL_CAP:
                carry = init
                for i in range(n):
                    carry = self.call_value(
                        body, node, [Conc(lo.value + i), carry])
                return carry
        carry = init
        for i in range(_LOOP_CAP):
            c2 = self.call_value(body, node, [UNKNOWN, carry])
            j = av_join(carry, c2)
            if i >= _WIDEN_AFTER:
                j = widen_any(carry, j)
            if j == carry:
                return carry
            carry = j
        self.an.emit("GL09", node,
                     "lax.fori_loop carry does not stabilize under "
                     "widening (no provable bound)")
        return TOPV

    def _lax_while(self, node, args, kwargs):
        if len(args) < 3:
            return TOPV
        _cond, body, init = args[0], args[1], args[2]
        carry = init
        for i in range(_LOOP_CAP):
            c2 = self.call_value(body, node, [carry])
            j = av_join(carry, c2)
            if i >= _WIDEN_AFTER:
                j = widen_any(carry, j)
            if j == carry:
                return carry
            carry = j
        self.an.emit("GL09", node,
                     "lax.while_loop carry does not stabilize under "
                     "widening (no provable bound)")
        return TOPV


class _PallasProg:
    """The callable pl.pallas_call returns: its result bound is the
    kernel contract's declared output (the ``->`` spec)."""

    def __init__(self, kernel, an):
        self.kernel = kernel
        self.an = an

    def result(self, interp):
        k = self.kernel
        key = None
        if isinstance(k, Closure) and isinstance(
                k.node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = (k.relpath, k.node.name)
        elif isinstance(k, FuncRef):
            key = (k.relpath, k.name)
        c = self.an.contracts.get(key) if key else None
        if c is None or c.ret is None:
            return TOPV
        return interp._ret_from_spec(c.ret, c.retdom or DOM_TOP,
                                     key[1] if key else "pallas kernel")


class _Partial:
    def __init__(self, fn, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs


_INPROGRESS = object()


def _conc_binop(a, op, b):
    import operator as O

    table = {
        ast.Add: O.add, ast.Sub: O.sub, ast.Mult: O.mul,
        ast.FloorDiv: O.floordiv, ast.Mod: O.mod, ast.Pow: O.pow,
        ast.LShift: O.lshift, ast.RShift: O.rshift,
        ast.BitAnd: O.and_, ast.BitOr: O.or_, ast.BitXor: O.xor,
        ast.Div: O.truediv,
    }
    fn = table.get(type(op))
    if fn is None:
        raise TypeError(type(op).__name__)
    if type(op) is ast.Pow and isinstance(b, int) and b > 4096:
        raise OverflowError("exponent too large to fold")
    return fn(a, b)


def _conc_compare(a, b, op) -> bool:
    import operator as O

    table = {
        ast.Eq: O.eq, ast.NotEq: O.ne, ast.Lt: O.lt, ast.LtE: O.le,
        ast.Gt: O.gt, ast.GtE: O.ge,
        ast.Is: lambda x, y: x is y,
        ast.IsNot: lambda x, y: x is not y,
        ast.In: lambda x, y: x in y,
        ast.NotIn: lambda x, y: x not in y,
    }
    return bool(table[type(op)](a, b))


def _opname(op) -> str:
    return {
        ast.Add: "+", ast.Sub: "-", ast.Mult: "*", ast.RShift: ">>",
        ast.LShift: "<<", ast.BitAnd: "&", ast.BitOr: "|",
        ast.BitXor: "^", ast.FloorDiv: "//", ast.Mod: "%",
        ast.MatMult: "@", ast.Div: "/", ast.Pow: "**",
    }.get(type(op), type(op).__name__)


# ---------------------------------------------------------------------------
# jnp / lax intrinsics


def _arrayify(v):
    if isinstance(v, AV):
        return v
    if isinstance(v, AbsTuple):
        out = None
        for e in v:
            a = _arrayify(e)
            out = a if out is None else av_join(out, to_av(a))
        return to_av(out) if out is not None else AV(0, 0, DOM_NEUTRAL)
    if is_known_conc(v):
        val = v.value
        if isinstance(val, (int, bool)):
            return AV(int(val), int(val), DOM_NEUTRAL)
        if isinstance(val, (list, tuple, range)):
            flat = list(_flatten_conc(val))
            if flat and all(isinstance(x, int) for x in flat):
                return AV(min(flat), max(flat), DOM_NEUTRAL)
    return TOPV


def _flatten_conc(val):
    for x in val:
        if isinstance(x, (list, tuple)):
            yield from _flatten_conc(x)
        elif isinstance(x, bool):
            yield int(x)
        else:
            yield x


def _i_asarray(interp, node, args, kwargs):
    return _arrayify(args[0]) if args else TOPV


def _i_join_seq(interp, node, args, kwargs):
    seq = args[0] if args and isinstance(args[0], AbsTuple) else \
        AbsTuple(args)
    out = None
    doms = []
    for e in seq:
        a = to_av(_arrayify(e) if not isinstance(e, AV) else e)
        doms.append(a.dom)
        out = a if out is None else av_join(out, a)
    if interp.check:
        interp._unify(node, doms, "stack/concatenate")
    return out if out is not None else TOPV


def _i_where(interp, node, args, kwargs):
    if len(args) != 3:
        return TOPV
    a, b = to_av(args[1]), to_av(args[2])
    if interp.check:
        interp._unify(node, [a.dom, b.dom], "jnp.where")
    return av_join(a, b)


def _i_zeros(interp, node, args, kwargs):
    return AV(0, 0, DOM_NEUTRAL)


def _i_ones(interp, node, args, kwargs):
    return AV(1, 1, DOM_NEUTRAL)


def _i_full(interp, node, args, kwargs):
    v = args[1] if len(args) > 1 else kwargs.get("fill_value")
    return to_av(v) if v is not None else TOPV


def _i_pad(interp, node, args, kwargs):
    fill = kwargs.get("constant_values")
    base = to_av(args[0]) if args else TOPV
    return av_join(base, to_av(fill) if fill is not None
                   else AV(0, 0, DOM_NEUTRAL))


def _i_first(interp, node, args, kwargs):
    return args[0] if args else TOPV


def _i_strip(interp, node, args, kwargs):
    a = to_av(args[0]) if args else TOPV
    return replace(a, limbaxis=False, scanlen=None)


def _i_moveaxis(interp, node, args, kwargs):
    if not args:
        return TOPV
    a = to_av(args[0])
    if a.limbaxis and len(args) >= 3 and \
            is_known_conc(args[1]) and args[1].value == -1 and \
            is_known_conc(args[2]) and args[2].value == 0:
        return replace(a, limbaxis=False, scanlen=N_LIMBS)
    return replace(a, limbaxis=False, scanlen=None)


def _i_split(interp, node, args, kwargs):
    a = replace(to_av(args[0]), limbaxis=False, scanlen=None) \
        if args else TOPV
    n = args[1].value if len(args) > 1 and is_known_conc(args[1]) and \
        isinstance(args[1].value, int) else 1
    return AbsTuple([a] * max(1, min(n, 64)))


def _i_bool(interp, node, args, kwargs):
    return AV(0, 1, DOM_NEUTRAL)


def _i_sum(interp, node, args, kwargs):
    return interp._reduce_sum(node, args[0]) if args else TOPV


def _einsum_contracts_last_axis(spec: str, arrays: list) -> bool:
    """True iff the (single) contracted index is the LAST axis of every
    operand that carries the limb axis — the only contraction whose
    length (N_LIMBS) the analysis can prove.  Anything else — another
    axis, several contracted indices, an unparseable spec — is
    unprovable and must stay unbounded."""
    try:
        inputs, out = spec.replace(" ", "").split("->")
        ins = [s.replace("...", "") for s in inputs.split(",")]
    except ValueError:
        return False  # implicit-output or malformed spec: unprovable
    contracted = {c for s in ins for c in s} - set(out)
    if len(contracted) != 1:
        return False
    (c,) = contracted
    return all(s.endswith(c) for s in ins if s) and \
        all(a.limbaxis for a in arrays)


def _i_einsum(interp, node, args, kwargs):
    arrays = [to_av(a) for a in args if isinstance(a, AV)]
    if not arrays:
        return TOPV
    spec = args[0].value if args and is_known_conc(args[0]) and \
        isinstance(args[0].value, str) else None
    provable = spec is not None and \
        _einsum_contracts_last_axis(spec, arrays)
    out = arrays[0]
    for b in arrays[1:]:
        out = interp._reduction_product(out, b,
                                        limb_contraction=provable)
        if interp.check:
            interp.an.check_overflow(node, out, "einsum contraction")
    if len(arrays) == 1:
        out = interp._reduce_sum(node, out) if provable else \
            AV(None, None, out.dom,
               prov="einsum over an unproven contraction")
    return out


def _i_matmul(interp, node, args, kwargs):
    if len(args) < 2:
        return TOPV
    out = interp._reduction_product(to_av(args[0]), to_av(args[1]))
    if interp.check:
        interp.an.check_overflow(node, out, "matmul contraction")
    return out


def _i_minmax(interp, node, args, kwargs):
    if len(args) >= 2:
        return av_join(to_av(args[0]), to_av(args[1]))
    return to_av(args[0]) if args else TOPV


def _i_abs(interp, node, args, kwargs):
    a = to_av(args[0]) if args else TOPV
    if a.bounded:
        return AV(0, max(abs(a.lo), abs(a.hi)), a.dom)
    return AV(0, None, a.dom)


def _i_scan(interp, node, args, kwargs):
    return interp._lax_scan(node, args, kwargs)


def _i_fori(interp, node, args, kwargs):
    return interp._lax_fori(node, args, kwargs)


def _i_while(interp, node, args, kwargs):
    return interp._lax_while(node, args, kwargs)


def _i_top(interp, node, args, kwargs):
    return TOPV


def _i_unknown(interp, node, args, kwargs):
    return UNKNOWN


def _i_pallas(interp, node, args, kwargs):
    return _PallasProg(args[0] if args else None, interp.an)


def _i_partial(interp, node, args, kwargs):
    if not args:
        return UNKNOWN
    return _Partial(args[0], args[1:], kwargs)


_INTRINSICS = {
    "jnp.asarray": _i_asarray, "jnp.array": _i_asarray,
    "jnp.stack": _i_join_seq, "jnp.concatenate": _i_join_seq,
    "jnp.hstack": _i_join_seq, "jnp.vstack": _i_join_seq,
    "jnp.where": _i_where,
    "jnp.zeros": _i_zeros, "jnp.zeros_like": _i_zeros,
    "jnp.empty": _i_zeros, "jnp.empty_like": _i_zeros,
    "jnp.ones": _i_ones, "jnp.ones_like": _i_ones,
    "jnp.full": _i_full, "jnp.full_like": _i_full,
    "jnp.pad": _i_pad,
    # broadcasting replicates elements, it never changes their bounds
    "jnp.broadcast_arrays": lambda i, n, a, k: AbsTuple(a),
    "jnp.broadcast_to": _i_first,
    "jnp.reshape": _i_strip, "jnp.squeeze": _i_strip,
    "jnp.transpose": _i_strip, "jnp.swapaxes": _i_strip,
    "jnp.expand_dims": _i_strip, "jnp.ravel": _i_strip,
    "jnp.flip": _i_strip, "jnp.roll": _i_strip,
    "jnp.moveaxis": _i_moveaxis,
    "jnp.split": _i_split,
    "jnp.all": _i_bool, "jnp.any": _i_bool,
    "jnp.logical_and": _i_bool, "jnp.logical_or": _i_bool,
    "jnp.logical_not": _i_bool, "jnp.equal": _i_bool,
    "jnp.sum": _i_sum,
    "jnp.einsum": _i_einsum,
    "jnp.matmul": _i_matmul, "jnp.dot": _i_matmul,
    "jnp.tensordot": _i_matmul,
    "jnp.minimum": _i_minmax, "jnp.maximum": _i_minmax,
    "jnp.abs": _i_abs, "jnp.absolute": _i_abs,
    "jnp.int32": _i_first, "jnp.int8": _i_first,
    "jnp.int16": _i_first, "jnp.int64": _i_first,
    "jnp.uint32": _i_first, "jnp.float32": _i_first,
    "lax.scan": _i_scan, "lax.fori_loop": _i_fori,
    "lax.while_loop": _i_while,
    "lax.associative_scan": _i_top, "lax.select": _i_where,
    "lax.cond": _i_top, "lax.switch": _i_top,
    "lax.dot_general": _i_matmul,
    "jax.jit": _i_first, "jit": _i_first,
    "jax.vmap": _i_first, "vmap": _i_first,
    "jax.ensure_compile_time_eval": _i_unknown,
    "pl.pallas_call": _i_pallas, "pltpu.pallas_call": _i_pallas,
    "pallas_call": _i_pallas,
    "functools.partial": _i_partial, "partial": _i_partial,
}

_NP_PREFIXES = ("jnp.", "np.", "jax.numpy.", "numpy.")


def _intrinsic_key(dotted: str | None) -> str | None:
    if not dotted:
        return None
    for p in _NP_PREFIXES:
        if dotted.startswith(p):
            cand = "jnp." + dotted[len(p):]
            return cand if cand in _INTRINSICS else None
    for p in ("jax.lax.", "lax."):
        if dotted.startswith(p):
            cand = "lax." + dotted[len(p):]
            return cand if cand in _INTRINSICS else None
    if dotted in _INTRINSICS:
        return dotted
    return None


# ---------------------------------------------------------------------------
# public entry


def kernel_findings(prog: Program) -> list[SiteFinding]:
    """Run GL09/GL10/GL11 over an analyzed interproc Program."""
    an = _Analysis(prog)
    try:
        out = an.run()
    except RecursionError:
        out = an.findings + [SiteFinding(
            sorted(prog.modules)[0] if prog.modules else "<unknown>",
            "GL09", 1, 0,
            "kernelcheck internal recursion limit", "<module>")]
    return sorted(out, key=lambda f: (f.relpath, f.line, f.col,
                                      f.rule, f.message))
