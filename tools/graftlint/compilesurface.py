"""Compile-surface pass: GL15 + GL16 + GL17 and the warmup manifest.

PR 15's NEWVIEW wedge was a COMPILE reachability bug: the first
view-change at a new committee width handed XLA a program shape nobody
had compiled, on the consensus pump thread, and every validator hung
~90s.  The runtime fix (breaker-guarded dispatch) made the wedge
survivable; this pass makes the CLASS statically impossible by treating
the jit surface as an enumerable, machine-checked artifact:

  GL15  bucket derivability — every *program site* (an f-string program
        name flowing into ``device._program_first_use`` or an
        ``aot.load/resolve/compiled/warm`` lookup) must have each
        placeholder's value set derivable from a pinned bucket registry
        (a module-level int tuple) through declared *bucket functions*
        (``# graftlint: bucket-fn registry=NAME[,NAME]`` — the pass
        VERIFIES every return of such a function stays inside its
        registry; an escaping return is the static generalization of
        committee_bucket's old unbounded overflow tail).  A placeholder
        fed by ``len(...)``, a raw argument, arithmetic or an
        undeclared call is exactly the NEWVIEW class: unbounded shapes
        reachable from serving paths.

  GL16  manifest coverage — the cross product of every derivable
        site's bucket domains IS the warmup manifest
        (tools/artifacts/aot/compile_manifest.json).  Derived programs
        missing from the committed manifest, and committed names no
        longer derivable, both fail the gate;
        ``python -m tools.graftlint --emit-compile-manifest`` emits the
        canonical JSON and CI diffs it against the committed copy.

  GL17  compile locality — ``.lower(args)`` / ``.lower().compile()``
        chains, first-traces of jit-bound callables and bare compile
        heads (jax.jit / pjit / pmap / shard_map / pallas_call) are
        flagged outside the sanctioned device layer
        (device.py, aot.py, ops/, parallel/) unless the enclosing
        function is annotated ``# graftlint: compile-phase=warmup`` (a
        startup precompile) or ``compile-phase=diagnostic`` (an
        armed-profiler-only recompile, never on the serving path).
        Files outside harmony_tpu/ opt in with a module-level
        ``# graftlint: compile-zone=serving`` marker (fixture /
        smoke-tool discipline, mirroring kernelcheck's kernel-module
        opt-in).

Static assumptions, both load-bearing and documented in
docs/ANALYSIS.md: ``kernel_twin_active()`` evaluates False (twin mode
keeps jax unloaded by contract, so twin-only widths are not XLA
programs — aot.warmup marks them separately), and an ``X if t else Y``
placeholder assignment is refined to one branch only when the
consuming sink is itself guarded by a structurally identical test.
"""

from __future__ import annotations

import ast
import itertools
import json
import re
from pathlib import Path

from .interproc import Program, SiteFinding
from .rules import dotted_name
from .threadrole import (
    _Index,
    _own_nodes,
    _role_annotations,
    _spawn_role,
)

REPO_ROOT = Path(__file__).resolve().parents[2]
MANIFEST_RELPATH = "tools/artifacts/aot/compile_manifest.json"
MANIFEST_PATH = REPO_ROOT / MANIFEST_RELPATH

_BUCKET_FN_RE = re.compile(
    r"graftlint:\s*bucket-fn\s+registry=([A-Za-z0-9_,\s]+)")
_PHASE_RE = re.compile(
    r"graftlint:\s*compile-phase=(warmup|diagnostic)")
_ZONE_RE = re.compile(r"graftlint:\s*compile-zone=([A-Za-z0-9_.\-]+)")

# the sanctioned compile layer: the guarded dispatch switch, the AOT
# cache/warmup, the kernel programs and the mesh shardings themselves
_SANCTIONED_FILES = {"harmony_tpu/device.py", "harmony_tpu/aot.py"}
_SANCTIONED_PREFIXES = ("harmony_tpu/ops/", "harmony_tpu/parallel/")

_COMPILE_HEADS = {
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap",
    "jax.shard_map", "shard_map",
}
_AOT_SINK_ATTRS = {"load", "resolve", "compiled", "warm"}

# the thread roles whose cones ARE the serving plane (witness detail
# for findings; program sites in the device layer are always in scope
# — that layer exists to serve these roles)
_SERVING_ROLES = {
    "consensus.pump", "sched.flush", "sidecar.reader", "serving",
}

_NAME_CAP = 4096  # cross-product backstop: beyond this it is unbounded


def _compile_sanctioned(relpath: str) -> bool:
    return (relpath in _SANCTIONED_FILES
            or relpath.startswith(_SANCTIONED_PREFIXES))


def _head_of(expr) -> str | None:
    """The compile-head name of ``expr`` (a call or a bare decorator
    expression), seeing through functools.partial(jax.jit, ...)."""
    if isinstance(expr, ast.Call):
        h = dotted_name(expr.func)
        if h:
            if h in _COMPILE_HEADS or h.split(".")[-1] == "pallas_call":
                return h
            if h.split(".")[-1] == "partial" and expr.args:
                inner = dotted_name(expr.args[0])
                if inner and (inner in _COMPILE_HEADS
                              or inner.split(".")[-1] == "pallas_call"):
                    return inner
        return None
    h = dotted_name(expr)
    if h and (h in _COMPILE_HEADS or h.split(".")[-1] == "pallas_call"):
        return h
    return None


# -- per-module facts --------------------------------------------------------


class _ModFacts:
    """Module-level bucket registries (int tuples), int constants, and
    the annotation line maps the pass keys on."""

    def __init__(self, mi):
        self.registries: dict[str, tuple] = {}
        self.int_consts: dict[str, int] = {}
        for node in mi.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name, val = node.targets[0].id, node.value
            if (isinstance(val, ast.Tuple) and val.elts
                    and all(isinstance(e, ast.Constant)
                            and isinstance(e.value, int)
                            for e in val.elts)):
                self.registries[name] = tuple(e.value for e in val.elts)
            elif (isinstance(val, ast.Constant)
                  and isinstance(val.value, int)
                  and not isinstance(val.value, bool)):
                self.int_consts[name] = val.value
        self.bucket_annos: dict[int, list] = {}
        self.phase_annos: dict[int, str] = {}
        self.zone: str | None = None
        for lineno, line in enumerate(mi.source.splitlines(), start=1):
            m = _BUCKET_FN_RE.search(line)
            if m:
                self.bucket_annos[lineno] = [
                    n.strip() for n in m.group(1).split(",") if n.strip()
                ]
            m = _PHASE_RE.search(line)
            if m:
                self.phase_annos[lineno] = m.group(1)
            m = _ZONE_RE.search(line)
            if m and self.zone is None:
                self.zone = m.group(1)


def _def_anno(node, annos: dict):
    """An annotation on the ``def`` line or the line directly above it
    (above any decorators, matching the bucket-fn grammar's examples)."""
    first = node.lineno
    if node.decorator_list:
        first = min(d.lineno for d in node.decorator_list)
    for ln in (node.lineno, first - 1, node.lineno - 1):
        if ln in annos:
            return annos[ln]
    return None


# -- the analysis ------------------------------------------------------------


class _Surface:
    def __init__(self, prog: Program):
        self.prog = prog
        self.idx = _Index(prog)
        self.idx.finalize()
        self.facts = {rel: _ModFacts(mi)
                      for rel, mi in prog.modules.items()}
        self.bucket_fns: dict[str, dict] = {}
        self.violations: list[SiteFinding] = []
        self.sites: list[dict] = []
        self.heads: list[dict] = []
        self.cone: dict[str, str] = {}
        self._collect_bucket_fns()
        self._collect_cone()
        self._collect_sites_and_heads()

    # -- registries / bucket functions ---------------------------------------

    def _registry(self, mi, name):
        """Resolve a registry NAME in module ``mi`` to its int tuple."""
        f = self.facts[mi.relpath]
        if name in f.registries:
            return f.registries[name]
        if name in mi.name_imports:
            modpath, orig = mi.name_imports[name]
            tgt = self.prog.modules.get(modpath)
            if tgt is not None:
                return self.facts[tgt.relpath].registries.get(orig)
        return None

    def _collect_bucket_fns(self):
        annotated = []
        for xf in self.idx.funcs.values():
            names = _def_anno(xf.node, self.facts[xf.relpath].bucket_annos)
            if names is None:
                continue
            mi = self.prog.modules[xf.relpath]
            domain: set = set()
            declared: dict[str, tuple] = {}
            for rname in names:
                reg = self._registry(mi, rname)
                if reg is None:
                    self.violations.append(SiteFinding(
                        xf.relpath, "GL15", xf.node.lineno,
                        xf.node.col_offset,
                        f"bucket-fn declares registry '{rname}' which is "
                        f"not a module-level int-tuple constant",
                        xf.qualname))
                    continue
                declared[rname] = reg
                domain.update(reg)
            self.bucket_fns[xf.fid] = {
                "declared": declared, "domain": domain, "kind": None,
            }
            annotated.append(xf)
        # pass 1: registry-valued fns (return a whole registry tuple)
        for xf in annotated:
            info = self.bucket_fns[xf.fid]
            rets = [n for n in _own_nodes(xf.node)
                    if isinstance(n, ast.Return) and n.value is not None]
            if rets and all(self._is_registry_expr(xf, r.value)
                            for r in rets):
                info["kind"] = "registry"
        # pass 2: verify element-valued returns stay inside the registry
        for xf in annotated:
            info = self.bucket_fns[xf.fid]
            if info["kind"] == "registry":
                continue
            info["kind"] = "element"
            loopvars = self._registry_loopvars(xf)
            for n in _own_nodes(xf.node):
                if not isinstance(n, ast.Return) or n.value is None:
                    continue
                bad = self._escaping_return(xf, n.value, loopvars,
                                            info["domain"])
                if bad:
                    self.violations.append(SiteFinding(
                        xf.relpath, "GL15", n.lineno, n.col_offset,
                        f"bucket-fn return escapes its declared "
                        f"registry: {bad}", xf.qualname))

    def _is_registry_expr(self, xf, expr) -> bool:
        """Is ``expr`` (a return value) a declared-registry tuple?"""
        if isinstance(expr, ast.IfExp):
            return (self._is_registry_expr(xf, expr.body)
                    and self._is_registry_expr(xf, expr.orelse))
        if isinstance(expr, ast.Name):
            info = self.bucket_fns.get(xf.fid, {})
            return expr.id in info.get("declared", {})
        return False

    def _registry_iter(self, xf, it) -> bool:
        """Is ``it`` (a for-loop iterable) registry-backed?"""
        info = self.bucket_fns.get(xf.fid, {})
        if isinstance(it, ast.Name) and it.id in info.get("declared", {}):
            return True
        if isinstance(it, ast.Call):
            mi = self.prog.modules[xf.relpath]
            for fid in self.idx._resolve_call(mi, xf, it):
                tgt = self.bucket_fns.get(fid)
                if tgt is not None and tgt["kind"] == "registry":
                    return True
        return False

    def _registry_loopvars(self, xf) -> set:
        out = set()
        for n in _own_nodes(xf.node):
            if (isinstance(n, ast.For)
                    and isinstance(n.target, ast.Name)
                    and self._registry_iter(xf, n.iter)):
                out.add(n.target.id)
        return out

    def _escaping_return(self, xf, expr, loopvars, domain) -> str | None:
        """None when the return provably stays inside the registry,
        else a short description of the escape."""
        if isinstance(expr, ast.IfExp):
            return (self._escaping_return(xf, expr.body, loopvars, domain)
                    or self._escaping_return(xf, expr.orelse, loopvars,
                                             domain))
        if isinstance(expr, ast.Name):
            if expr.id in loopvars:
                return None
            return f"name '{expr.id}' is not a registry loop variable"
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, int) and expr.value in domain:
                return None
            return f"constant {expr.value!r} outside the registry"
        if isinstance(expr, ast.Subscript):
            if self._registry_iter(xf, expr.value) or (
                    isinstance(expr.value, ast.Name)
                    and expr.value.id in self.bucket_fns.get(
                        xf.fid, {}).get("declared", {})):
                return None
            return "subscript of a non-registry value"
        if isinstance(expr, ast.Call):
            mi = self.prog.modules[xf.relpath]
            for fid in self.idx._resolve_call(mi, xf, expr):
                if fid in self.bucket_fns:
                    return None
            h = dotted_name(expr.func) or "<call>"
            return f"call to undeclared function {h}()"
        return ast.dump(expr)[:60]

    # -- serving cone --------------------------------------------------------

    def _collect_cone(self):
        roles_by_mod = {
            rel: _role_annotations(mi.source)
            for rel, mi in self.prog.modules.items()
        }
        roots = []
        for xf in self.idx.funcs.values():
            mi = self.prog.modules[xf.relpath]
            for spawn in xf.spawns:
                role = _spawn_role(spawn, roles_by_mod[xf.relpath])
                if role not in _SERVING_ROLES:
                    continue
                tkw = next((k.value for k in spawn.keywords
                            if k.arg == "target"), None)
                tgt = self.idx.resolve_target(mi, xf, tkw) \
                    if tkw is not None else None
                if tgt is not None:
                    roots.append((tgt, role))
        for tgt, role in roots:
            for fid, chain in self.idx.reach(tgt).items():
                label = f"{role}: {chain}" if chain else role
                self.cone.setdefault(fid, label)
        # close over nested defs: a reached dispatcher's closures run on
        # the same thread (the inverse of GL12's passed-not-called trick)
        frontier = list(self.cone)
        while frontier:
            fid = frontier.pop()
            xf = self.idx.funcs.get(fid)
            if xf is None:
                continue
            base = self.cone[fid]
            for nfid in xf.nested.values():
                if nfid in self.cone:
                    continue
                self.cone[nfid] = base
                frontier.append(nfid)
                for rfid, chain in self.idx.reach(nfid).items():
                    if rfid not in self.cone:
                        self.cone[rfid] = (
                            f"{base} -> {chain}" if chain else base)
                        frontier.append(rfid)

    def _in_cone(self, xf) -> str | None:
        p = xf
        while p is not None:
            if p.fid in self.cone:
                return self.cone[p.fid]
            p = p.parent
        return None

    # -- program sites + compile heads ---------------------------------------

    def _site_eligible(self, xf) -> bool:
        if not xf.relpath.startswith("harmony_tpu/"):
            return True  # fixtures / tools opt in by using the sinks
        return (_compile_sanctioned(xf.relpath)
                or self._in_cone(xf) is not None)

    def _collect_sites_and_heads(self):
        by_js: dict[int, dict] = {}  # id(JoinedStr) -> site
        for fid in sorted(self.idx.funcs):
            xf = self.idx.funcs[fid]
            mi = self.prog.modules[xf.relpath]
            self._scan_heads(xf, mi)
            if not self._site_eligible(xf):
                continue
            for node in _own_nodes(xf.node):
                if not isinstance(node, ast.Call):
                    continue
                if not self._is_sink(mi, node):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                trues = _guard_tests(xf, node)
                js_list = []
                if isinstance(arg, ast.JoinedStr):
                    js_list = [(arg, xf)]
                elif isinstance(arg, ast.Name):
                    js_list = self._name_joinedstrs(xf, arg.id)
                for js, owner in js_list:
                    site = by_js.get(id(js))
                    if site is None:
                        site = {
                            "js": js, "xf": owner,
                            "relpath": owner.relpath,
                            "line": js.lineno, "col": js.col_offset,
                            "trues": [],
                        }
                        by_js[id(js)] = site
                        self.sites.append(site)
                    site["trues"].append(trues)
        for site in self.sites:
            self._derive_site(site)
        self.sites.sort(key=lambda s: (s["relpath"], s["line"]))

    def _is_sink(self, mi, call: ast.Call) -> bool:
        head = dotted_name(call.func)
        if not head:
            return False
        parts = head.split(".")
        if parts[-1] == "_program_first_use":
            return True
        if parts[-1] in _AOT_SINK_ATTRS and len(parts) > 1:
            root = parts[0]
            if root == "aot":
                return True
            tgt = mi.mod_imports.get(root)
            return isinstance(tgt, str) and tgt.endswith("aot.py")
        return False

    def _name_joinedstrs(self, xf, name):
        """Every JoinedStr assigned to ``name`` in xf's lexical chain."""
        out = []
        p = xf
        while p is not None:
            for n in _own_nodes(p.node):
                if not isinstance(n, ast.Assign):
                    continue
                for tgt in n.targets:
                    if (isinstance(tgt, ast.Name) and tgt.id == name
                            and isinstance(n.value, ast.JoinedStr)):
                        out.append((n.value, p))
            if out:
                return out
            p = p.parent
        return out

    def _scan_heads(self, xf, mi):
        if not xf.relpath.startswith("harmony_tpu/"):
            return
        for dec in getattr(xf.node, "decorator_list", []):
            h = _head_of(dec)
            if h:
                self.heads.append({
                    "path": xf.relpath, "context": xf.qualname,
                    "kind": h, "line": dec.lineno,
                })
        for node in _own_nodes(xf.node):
            if isinstance(node, ast.Call):
                h = _head_of(node)
                if h:
                    self.heads.append({
                        "path": xf.relpath, "context": xf.qualname,
                        "kind": h, "line": node.lineno,
                    })

    # -- bucket-domain derivation --------------------------------------------

    def _derive_site(self, site):
        js, xf = site["js"], site["xf"]
        family_parts, fvs = [], []
        for v in js.values:
            if isinstance(v, ast.Constant):
                family_parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                family_parts.append("{}")
                fvs.append(v)
        site["family"] = "".join(family_parts)
        domains, reason = [], None
        for fv in fvs:
            dom: set = set()
            why = None
            for trues in site["trues"] or [set()]:
                d, w = self._domain(xf, fv.value, trues, 0)
                if d is None:
                    dom, why = None, w
                    break
                dom.update(d)
            if dom is None:
                reason = why
                site["bad_expr"] = fv
                break
            domains.append(dom)
        if reason is not None:
            site["names"], site["reason"] = None, reason
            return
        total = 1
        for d in domains:
            total *= max(len(d), 1)
        if total > _NAME_CAP:
            site["names"] = None
            site["reason"] = (f"bucket cross-product has {total} members "
                              f"(cap {_NAME_CAP}) — effectively unbounded")
            return
        names = set()
        for combo in itertools.product(
                *[sorted(d) for d in domains]) if domains else [()]:
            out, it = [], iter(combo)
            for part in family_parts:
                out.append(str(next(it)) if part == "{}" else part)
            names.add("".join(out))
        site["names"], site["reason"] = names, None
        site["domains"] = [sorted(d) for d in domains]

    def _domain(self, xf, expr, trues, depth):
        """(value set, None) when derivable, (None, reason) when not."""
        if depth > 8:
            return None, "derivation depth exceeded"
        mi = self.prog.modules[xf.relpath]
        f = self.facts[xf.relpath]
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, int) \
                    and not isinstance(expr.value, bool):
                return {expr.value}, None
            return None, f"non-int constant {expr.value!r}"
        if isinstance(expr, ast.IfExp):
            cond = _eval_test(expr.test, trues)
            if cond is True:
                return self._domain(xf, expr.body, trues, depth + 1)
            if cond is False:
                return self._domain(xf, expr.orelse, trues, depth + 1)
            a, wa = self._domain(xf, expr.body, trues, depth + 1)
            if a is None:
                return None, wa
            b, wb = self._domain(xf, expr.orelse, trues, depth + 1)
            if b is None:
                return None, wb
            return a | b, None
        if isinstance(expr, ast.Name):
            return self._name_domain(xf, expr.id, trues, depth)
        if isinstance(expr, ast.Call):
            head = dotted_name(expr.func) or "<call>"
            if head.split(".")[-1] == "len":
                return None, "len() of runtime data (unpinned width)"
            for fid in self.idx._resolve_call(mi, xf, expr):
                info = self.bucket_fns.get(fid)
                if info is not None:
                    return set(info["domain"]), None
            return None, f"call to {head}() which is not a declared " \
                         f"bucket-fn"
        if isinstance(expr, ast.Attribute):
            return self._attr_domain(xf, expr, trues, depth)
        if isinstance(expr, ast.BinOp):
            return None, "arithmetic on runtime values"
        return None, f"underivable expression ({type(expr).__name__})"

    def _name_domain(self, xf, name, trues, depth):
        f = self.facts[xf.relpath]
        mi = self.prog.modules[xf.relpath]
        assigns = []
        p = xf
        while p is not None:
            for n in _own_nodes(p.node):
                if isinstance(n, ast.Assign):
                    rhs = _unpack_assign(n, name)
                    if rhs is not None:
                        assigns.append((p, rhs))
                elif (isinstance(n, ast.AnnAssign) and n.value is not None
                      and isinstance(n.target, ast.Name)
                      and n.target.id == name):
                    assigns.append((p, n.value))
                elif (isinstance(n, ast.For)
                      and isinstance(n.target, ast.Name)
                      and n.target.id == name
                      and self._registry_iter(p, n.iter)):
                    dom = set()
                    info = self.bucket_fns.get(p.fid, {})
                    for reg in info.get("declared", {}).values():
                        dom.update(reg)
                    assigns.append((p, dom))
            if assigns:
                break
            p = p.parent
        if assigns:
            out: set = set()
            for owner, rhs in assigns:
                if isinstance(rhs, set):
                    out.update(rhs)
                    continue
                d, why = self._domain(owner, rhs, trues, depth + 1)
                if d is None:
                    return None, why
                out.update(d)
            return out, None
        if name in f.int_consts:
            return {f.int_consts[name]}, None
        if name in f.registries:
            return set(f.registries[name]), None
        if name in mi.name_imports:
            modpath, orig = mi.name_imports[name]
            tgt = self.prog.modules.get(modpath)
            if tgt is not None:
                tf = self.facts[tgt.relpath]
                if orig in tf.int_consts:
                    return {tf.int_consts[orig]}, None
                if orig in tf.registries:
                    return set(tf.registries[orig]), None
        if _is_param(xf, name):
            return None, f"function argument '{name}' with no bucket " \
                         f"derivation"
        return None, f"name '{name}' has no derivable binding"

    def _attr_domain(self, xf, expr, trues, depth):
        if not isinstance(expr.value, ast.Name):
            return None, "chained attribute access on runtime value"
        base, attr = expr.value.id, expr.attr
        mi = self.prog.modules[xf.relpath]
        # module constant through an import alias (DV._VERIFY_BUCKET)
        tgtmod = mi.mod_imports.get(base)
        if isinstance(tgtmod, str) and tgtmod in self.prog.modules:
            tf = self.facts[tgtmod]
            if attr in tf.int_consts:
                return {tf.int_consts[attr]}, None
            if attr in tf.registries:
                return set(tf.registries[attr]), None
        ann = _param_annotation(xf, base)
        if ann is None:
            return None, (f"attribute {base}.{attr} of a value with no "
                          f"class annotation")
        cls_mi, cls = self._resolve_class(mi, ann)
        if cls is None:
            return None, f"annotated class '{ann}' not found in program"
        out: set = set()
        found = False
        for fid in cls["methods"].values():
            mxf = self.idx.funcs.get(fid)
            if mxf is None:
                continue
            for n in _own_nodes(mxf.node):
                if not isinstance(n, ast.Assign):
                    continue
                for tgt in n.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr == attr):
                        found = True
                        d, why = self._domain(mxf, n.value, set(),
                                              depth + 1)
                        if d is None:
                            return None, (f"{ann}.{attr} assignment is "
                                          f"not bucket-derived: {why}")
                        out.update(d)
        if not found:
            return None, f"no 'self.{attr} =' assignment found in {ann}"
        return out, None

    def _resolve_class(self, mi, name):
        if name in mi.classes:
            return mi, mi.classes[name]
        if name in mi.name_imports:
            modpath, orig = mi.name_imports[name]
            tgt = self.prog.modules.get(modpath)
            if tgt is not None and orig in tgt.classes:
                return tgt, tgt.classes[orig]
        return None, None


def _unpack_assign(n: ast.Assign, name):
    """The RHS expr bound to ``name`` by this Assign (tuple-to-tuple
    unpacking resolved positionally), or None."""
    for tgt in n.targets:
        if isinstance(tgt, ast.Name) and tgt.id == name:
            return n.value
        if isinstance(tgt, ast.Tuple) and isinstance(n.value, ast.Tuple) \
                and len(tgt.elts) == len(n.value.elts):
            for t, v in zip(tgt.elts, n.value.elts):
                if isinstance(t, ast.Name) and t.id == name:
                    return v
    return None


def _is_param(xf, name) -> bool:
    p = xf
    while p is not None:
        a = p.node.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            if arg.arg == name:
                return True
        p = p.parent
    return False


def _param_annotation(xf, name) -> str | None:
    p = xf
    while p is not None:
        a = p.node.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            if arg.arg == name and arg.annotation is not None:
                return dotted_name(arg.annotation)
        p = p.parent
    return None


def _add_test(trues: set, test) -> None:
    """A dominating ``A and B`` guard means both conjuncts hold, so a
    placeholder tested on the bare conjunct (``x if fused else ...``
    under ``if fused and not twin():``) still refines."""
    trues.add(ast.dump(test))
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            _add_test(trues, v)


def _guard_tests(xf, target) -> set:
    """ast.dump of every test that dominates ``target`` (IfExp body /
    If body containment within xf's own nodes)."""
    trues = set()
    for n in _own_nodes(xf.node):
        if isinstance(n, ast.IfExp) and _contains(n.body, target):
            _add_test(trues, n.test)
        elif isinstance(n, ast.If) \
                and any(_contains(s, target) for s in n.body):
            _add_test(trues, n.test)
    return trues


def _contains(root, target) -> bool:
    return any(n is target for n in ast.walk(root))


def _eval_test(test, trues):
    """Three-valued static evaluation of a guard under the sink's
    dominating tests.  kernel_twin_active() is statically False: twin
    mode keeps jax unloaded by contract, so twin-only branches are not
    XLA programs (aot.warmup accounts for them separately)."""
    if ast.dump(test) in trues:
        return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = _eval_test(test.operand, trues)
        return None if inner is None else not inner
    if isinstance(test, ast.Call):
        h = dotted_name(test.func)
        if h and h.split(".")[-1] == "kernel_twin_active":
            return False
        return None
    if isinstance(test, ast.BoolOp):
        vals = [_eval_test(v, trues) for v in test.values]
        if isinstance(test.op, ast.And):
            if any(v is False for v in vals):
                return False
            if all(v is True for v in vals):
                return True
            return None
        if any(v is True for v in vals):
            return True
        if all(v is False for v in vals):
            return False
    return None


# -- manifest ----------------------------------------------------------------


def load_manifest(path: Path | None = None) -> dict | None:
    path = MANIFEST_PATH if path is None else Path(path)
    try:
        return json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None


def manifest_names(manifest: dict | None) -> set:
    if not manifest:
        return set()
    out = set()
    for entry in manifest.get("programs", []):
        out.update(entry.get("names", []))
    return out


def emit_manifest(prog: Program) -> dict:
    """The canonical warmup manifest for ``prog`` — deterministic JSON
    (sorted, no line numbers: it drifts only when the compile surface
    actually changes).  CI diffs this against the committed copy."""
    surf = _Surface(prog)
    fams: dict[str, dict] = {}
    for site in surf.sites:
        if site.get("names") is None:
            continue
        if not site["relpath"].startswith("harmony_tpu/"):
            continue
        fam = fams.setdefault(site["family"], {
            "family": site["family"], "sources": set(), "names": set(),
        })
        fam["sources"].add(f"{site['relpath']}::{site['xf'].qualname}")
        fam["names"].update(site["names"])
    heads = sorted(
        {(h["path"], h["context"], h["kind"]) for h in surf.heads})
    return {
        "version": 1,
        "generated_by":
            "python -m tools.graftlint --emit-compile-manifest",
        "note": ("every XLA program a serving path can request, derived "
                 "statically (GL15/GL16); aot.warmup precompiles this "
                 "set before the node serves"),
        "dtype": "int32",
        "device_counts": [1],
        "heads": [
            {"path": p, "context": c, "kind": k} for p, c, k in heads
        ],
        "programs": [
            {
                "family": fam["family"],
                "sources": sorted(fam["sources"]),
                "names": sorted(fam["names"]),
            }
            for fam in sorted(fams.values(),
                              key=lambda f: f["family"])
        ],
    }


# -- findings ----------------------------------------------------------------


def _phase(xf, facts) -> str | None:
    p = xf
    while p is not None:
        got = _def_anno(p.node, facts[p.relpath].phase_annos)
        if got:
            return got
        p = p.parent
    return None


def _gl17(surf: _Surface) -> list[SiteFinding]:
    out = []
    for fid in sorted(surf.idx.funcs):
        xf = surf.idx.funcs[fid]
        if _compile_sanctioned(xf.relpath):
            continue
        if _phase(xf, surf.facts) is not None:
            continue
        in_zone = (xf.relpath.startswith("harmony_tpu/")
                   or surf.facts[xf.relpath].zone is not None)
        jit_names, lowered_names = set(), set()
        for n in _own_nodes(xf.node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                v = n.value
                if _head_of(v):
                    jit_names.add(n.targets[0].id)
                elif (isinstance(v, ast.Call)
                      and isinstance(v.func, ast.Attribute)
                      and v.func.attr == "lower"
                      and (v.args or v.keywords)):
                    lowered_names.add(n.targets[0].id)

        def flag(node, msg):
            out.append(SiteFinding(
                xf.relpath, "GL17", node.lineno, node.col_offset,
                msg, xf.qualname,
                surf._in_cone(xf) or ""))

        if in_zone:
            for dec in getattr(xf.node, "decorator_list", []):
                h = _head_of(dec)
                if h:
                    flag(dec, f"compile head {h} outside the "
                              f"sanctioned device layer")
        for n in _own_nodes(xf.node):
            if not isinstance(n, ast.Call):
                continue
            fn = n.func
            if isinstance(fn, ast.Attribute):
                if fn.attr == "lower" and (n.args or n.keywords):
                    flag(n, "explicit .lower(...) outside the device "
                            "layer / warmup phase")
                    continue
                if fn.attr == "compile" and not n.args:
                    recv = fn.value
                    if (isinstance(recv, ast.Call)
                            and isinstance(recv.func, ast.Attribute)
                            and recv.func.attr == "lower"
                            and not (recv.args or recv.keywords)):
                        flag(n, ".lower().compile() chain outside the "
                                "device layer / warmup phase")
                        continue
                    if isinstance(recv, ast.Name) \
                            and recv.id in lowered_names:
                        flag(n, ".compile() of a lowered program "
                                "outside the device layer / warmup "
                                "phase")
                        continue
            if not in_zone:
                continue
            h = _head_of(n)
            if h:
                flag(n, f"compile head {h} outside the sanctioned "
                        f"device layer")
                continue
            if isinstance(fn, ast.Call) and _head_of(fn):
                flag(n, "immediate first-trace of a fresh compile "
                        "head (jit(f)(args))")
                continue
            if isinstance(fn, ast.Name) and fn.id in jit_names:
                flag(n, f"first-trace of jit-bound callable "
                        f"'{fn.id}' outside the device layer")
    return out


def compilesurface_findings(prog: Program) -> list[SiteFinding]:
    surf = _Surface(prog)
    out = list(surf.violations)
    manifest = load_manifest()
    covered = manifest_names(manifest)
    derived_repo: set = set()
    for site in surf.sites:
        xf = site["xf"]
        witness = surf._in_cone(xf) or ""
        if site.get("names") is None:
            bad = site.get("bad_expr")
            out.append(SiteFinding(
                site["relpath"], "GL15",
                bad.lineno if bad is not None else site["line"],
                bad.col_offset if bad is not None else site["col"],
                f"compile program '{site['family']}' has an "
                f"underivable bucket: {site['reason']}",
                site["family"], witness))
            continue
        if site["relpath"].startswith("harmony_tpu/"):
            derived_repo.update(site["names"])
        missing = sorted(site["names"] - covered)
        if missing:
            ex = ", ".join(missing[:3])
            out.append(SiteFinding(
                site["relpath"], "GL16", site["line"], site["col"],
                f"warmup manifest does not cover {len(missing)} "
                f"derived program(s) ({ex}{', ...' if len(missing) > 3 else ''}) — regenerate with "
                f"--emit-compile-manifest",
                site["family"], witness))
    if "harmony_tpu/device.py" in prog.modules and manifest is not None:
        stale = sorted(covered - derived_repo)
        if stale:
            ex = ", ".join(stale[:4])
            out.append(SiteFinding(
                "harmony_tpu/device.py", "GL16", 1, 0,
                f"{len(stale)} committed manifest name(s) no longer "
                f"derivable from any compile site ({ex}"
                f"{', ...' if len(stale) > 4 else ''}) — regenerate "
                f"with --emit-compile-manifest",
                "compile-manifest"))
    out.extend(_gl17(surf))
    return out
