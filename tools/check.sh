#!/usr/bin/env bash
# Pre-commit gate for harmony-tpu.
#
# Three stages, fail-fast:
#   1. graftlint — whole-program static analysis (GL01-GL17: the
#      classic families, the kernelcheck pass — GL09 limb
#      value-range abstract interpretation, GL10 Montgomery-domain
#      typestate, GL11 twin/padding discipline — the thread-role
#      & trust-boundary pass — GL12 dispatch discipline over the
#      role-annotated call graph, GL13 wire-taint budgets on every
#      trust-boundary decoder, GL14 watchdog heartbeat coverage for
#      spawned long-lived loops — and the compile-surface pass —
#      GL15 bucket derivability for every serving-path XLA program,
#      GL16 warmup-manifest coverage, GL17 compile locality) against
#      the committed baseline, gated at 0 new findings.  The stage
#      then re-derives the compile manifest and diffs it against the
#      committed tools/artifacts/aot/compile_manifest.json — drift
#      fails LOUDLY: a changed compile surface must ship its manifest.
#      Exit-code contract (stable for hooks): 0 clean,
#      1 new violations, 2 internal linter error — any non-zero stops
#      this script with the same code.  This stage warms the
#      content-hash result cache (.graftlint_cache.json), so the
#      tier-1 test_graftlint repo gate in stage 2 re-answers from it
#      instead of re-analyzing an unchanged tree.
#   2. tier-1 smoke subset — the fast, pure-CPU slices that catch the
#      classes of regression this repo's PRs most often introduce
#      (linter self-tests, device-path wiring, AOT executable cache,
#      thread-safety, codecs) — then tools/compile_surface_smoke.py,
#      the load-bearing end of the GL16 contract: warm every manifest
#      program, drive a localnet-shaped node across a committee-width
#      change (5 -> 12 keys, bucket 8 -> 16), and assert ZERO
#      post-warmup compiles (device JIT miss counter frozen).
#   3. chaos smoke — the fault-injection tier (resilience primitives +
#      flapping-backend/black-holed-peer scenarios).  Deterministic by
#      construction: faults are counted, jitter is hashed, breaker
#      clocks are injected — no RNG seed to pin.
#   4. observability smoke — one localnet round under the forced
#      device path (twin kernels + sidecar-verified seals), then the
#      tracer tier tests and tools/obs_smoke.py, which scrapes
#      /metrics + /debug/trace over HTTP and validates the Prometheus
#      exposition grammar and the Chrome trace-event JSON schema
#      (names/ts/dur/pid/tid, spans properly parented).
#   5. scheduler smoke — the continuous-batching verification
#      scheduler tier (tests/test_sched.py), then tools/sched_smoke.py:
#      a localnet where FBFT rounds, sync replay and an ingress flood
#      run CONCURRENTLY through the one shared device queue; the
#      /metrics exposition must show harmony_sched_batch_fill_ratio
#      above its floor and ZERO consensus-lane sheds.
#   6. perf observability — the kernel-stage profiler + ledger tiers
#      (tests/test_prof.py, tests/test_bench_ledger.py), then
#      tools/loadgen.py --check (sustained-rate floor, tracer-derived
#      p50<=p99 latency grammar, all three lanes active, zero
#      consensus sheds) and tools/bench_ledger.py --check over the
#      committed BENCH_r*.json rounds (machine-readable regression
#      flags; measurement redefinitions are exempt).
#   7. chaos sweep — the composed adversarial tier: the chaostest
#      framework unit tests, then tools/chaos_sweep.py --quick
#      --check runs the five composed scenarios (leader black-holed
#      under flood, epoch-boundary election under saturated lanes,
#      cross-shard traffic under partition, validator churn at the
#      quorum edge, sidecar flapping during quorum assembly) and
#      asserts the liveness + zero-consensus-shed + round-p99 +
#      no-divergent-heads invariants; the sweep's FRESH metrics are
#      written as an ephemeral BENCH round and bench_ledger --check
#      gates them against the committed history (wide 80% threshold:
#      composed-scenario latencies jitter more than kernel benches
#      on this box).
#   8. crash consistency — the durability tier (ISSUE 12): the KV
#      corruption/batch-replay suite (FileKV × NativeKV parity) and
#      the chain-level recovery tests, then tools/crash_sweep.py
#      --check (kill a block commit at EVERY enumerated kv.commit
#      crash point + byte-truncation offset; reopen must recover a
#      consistent head with zero manual repair), then the two
#      restart scenarios (leader hard-killed mid-commit + rolling
#      restarts of all validators) via chaos_sweep on durable
#      topologies; crash_* and restart_recovery_seconds_p99 land as
#      an ephemeral BENCH round gated by bench_ledger --check.
#   9. byzantine sweep — the ACTIVE-adversary tier (ISSUE 13): the
#      slashing-pipeline / wire-fuzz / byzantine-behavior unit
#      tiers, then the three byz_* scenarios (equivocating leader at
#      the quorum edge, commit-phase double voter slashed end to
#      end, invalid-proposal + malformed-wire sprayer throttled and
#      muted) via chaos_sweep --quick --check; byz_* metrics land as
#      an ephemeral BENCH round gated by bench_ledger --check.
#  10. overload survival — the robustness-past-rated-capacity tier
#      (ISSUE 14): the health-watchdog / resource-governor /
#      rate-limiter unit tiers, then tools/soak.py --quick --check
#      (resource-STATIONARITY regression slopes on RSS / fds /
#      threads / queue depth under sustained mixed load), then the
#      overload_storm (10x rated ingress against a governed
#      localnet: tiers engage, work is rejected-not-crashed,
#      consensus never sheds, resources bounded) and
#      wedged_thread_recovery (flush thread killed + sidecar reader
#      stalled mid-round; watchdog detects, dumps, restarts,
#      recovers) scenarios via chaos_sweep; soak_* + overload
#      metrics land as an ephemeral BENCH round gated by
#      bench_ledger --check.
#  11. WAN netem — the gray-failure tier (ISSUE 15): the netem /
#      roster unit tiers (link-spec grammar, seed-deterministic
#      delivery schedules, both transport integrations, sync EWMA
#      peer ordering, the 200-slot roster election), then the four
#      netem scenarios via chaos_sweep --quick --check: gray_leader
#      (leader degraded to 300 ms + jitter + 5 % loss — commit or
#      view-change, never wedge), asymmetric_partition (half-duplex
#      leader: sends, cannot receive; NEWVIEW without it),
#      minority_partition_heal (validator fully isolated >= 8 blocks
#      then healed; measured heal_catchup_seconds), wan_committee
#      (64-slot committee under a 50-150 ms RTT / 0.5 % loss WAN
#      matrix; round p99 in the ledger); chaos_*/netem_* metrics
#      land as an ephemeral BENCH round gated by bench_ledger
#      --check.
#  12. mainnet rehearsal — the composed dress rehearsal (ISSUE 18):
#      the snapshot / large-genesis unit tiers (export -> serve ->
#      import roundtrip at 10^4 accounts with a dev_genesis build-time
#      regression bound, the snapshot-import kv.commit crash matrix,
#      snapshot-codec wire-fuzz + inflation fast-fail), then
#      mainnet_rehearsal via chaos_sweep --quick --check — EVERY
#      hardening axis in one run (whole-window WAN matrix + staked
#      Byzantine double-voter + 10x overload flood + mid-commit
#      kill/restart-from-disk + epoch elections + a late-joining node
#      bootstrapping from a peer-served snapshot) judged by the
#      composed invariant set; rehearsal metrics
#      (snapshot_bootstrap_seconds, join_catchup_seconds, ...) land
#      as an ephemeral BENCH round gated by bench_ledger --check.
#  13. round forensics (ISSUE 19) — the obs unit tier (RoundTimeline
#      phase attribution >= 95% on a pump-driven round, span-sink
#      rotation/heartbeat/reader budgets, clock-skew alignment,
#      histogram exemplars), then tools/round_forensics.py --check
#      over a fresh in-process wan_committee --quick run: >= 95% of
#      committed-round wall time must attribute to named phases and
#      the report must name the dominating phase; bench_ledger
#      --check @ 0.8 covers the committed BENCH_r12.json
#      (round_phase_* / replay_stage_* as source: measured).
#
# Usage: tools/check.sh            (from anywhere; cd's to the repo)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint: whole-program gate vs committed baseline (GL01-GL17) =="
python -m tools.graftlint

echo "== compile manifest: committed copy vs derived surface =="
MANIFEST_TMP="$(mktemp)"
python -m tools.graftlint --emit-compile-manifest > "$MANIFEST_TMP"
if ! diff -u tools/artifacts/aot/compile_manifest.json "$MANIFEST_TMP"; then
  rm -f "$MANIFEST_TMP"
  echo "STALE COMPILE MANIFEST: the serving-path compile surface changed" >&2
  echo "but tools/artifacts/aot/compile_manifest.json was not regenerated." >&2
  echo "Run: python -m tools.graftlint --emit-compile-manifest \\" >&2
  echo "       > tools/artifacts/aot/compile_manifest.json  and commit it." >&2
  exit 1
fi
rm -f "$MANIFEST_TMP"

echo "== tier-1 smoke subset =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_graftlint.py \
  tests/test_device_path.py \
  tests/test_aot_cache.py \
  tests/test_concurrency.py \
  tests/test_rlp_trie.py \
  tests/test_config.py

echo "== compile surface smoke: zero post-warmup compiles across a width change =="
JAX_PLATFORMS=cpu python tools/compile_surface_smoke.py

echo "== chaos smoke: fault-injection tier =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_resilience.py \
  tests/test_chaos.py

echo "== observability smoke: tracer tier + /metrics + /debug/trace =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_trace.py
JAX_PLATFORMS=cpu python tools/obs_smoke.py

echo "== scheduler smoke: continuous-batching tier + mixed-lane localnet =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_sched.py
JAX_PLATFORMS=cpu python tools/sched_smoke.py

echo "== perf observability: profiler tier + loadgen floors + bench ledger =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_prof.py \
  tests/test_bench_ledger.py
JAX_PLATFORMS=cpu python tools/loadgen.py --duration 5 --check
python tools/bench_ledger.py --check > /dev/null

echo "== chaos sweep: composed adversarial scenarios =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_chaostest.py
CHAOS_ROUND="$(mktemp)"
CRASH_ROUND="$(mktemp)"
BYZ_ROUND="$(mktemp)"
SOAK_ROUND="$(mktemp)"
NETEM_ROUND="$(mktemp)"
REHEARSAL_ROUND="$(mktemp)"
AGG_ROUND="$(mktemp)"
trap 'rm -f "$CHAOS_ROUND" "$CRASH_ROUND" "$BYZ_ROUND" "$SOAK_ROUND" "$NETEM_ROUND" "$REHEARSAL_ROUND" "$AGG_ROUND"' EXIT
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario view_change_storm --scenario epoch_election_rotation \
  --scenario cross_shard_partition --scenario validator_churn \
  --scenario sidecar_flap \
  --bench-out "$CHAOS_ROUND" --bench-round 999 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$CHAOS_ROUND" > /dev/null

echo "== crash consistency: kv replay parity + crash-point sweep + restart scenarios =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_kv_corruption.py \
  tests/test_crash_recovery.py
JAX_PLATFORMS=cpu python tools/crash_sweep.py --check \
  --bench-out "$CRASH_ROUND" --bench-round 998 > /dev/null
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario leader_kill_restart --scenario rolling_restart \
  --bench-base "$CRASH_ROUND" --bench-out "$CRASH_ROUND" \
  --bench-round 998 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$CRASH_ROUND" > /dev/null

echo "== byzantine sweep: active adversaries + slashing pipeline =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_slash_pipeline.py \
  tests/test_wire_fuzz.py \
  tests/test_byzantine.py
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario byz_equivocating_leader \
  --scenario byz_double_voter_slashed \
  --scenario byz_invalid_proposal_flood \
  --bench-out "$BYZ_ROUND" --bench-round 997 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$BYZ_ROUND" > /dev/null

echo "== overload survival: watchdog/governor tiers + soak + overload scenarios =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_health.py \
  tests/test_governor.py \
  tests/test_ratelimit.py
JAX_PLATFORMS=cpu python tools/soak.py --quick --check \
  --bench-out "$SOAK_ROUND" --bench-round 996 > /dev/null
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario overload_storm --scenario wedged_thread_recovery \
  --bench-base "$SOAK_ROUND" --bench-out "$SOAK_ROUND" \
  --bench-round 996 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$SOAK_ROUND" > /dev/null

echo "== WAN netem: gray-failure tier + mainnet-shape committee =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_netem.py \
  tests/test_staking_shard.py
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario gray_leader --scenario asymmetric_partition \
  --scenario minority_partition_heal --scenario wan_committee \
  --bench-out "$NETEM_ROUND" --bench-round 995 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$NETEM_ROUND" > /dev/null

echo "== mainnet rehearsal: snapshot tiers + every axis composed =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_snapshot.py \
  tests/test_crash_recovery.py
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --only mainnet_rehearsal \
  --bench-out "$REHEARSAL_ROUND" --bench-round 994 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$REHEARSAL_ROUND" > /dev/null

echo "== round forensics: phase attribution + replay burn-down =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_obs.py
JAX_PLATFORMS=cpu python tools/round_forensics.py \
  --scenario wan_committee --quick --check > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json > /dev/null

echo "== vote aggregation: overlay unit tier + 200-slot WAN committee =="
JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
  -p no:cacheprovider \
  tests/test_aggregation.py
JAX_PLATFORMS=cpu python tools/chaos_sweep.py --quick --check \
  --scenario wan_committee_200 --scenario gray_aggregator \
  --bench-out "$AGG_ROUND" --bench-round 993 > /dev/null
python tools/bench_ledger.py --check --threshold 0.8 \
  BENCH_r*.json "$AGG_ROUND" > /dev/null

echo "check.sh: OK"
