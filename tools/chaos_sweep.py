"""Adversarial scenario sweep: run the named chaos scenarios and
gate on their liveness invariants.

Each scenario (harmony_tpu/chaostest/scenarios.py) composes a
topology, a traffic profile and a seed-deterministic fault script,
then asserts machine-checked invariants: liveness (the chain advances
>= N blocks inside the window), ZERO consensus-lane sheds, a round-p99
bound, no divergent heads, plus scenario-specific checks (committee
rotated, cross-shard value arrived).  Any violation produces exactly
one correlated flight-recorder dump (trace.anomaly's (kind, trace_id)
dedup) and fails ``--check``.

Every reported number is ledger-tagged ``source: measured`` and named
``chaos_<scenario>_<metric>`` so ``tools/bench_ledger.py --check``
gates them across BENCH rounds.

Usage:
    python tools/chaos_sweep.py                       # full durations
    python tools/chaos_sweep.py --quick --check       # check.sh stage 7
    python tools/chaos_sweep.py --scenario view_change_storm --quick
    python tools/chaos_sweep.py --quick --bench-out BENCH_r06.json \
        --bench-round 6 [--bench-base bench_line.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("HARMONY_KERNEL_TWIN", "1")  # twin kernels: the
# real device-path layers (tables, bitmaps, scheduler) without XLA
# pairing compiles — HARMONY_CHAOS_REAL_KERNELS=1 opts out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scenario", action="append", default=None,
                    help="run only this scenario (repeatable); default "
                         "all five")
    ap.add_argument("--only", default=None, metavar="SUBSTR",
                    help="filter the scenario list (exact name or "
                         "case-insensitive substring) — composes with "
                         "--scenario")
    ap.add_argument("--seed", type=int, default=None,
                    help="override every selected scenario's baked-in "
                         "seed (keys, fixtures, netem draws and garble "
                         "bytes all re-derive from it)")
    ap.add_argument("--quick", action="store_true",
                    help="reduced durations/targets (the CI stage "
                         "budget); same topology, faults, invariants")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any scenario violates an invariant")
    ap.add_argument("--bench-out", default=None,
                    help="write a BENCH round file carrying the "
                         "scenario metrics (ledger schema)")
    ap.add_argument("--bench-round", type=int, default=6,
                    help="round number stamped into --bench-out")
    ap.add_argument("--bench-base", default=None,
                    help="existing bench JSON (bench.py line or BENCH "
                         "round file) whose metrics ride alongside in "
                         "--bench-out")
    args = ap.parse_args(argv)

    from harmony_tpu.chaostest import SCENARIOS, run

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"chaos_sweep: unknown scenario(s) {unknown}; "
              f"known: {sorted(SCENARIOS)}", file=sys.stderr)
        return 2
    if args.only is not None:
        needle = args.only.lower()
        names = [
            n for n in names
            if n == args.only or needle in n.lower()
        ]
        if not names:
            print(f"chaos_sweep: --only {args.only!r} matches no "
                  f"scenario; known: {sorted(SCENARIOS)}",
                  file=sys.stderr)
            return 2

    results = []
    for name in names:
        scenario = SCENARIOS[name](quick=args.quick)
        if args.seed is not None:
            import dataclasses

            scenario = dataclasses.replace(scenario, seed=args.seed)
        print(f"chaos_sweep: running {name} "
              f"(seed={scenario.seed}, window={scenario.window_s:g}s, "
              f"{len(scenario.phases)} fault phase(s))...",
              file=sys.stderr, flush=True)
        try:
            r = run(scenario)
        except Exception as e:  # noqa: BLE001 — one scenario crashing
            # (build failure on a loaded box) must surface as ITS
            # violation, not kill the rest of the sweep
            from harmony_tpu.chaostest import ScenarioResult

            r = ScenarioResult(
                name=name, passed=False,
                violations=[{"invariant": "run_crashed",
                             "detail": repr(e)}],
                metrics={}, violation_dumps=[], all_dumps=[], heads={},
            )
        results.append(r)
        status = "OK" if r.passed else "VIOLATED"
        print(f"chaos_sweep: {name}: {status} heads={r.heads} "
              + " ".join(
                  f"{k}={v['value']}" for k, v in r.metrics.items()
              ), file=sys.stderr, flush=True)
        for v in r.violations:
            print(f"chaos_sweep:   {name}.{v['invariant']}: "
                  f"{v['detail']} (dump: {v.get('dump')})",
                  file=sys.stderr, flush=True)

    extra = {}
    for r in results:
        for metric, entry in r.metrics.items():
            if entry.get("value") is None:
                continue
            e = dict(entry)
            e["scenario"] = r.name
            e["quick"] = args.quick
            extra[f"chaos_{r.name}_{metric}"] = e
    passed = sum(1 for r in results if r.passed)
    extra["chaos_scenarios_passed"] = {
        "value": passed, "unit": "scenarios", "source": "measured",
        "total": len(results), "quick": args.quick,
    }
    doc = {
        "metric": "chaos_scenarios_passed",
        "value": passed,
        "unit": "scenarios",
        "source": "measured",
        "extra": extra,
        "meta": {
            "quick": args.quick,
            "scenarios": [r.name for r in results],
            "violations": [
                {"scenario": r.name, **v}
                for r in results for v in r.violations
            ],
            "violation_dumps": [
                p for r in results for p in r.violation_dumps
            ],
        },
    }
    print(json.dumps(doc), flush=True)

    if args.bench_out:
        parsed = doc
        if args.bench_base:
            with open(args.bench_base) as f:
                base = json.load(f)
            base_parsed = base.get("parsed", base)
            merged = dict(base_parsed)
            merged.setdefault("extra", {})
            merged["extra"] = dict(merged["extra"])
            merged["extra"].update(extra)
            parsed = merged
        with open(args.bench_out, "w") as f:
            json.dump({
                "n": args.bench_round,
                "cmd": "python tools/chaos_sweep.py"
                       + (" --quick" if args.quick else ""),
                "parsed": parsed,
            }, f, indent=2)
            f.write("\n")
        print(f"chaos_sweep: wrote {args.bench_out} "
              f"(round {args.bench_round})", file=sys.stderr)

    if args.check and passed != len(results):
        return 1
    return 0


if __name__ == "__main__":
    rc = main()
    # hard exit: the scenarios leave daemon pump/scheduler threads and
    # native-library state behind, and CPython teardown racing them
    # can abort (SIGABRT) AFTER the verdict is decided — the CI gate's
    # exit code must be the sweep's verdict, not the interpreter's
    # shutdown luck
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
