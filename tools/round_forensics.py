"""Round forensics: per-phase attribution report for committed rounds.

Stitches ``consensus.round`` traces (the live in-process store after a
scenario run, or JSONL span-sink files exported by real nodes) into
per-round ``RoundTimeline``s — announce_wire, verify_sched_wait,
verify_dispatch, vote_return, quorum_assembly, commit_insert — and
reports where the round time goes, naming the dominating phase.  This
is the attribution instrument the speed arc gates on: a kernel or
aggregation PR must move a *named phase*, not just the p99.

Usage:
    # analyze exported span sinks (merged across nodes; clock-skew
    # aligned per node)
    python tools/round_forensics.py /var/trace/spans_*.jsonl

    # self-driving: run a chaos scenario in-process, analyze its spans
    python tools/round_forensics.py --scenario wan_committee --quick

    # CI gate: >= min-fraction of committed-round wall time must be
    # attributed, and the report must name a dominating phase
    python tools/round_forensics.py --scenario wan_committee --quick \
        --check

Exit codes: 0 OK; 1 --check violated; 2 usage/no input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("HARMONY_KERNEL_TWIN", "1")


def _collect_paths(args_paths) -> list:
    out = []
    for p in args_paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "spans_*.jsonl*"))))
        else:
            out.append(p)
    return out


def _aggregate(timelines) -> dict:
    from harmony_tpu.obs import PHASES

    total_wall = sum(t.wall_s for t in timelines)
    phase_s = {p: 0.0 for p in PHASES}
    per_phase: dict = {p: [] for p in PHASES}
    for t in timelines:
        for p, s in t.phases.items():
            phase_s[p] += s
            per_phase[p].append(s)
    level_s: dict = {}
    for t in timelines:
        for lv, s in getattr(t, "levels", {}).items():
            level_s[lv] = level_s.get(lv, 0.0) + s
    attributed = sum(phase_s.values())
    frac = (attributed / total_wall) if total_wall > 0 else 0.0
    dominant = max(phase_s.items(), key=lambda kv: kv[1])[0] \
        if attributed > 0 else None
    quant = {}
    for p, vals in per_phase.items():
        if not vals:
            continue
        vals.sort()
        quant[p] = {
            "p50_s": round(vals[len(vals) // 2], 6),
            "p99_s": round(vals[min(len(vals) - 1,
                                    int(len(vals) * 0.99))], 6),
            "share": round(phase_s[p] / attributed, 4)
            if attributed > 0 else 0.0,
        }
    return {
        "rounds": len(timelines),
        "total_wall_s": round(total_wall, 6),
        "attributed_fraction": round(frac, 4),
        "dominant_phase": dominant,
        "phase_seconds": {p: round(s, 6) for p, s in phase_s.items()
                          if s > 0},
        "phases": quant,
        # aggregation-overlay attribution INSIDE quorum_assembly: time
        # spent merging/verifying contributions, keyed by Handel level
        # ("L1", "L2", ...) — nonempty only when the overlay ran
        "aggregation_levels": {lv: round(s, 6)
                               for lv, s in sorted(level_s.items())},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="span-sink JSONL files (or directories of "
                         "spans_*.jsonl) exported by --span-sink-dir "
                         "nodes")
    ap.add_argument("--scenario", default=None,
                    help="run this chaos scenario in-process and "
                         "analyze its live span store")
    ap.add_argument("--quick", action="store_true",
                    help="reduced scenario durations (with --scenario)")
    ap.add_argument("--check", action="store_true",
                    help="gate: fail unless committed rounds exist, "
                         ">= --min-fraction of their wall time is "
                         "attributed, and a dominating phase is named")
    ap.add_argument("--min-fraction", type=float, default=0.95,
                    help="attribution floor for --check (default 0.95)")
    ap.add_argument("--include-abandoned", action="store_true",
                    help="report abandoned rounds too (partial "
                         "timelines; never gated)")
    ap.add_argument("--out", default=None,
                    help="write the JSON report here (default stdout)")
    args = ap.parse_args(argv)

    from harmony_tpu import trace
    from harmony_tpu.obs import (build_timelines, observe_timelines,
                                 read_spans)

    if args.scenario:
        from harmony_tpu.chaostest import SCENARIOS, run

        if args.scenario not in SCENARIOS:
            print(f"round_forensics: unknown scenario {args.scenario}; "
                  f"known: {sorted(SCENARIOS)}", file=sys.stderr)
            return 2
        scenario = SCENARIOS[args.scenario](quick=args.quick)
        print(f"round_forensics: running {args.scenario} "
              f"(window={scenario.window_s:g}s)...",
              file=sys.stderr, flush=True)
        result = run(scenario)
        print(f"round_forensics: scenario "
              f"{'OK' if result.passed else 'VIOLATED'} "
              f"heads={result.heads}", file=sys.stderr, flush=True)
        # run() resets the store at START only: the spans are still live
        spans = trace.spans()
    elif args.paths:
        paths = _collect_paths(args.paths)
        spans = read_spans(paths)
        print(f"round_forensics: {len(spans)} spans from "
              f"{len(paths)} file(s)", file=sys.stderr)
    else:
        ap.print_usage(file=sys.stderr)
        print("round_forensics: need span-sink paths or --scenario",
              file=sys.stderr)
        return 2

    timelines = build_timelines(
        spans, committed_only=not args.include_abandoned
    )
    committed = [t for t in timelines if t.committed]
    observe_timelines(committed)  # populate harmony_round_phase_seconds

    agg = _aggregate(committed)
    report = {
        "aggregate": agg,
        "rounds": [t.to_dict() for t in timelines],
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"round_forensics: wrote {args.out}", file=sys.stderr)
    else:
        print(text)

    if agg["rounds"]:
        print(f"round_forensics: {agg['rounds']} committed round(s), "
              f"{agg['attributed_fraction'] * 100:.1f}% attributed, "
              f"dominant phase: {agg['dominant_phase']}",
              file=sys.stderr)
        if agg["aggregation_levels"]:
            lv = ", ".join(f"{k}={s:.3f}s"
                           for k, s in agg["aggregation_levels"].items())
            print(f"round_forensics: quorum_assembly overlay levels: {lv}",
                  file=sys.stderr)

    if args.check:
        if not committed:
            print("round_forensics: CHECK FAILED — no committed rounds",
                  file=sys.stderr)
            return 1
        if agg["attributed_fraction"] < args.min_fraction:
            print(f"round_forensics: CHECK FAILED — attributed "
                  f"{agg['attributed_fraction']:.3f} < "
                  f"{args.min_fraction}", file=sys.stderr)
            return 1
        if not agg["dominant_phase"]:
            print("round_forensics: CHECK FAILED — no dominating phase",
                  file=sys.stderr)
            return 1
        print("round_forensics: CHECK OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    rc = main()
    # scenario runs leave daemon threads behind (see chaos_sweep.py);
    # the verdict must not depend on interpreter shutdown luck
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
