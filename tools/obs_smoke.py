"""Observability smoke: one localnet FBFT round, then validate the
debug surfaces over HTTP.

The check.sh stage for ISSUE 4: drives one in-process round under the
forced device path (twin kernels — the same layer split a live
``--device-path`` localnet runs), with every chain verifying its seals
through a real verification sidecar, then scrapes

    GET /metrics       — validated against the Prometheus text
                         exposition grammar (every line must parse)
    GET /debug/trace   — validated as Chrome trace-event JSON
                         (names/ts/dur/pid/tid present, every span's
                         parent resolves, children never start before
                         their parent)

and asserts the round produced ONE trace whose spans cover >= 4
components (consensus, device, sidecar, chain).  Exit 0 on success;
any violation prints the offending line/event and exits 1.

Usage: python tools/obs_smoke.py
"""

from __future__ import annotations

import http.client
import json
import os
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["HARMONY_KERNEL_TWIN"] = "1"  # twin kernels: real device-
# path layers (tables, bitmaps, counters) without XLA pairing compiles

CHAIN_ID = 2

# -- Prometheus text exposition grammar (one line at a time) -----------------

_METRIC = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP {_METRIC} .*$")
_TYPE_RE = re.compile(
    rf"^# TYPE {_METRIC} (counter|gauge|histogram|summary|untyped)$"
)
_LABELS = r'\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}'
_NUMBER = r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|[0-9]+)"
_SAMPLE_RE = re.compile(rf"^{_METRIC}({_LABELS})? {_NUMBER}$")
# OpenMetrics exemplar suffix (``?exemplars=1`` scrape): only _bucket
# samples may carry ``# {trace_id="…"} value``
_SAMPLE_EX_RE = re.compile(
    rf"^{_METRIC}_bucket({_LABELS})? {_NUMBER}"
    rf'( # \{{trace_id="[0-9a-f]+"\}} {_NUMBER})?$'
)


def validate_prometheus(text: str, exemplars: bool = False) -> list:
    """Offending lines (empty = valid exposition).  ``exemplars``
    additionally admits the OpenMetrics trace-id suffix on _bucket
    sample lines — the grammar of a ``/metrics?exemplars=1`` scrape."""
    bad = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP"):
            ok = _HELP_RE.match(line)
        elif line.startswith("# TYPE"):
            ok = _TYPE_RE.match(line)
        elif line.startswith("#"):
            ok = True  # free-form comment
        else:
            ok = _SAMPLE_RE.match(line) or (
                exemplars and _SAMPLE_EX_RE.match(line)
            )
        if not ok:
            bad.append(line)
    return bad


def validate_trace_events(doc: dict) -> list:
    """Offending findings for a Chrome trace-event export."""
    bad = []
    if "traceEvents" not in doc:
        return ["missing traceEvents key"]
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_id = {}
    for e in events:
        for field in ("name", "cat", "ts", "dur", "pid", "tid"):
            if field not in e:
                bad.append(f"event missing {field}: {e}")
        span_id = e.get("args", {}).get("span_id")
        if not span_id:
            bad.append(f"event missing args.span_id: {e.get('name')}")
        by_id[span_id] = e
    for e in events:
        parent = e.get("args", {}).get("parent_id")
        if parent is None:
            continue
        if parent not in by_id:
            bad.append(f"orphan span {e['name']}: parent {parent} "
                       "not in export")
        elif by_id[parent]["ts"] > e["ts"] + 1e-3:
            bad.append(f"span {e['name']} starts before its parent")
    return bad


# -- the one-round localnet --------------------------------------------------


def run_round(metrics_registry):
    """One committed block across 4 in-process nodes; returns the
    round's trace id."""
    from harmony_tpu import device as DV
    from harmony_tpu import trace
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork
    from harmony_tpu.sidecar.client import SidecarClient
    from harmony_tpu.sidecar.server import SidecarServer

    trace.configure(enabled=True)
    DV.use_device(True)

    sidecar = SidecarServer().start()
    genesis, _, bls_keys = dev_genesis(n_keys=4)
    committee = [k.pub.bytes for k in bls_keys]
    net = InProcessNetwork()
    nodes, clients = [], []
    for i in range(4):
        client = SidecarClient(sidecar.address)
        clients.append(client)
        engine = Engine(lambda s, e, c=committee: EpochContext(c),
                        device=False, backend=client)
        chain = Blockchain(MemKV(), genesis, engine=engine,
                           blocks_per_epoch=16)
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(blockchain=chain, txpool=pool,
                       host=net.host(f"node{i}"))
        reg.set("metrics", metrics_registry)  # round histogram target
        nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))
    try:
        leader = next(n for n in nodes if n.is_leader)
        leader.start_round_if_leader()
        for _ in range(50):
            if not any(n.process_pending() for n in nodes):
                break
        heads = [n.chain.head_number for n in nodes]
        if heads != [1, 1, 1, 1]:
            raise SystemExit(f"round did not commit on every node: "
                             f"heads={heads}")
        rounds = [s for s in trace.spans() if s.name == "consensus.round"]
        if len(rounds) != 1:
            raise SystemExit(
                f"expected ONE round root span, got {len(rounds)}"
            )
        trace_id = rounds[0].trace_id
        comps = {s.component for s in trace.spans(trace_id)}
        need = {"consensus", "device", "sidecar", "chain"}
        if not need <= comps:
            raise SystemExit(
                f"round trace covers {sorted(comps)}, needs {sorted(need)}"
            )
        return trace_id
    finally:
        for c in clients:
            c.close()
        for n in nodes:
            n.stop()
        sidecar.stop()


def scrape(port: int, path: str) -> bytes:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    if resp.status != 200:
        raise SystemExit(f"GET {path} -> {resp.status}")
    return body


def main() -> int:
    from harmony_tpu.metrics import MetricsServer, Registry

    metrics_registry = Registry()
    trace_id = run_round(metrics_registry)
    print(f"obs_smoke: round committed, trace {trace_id}")

    srv = MetricsServer(metrics_registry, port=0).start()
    try:
        metrics_text = scrape(srv.port, "/metrics").decode()
        exemplar_text = scrape(srv.port, "/metrics?exemplars=1").decode()
        trace_doc = json.loads(
            scrape(srv.port, f"/debug/trace?trace_id={trace_id}")
        )
    finally:
        srv.stop()

    bad = validate_prometheus(metrics_text)
    if bad:
        print("obs_smoke: INVALID prometheus exposition lines:")
        for line in bad[:20]:
            print(f"  {line!r}")
        return 1
    for family in ("harmony_device_checks_total",
                   "harmony_device_dispatch_seconds",
                   "harmony_consensus_round_seconds",
                   "harmony_device_transfer_bytes_total",
                   "harmony_replay_stage_seconds",
                   "harmony_round_phase_seconds"):
        if family not in metrics_text:
            print(f"obs_smoke: /metrics missing family {family}")
            return 1
    print(f"obs_smoke: /metrics OK "
          f"({len(metrics_text.splitlines())} lines, grammar-valid)")

    bad = validate_prometheus(exemplar_text, exemplars=True)
    if bad:
        print("obs_smoke: INVALID exemplar exposition lines:")
        for line in bad[:20]:
            print(f"  {line!r}")
        return 1
    if ' # {trace_id="' not in exemplar_text:
        print("obs_smoke: ?exemplars=1 carried no trace-id exemplar "
              "despite a traced round")
        return 1
    print("obs_smoke: /metrics?exemplars=1 OK (grammar-valid, "
          "trace-linked)")

    bad = validate_trace_events(trace_doc)
    if bad:
        print("obs_smoke: INVALID trace export:")
        for b in bad[:20]:
            print(f"  {b}")
        return 1
    n = len([e for e in trace_doc["traceEvents"] if e.get("ph") == "X"])
    if n < 8:
        print(f"obs_smoke: suspiciously few spans in the round: {n}")
        return 1
    print(f"obs_smoke: /debug/trace OK ({n} spans, schema-valid, "
          "properly parented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
