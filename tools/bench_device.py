#!/usr/bin/env python3
"""Bare-kernel device bench: the first-device-hour command.

docs/PERF_MODEL.md §4 projects the as-written pairing kernel at
9k–21k pairings/s per chip; no TPU round has ever checked it (relay
dead r01–r05).  When the relay comes back, THIS is the one command to
run before any optimization lands on device:

    HARMONY_TPU_PROFILE_DIR=/tmp/tpu_prof python tools/bench_device.py

It (1) probes the relay, (2) measures the BARE pairing kernel (batch
pairings/s — no consensus, no scheduler, just the compiled program),
(3) checks the measurement against the modeled band and emits the
verdict machine-readably, (4) breaks the pipeline into its stages —
montmul, Miller loop, final exponentiation as separately-compiled
programs with a device sync between them, hash-to-G2 on host — into
the harmony_prof_* stage histograms, and (5) when
HARMONY_TPU_PROFILE_DIR is set, wraps the measured iterations in a
jax.profiler capture so a loadable trace exists after the FIRST
attempt (PERF_MODEL §6 step 3).

Every metric in the JSON line is tagged source: measured|modeled
(ISSUE 6 ledger discipline).  Without an accelerator the tool emits a
skip record and exits 0 — pairing-shaped programs take minutes to
build on XLA:CPU (use --allow-cpu --stages montmul for the one stage
that is CPU-feasible).
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

from bench import (  # noqa: E402 — repo root, via the path insert
    MODELED_BAND_PAIRINGS_S,
    _m,
    _probe_relay,
    pairing_fixture,
)

ALL_STAGES = ("montmul", "miller_loop", "final_exp", "hash_to_g2")


def _emit(obj) -> None:
    print(json.dumps(obj), flush=True)


def _time_calls(fn, warm_args, iters: int, stage: str, **attrs):
    """min-of-iters wall time of fn(*warm_args).  The compiling first
    call is excluded AND outside the prof stage: each timed iteration
    is its own harmony_prof_stage_seconds sample, so the stage
    breakdown compares EXECUTE time per stage — never compile time."""
    import jax

    from harmony_tpu import prof

    out = fn(*warm_args)
    jax.block_until_ready(out)
    best = None
    for _ in range(iters):
        t0 = time.perf_counter()
        with prof.stage(stage, **attrs):
            jax.block_until_ready(fn(*warm_args))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def bench_stages(stages, batch: int, iters: int, extra: dict) -> None:
    """Per-stage breakdown: each pipeline stage as its own compiled
    program with a sync between stages — what the fused production
    program cannot show.  Results land in the prof stage histograms
    AND the tagged output."""
    import jax
    import numpy as np

    from harmony_tpu import prof
    from harmony_tpu.ops import fp as FP
    from harmony_tpu.ops import pairing as OP
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    if "montmul" in stages:
        # dense (B, 32) limb tiles — the §2 C_mul unit the whole model
        # prices; B wide enough to fill the VPU lanes
        rng = np.random.default_rng(3)
        width = max(batch, 256) * 16
        a = np.asarray(rng.integers(0, 1 << 12, (width, 32)), np.int32)
        b = np.asarray(rng.integers(0, 1 << 12, (width, 32)), np.int32)
        fn = jax.jit(FP.mont_mul)
        best = _time_calls(fn, (a, b), iters, "montmul", width=width)
        extra["montmul_per_sec"] = _m(
            round(width / best, 1), "mont_muls/s", width=width
        )

    needs_points = {"miller_loop", "final_exp"} & set(stages)
    if needs_points:
        ps, qs = pairing_fixture(batch)
        if "miller_loop" in stages:
            fn = jax.jit(OP.miller_loop)
            best = _time_calls(fn, (ps, qs), iters, "miller_loop",
                               batch=batch)
            extra["miller_loop_per_sec"] = _m(
                round(batch / best, 1), "miller_loops/s", batch=batch
            )
        if "final_exp" in stages:
            fs = OP.miller_loop(ps, qs)  # stage input, not timed
            fn = jax.jit(OP.final_exponentiation)
            best = _time_calls(fn, (fs,), iters, "final_exp",
                               batch=batch)
            extra["final_exp_per_sec"] = _m(
                round(batch / best, 1), "final_exps/s", batch=batch
            )

    if "hash_to_g2" in stages:
        # the host stage (SURVEY §7.2: branchy SHA work stays off the
        # accelerator) — its rate bounds ingress, not the kernel
        n = 16
        t0 = time.perf_counter()
        for i in range(n):
            with prof.stage("hash_to_g2"):
                hash_to_g2(b"bench-device-stage-%d" % i)
        extra["hash_to_g2_per_sec"] = _m(
            round(n / (time.perf_counter() - t0), 1), "hashes/s"
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--stages", default=",".join(ALL_STAGES),
                    help="comma list of stages to break down "
                         f"(default: {','.join(ALL_STAGES)})")
    ap.add_argument("--allow-cpu", action="store_true",
                    help="run on XLA:CPU anyway (minutes per pairing "
                         "program; use --stages montmul,hash_to_g2)")
    ap.add_argument("--skip-pairing", action="store_true",
                    help="stages only — skip the bare e(P,Q) measure")
    args = ap.parse_args(argv)
    stages = [s for s in args.stages.split(",") if s]
    unknown = sorted(set(stages) - set(ALL_STAGES))
    if unknown:
        # a typo must not silently burn the one budgeted device hour
        # on a run with no stage breakdown
        ap.error(f"unknown stage(s) {unknown}; choose from "
                 f"{','.join(ALL_STAGES)}")

    relay = _probe_relay()
    lo, hi = MODELED_BAND_PAIRINGS_S
    out = {
        "metric": "bare_kernel_pairings_per_sec",
        "source": "measured",
        "extra": {
            "modeled_pairings_per_sec_lo": _m(lo, "pairings/s",
                                              "modeled",
                                              ref="docs/PERF_MODEL.md §4"),
            "modeled_pairings_per_sec_hi": _m(hi, "pairings/s",
                                              "modeled",
                                              ref="docs/PERF_MODEL.md §4"),
        },
        "meta": {"relay_tcp": relay},
    }
    extra = out["extra"]

    import jax

    backend = jax.default_backend()
    out["meta"]["backend"] = backend
    if backend == "cpu" and not args.allow_cpu:
        out["skipped"] = ("no accelerator (relay "
                          f"{relay}); use --allow-cpu for the "
                          "CPU-feasible stages")
        _emit(out)
        return 0

    from harmony_tpu import prof

    prof.configure(enabled=True)
    capture_dir = prof.capture_dir()
    with prof.capture():
        if not args.skip_pairing:
            import numpy as np

            from harmony_tpu.ops import interop as I
            from harmony_tpu.ops import pairing as OP
            from harmony_tpu.ref import pairing as RP
            from harmony_tpu.ref.curve import G1_GEN, G2_GEN

            ps, qs = pairing_fixture(args.batch)
            fn = jax.jit(OP.pairing)
            t0 = time.perf_counter()
            first = fn(ps, qs)
            jax.block_until_ready(first)
            compile_s = time.perf_counter() - t0
            # correctness gate: a wrong kernel's throughput is noise
            assert I.arr_to_fp12(np.array(first[0])) == RP.pairing(
                G1_GEN, G2_GEN
            ), "device pairing result wrong!"
            best = None
            for _ in range(args.iters):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(ps, qs))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            rate = args.batch / best
            out["value"] = round(rate, 1)
            out["unit"] = "pairings/s"
            extra["first_dispatch_seconds"] = _m(
                round(compile_s, 3), "s", batch=args.batch
            )
            extra["band_check"] = {
                "value": round(rate, 1), "unit": "pairings/s",
                "source": "measured", "band_lo": lo, "band_hi": hi,
                "in_band": bool(lo <= rate <= hi),
                "above_band": bool(rate > hi),
                "verdict": (
                    "in_band" if lo <= rate <= hi
                    else "above_band" if rate > hi
                    else "below_band_profile_before_optimizing"
                ),
            }
        bench_stages(stages, args.batch, args.iters, extra)

    if capture_dir:
        files = [
            os.path.join(r, f)
            for r, _, fs in os.walk(capture_dir) for f in fs
        ]
        out["meta"]["profile_dir"] = capture_dir
        out["meta"]["profile_files"] = len(files)
    out["meta"]["stage_summary"] = prof.stage_summary()
    _emit(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
