"""Localnet launcher: N validator processes + a bootnode on one machine.

The role of the reference's test/deploy.sh + test/configs/ (the
localnet tier of SURVEY §4): spawn a bootnode and one process per
validator, wire discovery + sync peers, wait for blocks to flow, and
tear everything down on Ctrl-C or --blocks N.

Round-4 scenarios (VERDICT r3 #4):
  --multikey M        first M nodes vote with TWO consecutive dev keys
                      (multi-BLS validators, reference: multibls)
  --kill-leader-at B  at shard-0 head B, SIGKILL node 0; the run then
                      requires the chain to keep committing through a
                      full leader-rotation cycle and at least one
                      "adopt new view" in a survivor's log (view change
                      completed)
  --shards S          S committees (S*nodes processes); with
                      --cross-shard a shard-0 -> shard-1 transfer is
                      submitted over RPC and must land as balance on
                      shard 1 (live CXReceiptsProof routing over TCP)

Durable operator runs (ISSUE 12): ``--data-dir PATH`` pins every
node's shard DB (NativeKV/FileKV) + tx journal + logs to a persistent
directory — Ctrl-C the net, relaunch with the same flag, and every
node reopens its chain from disk through crash recovery (torn batches
discarded, head verified, last-signed views reloaded) and resumes
committing where it stopped.

Usage:
    python tools/localnet.py --nodes 8 --blocks 6 --multikey 2
    python tools/localnet.py --nodes 8 --blocks 5 --kill-leader-at 2
    python tools/localnet.py --nodes 3 --shards 2 --cross-shard --blocks 8
    python tools/localnet.py --nodes 4 --data-dir /tmp/my-localnet
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).parent.parent


def _rpc(port: int, method: str, params=None, timeout: float = 5):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(
        "POST", "/",
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                    "params": params or []}),
        {"Content-Type": "application/json"},
    )
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out.get("result")


class Net:
    """Process supervisor for one localnet run."""

    def __init__(self, args, workdir: pathlib.Path):
        self.args = args
        self.workdir = workdir
        self.procs: dict[tuple[int, int], subprocess.Popen] = {}
        self.boot: subprocess.Popen | None = None
        # key layout per shard: first --multikey nodes take 2 keys each
        self.spans = [
            2 if i < args.multikey else 1 for i in range(args.nodes)
        ]
        self.total_keys = sum(self.spans)

    def rpc_port(self, shard: int, i: int) -> int:
        return 9500 + shard * self.args.nodes + i

    def start(self):
        self.boot = subprocess.Popen(
            [sys.executable, "-m", "harmony_tpu.p2p.discovery",
             "--port", "9900"],
            cwd=ROOT, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        print(f"bootnode :9900; {self.args.shards} shard(s) x "
              f"{self.args.nodes} nodes, {self.total_keys} keys/committee, "
              f"{self.args.multikey} multi-key validators")
        for s in range(self.args.shards):
            for i in range(self.args.nodes):
                self.spawn(s, i)

    def spawn(self, shard: int, i: int):
        g = shard * self.args.nodes + i
        key_index = sum(self.spans[:i])
        cmd = [
            sys.executable, "-m", "harmony_tpu.cli",
            "--datadir", str(self.workdir / f"s{shard}n{i}"),
            "--rpc-port", str(9500 + g),
            "--p2p-port", str(9000 + g),
            "--sync-port", str(9100 + g),
            "--metrics-port", str(9700 + g),
            "--bootnode", "127.0.0.1:9900",
            "--shard-id", str(shard),
            "--shard-count", str(self.args.shards),
            "--dev-key-index", str(key_index),
            "--dev-key-span", str(self.spans[i]),
            "--dev-keys", str(self.total_keys),
            "--block-time", str(self.args.block_time),
            "--phase-timeout", str(self.args.phase_timeout),
            "--skip-ntp-check",
        ]
        if self.args.trace or self.args.trace_dir:
            # round tracing on every node: each serves its own
            # /debug/trace; one round's spans share one trace_id
            # across processes (correlate by trace_id in Perfetto)
            cmd += ["--trace"]
        if self.args.trace_dir:
            # durable span export: every node writes rotating JSONL
            # into the shared dir (spans_<node>.jsonl — the node tag
            # disambiguates); feed the files to tools/round_forensics.py
            # for cross-node phase attribution
            trace_dir = pathlib.Path(self.args.trace_dir)
            trace_dir.mkdir(parents=True, exist_ok=True)
            cmd += ["--span-sink-dir", str(trace_dir)]
        if self.args.device_path:
            # VERDICT r4 #3: live consensus THROUGH the device path —
            # device.py forced on, every quorum check routed through
            # CommitteeTable + agg_verify_on_device (+ COUNTERS).  On
            # boxes without a usable accelerator the twin kernels
            # (ops/twin.py) stand in for the XLA programs unless
            # --device-real insists on them.
            cmd += ["--device-verify"]
        else:
            # localnets verify host-side: don't let a wedged
            # accelerator tunnel stall startup probing backends
            cmd += ["--host-verify"]
        # every node can pull from a neighbour — node 0 included: a
        # node that misses a COMMITTED message recovers via the
        # consensus-timeout sync path, which needs a stream peer
        peer = (i + 1) % self.args.nodes
        cmd += ["--sync-peer",
                f"127.0.0.1:{9100 + shard * self.args.nodes + peer}"]
        if shard > 0:
            cmd += ["--beacon-sync-peer", "127.0.0.1:9100"]
        log = open(self.workdir / f"s{shard}n{i}.log", "w")
        env = dict(os.environ)
        if self.args.device_path and not self.args.device_real:
            env["HARMONY_KERNEL_TWIN"] = "1"
        self.procs[(shard, i)] = subprocess.Popen(
            cmd, cwd=ROOT, stdout=log, stderr=log, env=env,
        )
        print(f"  shard {shard} node {i}: rpc :{9500 + g} "
              f"keys {key_index}..{key_index + self.spans[i] - 1}")

    def kill(self, shard: int, i: int):
        proc = self.procs.pop((shard, i))
        proc.kill()
        proc.wait(5)
        print(f"  KILLED shard {shard} node {i} (pid {proc.pid})")

    def alive_rpc_ports(self, shard: int):
        return [self.rpc_port(s, i) for (s, i) in self.procs
                if s == shard]

    def head(self, shard: int):
        """Network head = max over responding nodes (a lagging or
        resyncing node must not mask the committee's progress)."""
        best = None
        for port in self.alive_rpc_ports(shard):
            try:
                h = _rpc(port, "hmyv2_blockNumber")
            except OSError:
                continue
            if h is not None and (best is None or h > best):
                best = h
        return best

    def check_alive(self):
        for (s, i), proc in self.procs.items():
            if proc.poll() is not None:
                raise RuntimeError(
                    f"shard {s} node {i} exited rc={proc.returncode}; "
                    f"logs in {self.workdir}"
                )

    def grep_logs(self, needle: str, shard: int = 0) -> int:
        hits = 0
        for (s, i) in self.procs:
            if s != shard:
                continue
            path = self.workdir / f"s{s}n{i}.log"
            try:
                hits += open(path, errors="replace").read().count(needle)
            except OSError:
                pass
        return hits

    def stop(self):
        for proc in self.procs.values():
            proc.send_signal(signal.SIGTERM)
        if self.boot is not None:
            self.boot.send_signal(signal.SIGTERM)
        for proc in self.procs.values():
            try:
                proc.wait(5)
            except subprocess.TimeoutExpired:
                proc.kill()


def _submit_cross_shard_tx(net: Net, value: int) -> bytes:
    """Build + sign a shard-0 -> shard-1 transfer with dev account 0
    and push it through shard 0's RPC; returns the destination addr."""
    sys.path.insert(0, str(ROOT))
    from harmony_tpu.core import rawdb
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.types import Transaction

    _, ecdsa_keys, _ = dev_genesis(n_keys=net.total_keys, shard_id=0)
    sender_key = ecdsa_keys[0]
    dest = b"\x2c" * 20
    port = net.alive_rpc_ports(0)[0]
    nonce = _rpc(port, "hmyv2_getTransactionCount",
                 ["0x" + sender_key.address().hex(), "latest"]) or 0
    tx = Transaction(
        nonce=int(nonce), gas_price=1, gas_limit=30_000, shard_id=0,
        to_shard=1, to=dest, value=value,
    ).sign(sender_key, 2)
    blob = rawdb.encode_tx(tx, 2)
    _rpc(port, "hmyv2_sendRawTransaction", ["0x" + blob.hex()])
    print(f"  cross-shard tx submitted: {value} to 0x{dest.hex()[:12]}.. "
          f"on shard 1")
    return dest


def main(argv=None):
    p = argparse.ArgumentParser(description="harmony-tpu localnet")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--multikey", type=int, default=0,
                   help="first M nodes vote with 2 dev keys each")
    p.add_argument("--blocks", type=int, default=0,
                   help="stop after N blocks (0 = run until Ctrl-C)")
    p.add_argument("--kill-leader-at", type=int, default=0,
                   help="kill node 0 at this shard-0 height; require a "
                        "completed view change + continued commits")
    p.add_argument("--cross-shard", action="store_true",
                   help="submit a shard-0->1 transfer; require arrival")
    p.add_argument("--block-time", type=float, default=2.0)
    p.add_argument("--phase-timeout", type=float, default=27.0,
                   help="per-node consensus phase timeout; raise on "
                        "oversubscribed boxes (N nodes share the core)")
    p.add_argument("--timeout", type=float, default=900.0)
    p.add_argument("--keep-data", action="store_true")
    p.add_argument("--data-dir", default=None,
                   help="persistent data directory: nodes open their "
                        "shard DBs (NativeKV/FileKV) here and a "
                        "relaunch with the same dir RESUMES the chain "
                        "from disk (crash recovery + tx journals); "
                        "implies --keep-data.  Default: a throwaway "
                        "tempdir")
    p.add_argument("--device-path", action="store_true",
                   help="force the DEVICE verification path on every "
                        "node and assert (via metrics) that quorum "
                        "checks executed on it")
    p.add_argument("--device-real", action="store_true",
                   help="with --device-path: run the real XLA kernels "
                        "instead of the host-backed twins (needs an "
                        "accelerator; minutes-per-check on XLA:CPU)")
    p.add_argument("--trace", action="store_true",
                   help="arm round tracing + flight recorder on every "
                        "node (GET /debug/trace on each metrics port)")
    p.add_argument("--trace-dir", default=None,
                   help="durable span export: arm tracing (implies "
                        "--trace) and have every node write rotating "
                        "JSONL span files into this directory; analyze "
                        "them with tools/round_forensics.py")
    args = p.parse_args(argv)
    if args.cross_shard and args.shards < 2:
        args.shards = 2

    if args.data_dir:
        # durable operator localnet: survives Ctrl-C + relaunch (each
        # node reopens its shard DB through crash recovery)
        workdir = pathlib.Path(args.data_dir).absolute()
        workdir.mkdir(parents=True, exist_ok=True)
        args.keep_data = True
    else:
        workdir = pathlib.Path(
            tempfile.mkdtemp(prefix="harmony-tpu-localnet-")
        )
    net = Net(args, workdir)
    t_first_block = None
    killed_at = None
    cx_dest = None
    cx_value = 31337
    try:
        net.start()
        print("waiting for blocks...")
        last = {s: -1 for s in range(args.shards)}
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            time.sleep(2)
            net.check_alive()
            heads = {}
            for s in range(args.shards):
                h = net.head(s)
                heads[s] = h
                if h is not None and h != last[s]:
                    print(f"  shard {s} head = {h}")
                    last[s] = h
                    if s == 0 and h >= 1 and t_first_block is None:
                        t_first_block = time.monotonic()
            h0 = heads.get(0) or 0

            if (args.kill_leader_at and killed_at is None
                    and h0 >= args.kill_leader_at):
                net.kill(0, 0)
                killed_at = h0
                print(f"  leader-kill scenario armed at head {h0}: chain "
                      f"must advance {args.nodes} more blocks (a full "
                      f"rotation past the dead node's slot)")

            if args.cross_shard and cx_dest is None and h0 >= 2 and (
                    heads.get(1) or 0) >= 1:
                cx_dest = _submit_cross_shard_tx(net, cx_value)

            # completion: every requested criterion must hold; with no
            # criteria (pure watch mode) run until Ctrl-C
            criteria = []
            if args.blocks:
                criteria.append(h0 >= args.blocks)
            if args.kill_leader_at:
                criteria.append(
                    killed_at is not None and h0 >= killed_at + args.nodes
                )
            if args.cross_shard:
                arrived = False
                if cx_dest is not None:
                    try:
                        bal = _rpc(net.alive_rpc_ports(1)[0],
                                   "hmyv2_getBalance",
                                   ["0x" + cx_dest.hex(), "latest"])
                    except OSError:
                        bal = None  # transient RPC stall: retry next tick
                    arrived = int(bal or 0) >= cx_value
                    if arrived and not getattr(net, "_cx_done", False):
                        net._cx_done = True
                        print(f"  cross-shard transfer ARRIVED on shard 1 "
                              f"(balance {bal})")
                criteria.append(arrived)

            if criteria and all(criteria):
                if args.device_path:
                    # the flagship path must have carried the run:
                    # every live node reports device-path checks > 0
                    checks = {}
                    for (s, i) in net.procs:
                        port = 9700 + s * args.nodes + i
                        try:
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", port, timeout=5
                            )
                            conn.request("GET", "/metrics")
                            text = conn.getresponse().read().decode()
                            conn.close()
                        except OSError:
                            continue
                        total = sum(
                            int(line.rsplit(" ", 1)[1])
                            for line in text.splitlines()
                            if line.startswith(
                                "harmony_device_checks_total{"
                            )
                        )
                        checks[(s, i)] = total
                    if not checks or not all(
                        v > 0 for v in checks.values()
                    ):
                        raise RuntimeError(
                            f"--device-path run but device counters "
                            f"are not live on every node: {checks}"
                        )
                    print(f"  device-path checks per node: "
                          f"{sorted(checks.values())}")
                if killed_at is not None:
                    vcs = net.grep_logs("adopt new view", shard=0)
                    if not vcs:
                        raise RuntimeError(
                            "chain advanced but no survivor logged a "
                            "completed view change"
                        )
                    print(f"  view change completed ({vcs} 'adopt new "
                          f"view' log lines among survivors)")
                rate = None
                if t_first_block is not None and h0 > 1:
                    rate = (h0 - 1) / (time.monotonic() - t_first_block)
                print(
                    f"localnet OK: shard heads "
                    f"{ {s: net.head(s) for s in range(args.shards)} }"
                    + (f", commit rate {rate:.2f} blocks/s" if rate else "")
                )
                return 0
        if not (args.blocks or args.kill_leader_at or args.cross_shard):
            return 0  # watch mode: the timeout just bounds the run
        raise RuntimeError(f"scenario incomplete after {args.timeout}s; "
                           f"logs in {workdir}")
    except KeyboardInterrupt:
        return 0
    except Exception:
        args.keep_data = True  # failure evidence must survive teardown
        raise
    finally:
        net.stop()
        if args.keep_data:
            print(f"data kept in {workdir}")
        else:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
