"""Localnet launcher: N validator processes + a bootnode on one machine.

The role of the reference's test/deploy.sh + test/configs/ (the
localnet tier of SURVEY §4): spawn a bootnode and one process per
validator, wire discovery + sync peers, wait for blocks to flow, and
tear everything down on Ctrl-C or --blocks N.

Usage:
    python tools/localnet.py --nodes 4 --blocks 3
    python tools/localnet.py --nodes 4            # run until Ctrl-C

Each node gets an ephemeral datadir, RPC on 9500+i, p2p on 9000+i,
sync on 9100+i; node 0 is every later node's sync peer; all nodes find
each other through the bootnode (PEX — no static gossip peers).
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import pathlib
import shutil
import signal
import subprocess
import sys
import tempfile
import time

ROOT = pathlib.Path(__file__).parent.parent


def _rpc(port: int, method: str, params=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    conn.request(
        "POST", "/",
        json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                    "params": params or []}),
        {"Content-Type": "application/json"},
    )
    out = json.loads(conn.getresponse().read())
    conn.close()
    return out.get("result")


def main(argv=None):
    p = argparse.ArgumentParser(description="harmony-tpu localnet")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--blocks", type=int, default=0,
                   help="stop after N blocks (0 = run until Ctrl-C)")
    p.add_argument("--block-time", type=float, default=2.0)
    p.add_argument("--keep-data", action="store_true")
    args = p.parse_args(argv)

    workdir = pathlib.Path(tempfile.mkdtemp(prefix="harmony-tpu-localnet-"))
    procs: list[subprocess.Popen] = []
    boot = None
    try:
        boot = subprocess.Popen(
            [sys.executable, "-m", "harmony_tpu.p2p.discovery",
             "--port", "9900"],
            cwd=ROOT,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        print("bootnode listening on 9900")
        for i in range(args.nodes):
            cmd = [
                sys.executable, "-m", "harmony_tpu.cli",
                "--datadir", str(workdir / f"node{i}"),
                "--rpc-port", str(9500 + i),
                "--p2p-port", str(9000 + i),
                "--sync-port", str(9100 + i),
                "--metrics-port", str(9700 + i),
                "--bootnode", "127.0.0.1:9900",
                "--dev-key-index", str(i),
                "--dev-keys", str(args.nodes),
                "--skip-ntp-check",
                # localnets verify host-side: don't let a wedged
                # accelerator tunnel stall startup probing backends
                "--host-verify",
            ]
            if i > 0:
                cmd += ["--sync-peer", "127.0.0.1:9100"]
            log = open(workdir / f"node{i}.log", "w")
            procs.append(subprocess.Popen(
                cmd, cwd=ROOT, stdout=log, stderr=log,
            ))
            print(f"node {i}: rpc :{9500 + i} p2p :{9000 + i}")

        print("waiting for blocks...")
        last = -1
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            time.sleep(2)
            for proc in procs:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"a node exited rc={proc.returncode}; logs in "
                        f"{workdir}"
                    )
            try:
                head = _rpc(9500, "hmyv2_blockNumber")
            except OSError:
                continue
            if head is not None and head != last:
                print(f"  head = {head}")
                last = head
            if args.blocks and (head or 0) >= args.blocks:
                print(f"reached {head} blocks — localnet works")
                return 0
        if args.blocks:
            raise RuntimeError("timed out waiting for blocks")
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        for proc in procs:
            proc.send_signal(signal.SIGTERM)
        if boot is not None:
            boot.send_signal(signal.SIGTERM)
        for proc in procs:
            try:
                proc.wait(5)
            except subprocess.TimeoutExpired:
                proc.kill()
        if not args.keep_data:
            shutil.rmtree(workdir, ignore_errors=True)
        else:
            print(f"data kept in {workdir}")


if __name__ == "__main__":
    sys.exit(main())
