#!/usr/bin/env python3
"""Pin the herumi SignHash map convention from real signature vectors.

The one unpinned herumi interop convention (PARITY.md, VERDICT r4 #6)
is the SignHash map's sqrt-root choice and cofactor-clearing method:
no herumi-produced signature vector exists anywhere in the reference
tree (exhaustively mined in round 4), so ``ref/herumi.py`` carries the
candidate conventions behind ``MAP_CONVENTION``.

THIS is the one command to run the moment any herumi-signed vector
becomes available (a mainnet block's lastCommitSignature + its signers
and hash, or a signature produced by any herumi build):

    python tools/pin_herumi.py \
        --pk <96-hex herumi-serialized G1 pubkey> \
        --msg <64-hex 32-byte message hash> \
        --sig <192-hex herumi-serialized G2 signature> \
        [--pk ... --msg ... --sig ...]     # more vectors sharpen the pin

It tries every carried convention combination, reports which ones
verify ALL vectors, and emits the config pin (env vars consumed by
ref/herumi.py at import, no code change).

Vectors can also come from a JSON file: [{"pk": "..", "msg": "..",
"sig": ".."}, ...] via --vectors FILE.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

ROOTS = ("algorithmic", "even", "odd")
COFACTORS = ("h2", "heff")


def pin_from_vectors(vectors: list) -> dict:
    """vectors: [(pk_bytes, msg_bytes, sig_bytes)] herumi-serialized.

    Returns {"matches": [(root, cofactor)...], "pin": {...} | None}.
    Pure function of the vectors; restores the process convention.
    """
    from harmony_tpu.ref import herumi as HM

    decoded = []
    for pk_b, msg, sig_b in vectors:
        pk = HM.g1_deserialize(pk_b)
        sig = HM.g2_deserialize(sig_b)
        decoded.append((pk, msg, sig))

    saved = dict(HM.MAP_CONVENTION)
    matches = []
    try:
        for root in ROOTS:
            for cof in COFACTORS:
                HM.set_map_convention(root=root, cofactor=cof)
                if all(
                    HM.verify_hash(pk, msg, sig)
                    for pk, msg, sig in decoded
                ):
                    matches.append((root, cof))
    finally:
        HM.set_map_convention(**saved)
    pin = None
    if len(matches) == 1:
        pin = {"root": matches[0][0], "cofactor": matches[0][1]}
    return {"matches": matches, "pin": pin}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pk", action="append", default=[],
                    help="herumi-serialized G1 pubkey (96 hex chars)")
    ap.add_argument("--msg", action="append", default=[],
                    help="32-byte signed message hash (64 hex chars)")
    ap.add_argument("--sig", action="append", default=[],
                    help="herumi-serialized G2 signature (192 hex chars)")
    ap.add_argument("--vectors", help="JSON file of {pk,msg,sig} objects")
    args = ap.parse_args(argv)

    vectors = []
    if args.vectors:
        with open(args.vectors) as f:
            for v in json.load(f):
                vectors.append((bytes.fromhex(v["pk"]),
                                bytes.fromhex(v["msg"]),
                                bytes.fromhex(v["sig"])))
    if not (len(args.pk) == len(args.msg) == len(args.sig)):
        ap.error("--pk/--msg/--sig must be given the same number of times")
    for pk, msg, sig in zip(args.pk, args.msg, args.sig):
        vectors.append((bytes.fromhex(pk), bytes.fromhex(msg),
                        bytes.fromhex(sig)))
    if not vectors:
        ap.error("no vectors given (use --pk/--msg/--sig or --vectors)")

    res = pin_from_vectors(vectors)
    if not res["matches"]:
        print("NO carried convention verifies these vectors.")
        print("Either a vector is corrupt, or herumi's map uses a")
        print("convention outside {algorithmic,even,odd}x{h2,heff} —")
        print("extend ref/herumi.py MAP_CONVENTION candidates.")
        return 2
    if res["pin"] is None:
        print(f"UNDERDETERMINED: {len(res['matches'])} combinations "
              "verify all vectors:")
        for root, cof in res["matches"]:
            print(f"  root={root} cofactor={cof}")
        print("Add more vectors (different messages) to sharpen the pin.")
        return 3
    root, cof = res["pin"]["root"], res["pin"]["cofactor"]
    print("PINNED. Set for every node (or bake into the TOML config):")
    print(f"  HERUMI_MAP_ROOT={root}")
    print(f"  HERUMI_MAP_COFACTOR={cof}")
    print("and update ref/herumi.py MAP_CONVENTION defaults + PARITY.md.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
