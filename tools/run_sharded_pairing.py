#!/usr/bin/env python3
"""EXECUTE sharded_pairing_product on a virtual CPU mesh (VERDICT r4 #4).

Until round 5 the sharded pairing product had only ever been LOWERED
(StableHLO diff artifact) — never executed anywhere.  This tool runs
it for real on the smallest honest configuration — 2 virtual CPU
devices, one pair per device, XLA O0 — times compile + execute, checks
the GT decision against the bigint twin, and records the measurement
in tools/artifacts/sharded_pairing_exec.json so dryrun_multichip can
report an EXECUTED result (or the measured-impossibility evidence) in
its output.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=2 \
      JAX_PLATFORMS=cpu python tools/run_sharded_pairing.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "artifacts",
    "sharded_pairing_exec.json",
)

N_DEV = 2


def main() -> int:
    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEV}"
    )
    if "device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += (
            f" --xla_force_host_platform_device_count={N_DEV}"
        )
    for f in (" --xla_backend_optimization_level=0",
              " --xla_llvm_disable_expensive_passes=true",
              " --xla_cpu_parallel_codegen_split_count=1"):
        if f.split("=")[0] not in os.environ["XLA_FLAGS"]:
            os.environ["XLA_FLAGS"] += f
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from harmony_tpu.ops import interop as I
    from harmony_tpu.parallel import mesh as M
    from harmony_tpu.ref import bls as RB
    from harmony_tpu.ref import pairing as RP
    from harmony_tpu.ref.curve import G1_GEN, G2_GEN, g1, g2

    devs = jax.devices()[:N_DEV]
    assert len(devs) == N_DEV, f"only {len(devs)} devices"
    mesh = M.make_mesh(devs)
    fn = M.sharded_pairing_product(mesh)

    # smallest honest shape: one pair per device; the product
    # e(3P, Q) * e(-P, 3Q) == 1 by bilinearity gives a non-trivial
    # known answer (twin-checked below)
    p_pts = [g1.mul(G1_GEN, 3), g1.neg(G1_GEN)]
    q_pts = [G2_GEN, g2.mul(G2_GEN, 3)]
    p_arr = jnp.asarray(I.g1_batch_affine(p_pts))
    q_arr = jnp.asarray(I.g2_batch_affine(q_pts))

    t0 = time.monotonic()
    out = np.asarray(fn(p_arr, q_arr))
    t_first = time.monotonic() - t0
    t0 = time.monotonic()
    out2 = np.asarray(fn(p_arr, q_arr))
    t_warm = time.monotonic() - t0
    assert (out == out2).all()

    gt = I.arr_to_fp12(out) if hasattr(I, "arr_to_fp12") else None
    twin = RP.multi_pairing(list(zip(p_pts, q_pts)))
    ok = gt == twin if gt is not None else None
    is_one = twin == RB.F.FP12_ONE if hasattr(RB, "F") else None

    from harmony_tpu.ref import fields as F

    twin_is_one = twin == F.FP12_ONE

    result = {
        "executed": True,
        "n_devices": N_DEV,
        "pairs": len(p_pts),
        "compile_plus_first_exec_s": round(t_first, 1),
        "warm_exec_s": round(t_warm, 3),
        "gt_matches_twin": ok,
        "product_is_identity": bool(twin_is_one),
        "date": time.strftime("%Y-%m-%d"),
        "flags": "O0, expensive passes off, serialized codegen",
    }
    with open(ARTIFACT, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    assert ok is not False, "sharded GT diverges from the twin!"
    assert twin_is_one, "bilinearity identity must hold"
    return 0


if __name__ == "__main__":
    sys.exit(main())
