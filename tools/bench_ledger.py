#!/usr/bin/env python3
"""Bench ledger: diff BENCH_r*.json across rounds, flag regressions.

Every round the driver records one ``BENCH_rNN.json`` (bench.py's JSON
line under ``parsed``); until now nobody compared them — a kernel PR
that halved replay throughput would have shipped silently (ISSUE 6).
This tool normalizes every round's metrics (tagged r06+ schema and the
legacy untagged extras alike), diffs consecutive rounds direction-aware
(throughput up = good, latency down = good), and emits machine-readable
flags:

    regression   — a comparable metric moved WORSE than --threshold
    improvement  — moved better than the threshold (informational)
    redefined    — the metric's measurement changed between rounds
                   (source tag or mode stamp differs) — NOT comparable,
                   never a regression (e.g. r06 redefining
                   replay_headers_per_sec_host from a 1/p50 derivation
                   to the measured staged-sync pipeline)
    new/dropped  — metric (dis)appeared (informational)

Exit codes: ``--check`` exits 1 iff any regression flag survives; plain
runs always exit 0 (report mode).  Output is one JSON document.

Usage:
    python tools/bench_ledger.py                  # all BENCH_r*.json
    python tools/bench_ledger.py --check          # CI gate (check.sh)
    python tools/bench_ledger.py A.json B.json    # explicit rounds
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Direction of goodness by metric-name shape.  Metrics matching no
# pattern are diffed but never flagged (unknown direction).
_UP_PATTERNS = ("_per_sec", "_per_s", "pairings_per_s", "pairs_per_sec",
                "fill_ratio", "tx_per_s", "_passed", "blocks_min")
_DOWN_PATTERNS = ("_ms", "_seconds", "_s_", "p50", "p99", "latency")

# Bookkeeping values that are parameters, not performance metrics.
_SKIP = ("_n_keys", "_mode", "items_dispatched", "vs_baseline")

# Tagged fields that are run OUTCOMES or doc pointers, not measurement
# configuration — excluded from the definition params: `headers` is
# how many blocks the time-budgeted fixture build managed this round,
# and letting it veto comparability would launder a replay regression
# (slower build -> fewer headers -> "redefined") past --check.
_NON_DEFINITION_FIELDS = ("value", "unit", "source", "mode", "ref",
                          "headers", "window_s", "rounds")


def direction(name: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 unknown."""
    low = name.lower()
    if any(p in low for p in _SKIP):
        return 0
    if any(p in low for p in _UP_PATTERNS):
        return 1
    if any(p in low for p in _DOWN_PATTERNS):
        return -1
    return 0


def _attach_legacy_modes(out: dict, extra: dict) -> None:
    """Legacy ``<stem>_mode`` string siblings (pre-r06 convention:
    ``agg_verify_1k_mode`` pairs with ``agg_verify_p50_ms_host_1k``)
    attach to the UNIQUE metric containing every stem token.  An
    ambiguous stem (several candidates) attaches to NONE: mis-stamping
    a mode would launder a real regression into a 'redefined' flag,
    which is exactly what the --check gate exists to catch."""
    for k, v in extra.items():
        if not (k.endswith("_mode") and isinstance(v, str)):
            continue
        tokens = [t for t in k[: -len("_mode")].split("_") if t]
        matches = [
            name for name in out
            if all(t in name.split("_") for t in tokens)
        ]
        if len(matches) == 1 and out[matches[0]]["mode"] is None:
            out[matches[0]]["mode"] = v


def normalize(parsed) -> dict:
    """One round's record -> {metric: {value, source, mode, unit}}.

    Accepts the r06+ tagged schema ({"value", "unit", "source", ...}
    dicts in ``extra``), the legacy flat-number extras, and None
    (rounds whose bench never emitted — r01/r02)."""
    out: dict = {}
    if not isinstance(parsed, dict):
        return out
    if "metric" in parsed and isinstance(parsed.get("value"), (int, float)):
        out[parsed["metric"]] = {
            "value": float(parsed["value"]),
            "source": parsed.get("source"),
            "mode": None,
            "unit": parsed.get("unit"),
        }
    extra = parsed.get("extra") or {}
    for name, entry in extra.items():
        if isinstance(entry, dict) and isinstance(
            entry.get("value"), (int, float)
        ):
            out[name] = {
                "value": float(entry["value"]),
                "source": entry.get("source"),
                "mode": entry.get("mode") if isinstance(
                    entry.get("mode"), str
                ) else None,
                "unit": entry.get("unit"),
                # the measurement's parameters (n_keys, width,
                # committee_keys, ...) — part of its DEFINITION for
                # the comparability check below; run outcomes and doc
                # pointers are not (_NON_DEFINITION_FIELDS)
                "params": {
                    k: v for k, v in entry.items()
                    if k not in _NON_DEFINITION_FIELDS
                },
            }
        elif isinstance(entry, (int, float)) and not isinstance(
            entry, bool
        ):
            out[name] = {
                "value": float(entry),
                "source": None,  # legacy untagged round
                "mode": None,
                "unit": None,
                "params": {},
            }
    _attach_legacy_modes(out, extra)
    return out


def definition_changed(a: dict, b: dict) -> bool:
    """Did the MEASUREMENT change between two entries of one metric?

    - both sides tagged with different sources -> changed; a
      None->tagged source backfill alone is NOT a change (legacy
      rounds were measured too — treating the r06 schema migration as
      all-redefined would blind --check for exactly that round);
    - mode stamp differs -> changed;
    - both sides carry params and they differ (e.g. a different
      BENCH_REPLAY_COMMITTEE) -> changed; a legacy side with no params
      recorded cannot veto comparison."""
    sa, sb = a.get("source"), b.get("source")
    if sa is not None and sb is not None and sa != sb:
        return True
    if (a.get("mode") or None) != (b.get("mode") or None):
        return True
    pa, pb = a.get("params") or {}, b.get("params") or {}
    return bool(pa and pb and pa != pb)


def _round_number(path: str) -> int:
    m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_rounds(paths: list) -> list:
    """[(round_id, path, normalized)] in round order."""
    rounds = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        parsed = doc.get("parsed", doc)  # driver wrapper or bare line
        rid = doc.get("n", _round_number(path))
        rounds.append((rid, path, normalize(parsed)))
    rounds.sort(key=lambda r: r[0])
    return rounds


def diff(rounds: list, threshold: float) -> list:
    """Flags across every consecutive round pair."""
    flags = []
    for (ra, _, ma), (rb, _, mb) in zip(rounds, rounds[1:]):
        for name in sorted(set(ma) | set(mb)):
            a, b = ma.get(name), mb.get(name)
            if a is None or b is None:
                flags.append({
                    "kind": "new" if a is None else "dropped",
                    "metric": name, "from_round": ra, "to_round": rb,
                })
                continue
            if definition_changed(a, b):
                flags.append({
                    "kind": "redefined", "metric": name,
                    "from_round": ra, "to_round": rb,
                    "prev": a["value"], "cur": b["value"],
                    "prev_mode": [a.get("source"), a.get("mode"),
                                  a.get("params")],
                    "cur_mode": [b.get("source"), b.get("mode"),
                                 b.get("params")],
                })
                continue
            d = direction(name)
            if d == 0 or a["value"] == 0:
                continue
            change = (b["value"] - a["value"]) / abs(a["value"])
            worse = -change * d > threshold
            better = change * d > threshold
            if worse or better:
                flags.append({
                    "kind": "regression" if worse else "improvement",
                    "metric": name, "from_round": ra, "to_round": rb,
                    "prev": a["value"], "cur": b["value"],
                    "change_pct": round(change * 100, 1),
                })
    return flags


def run(paths: list, threshold: float) -> dict:
    rounds = load_rounds(paths)
    flags = diff(rounds, threshold)
    regressions = [f for f in flags if f["kind"] == "regression"]
    return {
        "rounds": [
            {"round": rid, "file": os.path.relpath(path, ROOT),
             "metrics": metrics}
            for rid, path, metrics in rounds
        ],
        "threshold_pct": round(threshold * 100, 1),
        "flags": flags,
        "ok": not regressions,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="BENCH round files (default: BENCH_r*.json "
                         "in the repo root)")
    ap.add_argument("--threshold", type=float, default=0.30,
                    help="fractional change that flags (default 0.30; "
                         "this box's vCPU jitters same-code runs by "
                         "~20%% — see PERF_MODEL §5)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any regression flag (CI gate)")
    args = ap.parse_args(argv)

    paths = args.files or sorted(
        glob.glob(os.path.join(ROOT, "BENCH_r*.json"))
    )
    if len(paths) < 2:
        print(json.dumps({"rounds": [], "flags": [],
                          "ok": True, "note": "fewer than 2 rounds"}))
        return 0
    report = run(paths, args.threshold)
    print(json.dumps(report, indent=2))
    if args.check and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
