"""Precompile the pinned device-program shapes into .jax_cache.

The test suite runs with a READ-ONLY compile cache (XLA's cache/compile
path has segfaulted intermittently on this image — tests/conftest.py);
this tool, run manually/rarely, compiles every pinned batch shape the
framework dispatches (chain.engine.VERIFY_BUCKETS) with writes ENABLED
so test/replay runs are pure cache hits.

Usage: python tools/warm_cache.py [cpu|tpu]
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    platform = sys.argv[1] if len(sys.argv) > 1 else "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "parallel_codegen" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_parallel_codegen_split_count=1"
        ).strip()
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import time

    import jax.numpy as jnp
    import numpy as np

    from harmony_tpu import bls as B
    from harmony_tpu.chain.engine import VERIFY_BUCKETS
    from harmony_tpu.ops import bls as OB
    from harmony_tpu.ops import interop as I
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    key = B.PrivateKey.generate(b"warm-cache")
    h = hash_to_g2(b"warm-cache-msg")
    sig = key.sign_hash(b"warm-cache-msg-hash-32-bytes-pad")
    pk1 = np.asarray(I.g1_batch_affine([key.pub.point]))
    h1 = np.asarray(I.g2_batch_affine([h]))
    sg1 = np.asarray(I.g2_batch_affine([sig.point]))

    for bucket in VERIFY_BUCKETS:
        t0 = time.time()
        pk = jnp.asarray(np.repeat(pk1, bucket, axis=0))
        hh = jnp.asarray(np.repeat(h1, bucket, axis=0))
        sg = jnp.asarray(np.repeat(sg1, bucket, axis=0))
        OB.verify(pk, hh, sg).block_until_ready()
        print(f"verify[B={bucket}]: compiled+cached in "
              f"{time.time() - t0:.1f}s", flush=True)


if __name__ == "__main__":
    main()
