"""Scheduler smoke: concurrent FBFT rounds + sync replay + an ingress
burst through ONE shared verification queue, asserted over /metrics.

The check.sh stage for ISSUE 5: a 4-node in-process localnet under the
forced device path (twin kernels) commits two blocks while

  * a replay worker re-verifies the committed chain into fresh replica
    chains (engine seal batches -> the scheduler's SYNC lane), and
  * an ingress worker floods staking-tx submissions whose BLS
    proofs-of-possession verify on the INGRESS lane,

then scrapes GET /metrics over HTTP and asserts

  * the exposition parses (Prometheus text grammar),
  * harmony_sched_batch_fill_ratio  >  FILL_FLOOR  (continuous
    batching actually coalesced: well above the 1/8 a lone check gets
    on the smallest pinned bucket),
  * ZERO consensus-lane sheds (the priority lane never overflowed or
    hit an open breaker),
  * the sched families are present and flushes happened.

Exit 0 on success; any violation prints the offending value and exits 1.

Usage: python tools/sched_smoke.py
"""

from __future__ import annotations

import http.client
import os
import pathlib
import re
import sys
import threading

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["HARMONY_KERNEL_TWIN"] = "1"  # twin kernels: real device-
# path layers (tables, bitmaps, scheduler) without XLA pairing compiles

CHAIN_ID = 2
ROUNDS = 2
FILL_FLOOR = 0.2

from obs_smoke import validate_prometheus  # noqa: E402 — same dir


def _metric_value(text: str, name: str, **labels) -> float | None:
    """First sample of ``name`` whose label set CONTAINS ``labels``."""
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? (.+)$",
                     line)
        if m is None or m.group(1) != name:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(3) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            return float(m.group(4))
    return None


def _metric_sum(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? (.+)$",
                     line)
        if m is None or m.group(1) != name:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(3) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            total += float(m.group(4))
    return total


def run_localnet(metrics_registry):
    from harmony_tpu import bls as B
    from harmony_tpu import device as DV
    from harmony_tpu import sched
    from harmony_tpu.chain.engine import Engine, EpochContext
    from harmony_tpu.core.blockchain import Blockchain
    from harmony_tpu.core.genesis import dev_genesis
    from harmony_tpu.core.kv import MemKV
    from harmony_tpu.core.tx_pool import TxPool
    from harmony_tpu.core.types import Directive, StakingTransaction
    from harmony_tpu.crypto_ecdsa import ECDSAKey
    from harmony_tpu.multibls import PrivateKeys
    from harmony_tpu.node.node import Node
    from harmony_tpu.node.registry import Registry
    from harmony_tpu.p2p import InProcessNetwork

    DV.use_device(True)
    sched.reset()
    # throughput-leaning flush window (the operator knob a replay-heavy
    # deployment turns): 10 ms of extra batching latency is noise
    # against block time, and lets concurrent bursts actually coalesce
    sched.configure(flush_window_s=0.01)

    genesis, ecdsa_keys, bls_keys = dev_genesis(n_keys=4)
    committee = [k.pub.bytes for k in bls_keys]

    # ONE shared epoch context = ONE device-resident committee table
    # across every engine (nodes + replay replicas): same-committee
    # seal checks from different chains coalesce into shared fused
    # batches — the deployment shape (committee tables are per-epoch
    # state, not per-caller state)
    shared_ctx = EpochContext(committee)

    def provider(shard_id, epoch):
        return shared_ctx

    def mk_chain():
        return Blockchain(
            MemKV(), genesis, engine=Engine(provider, device=True),
            blocks_per_epoch=16,
        )

    net = InProcessNetwork()
    nodes = []
    for i in range(4):
        chain = mk_chain()
        pool = TxPool(CHAIN_ID, 0, chain.state)
        reg = Registry(blockchain=chain, txpool=pool,
                       host=net.host(f"node{i}"))
        reg.set("metrics", metrics_registry)
        nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))

    stop = threading.Event()
    ready = threading.Event()  # gates the ingress floods until the
    # localnet is live, so the bursts overlap real round traffic
    errors: list = []

    def replay_worker():
        """Re-verify whatever the localnet has committed, repeatedly,
        into fresh replica chains — sustained SYNC-lane seal batches
        concurrent with the live rounds."""
        try:
            import time as _time

            while not stop.is_set():
                head = nodes[0].chain.head_number
                if head < 1:
                    _time.sleep(0.01)
                    continue
                replica = mk_chain()
                blocks, proofs = [], []
                for n in range(1, head + 1):
                    blk = nodes[0].chain.block_by_number(n)
                    proof = nodes[0].chain.read_commit_sig(n)
                    if blk is None or proof is None:
                        break
                    blocks.append(blk)
                    proofs.append(proof)
                if blocks:
                    replica.insert_chain(blocks, commit_sigs=proofs,
                                         verify_seals=True)
        except Exception as e:  # noqa: BLE001 — fail the smoke loudly
            errors.append(f"replay worker: {e!r}")

    def ingress_worker(seed: int):
        """Staking-tx POP floods: multi-key registrations whose BLS
        proofs-of-possession verify on the ingress lane — concurrent
        bursts that must coalesce (and never outrank consensus)."""
        try:
            state = type("S", (), {"nonce": lambda s, a: 0,
                                   "balance": lambda s, a: 10**30})()
            pool = TxPool(CHAIN_ID, 0, lambda: state)
            staker = ECDSAKey.from_seed(b"smoke-%d" % seed)
            # build every tx up front: the submit loop below is a TIGHT
            # flood (the burst shape RPC admission sees), not paced by
            # key generation
            txs = []
            for i in range(6):
                bks = [B.PrivateKey.generate(bytes([seed, i, j]))
                       for j in range(3)]
                txs.append(StakingTransaction(
                    nonce=i, gas_price=1, gas_limit=50_000,
                    directive=Directive.CREATE_VALIDATOR,
                    fields={
                        "amount": 10**20, "min_self_delegation": 10**18,
                        "bls_keys": b"".join(k.pub.bytes for k in bks),
                        "bls_key_sigs": b"".join(
                            B.proof_of_possession(k) for k in bks
                        ),
                    },
                ).sign(staker, CHAIN_ID))
            ready.wait()
            for tx in txs:
                if stop.is_set():
                    return
                pool.add(tx, is_staking=True)
        except Exception as e:  # noqa: BLE001
            errors.append(f"ingress worker {seed}: {e!r}")

    workers = [threading.Thread(target=replay_worker, daemon=True)
               for _ in range(2)]
    workers += [
        threading.Thread(target=ingress_worker, args=(s,), daemon=True)
        for s in (1, 2, 3, 4, 5, 6)
    ]
    import time as _time

    pumps: list = []
    try:
        # every node pumps on ITS OWN thread (run_forever): sender-sig
        # checks, proof verifies and seal batches from four nodes plus
        # the workers genuinely overlap on the one shared queue — the
        # concurrency continuous batching exists to exploit
        for w in workers:
            w.start()
        pumps = [
            n.run_forever(poll_interval=0.002, block_time=0.2,
                          phase_timeout=120.0)
            for n in nodes
        ]
        ready.set()
        deadline = _time.monotonic() + 240
        while _time.monotonic() < deadline:
            if all(n.chain.head_number >= ROUNDS for n in nodes):
                break
            _time.sleep(0.05)
        else:
            raise SystemExit(
                "localnet stalled: heads="
                f"{[n.chain.head_number for n in nodes]}"
            )
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=60)
        for n in nodes:
            n.stop()
        for p in pumps:
            p.join(timeout=10)
    if errors:
        raise SystemExit("worker errors: " + "; ".join(errors))


def scrape(port: int, path: str) -> bytes:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    if resp.status != 200:
        raise SystemExit(f"GET {path} -> {resp.status}")
    return body


def main() -> int:
    from harmony_tpu.metrics import MetricsServer, Registry

    registry = Registry()
    run_localnet(registry)
    print(f"sched_smoke: {ROUNDS} rounds committed under concurrent "
          "replay + ingress load")

    srv = MetricsServer(registry, port=0).start()
    try:
        text = scrape(srv.port, "/metrics").decode()
    finally:
        srv.stop()

    bad = validate_prometheus(text)
    if bad:
        print("sched_smoke: INVALID prometheus exposition lines:")
        for line in bad[:20]:
            print(f"  {line!r}")
        return 1
    for family in ("harmony_sched_queue_depth", "harmony_sched_wait_seconds",
                   "harmony_sched_flushes_total",
                   "harmony_sched_items_total",
                   "harmony_sched_batch_fill_ratio"):
        if family not in text:
            print(f"sched_smoke: /metrics missing family {family}")
            return 1

    fill = _metric_value(text, "harmony_sched_batch_fill_ratio")
    if fill is None or fill <= FILL_FLOOR:
        print(f"sched_smoke: batch fill ratio {fill} <= floor "
              f"{FILL_FLOOR} — continuous batching did not coalesce")
        return 1
    consensus_sheds = _metric_sum(text, "harmony_sched_shed_total",
                                  lane="consensus")
    if consensus_sheds:
        print(f"sched_smoke: {consensus_sheds:g} consensus-lane sheds "
              "(priority lane must never shed in a healthy localnet)")
        return 1
    flushes = _metric_sum(text, "harmony_sched_flushes_total")
    items = _metric_sum(text, "harmony_sched_items_total")
    lanes_seen = {
        lane for lane in ("consensus", "sync", "ingress")
        if _metric_value(text, "harmony_sched_items_total", lane=lane)
    }
    if not flushes or not items or len(lanes_seen) < 3:
        print(f"sched_smoke: thin traffic — flushes={flushes:g} "
              f"items={items:g} lanes={sorted(lanes_seen)}")
        return 1
    print(f"sched_smoke: /metrics OK — fill ratio {fill:.3f} "
          f"(floor {FILL_FLOOR}), {items:g} items over {flushes:g} "
          f"flushes across lanes {sorted(lanes_seen)}, "
          "0 consensus-lane sheds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
