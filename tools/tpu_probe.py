"""Staged axon-TPU tunnel probe with per-stage timing and hard watchdog.

Run as a CHILD process (parent should apply a hard timeout): each stage
appends a JSON line to stdout so a hang still leaves a partial record of
how far init got.  Stages mirror VERDICT r3 #1: backend init, device_put,
tiny arithmetic, then one 8-lane mont_mul (the first pairing-shaped op).
"""
import json, os, sys, time, faulthandler, threading

def emit(stage, ok, t0, **extra):
    rec = {"stage": stage, "ok": ok, "dt_s": round(time.time() - t0, 3)}
    rec.update(extra)
    print(json.dumps(rec), flush=True)

def main():
    faulthandler.register(__import__("signal").SIGUSR1)
    # Watchdog: dump all thread stacks shortly before the parent kills us,
    # so the hang location lands in the diagnostic bundle.
    budget = float(os.environ.get("PROBE_BUDGET_S", "240"))
    faulthandler.dump_traceback_later(budget - 10, exit=False, file=sys.stderr)

    t0 = time.time()
    try:
        import jax
        emit("import_jax", True, t0, jax_version=jax.__version__,
             platforms_cfg=str(jax.config.jax_platforms))
    except Exception as e:
        emit("import_jax", False, t0, error=repr(e)); return

    t0 = time.time()
    try:
        devs = jax.devices()
        emit("jax_devices", True, t0, devices=[str(d) for d in devs],
             backend=jax.default_backend())
        if jax.default_backend() in ("cpu",):
            emit("verdict", False, t0, reason="only-cpu-backend"); return
    except Exception as e:
        emit("jax_devices", False, t0, error=repr(e)[:2000]); return

    t0 = time.time()
    try:
        import numpy as np
        x = jax.device_put(np.arange(8, dtype=np.int32))
        y = (x + 1).block_until_ready()
        emit("device_put_add", True, t0, result=[int(v) for v in y])
    except Exception as e:
        emit("device_put_add", False, t0, error=repr(e)[:2000]); return

    t0 = time.time()
    try:
        import numpy as np
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        from harmony_tpu.ops import fp
        from harmony_tpu.ops.limbs import int_to_limbs
        av = np.stack([int_to_limbs(12345 + i) for i in range(8)])
        f = jax.jit(lambda x: fp.mont_mul(fp.to_mont(x), fp.to_mont(x)))
        r = f(av)
        jax.block_until_ready(r)
        emit("mont_mul_8lane", True, t0, out_limb0=int(np.asarray(r)[0, 0]))
    except Exception as e:
        emit("mont_mul_8lane", False, t0, error=repr(e)[:2000]); return

    emit("verdict", True, t0, reason="tpu-usable")

if __name__ == "__main__":
    main()
