"""Compile-surface smoke: ZERO first-use compiles after warmup across
a committee-width change — the exact PR-15 trigger.

The check.sh stage for ISSUE 17's acceptance: the first NEWVIEW at a
new committee width used to mint a fresh XLA program on the consensus
pump thread and wedge every validator ~90s.  This smoke proves the
warmup manifest actually covers the serving surface:

  1. ``aot.startup_warmup()`` warms every program in the committed
     compile manifest (GL16's machine-checked shape set);
  2. every device entry family (agg_verify, batched replay, single
     verify, continuous-batch verify_many) is driven at committee
     width 5 (bucket 8) and AGAIN at width 12 (bucket 16 — the width
     change that wedged PR 15);
  3. the device JIT first-use counter must not move: every program
     the drive dispatched was already warm, and every program it
     touched is in the manifest.

Runs under the kernel twins (the same layer split every other CI
localnet stage uses): first-use accounting is identical on the twin
path — ``_program_first_use`` fires per program name regardless of
backend — so a manifest gap shows up as a JIT miss here in seconds
instead of a 90s pump wedge on a TPU.

Usage: python tools/compile_surface_smoke.py   (exit 0 = gate passed)
"""

from __future__ import annotations

import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["HARMONY_KERNEL_TWIN"] = "1"  # twin kernels: real device-
# path layers (tables, bitmaps, counters) without XLA pairing compiles


def fail(msg: str) -> None:
    print(f"compile_surface_smoke FAIL: {msg}", flush=True)
    sys.exit(1)


def drive_width(n_keys: int) -> list:
    """Every serving-path device entry family at one committee width;
    returns the program names dispatched (from the seen-set)."""
    from harmony_tpu import bls as B
    from harmony_tpu import device as DV
    from harmony_tpu.ref.hash_to_curve import hash_to_g2

    payload = b"compile-surface-smoke-payload-32"
    keys = [B.PrivateKey.generate(bytes([30 + n_keys + i]))
            for i in range(n_keys)]
    sigs = [k.sign_hash(payload) for k in keys]
    agg = B.aggregate_sigs(sigs)
    h = hash_to_g2(payload)
    table = DV.CommitteeTable([k.pub.point for k in keys])

    # fused quorum check (consensus pump shape), accept AND reject
    ok = DV.agg_verify_hashed_on_device(
        table, [1] * n_keys, h, agg.point)
    if not ok:
        fail(f"agg_verify accept failed at width {n_keys}")
    if DV.agg_verify_hashed_on_device(
            table, [1] * (n_keys - 1) + [0], h, agg.point):
        fail(f"agg_verify reject failed at width {n_keys}")

    # batched replay (sync/catch-up shape)
    batch = DV.agg_verify_batch_on_device(
        table, [[1] * n_keys] * 3, [h] * 3, [agg.point] * 3)
    if batch != [True, True, True]:
        fail(f"agg_verify_batch failed at width {n_keys}: {batch}")

    # single check (view-change vote shape)
    if not DV.verify_on_device(keys[0].pub.point, payload,
                               sigs[0].point):
        fail(f"verify_single failed at width {n_keys}")

    # continuous-batch independent checks (scheduler coalesce shape)
    many = DV.verify_many_on_device(
        [k.pub.point for k in keys], [h] * n_keys,
        [s.point for s in sigs])
    if many != [True] * n_keys:
        fail(f"verify_many failed at width {n_keys}: {many}")


def main() -> int:
    from harmony_tpu import aot
    from harmony_tpu import device as DV

    DV.use_device(True)
    manifest = aot.load_manifest()
    if manifest is None:
        fail(f"no compile manifest at {aot.MANIFEST_PATH} — "
             "regenerate with python -m tools.graftlint "
             "--emit-compile-manifest")
    covered = set(aot.manifest_names(manifest)) | {"verify_w1"}

    stats = aot.startup_warmup()
    if not stats or stats["mode"] != "twin":
        fail(f"warmup did not run in twin mode: {stats}")
    if stats["warmed"] < len(covered):
        fail(f"warmup marked {stats['warmed']} programs, manifest has "
             f"{len(covered)}")

    miss0, hit0 = DV.JIT["miss"], DV.JIT["hit"]
    drive_width(5)    # committee bucket 8
    drive_width(12)   # committee bucket 16 — the PR-15 width change
    misses = DV.JIT["miss"] - miss0
    hits = DV.JIT["hit"] - hit0

    if misses:
        cold = sorted(DV._SEEN_PROGRAMS - covered)
        fail(f"{misses} post-warmup first-use compile(s); programs "
             f"outside the manifest: {cold}")
    if hits <= 0:
        fail("drive dispatched no warm programs — smoke drove nothing")
    uncovered = sorted(DV._SEEN_PROGRAMS - covered)
    if uncovered:
        fail(f"programs dispatched outside the manifest: {uncovered}")

    print(
        "compile_surface_smoke OK: committee width 5 -> 12 (bucket "
        f"8 -> 16), {hits} warm dispatches, 0 post-warmup compiles "
        f"({stats['warmed']} programs warmed, mode={stats['mode']})",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
