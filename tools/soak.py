"""Sustained-load soak harness: resource STATIONARITY, measured.

ISSUE 14's long-run leg: every chaos scenario finishes in under two
minutes, so a leak that costs 100 KiB/s — fatal within a day on a real
validator — has never been observable.  This harness runs a 4-node
localnet committing FBFT rounds under steady mixed traffic (paced
transfers into the REAL node pools so admission/commit/evict churn is
included, staking POPs on the scheduler's INGRESS lane, a replay
worker on SYNC) for a wall-clock window, samples process resources the
whole time (RSS / open fds / threads from /proc via
``metrics.process_sample``, scheduler queue depth, pool occupancy),
and fits a least-squares REGRESSION SLOPE per signal over the
post-warmup samples.

``--check`` asserts stationarity: each slope inside its bound, net
thread/fd growth bounded, the chain alive, ZERO consensus-lane sheds.
A node that serves the window but climbs monotonically fails — that is
the point.

Slopes are reported per MINUTE (``soak_rss_slope_kib_per_min``, ...):
deliberately outside the bench ledger's ``_per_s`` higher-is-better
direction patterns, since a slope has no goodness direction the ledger
could flag on (smaller-magnitude is better, sign flips legal).

Usage:
    python tools/soak.py                          # 120 s report run
    python tools/soak.py --quick --check          # check.sh stage 10
    python tools/soak.py --quick --check --bench-out BENCH_rNN.json \
        --bench-round NN [--bench-base PRIOR.json]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["HARMONY_KERNEL_TWIN"] = "1"  # twin kernels: real device-
# path layers (tables, bitmaps, scheduler) without XLA pairing compiles

CHAIN_ID = 2
WARMUP_FRACTION = 0.3  # samples in the first 30% of the window are
# warm-up (allocator arenas, jit caches, thread spawn) — stationarity
# is judged on the steady tail


def _m(value, unit: str, **fields) -> dict:
    out = {"value": value, "unit": unit, "source": "measured"}
    out.update(fields)
    return out


def slope_per_s(samples: list) -> float | None:
    """Least-squares slope of (t_seconds, value) pairs, per second."""
    pts = [(t, v) for t, v in samples if v is not None]
    if len(pts) < 3:
        return None
    n = len(pts)
    mean_t = sum(t for t, _ in pts) / n
    mean_v = sum(v for _, v in pts) / n
    var_t = sum((t - mean_t) ** 2 for t, _ in pts)
    if var_t == 0:
        return 0.0
    cov = sum((t - mean_t) * (v - mean_v) for t, v in pts)
    return cov / var_t


class SoakRun:
    """Build the localnet, pour steady traffic, sample resources."""

    def __init__(self, args):
        self.args = args
        self.errors: list = []
        self.samples: list = []  # (t, {signal: value})
        self.submitted = 0
        self._stop = threading.Event()
        self._ready = threading.Event()

    # -- traffic -------------------------------------------------------------

    def _overload_txs(self, ecdsa_keys):
        """Funded-sender transfers — the SAME cycling flood fixture
        the overload_storm scenario pours (chaostest.fixtures), so the
        soak and the storm cannot silently diverge in load shape."""
        from harmony_tpu.chaostest import fixtures as FX

        return FX.overload_transfers(ecdsa_keys, to_byte=0x2f)

    def _pool_flood(self, pools, txs, rate: float, window_s: float):
        """Round-robin paced submission into the REAL node pools for
        the whole window; rejections (caps, replacement) are routine —
        steady churn is the point, not acceptance."""
        from harmony_tpu.chaostest import fixtures as FX
        from harmony_tpu.core.tx_pool import PoolError

        try:
            n = 0
            for i in FX.paced_ticks(rate, self._stop, window_s,
                                    ready=self._ready):
                tx, sender = txs[i % len(txs)]
                try:
                    pools[i % len(pools)].add(tx, sender=sender)
                except PoolError:
                    pass
                n += 1
            self.submitted = n
        except Exception as e:  # noqa: BLE001 — fail the soak loudly
            self.errors.append(f"pool flood: {e!r}")

    def _pop_flood(self, rate: float, window_s: float):
        """Steady staking-POP admissions on the INGRESS lane (a side
        pool: the POP pairing work is the load, not pool state)."""
        from harmony_tpu import bls as B
        from harmony_tpu.core.tx_pool import PoolError, TxPool
        from harmony_tpu.core.types import Directive, StakingTransaction

        class _Stub:
            def nonce(self, addr):
                return 0

            def balance(self, addr):
                return 10**30

        from harmony_tpu.chaostest import fixtures as FX

        try:
            pool = TxPool(CHAIN_ID, 0, _Stub, cap=1 << 16)
            for n in FX.paced_ticks(rate, self._stop, window_s,
                                    ready=self._ready):
                i = n % 64
                bk = B.PrivateKey.generate(bytes([9, i, 1]))
                try:
                    pool.add(StakingTransaction(
                        nonce=n % 16, gas_price=1, gas_limit=50_000,
                        directive=Directive.CREATE_VALIDATOR,
                        fields={
                            "amount": 10**20,
                            "min_self_delegation": 10**18,
                            "bls_keys": bk.pub.bytes,
                            "bls_key_sigs": B.proof_of_possession(bk),
                        },
                    ), is_staking=True,
                        sender=bytes([0x51, i]) + b"\x00" * 18)
                except PoolError:
                    pass
        except Exception as e:  # noqa: BLE001
            self.errors.append(f"pop flood: {e!r}")

    def _replay_worker(self, nodes, mk_chain):
        try:
            while not self._stop.is_set():
                head = nodes[0].chain.head_number
                if head < 1:
                    time.sleep(0.05)
                    continue
                replica = mk_chain()
                blocks, proofs = [], []
                for n in range(1, head + 1):
                    blk = nodes[0].chain.block_by_number(n)
                    proof = nodes[0].chain.read_commit_sig(n)
                    if blk is None or proof is None:
                        break
                    blocks.append(blk)
                    proofs.append(proof)
                if blocks:
                    replica.insert_chain(blocks, commit_sigs=proofs,
                                         verify_seals=True)
        except Exception as e:  # noqa: BLE001
            self.errors.append(f"replay worker: {e!r}")

    # -- sampling ------------------------------------------------------------

    def _sampler(self, pools, interval_s: float):
        from harmony_tpu.metrics import process_sample
        from harmony_tpu.sched.scheduler import max_queue_depth

        self._ready.wait()
        start = time.monotonic()
        while not self._stop.is_set():
            s = process_sample()
            s["queue_depth"] = max_queue_depth()
            s["pool_txs"] = sum(len(p) for p in pools)
            self.samples.append((time.monotonic() - start, s))
            self._stop.wait(interval_s)

    # -- the run -------------------------------------------------------------

    def run(self) -> dict:
        from harmony_tpu import device as DV
        from harmony_tpu import sched, trace
        from harmony_tpu.chain.engine import Engine, EpochContext
        from harmony_tpu.core.blockchain import Blockchain
        from harmony_tpu.core.genesis import dev_genesis
        from harmony_tpu.core.kv import MemKV
        from harmony_tpu.core.tx_pool import TxPool
        from harmony_tpu.multibls import PrivateKeys
        from harmony_tpu.node.node import Node
        from harmony_tpu.node.registry import Registry
        from harmony_tpu.p2p import InProcessNetwork

        args = self.args
        trace.configure(enabled=True)
        DV.use_device(True)
        sched.reset()
        sched.configure(flush_window_s=0.01)

        genesis, ecdsa_keys, bls_keys = dev_genesis(
            n_accounts=32, n_keys=args.nodes,
        )
        committee = [k.pub.bytes for k in bls_keys]
        shared_ctx = EpochContext(committee)

        def mk_chain():
            return Blockchain(
                MemKV(), genesis,
                engine=Engine(lambda s, e: shared_ctx, device=True),
                blocks_per_epoch=16,
            )

        net = InProcessNetwork()
        nodes, pools = [], []
        for i in range(args.nodes):
            chain = mk_chain()
            pool = TxPool(CHAIN_ID, 0, chain.state)
            reg = Registry(blockchain=chain, txpool=pool,
                           host=net.host(f"soak{i}"))
            nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))
            pools.append(pool)

        txs = self._overload_txs(ecdsa_keys)
        workers = [
            threading.Thread(
                target=self._pool_flood,
                args=(pools, txs, args.rate, args.window), daemon=True,
            ),
            threading.Thread(
                target=self._pop_flood,
                args=(args.pop_rate, args.window), daemon=True,
            ),
            threading.Thread(
                target=self._replay_worker, args=(nodes, mk_chain),
                daemon=True,
            ),
            threading.Thread(
                target=self._sampler,
                args=(pools, args.sample_interval), daemon=True,
            ),
        ]
        pumps = []
        t0 = time.monotonic()
        try:
            for w in workers:
                w.start()
            pumps = [
                n.run_forever(poll_interval=0.002, block_time=0.25,
                              phase_timeout=120.0)
                for n in nodes
            ]
            # short maintenance period so evict_stale churn is part of
            # what the soak measures
            for n in nodes:
                n.maintenance_interval_s = 5.0
            self._ready.set()
            deadline = t0 + args.window + args.timeout
            while time.monotonic() < deadline:
                if self.errors:
                    raise SystemExit(
                        "soak worker errors: " + "; ".join(self.errors)
                    )
                if time.monotonic() - t0 >= args.window and all(
                    n.chain.head_number >= args.rounds for n in nodes
                ):
                    break
                time.sleep(0.1)
            else:
                raise SystemExit(
                    "soak stalled: heads="
                    f"{[n.chain.head_number for n in nodes]} after "
                    f"{args.window + args.timeout:.0f}s"
                )
        finally:
            # the measured window ENDS when the drive loop exits —
            # worker/pump join latency below must not inflate the
            # denominator of the ledger-gated soak_submitted_tx_per_s
            # (a slow teardown would read as a phantom throughput
            # regression)
            window_s = time.monotonic() - t0
            self._stop.set()
            for w in workers:
                w.join(timeout=30)
            for n in nodes:
                n.stop()
            for p in pumps:
                p.join(timeout=10)
        if self.errors:
            raise SystemExit(
                "soak worker errors: " + "; ".join(self.errors)
            )
        return {
            "heads": [n.chain.head_number for n in nodes],
            "window_s": window_s,
        }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--window", type=float, default=120.0,
                    help="soak window, seconds (default 120)")
    ap.add_argument("--rate", type=float, default=800.0,
                    help="steady pool-submission pace, tx/s")
    ap.add_argument("--pop-rate", type=float, default=8.0,
                    help="staking-POP admissions/s (INGRESS lane)")
    ap.add_argument("--rounds", type=int, default=8,
                    help="minimum FBFT rounds that must commit")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--sample-interval", type=float, default=0.5)
    ap.add_argument("--timeout", type=float, default=240.0,
                    help="grace past the window before declaring a "
                         "stall")
    ap.add_argument("--quick", action="store_true",
                    help="CI-budget window (check.sh stage 10)")
    ap.add_argument("--check", action="store_true",
                    help="assert the stationarity bounds; exit 1 on "
                         "violation")
    ap.add_argument("--rss-slope-max-kib-s", type=float, default=512.0,
                    help="max steady-state RSS slope, KiB/s")
    ap.add_argument("--thread-slope-max-s", type=float, default=0.25,
                    help="max thread-count slope, threads/s")
    ap.add_argument("--fd-slope-max-s", type=float, default=1.0,
                    help="max open-fd slope, fds/s")
    ap.add_argument("--queue-slope-max-s", type=float, default=4.0,
                    help="max scheduler queue-depth slope, items/s")
    ap.add_argument("--bench-out", default=None,
                    help="write a BENCH round file (ledger schema)")
    ap.add_argument("--bench-round", type=int, default=9)
    ap.add_argument("--bench-base", default=None,
                    help="existing bench JSON whose metrics ride "
                         "alongside in --bench-out")
    args = ap.parse_args(argv)
    if args.quick:
        args.window = min(args.window, 22.0)
        args.rate = min(args.rate, 300.0)
        args.rounds = min(args.rounds, 4)

    run = SoakRun(args)
    outcome = run.run()

    # -- stationarity fit ----------------------------------------------------
    warm_t = args.window * WARMUP_FRACTION
    tail = [(t, s) for t, s in run.samples if t >= warm_t]

    def sig(name):
        return slope_per_s([(t, s.get(name)) for t, s in tail])

    rss_slope = sig("rss_bytes")
    fd_slope = sig("open_fds")
    thread_slope = sig("threads")
    queue_slope = sig("queue_depth")
    pool_slope = sig("pool_txs")
    last = run.samples[-1][1] if run.samples else {}
    net = {}
    if tail:
        first = tail[0][1]
        for key in ("open_fds", "threads"):
            a, b = first.get(key), last.get(key)
            net[key] = (b - a) if (a is not None and b is not None) \
                else None

    from harmony_tpu.sched.scheduler import SHED

    sheds = sum(
        SHED.value(lane="consensus", reason=r)
        for r in ("breaker_open", "queue_full", "deadline", "expired",
                  "governor")
    )

    def _kib_min(v):
        return None if v is None else round(v * 60 / 1024, 2)

    def _per_min(v):
        return None if v is None else round(v * 60, 3)

    extra = {
        "soak_rss_slope_kib_per_min": _m(
            _kib_min(rss_slope), "KiB/min",
            bound_kib_per_min=round(args.rss_slope_max_kib_s * 60, 1),
        ),
        "soak_fd_slope_per_min": _m(
            _per_min(fd_slope), "fds/min",
            net_growth=net.get("open_fds"),
        ),
        "soak_thread_slope_per_min": _m(
            _per_min(thread_slope), "threads/min",
            net_growth=net.get("threads"),
        ),
        "soak_queue_slope_per_min": _m(
            _per_min(queue_slope), "items/min",
        ),
        "soak_pool_slope_per_min": _m(_per_min(pool_slope), "txs/min"),
        "soak_rss_final_mib": _m(
            round((last.get("rss_bytes") or 0) / (1 << 20), 1), "MiB",
        ),
        "soak_threads_final": _m(last.get("threads"), "threads"),
        "soak_fds_final": _m(last.get("open_fds"), "fds"),
        "soak_submitted_tx_per_s": _m(
            round(run.submitted / outcome["window_s"], 1), "tx/s",
        ),
        "soak_blocks_min": _m(min(outcome["heads"]), "blocks",
                              floor=args.rounds),
        "soak_samples": _m(len(run.samples), "samples",
                           window_s=round(outcome["window_s"], 1)),
    }
    checks = [
        ("samples_collected", len(tail) >= 8),
        ("rss_stationary",
         rss_slope is not None
         and rss_slope <= args.rss_slope_max_kib_s * 1024),
        ("threads_stationary",
         thread_slope is not None
         and thread_slope <= args.thread_slope_max_s
         and (net.get("threads") is None or net["threads"] <= 8)),
        ("fds_stationary",
         fd_slope is not None and fd_slope <= args.fd_slope_max_s
         and (net.get("open_fds") is None or net["open_fds"] <= 16)),
        ("queue_stationary",
         queue_slope is None or queue_slope <= args.queue_slope_max_s),
        ("liveness", min(outcome["heads"]) >= args.rounds),
        ("zero_consensus_sheds", sheds == 0),
    ]
    doc = {
        "metric": "soak_rss_slope_kib_per_min",
        "value": _kib_min(rss_slope),
        "unit": "KiB/min",
        "source": "measured",
        "extra": extra,
        "meta": {
            "window_s": round(outcome["window_s"], 1),
            "heads": outcome["heads"],
            "quick": args.quick,
            "checks": {name: ok for name, ok in checks},
        },
    }
    print(json.dumps(doc), flush=True)

    if args.bench_out:
        parsed = doc
        if args.bench_base:
            with open(args.bench_base) as f:
                base = json.load(f)
            base_parsed = base.get("parsed", base)
            merged = dict(base_parsed)
            merged.setdefault("extra", {})
            merged["extra"] = dict(merged["extra"])
            merged["extra"].update(extra)
            parsed = merged
        with open(args.bench_out, "w") as f:
            json.dump({
                "n": args.bench_round,
                "cmd": "python tools/soak.py"
                       + (" --quick" if args.quick else ""),
                "parsed": parsed,
            }, f, indent=2)
            f.write("\n")
        print(f"soak: wrote {args.bench_out} "
              f"(round {args.bench_round})", file=sys.stderr)

    failed = [name for name, ok in checks if not ok]
    if failed:
        print(f"soak: FAILED checks: {failed}", file=sys.stderr)
        if args.check:
            return 1
    return 0


if __name__ == "__main__":
    rc = main()
    # hard exit, like chaos_sweep: daemon pump/scheduler threads racing
    # CPython teardown can abort AFTER the verdict is decided
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
