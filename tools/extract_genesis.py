#!/usr/bin/env python3
"""Extract the reference's genesis account tables into a data artifact.

The reference carries its mainnet/testnet genesis committees as Go
source (reference: internal/genesis/*.go, ~7k lines of DeployAccount
literals).  Those are CHAIN CONSTANTS — public addresses + BLS pubkeys
that any parity implementation must agree on byte-for-byte — so this
tool transcribes them once into
harmony_tpu/config/genesis_accounts.json.gz and the framework loads
the artifact (harmony_tpu/config/genesis_accounts.py).

Rerun after a reference update:
    python tools/extract_genesis.py [/path/to/reference]
"""

from __future__ import annotations

import gzip
import json
import os
import re
import sys

_TABLE_RE = re.compile(
    r"var\s+(\w+)\s*=\s*\[\]DeployAccount\s*\{(.*?)\n\}", re.S
)
_ENTRY_RE = re.compile(
    r'Index:\s*"\s*([\d]+)\s*"\s*,\s*Address:\s*"(\w+)"\s*,'
    r'\s*BLSPublicKey:\s*"([0-9a-fA-F]+)"'
)

FILES = (
    "foundational.go",
    "harmony.go",
    "localnodes.go",
    "newnodes.go",
    "tn_harmony.go",
    "pangaea.go",
    "foundational_pangaea.go",
)


def extract(ref_dir: str) -> dict:
    tables: dict[str, list] = {}
    gen_dir = os.path.join(ref_dir, "internal", "genesis")
    for fname in FILES:
        path = os.path.join(gen_dir, fname)
        if not os.path.exists(path):
            continue
        src = open(path).read()
        for m in _TABLE_RE.finditer(src):
            name, body = m.group(1), m.group(2)
            entries = [
                {"index": int(e.group(1)), "address": e.group(2),
                 "bls": e.group(3).lower()}
                for e in _ENTRY_RE.finditer(body)
            ]
            if entries:
                tables[name] = entries
    return tables


def main() -> int:
    ref = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    tables = extract(ref)
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "harmony_tpu", "config", "genesis_accounts.json.gz",
    )
    blob = json.dumps(tables, separators=(",", ":"), sort_keys=True)
    with gzip.open(out, "wb", compresslevel=9) as f:
        f.write(blob.encode())
    total = sum(len(v) for v in tables.values())
    print(f"{len(tables)} tables, {total} accounts -> {out} "
          f"({os.path.getsize(out)} bytes)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
