#!/usr/bin/env python3
"""AOT-export the production quorum-check programs (VERDICT r4 #2).

Every round so far burned its only TPU contact on COMPILING the
pairing programs instead of measuring them.  ``jax.export`` lowers a
jitted function to serialized StableHLO without touching any backend
(tracing + emission only — seconds on CPU), and the artifact carries a
TPU lowering: the first live relay contact deserializes and compiles
on the TPU toolchain (fast) instead of re-tracing Python, and bench.py
measures inside its budget.

Exports (the pinned production shapes of device.py):
  agg_verify     at every committee bucket   (the FBFT quorum check)
  verify         at the width-8 lane bucket  (single signature checks)
  agg_verify_batch at (1024-key table x 64)  (the replay shape)

Run:  python tools/aot_export.py [--out DIR]
Load: jax.export.deserialize(path.read_bytes()).call(*args)
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_OUT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "artifacts", "aot"
)

# committee buckets worth shipping (device.py COMMITTEE_BUCKETS; 1024
# covers the BASELINE 1000-key config)
AGG_BUCKETS = (8, 16, 32, 64, 128, 256, 1024)
REPLAY_SHAPE = (1024, 64)  # (committee bucket, batch lanes)


def export_all(out_dir: str) -> list:
    import jax

    jax.config.update("jax_platforms", "cpu")  # lowering needs no device
    import jax.numpy as jnp
    from jax import export as jexport

    from harmony_tpu.ops import bls as OB

    os.makedirs(out_dir, exist_ok=True)
    written = []

    def emit(name: str, fn, *specs):
        import gzip

        path = os.path.join(out_dir, name + ".jaxexport.gz")
        if os.path.exists(path) or os.path.exists(path[:-3]):
            print(f"  {name}: exists, skipped")
            return
        exp = jexport.export(
            jax.jit(fn), platforms=("tpu", "cpu")
        )(*specs)
        blob = exp.serialize()
        with gzip.open(path, "wb", compresslevel=9) as f:
            f.write(blob)
        written.append((name, len(blob)))
        print(f"  {name}: {len(blob):,} bytes")

    i32 = jnp.int32

    def S(shape):
        return jax.ShapeDtypeStruct(shape, i32)

    for n in AGG_BUCKETS:
        emit(
            f"agg_verify_b{n}", OB.agg_verify,
            S((n, 2, 32)), S((n,)), S((2, 2, 32)), S((2, 2, 32)),
        )
    emit(
        "verify_w8", OB.verify,
        S((8, 2, 32)), S((8, 2, 2, 32)), S((8, 2, 2, 32)),
    )
    n, b = REPLAY_SHAPE
    emit(
        f"agg_verify_batch_b{n}x{b}", OB.agg_verify_batch,
        S((n, 2, 32)), S((b, n)), S((b, 2, 2, 32)), S((b, 2, 2, 32)),
    )
    return written


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_OUT)
    args = ap.parse_args()
    written = export_all(args.out)
    total = sum(sz for _, sz in written)
    print(f"{len(written)} artifacts, {total:,} bytes -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
