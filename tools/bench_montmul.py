"""Microbenchmark: mont_mul scan (fp.py) vs Pallas kernel (fp_pallas.py)
on the current default JAX platform, at pairing-realistic shapes.

Usage: python tools/bench_montmul.py [rows ...]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench(fn, args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    sys.path.insert(0, ".")
    from harmony_tpu.ops import fp
    from harmony_tpu.ops.fp_pallas import mont_mul_pallas

    rows_list = [int(x) for x in sys.argv[1:]] or [1024, 16384, 55296]
    chain = 64  # muls chained inside ONE jit: amortizes dispatch latency
    rng = np.random.default_rng(0)

    def chained(mul):
        def fn(a, b):
            c = a
            for _ in range(chain):
                c = mul(c, b)
            return c
        return fn

    for rows in rows_list:
        a = jnp.asarray(
            rng.integers(0, 4096, size=(rows, 32), dtype=np.int32)
        )
        b = jnp.asarray(
            rng.integers(0, 4096, size=(rows, 32), dtype=np.int32)
        )
        scan_fn = jax.jit(chained(fp.mont_mul))
        t_scan = bench(scan_fn, (a, b)) / chain
        try:
            pallas_fn = jax.jit(chained(mont_mul_pallas))
            t_pal = bench(pallas_fn, (a, b)) / chain
            same = bool(jnp.all(scan_fn(a, b) == pallas_fn(a, b)))
        except Exception as e:  # noqa: BLE001
            t_pal, same = float("nan"), f"ERR {type(e).__name__}: {e}"
        mps = rows / t_pal / 1e6 if t_pal == t_pal else 0
        print(
            f"rows={rows}: scan {t_scan*1e6:.0f}us "
            f"pallas {t_pal*1e6:.0f}us ({t_scan/t_pal:.1f}x, "
            f"{mps:.0f}M muls/s) match={same}",
            flush=True,
        )


if __name__ == "__main__":
    main()
