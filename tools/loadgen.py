"""Sustained-traffic load generator: latency under load, measured.

ROADMAP item 4's harness (arXiv:2302.00418 is the yardstick: committee
consensus is gated by verification latency UNDER LOAD, not by peak
kernel throughput; Handel, arXiv:1906.05132, sets the committee-scale
load shape).  A 4-node threaded localnet commits FBFT rounds while

  * plain-transfer floods hit tx-pool admission at a paced, configurable
    tx/s rate (the RPC-submit shape; senders pre-recovered exactly as
    the gossip pre-filter hands them over — the pure-Python secp256k1
    stand-in must not be what a TPU repo's load harness measures),
  * staking submissions carrying BLS proofs-of-possession verify on the
    scheduler's INGRESS lane,
  * replay workers re-verify the committed chain down the SYNC lane,

and the REPORTED numbers come straight from the PR-4 observability
surfaces: round p50/p99 from the tracer's ``consensus.round`` spans
(cross-checked against the ``harmony_consensus_round_seconds``
histogram via ``Histogram.quantile``) and ingress latency from the
``harmony_sched_wait_seconds{lane="ingress"}`` histogram.  No
hand-parsed bucket counts, no synthetic timers around the thing being
measured.

``--check`` (check.sh stage 6) asserts the floors: the Prometheus
exposition parses, every scheduler lane carried traffic, ZERO
consensus-lane sheds, the submitted rate holds its floor, and the
latency grammar is sane (0 < p50 <= p99).  Every metric in the output
line is ledger-tagged ``source: measured``.

Usage:
    python tools/loadgen.py                      # report mode
    python tools/loadgen.py --duration 5 --check # the CI gate
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import pathlib
import sys
import threading
import time

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["HARMONY_KERNEL_TWIN"] = "1"  # twin kernels: real device-
# path layers (tables, bitmaps, scheduler) without XLA pairing compiles

from obs_smoke import validate_prometheus  # noqa: E402 — same dir

CHAIN_ID = 2


def _m(value, unit: str, **fields) -> dict:
    out = {"value": value, "unit": unit, "source": "measured"}
    out.update(fields)
    return out


def _quantiles(values: list) -> tuple:
    """Exact (p50, p99) of raw samples."""
    if not values:
        return None, None
    s = sorted(values)
    return (s[len(s) // 2],
            s[min(len(s) - 1, int(len(s) * 0.99))])


class _StubState:
    """Balance/nonce view for the side pools — admission sees funded,
    fresh senders without a chain behind them."""

    def nonce(self, addr) -> int:
        return 0

    def balance(self, addr) -> int:
        return 10**30


class LoadRun:
    def __init__(self, args, registry):
        self.args = args
        self.registry = registry
        self.errors: list = []
        # one (category, count, elapsed_s) record PER flood thread,
        # appended under the lock: the submitted rate is computed over
        # the window each flood actually RAN, never over the post-flood
        # wait for rounds to commit, and never through a racy shared
        # read-modify-write counter
        self.floods_done: list = []
        self._floods_lock = threading.Lock()
        self.round_durs: dict = {}  # span_id -> dur_s (tracer-derived)
        self._stop = threading.Event()
        self._ready = threading.Event()

    # -- fixture builders (untimed) ------------------------------------------

    def _plain_txs(self, count: int, tag: int):
        """Unsigned transfers + synthetic pre-recovered senders: the
        shape admission sees after signature recovery, which is what
        this harness paces (the recover itself is the stand-in's cost,
        not the system's)."""
        from harmony_tpu.core.types import Transaction

        out = []
        per_sender = 16  # ACCOUNT_SLOTS: stay in the executable tier
        n_senders = (count + per_sender - 1) // per_sender
        for s in range(n_senders):
            sender = bytes([0x4c, tag, s // 256, s % 256]) + b"\x00" * 16
            for n in range(min(per_sender, count - s * per_sender)):
                out.append((Transaction(
                    nonce=n, gas_price=1, gas_limit=21_000, shard_id=0,
                    to_shard=0, to=b"\x2d" * 20, value=1,
                ), sender))
        return out

    def _pop_txs(self, count: int, tag: int):
        """CREATE_VALIDATOR submissions whose BLS proofs-of-possession
        verify on the INGRESS lane (2 keys each — one fused 2-wide
        check per admission).  Same shape as the plain flood: one
        sender per 16 txs with contiguous nonces, so every submission
        lands in the executable tier."""
        from harmony_tpu import bls as B
        from harmony_tpu.core.types import Directive, StakingTransaction

        out = []
        for i in range(count):
            group = i // 16
            sender = bytes([0x50, tag, group // 256, group % 256]
                           ) + b"\x00" * 16
            bks = [B.PrivateKey.generate(bytes([tag, i % 251, j]))
                   for j in range(2)]
            out.append((StakingTransaction(
                nonce=i % 16, gas_price=1, gas_limit=50_000,
                directive=Directive.CREATE_VALIDATOR,
                fields={
                    "amount": 10**20, "min_self_delegation": 10**18,
                    "bls_keys": b"".join(k.pub.bytes for k in bks),
                    "bls_key_sigs": b"".join(
                        B.proof_of_possession(k) for k in bks
                    ),
                },
            ), sender))
        return out

    # -- workers -------------------------------------------------------------

    def _paced_flood(self, txs, rate: float, is_staking: bool,
                     category: str):
        """Token-bucket paced pool.add flood; records (count, window)."""
        from harmony_tpu.core.tx_pool import PoolError, TxPool

        try:
            pool = TxPool(CHAIN_ID, 0, _StubState, cap=len(txs) + 64)
            self._ready.wait()
            start = time.monotonic()
            n = 0
            for i, (tx, sender) in enumerate(txs):
                if self._stop.is_set():
                    break
                target = start + i / rate
                now = time.monotonic()
                if now < target:
                    time.sleep(min(target - now, 0.05))
                try:
                    pool.add(tx, is_staking=is_staking, sender=sender)
                except PoolError:
                    pass  # replacement/caps: still a submission
                n += 1
            elapsed = time.monotonic() - start
            with self._floods_lock:
                self.floods_done.append((category, n, elapsed))
        except Exception as e:  # noqa: BLE001 — fail the harness loudly
            self.errors.append(f"{category} flood: {e!r}")

    def _replay_worker(self, nodes, mk_chain):
        """Re-verify the committed chain into fresh replicas — the
        SYNC-lane seal batches concurrent with live rounds."""
        try:
            while not self._stop.is_set():
                head = nodes[0].chain.head_number
                if head < 1:
                    time.sleep(0.01)
                    continue
                replica = mk_chain()
                blocks, proofs = [], []
                for n in range(1, head + 1):
                    blk = nodes[0].chain.block_by_number(n)
                    proof = nodes[0].chain.read_commit_sig(n)
                    if blk is None or proof is None:
                        break
                    blocks.append(blk)
                    proofs.append(proof)
                if blocks:
                    replica.insert_chain(blocks, commit_sigs=proofs,
                                         verify_seals=True)
        except Exception as e:  # noqa: BLE001
            self.errors.append(f"replay worker: {e!r}")

    def _sweep_round_spans(self):
        from harmony_tpu import trace

        for s in trace.spans():
            if s.name == "consensus.round" and s.dur_s is not None:
                self.round_durs[s.span_id] = s.dur_s

    def _round_collector(self):
        """Poll the tracer for finished consensus.round spans — the
        bounded span store must not age them out before we read them."""
        while not self._stop.is_set():
            self._sweep_round_spans()
            time.sleep(0.25)

    # -- the run -------------------------------------------------------------

    def run(self) -> None:
        from harmony_tpu import device as DV
        from harmony_tpu import sched, trace
        from harmony_tpu.chain.engine import Engine, EpochContext
        from harmony_tpu.core.blockchain import Blockchain
        from harmony_tpu.core.genesis import dev_genesis
        from harmony_tpu.core.kv import MemKV
        from harmony_tpu.core.tx_pool import TxPool
        from harmony_tpu.multibls import PrivateKeys
        from harmony_tpu.node.node import Node
        from harmony_tpu.node.registry import Registry
        from harmony_tpu.p2p import InProcessNetwork

        args = self.args
        trace.configure(enabled=True)
        DV.use_device(True)
        sched.reset()
        sched.configure(flush_window_s=0.01)

        genesis, _, bls_keys = dev_genesis(n_keys=args.nodes)
        committee = [k.pub.bytes for k in bls_keys]
        shared_ctx = EpochContext(committee)

        def mk_chain():
            return Blockchain(
                MemKV(), genesis,
                engine=Engine(lambda s, e: shared_ctx, device=True),
                blocks_per_epoch=16,
            )

        net = InProcessNetwork()
        nodes = []
        for i in range(args.nodes):
            chain = mk_chain()
            pool = TxPool(CHAIN_ID, 0, chain.state)
            reg = Registry(blockchain=chain, txpool=pool,
                           host=net.host(f"node{i}"))
            reg.set("metrics", self.registry)
            nodes.append(Node(reg, PrivateKeys.from_keys([bls_keys[i]])))

        # fixtures before the clock starts
        plain_target = int(args.rate * args.duration * 1.25)
        pop_target = max(8, int(args.pop_rate * args.duration))
        half = (plain_target + 1) // 2
        floods = [
            (self._plain_txs(half, 1), args.rate / 2, False, "plain"),
            (self._plain_txs(plain_target - half, 2), args.rate / 2,
             False, "plain"),
            (self._pop_txs(pop_target, 3), args.pop_rate, True, "pop"),
        ]
        workers = [
            threading.Thread(target=self._paced_flood, args=f,
                             daemon=True)
            for f in floods
        ]
        workers += [
            threading.Thread(target=self._replay_worker,
                             args=(nodes, mk_chain), daemon=True)
            for _ in range(2)
        ]
        collector = threading.Thread(target=self._round_collector,
                                     daemon=True)

        pumps = []
        try:
            for w in workers:
                w.start()
            collector.start()
            pumps = [
                n.run_forever(poll_interval=0.002, block_time=0.2,
                              phase_timeout=120.0)
                for n in nodes
            ]
            self._ready.set()
            deadline = time.monotonic() + args.timeout
            while time.monotonic() < deadline:
                if self.errors:
                    # a dead worker never reaches floods_done — fail
                    # NOW with its exception, not a 240s stall message
                    raise SystemExit(
                        "worker errors: " + "; ".join(self.errors)
                    )
                rounds_ok = all(
                    n.chain.head_number >= args.rounds for n in nodes
                )
                with self._floods_lock:
                    floods_ok = len(self.floods_done) == len(floods)
                if rounds_ok and floods_ok:
                    break
                time.sleep(0.05)
            else:
                raise SystemExit(
                    "loadgen localnet stalled: heads="
                    f"{[n.chain.head_number for n in nodes]}, "
                    f"floods done {len(self.floods_done)}/{len(floods)}"
                )
        finally:
            self._stop.set()
            for w in workers:
                w.join(timeout=60)
            collector.join(timeout=10)
            for n in nodes:
                n.stop()
            for p in pumps:
                p.join(timeout=10)
            # the round that satisfied --rounds often finishes after
            # the collector's last poll — sweep once more before the
            # store is read (a missed tail round skews p99 low, and a
            # --rounds 1 run could report no spans at all)
            self._sweep_round_spans()
        if self.errors:
            raise SystemExit("worker errors: " + "; ".join(self.errors))


def scrape(port: int, path: str) -> bytes:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    if resp.status != 200:
        raise SystemExit(f"GET {path} -> {resp.status}")
    return body


def _metric_sum(text: str, name: str, **labels) -> float:
    import re

    total = 0.0
    for line in text.splitlines():
        m = re.match(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? (.+)$", line
        )
        if m is None or m.group(1) != name:
            continue
        got = dict(re.findall(r'(\w+)="([^"]*)"', m.group(3) or ""))
        if all(got.get(k) == v for k, v in labels.items()):
            total += float(m.group(4))
    return total


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=1500.0,
                    help="plain-submission pace, tx/s (default 1500)")
    ap.add_argument("--rate-floor", type=float, default=1000.0,
                    help="--check fails below this submitted tx/s")
    ap.add_argument("--pop-rate", type=float, default=20.0,
                    help="staking-POP submissions/s on the INGRESS lane")
    ap.add_argument("--duration", type=float, default=6.0,
                    help="flood window, seconds")
    ap.add_argument("--rounds", type=int, default=2,
                    help="minimum FBFT rounds that must commit")
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--timeout", type=float, default=240.0)
    ap.add_argument("--check", action="store_true",
                    help="assert the floors; exit 1 on violation")
    args = ap.parse_args(argv)

    from harmony_tpu.metrics import MetricsServer, Registry
    from harmony_tpu.sched.scheduler import WAIT_SECONDS, Lane

    registry = Registry()
    run = LoadRun(args, registry)
    run.run()

    srv = MetricsServer(registry, port=0).start()
    try:
        text = scrape(srv.port, "/metrics").decode()
    finally:
        srv.stop()

    # -- collect the report numbers ------------------------------------------
    # rate per category over the window that category's floods RAN
    # (concurrent same-pace threads: the slowest sibling's window),
    # summed — the post-flood wait for rounds never dilutes it
    def _cat_rate(cat):
        recs = [(n, e) for c, n, e in run.floods_done if c == cat]
        if not recs:
            return 0, 0.0, 0.0
        window = max(e for _, e in recs)
        total = sum(n for n, _ in recs)
        return total, (total / window if window else 0.0), window

    n_plain, plain_rate, plain_window = _cat_rate("plain")
    n_pop, pop_rate, pop_window = _cat_rate("pop")
    submitted = n_plain + n_pop
    rate = plain_rate + pop_rate
    span_p50, span_p99 = _quantiles(list(run.round_durs.values()))
    round_hist = registry.histogram("harmony_consensus_round_seconds")
    ingress_hist = WAIT_SECONDS[Lane.INGRESS]
    sheds = _metric_sum(text, "harmony_sched_shed_total",
                        lane="consensus")
    lanes = {
        lane for lane in ("consensus", "sync", "ingress")
        if _metric_sum(text, "harmony_sched_items_total", lane=lane)
    }

    extra = {
        # rate = Σ per-category count/window — the windows are stamped
        # per category so the record is self-consistent (the slow POP
        # flood's window must not be divided into the plain count)
        "submitted_tx_per_s": _m(round(rate, 1), "tx/s",
                                 floor=args.rate_floor,
                                 plain_rate=round(plain_rate, 1),
                                 plain_window_s=round(plain_window, 2),
                                 pop_rate=round(pop_rate, 1),
                                 pop_window_s=round(pop_window, 2)),
        "submitted_total": _m(submitted, "txs",
                              plain=n_plain, pop=n_pop),
        "round_p50_s": _m(span_p50 and round(span_p50, 4), "s",
                          derived_from="tracer_spans",
                          rounds=len(run.round_durs)),
        "round_p99_s": _m(span_p99 and round(span_p99, 4), "s",
                          derived_from="tracer_spans",
                          rounds=len(run.round_durs)),
        "round_hist_p50_s": _m(
            _r(round_hist.quantile(0.5)), "s",
            derived_from="metrics_histogram"),
        "round_hist_p99_s": _m(
            _r(round_hist.quantile(0.99)), "s",
            derived_from="metrics_histogram"),
        "ingress_wait_p50_s": _m(
            _r(ingress_hist.quantile(0.5)), "s",
            derived_from="metrics_histogram"),
        "ingress_wait_p99_s": _m(
            _r(ingress_hist.quantile(0.99)), "s",
            derived_from="metrics_histogram"),
        "consensus_lane_sheds": _m(sheds, "sheds"),
    }
    checks = [
        ("prometheus_grammar", not validate_prometheus(text)),
        ("all_lanes_active",
         lanes == {"consensus", "sync", "ingress"}),
        ("zero_consensus_sheds", sheds == 0),
        ("rate_floor", rate >= args.rate_floor),
        ("round_latency_grammar",
         span_p50 is not None and span_p99 is not None
         and 0 < span_p50 <= span_p99),
        ("ingress_latency_grammar",
         ingress_hist.quantile(0.5) is not None
         and ingress_hist.quantile(0.5)
         <= (ingress_hist.quantile(0.99) or 0)),
    ]
    out = {
        "metric": "loadgen_submitted_tx_per_s",
        "value": round(rate, 1),
        "unit": "tx/s",
        "source": "measured",
        "extra": extra,
        "meta": {
            "nodes": args.nodes,
            "lanes_active": sorted(lanes),
            "checks": {name: ok for name, ok in checks},
        },
    }
    print(json.dumps(out), flush=True)
    failed = [name for name, ok in checks if not ok]
    if failed:
        print(f"loadgen: FAILED checks: {failed}", file=sys.stderr)
        if args.check:
            return 1
    return 0


def _r(v, digits: int = 5):
    return None if v is None else round(v, digits)


if __name__ == "__main__":
    sys.exit(main())
