"""Metrics: counters/gauges/histograms with Prometheus text exposition.

The role of the reference's prometheus service (reference:
api/service/prometheus/service.go:91-120 — a registry served over
HTTP /metrics; metric families registered from consensus
(consensus/metrics.go:58-96), node pubsub counters (node.go:479+),
p2p, and sync).  Stdlib-only registry + the text format scrapers
consume; the node wires one Registry through its subsystems.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import trace as _TR  # stdlib-only; log.py already imports it


def process_sample() -> dict:
    """Live process resources, stdlib-only (no psutil): RSS and thread
    count from ``/proc/self/status``, open fds from ``/proc/self/fd``.
    Platforms without procfs degrade per-signal to the best stdlib
    fallback (``resource`` high-water RSS, ``threading`` count) or
    ``None`` — a missing signal is simply not judged/exposed."""
    rss = threads = fds = None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("Threads:"):
                    threads = int(line.split()[1])
    except (OSError, ValueError, IndexError):
        pass
    if rss is None:
        try:
            import resource
            import sys

            # high-water mark, not current — an honest degraded
            # signal.  ru_maxrss units differ by platform: bytes on
            # macOS, KiB elsewhere — and this branch only RUNS where
            # procfs is absent, so the Linux KiB convention must not
            # be hardcoded (a 1024x-inflated RSS would pin the
            # governor at CRITICAL forever)
            raw = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            rss = raw if sys.platform == "darwin" else raw * 1024
        except (ImportError, OSError, ValueError):
            rss = None
    if threads is None:
        threads = threading.active_count()
    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = None
    return {"rss_bytes": rss, "open_fds": fds, "threads": threads}


class LockedCounters:
    """Named monotonic counters behind one lock, with a read-only
    dict-like surface (``x["verify"]``, ``dict(x)``, ``.items()``).

    Replaces the bare ``COUNTERS[kind] += 1`` module dict in
    device.py: that read-modify-write raced the consensus, view-change
    and replay threads and lived as three pinned GL03 findings.  One
    uncontended lock per *signature check* (not per signature) is
    noise against the pairing work it counts."""

    def __init__(self, *names: str):
        self._lock = threading.Lock()
        self._v: dict[str, int] = {n: 0 for n in names}

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._v[name] = self._v.get(name, 0) + amount

    def __getitem__(self, name: str) -> int:
        with self._lock:
            return self._v.get(name, 0)

    def __setitem__(self, name: str, value: int) -> None:
        # tests pin counters to known values around a scenario
        with self._lock:
            self._v[name] = int(value)

    def keys(self):
        with self._lock:
            return list(self._v)

    def items(self):
        with self._lock:
            return sorted(self._v.items())

    def __iter__(self):
        return iter(self.keys())


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._values: dict = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        # locked like inc/expose: the bare dict read raced concurrent
        # first-inc inserts (dict resize mid-read) on the consensus
        # threads; Gauge inherits this read too
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum across every label combination (delta accounting over
        a whole family — e.g. governor rejections per run)."""
        with self._lock:
            return sum(self._values.values())

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(
                    f"{self.name}{_fmt_labels(dict(key))} {v:g}"
                )
        return "\n".join(lines)


class Gauge(Counter):
    def set(self, value: float, **labels):
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def expose(self) -> str:
        return super().expose().replace(" counter", " gauge", 1)


class Histogram:
    DEFAULT_BUCKETS = (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
    )

    def __init__(self, name: str, help_: str = "", buckets=None,
                 labels: dict | None = None):
        """``labels``: constant label set stamped on every sample line
        (the scheduler keeps one Histogram per lane under one family
        name this way — the module has no dynamic label indexing)."""
        self.name, self.help = name, help_
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.labels = dict(labels or {})
        self._counts = [0] * (len(self.buckets) + 1)
        # bucket index -> (trace_id, value): the LAST traced
        # observation that landed in each bucket — bounded by the
        # bucket count, so a p99 outlier links straight to the trace
        # that produced it (OpenMetrics exemplars)
        self._exemplars: dict = {}
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, value: float):
        ids = _TR.current_ids()  # None unless tracing is armed
        with self._lock:
            self._sum += value
            self._total += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    if ids is not None:
                        self._exemplars[i] = (ids[0], value)
                    return
            self._counts[-1] += 1
            if ids is not None:
                self._exemplars[len(self.buckets)] = (ids[0], value)

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0 <= q <= 1) from the bucket counts —
        Prometheus histogram_quantile semantics: find the bucket the
        rank lands in, interpolate linearly inside it.  The +Inf bucket
        clamps to the last finite bound (the standard overestimate-free
        convention).  None while the histogram is empty.

        This is the helper that lets loadgen/bench report p99 without
        hand-parsing bucket counts (ISSUE 6 satellite)."""
        with self._lock:
            counts = list(self._counts)
            total = self._total
        if total == 0:
            return None
        q = min(1.0, max(0.0, q))
        rank = q * total
        cum = 0
        for i, b in enumerate(self.buckets):
            prev_cum, cum = cum, cum + counts[i]
            if cum >= rank and counts[i]:
                lo = self.buckets[i - 1] if i else 0.0
                frac = (rank - prev_cum) / counts[i]
                return lo + (b - lo) * frac
        return float(self.buckets[-1])

    def summary(self, quantiles=(0.5, 0.99)) -> dict:
        """{count, sum_s, p50_s, p99_s, ...} — the report-ready digest
        (keys follow ``p{percent}_s`` for each requested quantile)."""
        with self._lock:
            total, sum_ = self._total, self._sum
        out = {"count": total, "sum_s": round(sum_, 6)}
        for q in quantiles:
            v = self.quantile(q)
            key = f"p{q * 100:g}_s"
            out[key] = round(v, 6) if v is not None else None
        return out

    @staticmethod
    def _exemplar_suffix(ex) -> str:
        """OpenMetrics exemplar: ``# {trace_id="…"} value`` appended to
        a _bucket sample — the p99 bucket links to its forensic trace."""
        if ex is None:
            return ""
        trace_id, value = ex
        return f' # {{trace_id="{trace_id}"}} {value:g}'

    def expose(self, exemplars: bool = False) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        base = _fmt_labels(self.labels)
        with self._lock:
            exs = dict(self._exemplars) if exemplars else {}
            cum = 0
            for i, (b, c) in enumerate(zip(self.buckets, self._counts)):
                cum += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**self.labels, 'le': f'{b:g}'})} {cum}"
                    f"{self._exemplar_suffix(exs.get(i))}"
                )
            cum += self._counts[-1]
            lines.append(
                f"{self.name}_bucket"
                f"{_fmt_labels({**self.labels, 'le': '+Inf'})} {cum}"
                f"{self._exemplar_suffix(exs.get(len(self.buckets)))}"
            )
            lines.append(f"{self.name}_sum{base} {self._sum:g}")
            lines.append(f"{self.name}_count{base} {self._total}")
        return "\n".join(lines)


class Registry:
    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_))

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_))

    def histogram(self, name: str, help_: str = "",
                  buckets=None) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_, buckets)
        )

    def _get_or_make(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def expose(self, exemplars: bool = False) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines = [m.expose(exemplars=exemplars)
                 if isinstance(m, Histogram) else m.expose()
                 for m in metrics]
        lines.append(self._device_counters())
        lines.append(self._resilience_counters())
        lines.append(self._sched_counters())
        lines.append(self._p2p_counters())
        lines.append(self._slash_counters())
        netem = self._netem_counters()
        if netem:
            lines.append(netem)
        lines.append(self._process_gauges())
        lines.append(self._health_metrics())
        lines.append(self._governor_metrics())
        prof = self._prof_counters()
        if prof:
            lines.append(prof)
        lines.append(self._aot_counters())
        lines.append(self._snapshot_counters())
        obs = self._obs_counters(exemplars)
        if obs:
            lines.append(obs)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _obs_counters(exemplars: bool = False) -> str:
        """Round-forensics families (obs module singletons) — only
        when the obs package was ever imported (it always is on a full
        node via the chain insert path; pure-metrics tests stay lean)."""
        import sys

        mod = sys.modules.get("harmony_tpu.obs")
        if mod is None:
            return ""
        return mod.expose_metrics(exemplars=exemplars)

    @staticmethod
    def _process_gauges() -> str:
        """Process resource gauges from /proc/self (ISSUE 14 satellite:
        the raw signals the resource governor tiers on, scrapeable even
        where no governor is armed)."""
        s = process_sample()
        names = {
            "rss_bytes": (
                "harmony_process_rss_bytes",
                "resident set size of this process",
            ),
            "open_fds": (
                "harmony_process_open_fds",
                "open file descriptors of this process",
            ),
            "threads": (
                "harmony_process_threads",
                "live threads of this process",
            ),
        }
        out = []
        for key, (name, help_) in names.items():
            v = s.get(key)
            if v is None:
                continue  # signal unavailable on this platform
            out.append(f"# HELP {name} {help_}\n"
                       f"# TYPE {name} gauge\n"
                       f"{name} {v}")
        return "\n".join(out)

    @staticmethod
    def _netem_counters() -> str:
        """Link-conditioning families (chaostest.netem singletons) —
        only when the netem module was ever imported: production
        exposition must not pull the chaos framework in."""
        import sys

        mod = sys.modules.get("harmony_tpu.chaostest.netem")
        if mod is None:
            return ""
        return mod.expose()

    @staticmethod
    def _health_metrics() -> str:
        """Watchdog liveness families (health module singletons)."""
        from . import health as HL

        return HL.expose()

    @staticmethod
    def _governor_metrics() -> str:
        """Resource-governor families (governor module singletons)."""
        from . import governor as GV

        return GV.expose()

    @staticmethod
    def _p2p_counters() -> str:
        """Hostile-wire defense surface (p2p.host module singletons):
        invalid-message verdicts per transport, the throttle/drop/ban
        ladder, and the worst live per-peer score per host."""
        from .p2p import host as PH

        out = [
            "# HELP harmony_p2p_invalid_messages_total invalid-message "
            "events by kind (REJECT verdicts, throttles, drops, bans)",
            "# TYPE harmony_p2p_invalid_messages_total counter",
        ]
        for kind, v in PH.P2P_COUNTERS.items():
            out.append(
                f'harmony_p2p_invalid_messages_total{{kind="{kind}"}} {v}'
            )
        out.append(
            "# HELP harmony_p2p_peer_score worst per-peer gossip "
            "score observed at each host since process start "
            "(a low-water mark, not a live reading)\n"
            "# TYPE harmony_p2p_peer_score gauge"
        )
        for host_name, score in sorted(PH.worst_peer_scores().items()):
            out.append(
                f'harmony_p2p_peer_score{{host="{host_name}"}} {score:g}'
            )
        out.append(PH.INBOUND_VOTES.expose())
        return "\n".join(out)

    @staticmethod
    def _slash_counters() -> str:
        """Double-sign slashing pipeline (staking.slash module
        singletons): detected -> gossiped -> queued -> included ->
        verified -> applied event counts plus the atto amounts moved."""
        from .staking import slash as SL

        out = [
            "# HELP harmony_slash_events_total slashing pipeline "
            "events by stage",
            "# TYPE harmony_slash_events_total counter",
        ]
        for kind, v in SL.COUNTERS.items():
            out.append(
                f'harmony_slash_events_total{{stage="{kind}"}} {v}'
            )
        out.append(
            "# HELP harmony_slash_amount_atto_total atto slashed from "
            "offenders / rewarded to reporters\n"
            "# TYPE harmony_slash_amount_atto_total counter"
        )
        for kind, v in SL.AMOUNTS.items():
            out.append(
                f'harmony_slash_amount_atto_total{{kind="{kind}"}} {v}'
            )
        return "\n".join(out)

    @staticmethod
    def _device_counters() -> str:
        """Device-path liveness (device.COUNTERS): lets a localnet run
        ASSERT over HTTP that quorum checks executed on the device
        path (VERDICT r4 #3 — the flagship path must carry real
        consensus, observably)."""
        from . import device as DV

        out = [
            "# HELP harmony_device_checks_total verification checks "
            "executed on the device path",
            "# TYPE harmony_device_checks_total counter",
        ]
        for kind, v in sorted(DV.COUNTERS.items()):
            out.append(
                f'harmony_device_checks_total{{kind="{kind}"}} {v}'
            )
        out.append(
            "# HELP harmony_device_kernel_twin device kernels are the "
            "host-backed twins (1) vs XLA (0)\n"
            "# TYPE harmony_device_kernel_twin gauge\n"
            f"harmony_device_kernel_twin "
            f"{1 if DV.kernel_twin_active() else 0}"
        )
        # the observability tier (ISSUE 4): dispatch latency histogram,
        # host<->device transfer bytes, jit program-cache hits/misses
        # and last-compile gauges — all module singletons in device.py
        out.append(DV.DISPATCH_SECONDS.expose())
        out.append(
            "# HELP harmony_device_transfer_bytes_total host<->device "
            "bytes shipped by dispatches\n"
            "# TYPE harmony_device_transfer_bytes_total counter"
        )
        for direction, v in DV.TRANSFER.items():
            out.append(
                "harmony_device_transfer_bytes_total"
                f'{{direction="{direction}"}} {v}'
            )
        out.append(
            "# HELP harmony_device_jit_programs_total dispatches that "
            "hit (reused) vs missed (compiled) a program shape\n"
            "# TYPE harmony_device_jit_programs_total counter"
        )
        for kind, v in DV.JIT.items():
            out.append(
                f'harmony_device_jit_programs_total{{cache="{kind}"}} {v}'
            )
        out.append(DV.JIT_COMPILE_SECONDS.expose())
        return "\n".join(out)

    @staticmethod
    def _sched_counters() -> str:
        """Verification-scheduler families (queue depth, per-lane wait,
        batch fill ratio, sheds) — a localnet run can ASSERT over HTTP
        that continuous batching actually coalesced (fill ratio) and
        that the consensus lane never shed (ISSUE 5 acceptance)."""
        from . import sched

        return sched.expose_metrics()

    @staticmethod
    def _aot_counters() -> str:
        """AOT artifact/executable-cache families (aot module
        singletons): fallback-to-jit verdicts by reason and
        content-addressed cache traffic (hit/miss/store/corrupt/skew)
        — the operator's answer to 'did warmup actually warm?'."""
        from . import aot

        return aot.expose()

    @staticmethod
    def _snapshot_counters() -> str:
        """Snapshot serve/bootstrap families (ISSUE 18 module
        singletons): late-join bootstrap attempts by outcome, account
        bytes installed, and responses served to joining peers — the
        operator's answer to 'did the late joiner take the fast path,
        and who is feeding it?'."""
        from .p2p import stream as PS
        from .sync import staged as SS

        return "\n".join([
            SS.SNAPSHOT_BOOTSTRAPS.expose(),
            SS.SNAPSHOT_BYTES.expose(),
            PS.SNAPSHOT_SERVED.expose(),
        ])

    @staticmethod
    def _prof_counters() -> str:
        """Kernel-stage profiler families (stage/execute/compile
        histograms, per-program XLA cost-analysis gauges) — empty
        until the profiler has recorded anything (ISSUE 6)."""
        from . import prof

        return prof.expose()

    @staticmethod
    def _resilience_counters() -> str:
        """Circuit-breaker lifecycle (resilience.TRANSITIONS): lets a
        localnet run ASSERT over HTTP that the node NOTICED a flapping
        backend (open/half_open/close) instead of silently degrading."""
        from . import resilience as RS

        out = [
            "# HELP harmony_resilience_events_total circuit-breaker "
            "transitions and rejected dispatches",
            "# TYPE harmony_resilience_events_total counter",
        ]
        for key, v in RS.TRANSITIONS.items():
            breaker, _, event = key.partition(":")
            out.append(
                "harmony_resilience_events_total"
                f'{{breaker="{breaker}",event="{event}"}} {v}'
            )
        return "\n".join(out)


class MetricsServer:
    """The node's always-on debug listener: GET /metrics (Prometheus
    text), /healthz + /readyz (JSON watchdog/governor verdicts with
    200/503 semantics — the orchestrator probes), /debug/pprof/*
    (mounted from pprof.py — the richer profiles; this server used to
    carry its own weaker stack-dump/profiler copies), and /debug/trace
    (Chrome trace-event JSON from the span tracer's bounded store —
    load it in Perfetto)."""

    def __init__(self, registry: Registry, port: int = 0):
        outer_registry = registry

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = dict(
                    kv.split("=", 1)
                    for kv in query.split("&") if "=" in kv
                )
                status = 200
                try:
                    if path == "/metrics":
                        # ?exemplars=1 opts into the OpenMetrics
                        # trace-id exemplar suffix; the default stays
                        # plain Prometheus 0.0.4 text
                        data = outer_registry.expose(
                            exemplars=params.get("exemplars") == "1"
                        ).encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path == "/healthz":
                        # per-subsystem watchdog verdicts; 503 when any
                        # CRITICAL participant is wedged or dead — the
                        # orchestrator's liveness probe
                        from . import health as HL

                        verdict = HL.verdicts()
                        data = json.dumps(verdict).encode()
                        ctype = "application/json"
                        status = 200 if verdict["ok"] else 503
                    elif path == "/readyz":
                        # liveness AND the governor below its CRITICAL
                        # shed tier — the load balancer's traffic gate
                        from . import health as HL

                        verdict = HL.readiness()
                        data = json.dumps(verdict).encode()
                        ctype = "application/json"
                        status = 200 if verdict["ready"] else 503
                    elif path == "/debug/trace":
                        from . import trace as TR

                        data = json.dumps(
                            TR.export_chrome(params.get("trace_id"))
                        ).encode()
                        ctype = "application/json"
                    elif path.startswith("/debug/pprof"):
                        from . import pprof as PP

                        body = PP.handle(path, params)
                        if body is None:
                            self.send_response(404)
                            self.end_headers()
                            return
                        data = body.encode()
                        ctype = "text/plain; charset=utf-8"
                    else:
                        self.send_response(404)
                        self.end_headers()
                        return
                except Exception as e:  # noqa: BLE001 — debug surface
                    self.send_error(500, str(e))
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        # shutdown() BLOCKS FOREVER if serve_forever never ran — guard
        # so stopping a constructed-but-never-started server is a no-op
        if self._thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
