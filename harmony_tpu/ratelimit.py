"""Token-bucket rate limiting, shared by the RPC and sync-stream
servers (reference: rpc rate limiting, rpc.go:158-216 + the p2p/stream
rate-limiter tiers)."""

from __future__ import annotations

import threading
import time


class RateLimiter:
    """Token bucket per key (client ip, connection id, ...).

    ``max_keys`` bounds the per-key state: past it, the stalest bucket
    (oldest refill stamp) is evicted to admit a new key.  An attacker
    cycling source addresses — exactly the traffic shape a limiter
    meets — must not grow the LIMITER's own memory without bound; an
    evicted key simply starts over with a full burst.  Eviction is
    O(1): ``_state`` is kept in touch order (every ``allow`` re-stamps
    and re-inserts its key, so dict order IS refill-stamp order) and
    the front entry is the stalest — a full table must not buy every
    new-key admission a ``max_keys`` scan under the lock precisely
    when the node is already pressured."""

    def __init__(self, per_second: float = 100.0, burst: int = 200,
                 max_keys: int = 4096):
        self.rate = per_second
        self.burst = burst
        self.max_keys = max_keys
        self._state: dict = {}
        self._lock = threading.Lock()

    def allow(self, key: str) -> bool:
        now = time.monotonic()
        with self._lock:
            entry = self._state.pop(key, None)
            if entry is None and len(self._state) >= self.max_keys:
                del self._state[next(iter(self._state))]
            tokens, last = entry if entry is not None else (self.burst, now)
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            if tokens < 1.0:
                self._state[key] = (tokens, now)
                return False
            self._state[key] = (tokens - 1.0, now)
            return True

    def drop(self, key: str):
        """Forget a key's bucket (a disconnected peer's state must
        not accumulate across churn)."""
        with self._lock:
            self._state.pop(key, None)

    def wait(self, key: str):
        """Block until a token is available, then consume it — the
        back-pressure shape (serve slowly, never drop)."""
        while not self.allow(key):
            time.sleep(1.0 / self.rate)
