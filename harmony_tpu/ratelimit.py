"""Token-bucket rate limiting, shared by the RPC and sync-stream
servers (reference: rpc rate limiting, rpc.go:158-216 + the p2p/stream
rate-limiter tiers)."""

from __future__ import annotations

import threading
import time


class RateLimiter:
    """Token bucket per key (client ip, connection id, ...)."""

    def __init__(self, per_second: float = 100.0, burst: int = 200):
        self.rate = per_second
        self.burst = burst
        self._state: dict = {}
        self._lock = threading.Lock()

    def allow(self, key: str) -> bool:
        now = time.monotonic()
        with self._lock:
            tokens, last = self._state.get(key, (self.burst, now))
            tokens = min(self.burst, tokens + (now - last) * self.rate)
            if tokens < 1.0:
                self._state[key] = (tokens, now)
                return False
            self._state[key] = (tokens - 1.0, now)
            return True

    def drop(self, key: str):
        """Forget a key's bucket (a disconnected peer's state must
        not accumulate across churn)."""
        with self._lock:
            self._state.pop(key, None)

    def wait(self, key: str):
        """Block until a token is available, then consume it — the
        back-pressure shape (serve slowly, never drop)."""
        while not self.allow(key):
            time.sleep(1.0 / self.rate)
