"""Liveness watchdog: heartbeats for every long-lived thread, wedge /
death detection, flight-recorder evidence, and supervised restarts.

Every subsystem that owns a long-lived thread — the FBFT pump
(node/node.py run_forever), the scheduler flush thread
(sched/scheduler.py), the sidecar reader (sidecar/client.py), the
background sync downloader (node/node.py _spin_up_sync), the p2p
validate workers + mesh heartbeat (p2p/host.py), the webhook sender
(webhooks.py) — registers a :class:`Heartbeat` and beats it from its
loop.  A participant about to park in a *healthy* unbounded wait (a
condition variable with no work, a socket recv with no traffic) marks
itself ``idle()`` first: idle is not wedged, and the watchdog must not
confuse a quiet subsystem with a dead one.

The watchdog thread classifies each participant:

    ok      beaten within its ``max_age_s`` while busy
    idle    parked in a declared-healthy wait
    stale   BUSY and silent past ``max_age_s`` — a wedged thread
    dead    its bound thread object is no longer alive

On the transition INTO stale/dead it fires exactly one flight-recorder
dump (``trace.anomaly("watchdog.<name>")`` — the per-(kind, trace)
dedup and per-kind cooldown make repeats free), counts the event, and
— where the participant registered a ``restart`` callback — supervises
a restart.  Restarts run only for DEAD participants: a wedged (alive
but stuck) Python thread cannot be killed, so spawning a replacement
would double-run its loop; the restart-safety matrix lives in
docs/ANALYSIS.md ("Overload & degradation model").

``verdicts()`` / ``readiness()`` are the JSON bodies behind the
MetricsServer's ``/healthz`` and ``/readyz`` endpoints;  ``expose()``
is the ``harmony_health_*`` Prometheus family hooked into
``metrics.Registry``.

Everything is process-global (like sched/trace/faultinject):
``reset()`` in test teardown.
"""

from __future__ import annotations

import threading
import time

from .log import get_logger
from .metrics import LockedCounters

_log = get_logger("health")

# watchdog lifecycle events, exposed as
# harmony_health_watchdog_total{event=...}
EVENTS = LockedCounters(
    "stale", "dead", "restart", "restart_failed", "recovered",
)

_LOCK = threading.Lock()
_PARTICIPANTS: dict[str, "Heartbeat"] = {}
_MAX_PARTICIPANTS = 256  # cardinality bound (names are label values)
# names of participants seen recovering (watchdog-observed or
# close-while-flagged), bounded — scenario invariants attribute a
# recovery to a SPECIFIC participant with this, not the global count
_RECOVERED_NAMES: set = set()
_CHECK_INTERVAL_S = 0.5
_DEFAULT_MAX_AGE_S = 30.0
_enabled = True
_watchdog: threading.Thread | None = None
_stop = threading.Event()


class Heartbeat:
    """One monitored participant.  ``beat()``/``idle()`` are single
    attribute stores (GIL-atomic, lock-free — the discipline trace.py
    uses): a heartbeat on a hot loop must cost nanoseconds."""

    __slots__ = ("name", "max_age_s", "critical", "restart", "_thread",
                 "_last", "_idle", "beats", "restarts", "closed",
                 "_flagged")

    def __init__(self, name: str, max_age_s: float, critical: bool,
                 restart, thread):
        self.name = name
        self.max_age_s = max_age_s
        self.critical = critical
        self.restart = restart  # zero-arg callable; DEAD-state only
        self._thread = thread
        self._last = time.monotonic()
        self._idle = False
        self.beats = 0
        self.restarts = 0
        self.closed: str | None = None  # close reason once closed
        self._flagged: str | None = None  # state the watchdog reported

    def beat(self) -> None:
        """I am alive and busy."""
        self._last = time.monotonic()
        self._idle = False
        self.beats += 1

    def idle(self) -> None:
        """I am about to park in a healthy unbounded wait."""
        self._last = time.monotonic()
        self._idle = True

    def bind(self, thread) -> None:
        """(Re)bind the monitored thread object (restart paths)."""
        self._thread = thread

    def close(self, reason: str = "stopped") -> None:
        """Controlled exit: deregister.  Identity-guarded — a moribund
        reader closing late must not evict a successor that took the
        same name.  A participant closing while flagged STALE counts
        as a recovery: its subsystem exited the wedge through its own
        fail-closed path (e.g. a stalled sidecar reader dropping the
        connection so the client redials).  Closing while flagged
        DEAD is just cleanup — a permanent thread death deregistered
        at teardown must not be reported as a recovery."""
        self.closed = reason
        if self._flagged == "stale":
            EVENTS.inc("recovered")
            _note_recovered(self.name)
        self._flagged = None
        with _LOCK:
            if _PARTICIPANTS.get(self.name) is self:
                del _PARTICIPANTS[self.name]

    def age_s(self, now: float | None = None) -> float:
        return (now if now is not None else time.monotonic()) - self._last

    def state(self, now: float | None = None) -> str:
        if self.closed is not None:
            return "closed"
        t = self._thread
        if t is not None and not t.is_alive():
            return "dead"
        if self._idle:
            return "idle"
        if self.age_s(now) > self.max_age_s:
            return "stale"
        return "ok"


def configure(enabled: bool | None = None,
              check_interval_s: float | None = None,
              default_max_age_s: float | None = None) -> None:
    global _enabled, _CHECK_INTERVAL_S, _DEFAULT_MAX_AGE_S
    if enabled is not None:
        _enabled = enabled
    if check_interval_s is not None:
        _CHECK_INTERVAL_S = float(check_interval_s)
    if default_max_age_s is not None:
        _DEFAULT_MAX_AGE_S = float(default_max_age_s)


def reset() -> None:
    """Stop the watchdog, drop every participant, restore defaults,
    zero the counters (test / scenario teardown)."""
    global _watchdog, _stop, _enabled, _CHECK_INTERVAL_S
    global _DEFAULT_MAX_AGE_S
    with _LOCK:
        watchdog, _watchdog = _watchdog, None
        stop, _stop = _stop, threading.Event()
        _PARTICIPANTS.clear()
        _RECOVERED_NAMES.clear()
        _enabled = True
        _CHECK_INTERVAL_S = 0.5
        _DEFAULT_MAX_AGE_S = 30.0
    stop.set()
    if watchdog is not None:
        watchdog.join(timeout=5.0)
    for name in EVENTS.keys():
        EVENTS[name] = 0


def register(name: str, *, max_age_s: float | None = None,
             critical: bool = False, restart=None,
             thread=None) -> Heartbeat:
    """Register (or replace) a participant and lazily start the
    watchdog.  Returns the handle the owning loop beats."""
    hb = Heartbeat(
        name,
        _DEFAULT_MAX_AGE_S if max_age_s is None else float(max_age_s),
        critical, restart, thread,
    )
    with _LOCK:
        if (name not in _PARTICIPANTS
                and len(_PARTICIPANTS) >= _MAX_PARTICIPANTS):
            # cardinality bound: evict a NON-critical entry before ever
            # refusing a fresh registration — preferring (1) entries
            # whose thread is dead (leaked transients that never
            # closed), then (2) busy-but-silent ones, and only as a
            # last resort (3) healthy IDLE long-lived participants: a
            # reader parked in recv for minutes has the oldest beat
            # stamp of all, and raw-age eviction would silently
            # deregister exactly the participants the watchdog exists
            # to watch.  Oldest beat breaks ties within a class.
            def _evict_rank(p):
                t = p._thread
                if t is not None and not t.is_alive():
                    cls = 0
                elif not p._idle:
                    cls = 1
                else:
                    cls = 2
                return (cls, p._last)

            victims = [
                p for p in _PARTICIPANTS.values() if not p.critical
            ] or list(_PARTICIPANTS.values())
            del _PARTICIPANTS[min(victims, key=_evict_rank).name]
        _PARTICIPANTS[name] = hb
        _ensure_watchdog_locked()
    return hb


def participants() -> list:
    with _LOCK:
        return list(_PARTICIPANTS.values())


def _ensure_watchdog_locked() -> None:
    global _watchdog
    if not _enabled:
        return
    if _watchdog is not None and _watchdog.is_alive():
        return
    _watchdog = threading.Thread(
        # graftlint: thread-role=watchdog
        target=_watch_loop, args=(_stop,), name="health-watchdog",
        daemon=True,
    )
    _watchdog.start()


def _watch_loop(stop: threading.Event) -> None:
    while not stop.wait(_CHECK_INTERVAL_S):
        check_once()


def check_once() -> dict:
    """One watchdog sweep (also the deterministic test hook): classify
    every participant, report transitions, supervise restarts.
    Returns {name: state}.  All detection work runs OUTSIDE the
    registry lock — restart callbacks and anomaly dumps may block."""
    from . import trace

    now = time.monotonic()
    snapshot = participants()
    states: dict = {}
    for hb in snapshot:
        st = hb.state(now)
        states[hb.name] = st
        if st in ("stale", "dead"):
            if hb._flagged != st:
                hb._flagged = st
                EVENTS.inc(st)
                _log.error(
                    "watchdog: participant " + st,
                    participant=hb.name, age_s=round(hb.age_s(now), 3),
                    max_age_s=hb.max_age_s, critical=hb.critical,
                )
                trace.anomaly(
                    f"watchdog.{hb.name}", participant=hb.name,
                    state=st, age_s=round(hb.age_s(now), 3),
                    critical=hb.critical,
                )
            # restarts ONLY for dead threads: a wedged-but-alive thread
            # cannot be killed, and a second copy of its loop would
            # race the first (the restart-safety matrix in ANALYSIS.md)
            if st == "dead" and hb.restart is not None:
                try:
                    # a supervisor may DECLINE (return False) when
                    # there is nothing to respawn — racing a stop(),
                    # or the thread came back on its own; declined is
                    # not a restart: no count, flag stays, age stays
                    if hb.restart() is False:
                        continue
                    hb.restarts += 1
                    hb._flagged = None
                    hb.beat()
                    EVENTS.inc("restart")
                    _log.warn("watchdog: participant restarted",
                              participant=hb.name,
                              restarts=hb.restarts)
                except Exception as e:  # noqa: BLE001 — a failing
                    # supervisor must keep watching, not die with its
                    # supervisee
                    EVENTS.inc("restart_failed")
                    _log.error("watchdog: restart failed",
                               participant=hb.name, error=repr(e))
        elif hb._flagged is not None:
            hb._flagged = None
            EVENTS.inc("recovered")
            _note_recovered(hb.name)
            _log.warn("watchdog: participant recovered",
                      participant=hb.name, state=st)
    return states


def _note_recovered(name: str) -> None:
    with _LOCK:
        if len(_RECOVERED_NAMES) < _MAX_PARTICIPANTS:
            _RECOVERED_NAMES.add(name)


def recovered_names() -> frozenset:
    """Names of every participant seen recovering since the last
    reset() — the per-participant attribution behind the global
    ``recovered`` counter (bounded at the registry's cardinality)."""
    with _LOCK:
        return frozenset(_RECOVERED_NAMES)


# -- verdict surfaces (MetricsServer /healthz + /readyz) ---------------------


def verdicts() -> dict:
    """Per-subsystem health verdicts.  ``ok`` is False when any
    CRITICAL participant is stale or dead (degraded non-critical
    participants are listed but do not fail the probe)."""
    now = time.monotonic()
    out: dict = {}
    ok = True
    degraded: list = []
    for hb in participants():
        st = hb.state(now)
        out[hb.name] = {
            "state": st,
            "age_s": round(hb.age_s(now), 3),
            "max_age_s": hb.max_age_s,
            "critical": hb.critical,
            "restarts": hb.restarts,
        }
        if st in ("stale", "dead"):
            degraded.append(hb.name)
            if hb.critical:
                ok = False
    return {"ok": ok, "degraded": degraded, "participants": out}


def healthy() -> bool:
    return verdicts()["ok"]


def readiness() -> dict:
    """Readiness = liveness AND the resource governor is not in its
    CRITICAL shed tier.  A node that is alive but actively shedding
    should be drained by its load balancer, not handed more traffic."""
    from . import governor as GV

    v = verdicts()
    gov = GV.current()
    tier = gov.state() if gov is not None else None
    ready = v["ok"] and (tier is None or tier < GV.Tier.CRITICAL)
    return {
        "ready": ready,
        "health_ok": v["ok"],
        "degraded": v["degraded"],
        "governor": GV.TIER_NAMES[tier] if tier is not None else None,
    }


def expose() -> str:
    """Prometheus text: per-participant liveness + watchdog totals."""
    now = time.monotonic()
    lines = [
        "# HELP harmony_health_up participant liveness verdict "
        "(1 = ok/idle, 0 = stale/dead)",
        "# TYPE harmony_health_up gauge",
    ]
    snapshot = sorted(participants(), key=lambda p: p.name)
    for hb in snapshot:
        up = 0 if hb.state(now) in ("stale", "dead") else 1
        lines.append(
            f'harmony_health_up{{participant="{hb.name}"}} {up}'
        )
    lines.append(
        "# HELP harmony_health_beat_age_seconds seconds since the "
        "participant's last beat\n"
        "# TYPE harmony_health_beat_age_seconds gauge"
    )
    for hb in snapshot:
        lines.append(
            "harmony_health_beat_age_seconds"
            f'{{participant="{hb.name}"}} {hb.age_s(now):.3f}'
        )
    lines.append(
        "# HELP harmony_health_watchdog_total watchdog events "
        "(stale/dead detections, restarts, recoveries)\n"
        "# TYPE harmony_health_watchdog_total counter"
    )
    for event, v in EVENTS.items():
        lines.append(
            f'harmony_health_watchdog_total{{event="{event}"}} {v}'
        )
    return "\n".join(lines)
