"""The hmy facade: the read/write surface RPC serves.

The role of the reference's hmy.Harmony struct (reference:
hmy/hmy.go:48-85 — one object bundling chain, txpool, and cached
staking reads for every RPC namespace).
"""

from .facade import Harmony

__all__ = ["Harmony"]
