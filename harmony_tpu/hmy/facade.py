"""Harmony facade: chain/txpool/staking reads behind one object.

Behavioral parity with the reference's facade (reference:
hmy/hmy.go:48-85: BlockChain + TxPool + caches for leader, total
stake, validator information; rpc namespaces call only this).
"""

from __future__ import annotations

import threading

from ..core import rawdb
from ..core.tx_pool import PoolError


class Harmony:
    def __init__(self, chain, tx_pool=None, node=None):
        self.chain = chain
        self.tx_pool = tx_pool
        self.node = node  # optional: consensus state reads
        self._lock = threading.Lock()
        self._total_stake_cache: tuple | None = None  # (epoch, value)

    # -- chain reads --------------------------------------------------------

    def block_number(self) -> int:
        return self.chain.head_number

    def header_by_number(self, num: int):
        return self.chain.header_by_number(num)

    def block_by_number(self, num: int):
        if num < 0:  # "latest"
            num = self.chain.head_number
        return self.chain.block_by_number(num)

    def block_by_hash(self, block_hash: bytes):
        return self.chain.block_by_hash(block_hash)

    def get_balance(self, address: bytes, block_num: int | None = None):
        if block_num is None or block_num >= self.chain.head_number:
            return self.chain.state().balance(address)
        return self.chain.state_at(block_num).balance(address)

    def get_nonce(self, address: bytes) -> int:
        return self.chain.state().nonce(address)

    def get_cx_receipt_by_hash(self, tx_hash: bytes):
        """The outgoing cross-shard receipt a source-shard tx produced
        (reference: rpc hmyv2_getCXReceiptByHash).  Also the operator's
        re-export handle when the committing leader's broadcast was
        lost: any validator holds the same rawdb batch."""
        from ..core import rawdb

        num = rawdb.read_receipt_block_num(self.chain.db, tx_hash)
        if num is None:
            return None
        block = self.chain.block_by_number(num)
        if block is None:
            return None
        tx = next(
            (t for t in block.transactions
             if t.hash(self.chain.config.chain_id) == tx_hash), None
        )
        if tx is None or not tx.is_cross_shard():
            return None
        for cx in self.chain.outgoing_cx(tx.to_shard, num):
            if cx.tx_hash == tx_hash:
                return cx
        return None

    def get_proof(self, address: bytes, slots: list,
                  block_num: int | None = None):
        """eth_getProof backing: (mpt_root, account leaf, account
        proof nodes, storage proofs) at a block's state."""
        if block_num is None or block_num >= self.chain.head_number:
            state = self.chain.state()
        else:
            state = self.chain.state_at(block_num)
        return state.account_proof(address, slots)

    def chain_id(self) -> int:
        return self.chain.config.chain_id

    def shard_id(self) -> int:
        return self.chain.shard_id

    def current_epoch(self) -> int:
        return self.chain.epoch_of(self.chain.head_number)

    def committee(self, epoch: int | None = None) -> list:
        if epoch is None:
            epoch = self.current_epoch()
        return self.chain.committee_for_epoch(epoch)

    def read_commit_sig(self, num: int):
        return self.chain.read_commit_sig(num)

    def get_transaction(self, tx_hash: bytes):
        """(block_num, index, tx) or None — linear scan fallback; an
        index column is a straightforward rawdb extension."""
        for num in range(self.chain.head_number, 0, -1):
            block = self.chain.block_by_number(num)
            if block is None:
                continue
            for i, tx in enumerate(block.transactions):
                if tx.hash(self.chain.config.chain_id) == tx_hash:
                    return num, i, tx
        return None

    def get_receipt(self, tx_hash: bytes):
        """(block_num, index, receipt) or None (reference:
        rpc GetTransactionReceipt over the rawdb tx-hash index)."""
        from ..core import rawdb

        num = rawdb.read_receipt_block_num(self.chain.db, tx_hash)
        if num is None:
            return None
        for i, rc in enumerate(rawdb.read_receipts(self.chain.db, num)):
            if rc.tx_hash == tx_hash:
                return num, i, rc
        return None

    def get_logs(self, from_block: int, to_block: int,
                 address: bytes | None = None,
                 topics: list | None = None) -> list:
        """Matching logs as (block_num, tx_hash, log_index, addr,
        topics, data) tuples (reference: eth filters GetLogs)."""
        from ..core import rawdb

        out = []
        to_block = min(to_block, self.chain.head_number)
        for num in range(max(from_block, 1), to_block + 1):
            idx = 0
            for rc in rawdb.read_receipts(self.chain.db, num):
                for addr, tps, data in rc.logs:
                    match = address is None or addr == address
                    if match and topics:
                        for want, got in zip(topics, tps):
                            if want is not None and want != got:
                                match = False
                                break
                        if len(topics) > len(tps):
                            match = False
                    if match:
                        out.append((num, rc.tx_hash, idx, addr, tps, data))
                    idx += 1
        return out

    def get_code(self, address: bytes) -> bytes:
        return self.chain.state().code(address)

    def get_storage_at(self, address: bytes, slot: bytes) -> int:
        return self.chain.state().storage_get(address, slot)

    def call(self, frm: bytes, to: bytes | None, value: int,
             data: bytes, gas: int, trace: bool = False):
        """Read-only EVM simulation against the head state (reference:
        rpc Call / DoEVMCall).  Returns (ok, gas_left, output, tracer)."""
        from ..core.vm import EVM, CallTracer, Env

        state = self.chain.state().copy()
        env = Env(
            block_num=self.chain.head_number,
            chain_id=self.chain.config.chain_id,
            epoch=self.current_epoch(),
            shard_id=self.chain.shard_id,
        )
        tracer = CallTracer() if trace else None
        evm = EVM(state, env, origin=frm, gas_price=1, tracer=tracer)
        if to is None:
            ok, gas_left, out = evm.create(frm, value, data, gas)
        else:
            ok, gas_left, out = evm.call(frm, to, value, data, gas)
        return ok, gas_left, out, tracer

    def estimate_gas(self, frm: bytes, to: bytes | None, value: int,
                     data: bytes) -> int:
        """Binary-search the minimum sufficient gas (reference:
        rpc EstimateGas shape, simplified to one upper-bound probe +
        bisection)."""
        hi = 10_000_000
        ok, gas_left, _, _ = self.call(frm, to, value, data, hi)
        if not ok:
            raise ValueError("execution reverts at gas cap")
        lo, best = 21000, hi
        while lo <= best:
            mid = (lo + best) // 2
            ok, _, _, _ = self.call(frm, to, value, data, mid)
            if ok:
                best = mid - 1
            else:
                lo = mid + 1
        return lo

    # -- staking reads ------------------------------------------------------

    def validator_addresses(self) -> list:
        return self.chain.state().validator_addresses()

    def validator_information(self, address: bytes):
        w = self.chain.state().validator(address)
        if w is None:
            return None
        return {
            "address": "0x" + address.hex(),
            "bls_keys": [k.hex() for k in w.bls_keys],
            "total_delegation": w.total_delegation(),
            "self_delegation": w.self_delegation(),
            "min_self_delegation": w.min_self_delegation,
            "commission_rate": w.commission_rate,
            "status": ("active", "inactive", "banned")[w.status],
            "blocks_signed": w.blocks_signed,
            "blocks_to_sign": w.blocks_to_sign,
            "last_epoch_in_committee": w.last_epoch_in_committee,
            "delegations": [
                {
                    "delegator": "0x" + d.delegator.hex(),
                    "amount": d.amount,
                    "reward": d.reward,
                    "undelegations": [
                        {"amount": a, "epoch": e}
                        for a, e in d.undelegations
                    ],
                }
                for d in w.delegations
            ],
        }

    def delegations_by_delegator(self, delegator: bytes) -> list:
        """Every (validator, amount, reward) this address delegates to
        (reference: rpc GetDelegationsByDelegator)."""
        out = []
        state = self.chain.state()
        for addr in state.validator_addresses():
            w = state.validator(addr)
            for d in w.delegations:
                if d.delegator == delegator:
                    out.append({
                        "validator_address": "0x" + addr.hex(),
                        "delegator_address": "0x" + delegator.hex(),
                        "amount": d.amount,
                        "reward": d.reward,
                        "undelegations": [
                            {"amount": a, "epoch": e}
                            for a, e in d.undelegations
                        ],
                    })
        return out

    def delegations_by_validator(self, validator: bytes) -> list:
        """All delegations into one validator (reference:
        rpc GetDelegationsByValidator)."""
        w = self.chain.state().validator(validator)
        if w is None:
            return []
        return [
            {
                "validator_address": "0x" + validator.hex(),
                "delegator_address": "0x" + d.delegator.hex(),
                "amount": d.amount,
                "reward": d.reward,
                "undelegations": [
                    {"amount": a, "epoch": e} for a, e in d.undelegations
                ],
            }
            for d in w.delegations
        ]

    def elected_validator_addresses(self) -> list:
        """Validators in the CURRENT epoch's committee (reference:
        rpc GetElectedValidatorAddresses)."""
        state = self.chain.shard_state_for_epoch(self.current_epoch())
        if state is None:
            return []
        out = set()
        for com in state.shards:
            for slot in com.slots:
                if slot.effective_stake is not None:
                    out.add(slot.ecdsa_address)
        return sorted(out)

    def median_raw_stake_snapshot(self):
        """The EPoS median-stake view of the upcoming auction
        (reference: rpc GetMedianRawStakeSnapshot over
        staking/effective's compute) — same eligibility filter and
        slot budget as the real election (chain/finalize.py elect)."""
        from ..staking.effective import SlotOrder, compute

        state = self.chain.state()
        orders = {}
        for addr in state.validator_addresses():
            w = state.validator(addr)
            if w.status != 0 or not w.bls_keys:
                continue
            if w.self_delegation() < w.min_self_delegation:
                continue
            orders[addr] = SlotOrder(
                stake=w.total_delegation(),
                spread_among=list(w.bls_keys),
                address=addr,
            )
        if not orders:
            return {"median_raw_stake": "0", "slot_count": 0}
        fin = getattr(self.chain, "finalizer", None)
        if fin is not None and getattr(fin, "cfg", None) is not None:
            pull = (
                fin.cfg.external_slots_per_shard * fin.cfg.shard_count
            )
        else:  # no finalizer wired (dev chains): whole candidate set
            pull = sum(len(o.spread_among) for o in orders.values())
        med, purchases = compute(orders, pull)
        return {
            "median_raw_stake": str(med),
            "slot_count": len(purchases),
        }

    def total_staking(self) -> int:
        """Network total delegation (cached per epoch — hmy.go:73
        totalStakeCache)."""
        epoch = self.current_epoch()
        with self._lock:
            if (
                self._total_stake_cache is not None
                and self._total_stake_cache[0] == epoch
            ):
                return self._total_stake_cache[1]
        state = self.chain.state()
        total = sum(
            state.validator(a).total_delegation()
            for a in state.validator_addresses()
        )
        with self._lock:
            self._total_stake_cache = (epoch, total)
        return total

    # -- writes -------------------------------------------------------------

    def send_raw_transaction(self, blob: bytes) -> bytes:
        """Decode, admit to the pool, return the tx hash (reference:
        SendRawTransaction -> AddPendingTransaction)."""
        if self.tx_pool is None:
            raise PoolError("node has no transaction pool")
        tx = rawdb.decode_tx(blob)
        # RPC-submitted txs are LOCAL: journaled across restarts
        # (reference: tx_journal.go locals semantics)
        self.tx_pool.add(tx, local=True)
        return tx.hash(self.chain.config.chain_id)

    def send_raw_staking_transaction(self, blob: bytes) -> bytes:
        if self.tx_pool is None:
            raise PoolError("node has no transaction pool")
        tx = rawdb.decode_staking_tx(blob)
        self.tx_pool.add(tx, is_staking=True, local=True)
        return tx.hash(self.chain.config.chain_id)
