"""Live profiling endpoint: the pprof service, Python-shaped.

The role of the reference's pprof service (reference:
api/service/pprof/service.go — net/http/pprof mounted on a debug
listener; cmd/harmony wires it behind --pprof flags).  Go's pprof
surface maps onto the Python runtime as:

    /debug/pprof/            -> index
    /debug/pprof/goroutine   -> every live thread's stack (the Go
                                "goroutine" profile == thread dump)
    /debug/pprof/profile?seconds=N
                             -> statistical CPU profile: samples
                                sys._current_frames at ~100 Hz for N
                                seconds, reports flat sample counts
                                per frame (folded-stack text, the
                                format flamegraph tooling eats)
    /debug/pprof/heap        -> tracemalloc top allocation sites
                                (starts tracing on first use)
    /debug/pprof/threadz     -> thread table: name, ident, daemon

Text output throughout — the operator's consumers are curl and
flamegraph scripts, not the binary protobuf toolchain.  Like the
reference, the service binds localhost by default and is OFF unless a
port is configured (cli --pprof-port).
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_INDEX = """harmony-tpu pprof
/debug/pprof/goroutine   thread stack dump
/debug/pprof/profile     CPU profile (?seconds=5, folded stacks)
/debug/pprof/heap        top allocation sites (tracemalloc)
/debug/pprof/threadz     thread table
"""


def thread_dump() -> str:
    """All live threads' stacks — the goroutine-profile analog."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(
            f"thread {names.get(ident, '?')} (ident {ident}):\n"
            + "".join(traceback.format_stack(frame))
        )
    return "\n".join(out)


def cpu_profile(seconds: float = 5.0, hz: float = 100.0) -> str:
    """Statistical sampler over every thread, folded-stack output.

    ``sys._current_frames`` costs one dict build per tick — cheap
    enough that sampling a live node does not distort it, unlike
    cProfile's per-call tracing (which also only sees one thread).
    """
    counts: collections.Counter = collections.Counter()
    period = 1.0 / hz
    deadline = time.monotonic() + seconds
    me = threading.get_ident()
    n = 0
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue  # the sampler itself is noise
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name}@{code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}")
                f = f.f_back
            counts[";".join(reversed(stack))] += 1
        n += 1
        time.sleep(period)
    lines = [f"# {n} ticks @ {hz:g} Hz over {seconds:g}s"]
    for stack, c in counts.most_common():
        lines.append(f"{stack} {c}")
    return "\n".join(lines)


def heap_profile(top: int = 32) -> str:
    """tracemalloc top allocation sites; tracing starts on first call
    (so the first response only covers allocations made after it)."""
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "# tracemalloc started; allocations record from now"
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    total = sum(s.size for s in snap.statistics("filename"))
    lines = [f"# tracked total {total} bytes"]
    for s in stats:
        lines.append(f"{s.traceback} size={s.size} count={s.count}")
    return "\n".join(lines)


def threadz() -> str:
    lines = []
    for t in threading.enumerate():
        lines.append(
            f"{t.name} ident={t.ident} daemon={t.daemon} "
            f"alive={t.is_alive()}"
        )
    return "\n".join(lines)


def handle(path: str, params: dict) -> str | None:
    """Route one /debug/pprof request to its profile; None = unknown
    path.  The ONE routing table for every debug listener — both
    PprofServer and metrics.MetricsServer mount this (the r3 metrics
    server carried its own weaker copies of the stack dump and CPU
    profiler; those are gone)."""
    if path in ("/", "/debug/pprof", "/debug/pprof/"):
        return _INDEX
    if path in ("/debug/pprof/goroutine", "/debug/pprof/stacks"):
        # /stacks kept as an operator-facing alias of the old metrics
        # endpoint name
        return thread_dump()
    if path == "/debug/pprof/profile":
        secs = min(float(params.get("seconds", 5)), 120.0)
        return cpu_profile(secs)
    if path == "/debug/pprof/heap":
        return heap_profile()
    if path == "/debug/pprof/threadz":
        return threadz()
    return None


class PprofServer:
    """Serves the profiles over localhost HTTP (reference:
    api/service/pprof/service.go Start/Stop lifecycle)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                path, _, query = self.path.partition("?")
                params = dict(
                    kv.split("=", 1) for kv in query.split("&") if "=" in kv
                )
                try:
                    body = handle(path, params)
                    if body is None:
                        self.send_error(404)
                        return
                except Exception as e:  # noqa: BLE001 — debug surface
                    self.send_error(500, str(e))
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="pprof-server",
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        if self._thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
