"""Affine group law on BLS12-381's G1, G2 and E(Fp12), over bigints.

Points are ``(x, y)`` tuples in the respective field, with ``None`` as the
point at infinity.  All three curves share a = 0 short-Weierstrass form:

    E  / Fp  : y^2 = x^3 + 4            (G1)
    E' / Fp2 : y^2 = x^3 + 4 (u + 1)    (G2, M-twist)
    E  / Fp12: y^2 = x^3 + 4            (untwist target for pairing)

Mirrors the reference's use of herumi G1/G2 ops (PublicKey.Add/Sub,
Sign.Add — reference: crypto/bls/mask.go:113-153, consensus/quorum/
quorum.go:164-196), which the batched JAX versions in ops/curve.py
re-implement TPU-side.
"""

from . import fields as F
from .params import B_G1, G1_X, G1_Y, G2_X, G2_Y, H1, H2, P, R_ORDER, XI


class CurveOps:
    """Affine a=0 curve over a field described by a small op table."""

    def __init__(self, add, sub, mul, inv, neg, zero, one, b):
        self.fadd, self.fsub, self.fmul = add, sub, mul
        self.finv, self.fneg = inv, neg
        self.zero, self.one, self.b = zero, one, b

    def is_on_curve(self, pt):
        if pt is None:
            return True
        x, y = pt
        lhs = self.fmul(y, y)
        rhs = self.fadd(self.fmul(self.fmul(x, x), x), self.b)
        return lhs == rhs

    def neg(self, pt):
        if pt is None:
            return None
        return (pt[0], self.fneg(pt[1]))

    def add(self, p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        x1, y1 = p1
        x2, y2 = p2
        if x1 == x2:
            if y1 != y2 or y1 == self.zero:
                return None  # p1 == -p2
            return self.dbl(p1)
        lam = self.fmul(self.fsub(y2, y1), self.finv(self.fsub(x2, x1)))
        x3 = self.fsub(self.fsub(self.fmul(lam, lam), x1), x2)
        y3 = self.fsub(self.fmul(lam, self.fsub(x1, x3)), y1)
        return (x3, y3)

    def dbl(self, pt):
        if pt is None:
            return None
        x, y = pt
        if y == self.zero:
            return None
        three_x2 = self.fmul(self.fadd(self.fadd(x, x), x), x)
        lam = self.fmul(three_x2, self.finv(self.fadd(y, y)))
        x3 = self.fsub(self.fsub(self.fmul(lam, lam), x), x)
        y3 = self.fsub(self.fmul(lam, self.fsub(x, x3)), y)
        return (x3, y3)

    def mul(self, pt, k):
        """Scalar multiplication (double-and-add, MSB first).

        Scalars are NOT reduced mod r — cofactor clearing passes scalars
        far larger than the subgroup order.
        """
        if k < 0:
            return self.mul(self.neg(pt), -k)
        acc = None
        for bit in bin(k)[2:] if k else "":
            acc = self.dbl(acc)
            if bit == "1":
                acc = self.add(acc, pt)
        return acc


# --- concrete curves -------------------------------------------------------

g1 = CurveOps(
    add=F.fp_add,
    sub=F.fp_sub,
    mul=F.fp_mul,
    inv=F.fp_inv,
    neg=F.fp_neg,
    zero=0,
    one=1,
    b=B_G1 % P,
)

g2 = CurveOps(
    add=F.fp2_add,
    sub=F.fp2_sub,
    mul=F.fp2_mul,
    inv=F.fp2_inv,
    neg=F.fp2_neg,
    zero=F.FP2_ZERO,
    one=F.FP2_ONE,
    b=F.fp2_scalar(XI, B_G1),  # 4 (u + 1)
)

e12 = CurveOps(
    add=F.fp12_add,
    sub=F.fp12_sub,
    mul=F.fp12_mul,
    inv=F.fp12_inv,
    neg=lambda a: F.fp12_sub(F.FP12_ZERO, a),
    zero=F.FP12_ZERO,
    one=F.FP12_ONE,
    b=F.fp_to_fp12(B_G1),
)

G1_GEN = (G1_X, G1_Y)
G2_GEN = (G2_X, G2_Y)


# --- untwist E'(Fp2) -> E(Fp12) -------------------------------------------
# psi(x, y) = (x / w^2, y / w^3); with w^6 = xi this maps the M-twist onto
# E(Fp12): y^2 = x^3 + 4.  Precompute the two inverse powers of w once.

_W2_INV = F.fp12_inv(F.fp12_mul(F.FP12_W, F.FP12_W))
_W3_INV = F.fp12_inv(F.fp12_mul(F.fp12_mul(F.FP12_W, F.FP12_W), F.FP12_W))


def untwist(q):
    """Map a G2 (twist) point into E(Fp12)."""
    if q is None:
        return None
    x = F.fp12_mul(F.fp2_to_fp12(q[0]), _W2_INV)
    y = F.fp12_mul(F.fp2_to_fp12(q[1]), _W3_INV)
    return (x, y)


def g1_embed(p):
    """Embed a G1 point into E(Fp12) coordinate-wise."""
    if p is None:
        return None
    return (F.fp_to_fp12(p[0]), F.fp_to_fp12(p[1]))


def clear_cofactor_g1(pt):
    return g1.mul(pt, H1)


def clear_cofactor_g2(pt):
    return g2.mul(pt, H2)


__all__ = [
    "g1",
    "g2",
    "e12",
    "G1_GEN",
    "G2_GEN",
    "untwist",
    "g1_embed",
    "clear_cofactor_g1",
    "clear_cofactor_g2",
    "R_ORDER",
]
