"""Tower field arithmetic for BLS12-381 over Python bigints.

Tower (the one every BLS12-381 deployment uses, herumi/mcl included):

    Fp2  = Fp [u] / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),   xi = u + 1
    Fp12 = Fp6[w] / (w^2 - v)

Representation: Fp is ``int`` in [0, p); Fp2 is ``(c0, c1)``; Fp6 is
``(c0, c1, c2)`` of Fp2; Fp12 is ``(c0, c1)`` of Fp6.  All functions are
pure.  This is the ground truth the JAX limb kernels are tested against
(ops/fp.py, ops/towers.py).
"""

from .params import P

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------

def fp_add(a, b):
    return (a + b) % P


def fp_sub(a, b):
    return (a - b) % P


def fp_mul(a, b):
    return (a * b) % P


def fp_neg(a):
    return (-a) % P


def fp_inv(a):
    if a % P == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    return pow(a, P - 2, P)


def fp_sqrt(a):
    """Square root in Fp (p = 3 mod 4), or None if a is a non-residue."""
    a %= P
    cand = pow(a, (P + 1) // 4, P)
    return cand if cand * cand % P == a else None


def fp_is_neg(a):
    """Lexicographic 'sign': True if a > (p-1)/2 (the larger of {a, -a})."""
    return a % P > (P - 1) // 2


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1)
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) = a0 b0 - a1 b1 + (a0 b1 + a1 b0) u
    return (
        (a[0] * b[0] - a[1] * b[1]) % P,
        (a[0] * b[1] + a[1] * b[0]) % P,
    )


def fp2_sqr(a):
    return fp2_mul(a, a)


def fp2_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    """Frobenius x -> x^p on Fp2: conjugation a0 - a1 u."""
    return (a[0], (-a[1]) % P)


def fp2_inv(a):
    # (a0 + a1 u)^-1 = (a0 - a1 u) / (a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = fp_inv(norm)
    return (a[0] * ninv % P, -a[1] * ninv % P)


def fp2_mul_xi(a):
    """Multiply by xi = u + 1: (a0 + a1 u)(1 + u) = a0 - a1 + (a0 + a1) u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp2_sqrt(a):
    """Square root in Fp2 via the norm trick, or None if non-square.

    For x = x0 + x1 u with x^2 = a:  norm(a) = a0^2 + a1^2 must be a QR in
    Fp; with alpha = sqrt(norm), x0^2 = (a0 + alpha)/2 or (a0 - alpha)/2.
    """
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # a0 is a non-residue => sqrt is purely imaginary: (x1 u)^2 = -x1^2
        s = fp_sqrt((-a0) % P)
        return None if s is None else (0, s)
    alpha = fp_sqrt((a0 * a0 + a1 * a1) % P)
    if alpha is None:
        return None
    inv2 = fp_inv(2)
    delta = (a0 + alpha) * inv2 % P
    x0 = fp_sqrt(delta)
    if x0 is None:
        delta = (a0 - alpha) * inv2 % P
        x0 = fp_sqrt(delta)
        if x0 is None:
            return None
    x1 = a1 * fp_inv(2 * x0 % P) % P
    cand = (x0, x1)
    return cand if fp2_sqr(cand) == (a0, a1) else None


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi)
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    t00 = fp2_mul(a[0], b[0])
    t11 = fp2_mul(a[1], b[1])
    t22 = fp2_mul(a[2], b[2])
    # c0 = a0 b0 + xi (a1 b2 + a2 b1)
    c0 = fp2_add(t00, fp2_mul_xi(fp2_add(fp2_mul(a[1], b[2]), fp2_mul(a[2], b[1]))))
    # c1 = a0 b1 + a1 b0 + xi a2 b2
    c1 = fp2_add(fp2_add(fp2_mul(a[0], b[1]), fp2_mul(a[1], b[0])), fp2_mul_xi(t22))
    # c2 = a0 b2 + a1 b1 + a2 b0
    c2 = fp2_add(fp2_add(fp2_mul(a[0], b[2]), t11), fp2_mul(a[2], b[0]))
    return (c0, c1, c2)


def fp6_mul_v(a):
    """Multiply by v: (c0, c1, c2) -> (xi c2, c0, c1)."""
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    # Standard formula (e.g. Beuchat et al.): with
    #   t0 = a0^2 - xi a1 a2, t1 = xi a2^2 - a0 a1, t2 = a1^2 - a0 a2
    # a^-1 = (t0, t1, t2) / (a0 t0 + xi a2 t1 + xi a1 t2)
    a0, a1, a2 = a
    t0 = fp2_sub(fp2_sqr(a0), fp2_mul_xi(fp2_mul(a1, a2)))
    t1 = fp2_sub(fp2_mul_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    t2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    norm = fp2_add(
        fp2_mul(a0, t0),
        fp2_add(fp2_mul_xi(fp2_mul(a2, t1)), fp2_mul_xi(fp2_mul(a1, t2))),
    )
    ninv = fp2_inv(norm)
    return (fp2_mul(t0, ninv), fp2_mul(t1, ninv), fp2_mul(t2, ninv))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_mul(a, b):
    t0 = fp6_mul(a[0], b[0])
    t1 = fp6_mul(a[1], b[1])
    c0 = fp6_add(t0, fp6_mul_v(t1))  # w^2 = v
    c1 = fp6_sub(
        fp6_mul(fp6_add(a[0], a[1]), fp6_add(b[0], b[1])), fp6_add(t0, t1)
    )
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """x -> x^(p^6): conjugation over Fp6 (negate the w coefficient)."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    # (d0 + d1 w)^-1 = (d0 - d1 w) / (d0^2 - v d1^2)
    norm = fp6_sub(fp6_mul(a[0], a[0]), fp6_mul_v(fp6_mul(a[1], a[1])))
    ninv = fp6_inv(norm)
    return (fp6_mul(a[0], ninv), fp6_neg(fp6_mul(a[1], ninv)))


def fp12_pow(a, e):
    if e < 0:
        a, e = fp12_inv(a), -e
    result = FP12_ONE
    base = a
    while e:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


# --- embeddings ------------------------------------------------------------

def fp2_to_fp12(a):
    return ((a, FP2_ZERO, FP2_ZERO), FP6_ZERO)


def fp_to_fp12(a):
    return fp2_to_fp12((a % P, 0))


# w as an Fp12 element (0, 1): used to untwist G2 points.
FP12_W = (FP6_ZERO, FP6_ONE)
