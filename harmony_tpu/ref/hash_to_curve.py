"""Deterministic hash-to-G2 for BLS signatures.

The reference signs through herumi's ``SignHash`` (reference:
consensus/construct.go:99-114, crypto/bls via go.mod:27), whose map-to-point
runs inside the C++ mcl library.  mcl's pre-ETH default is itself a
nonstandard try-and-increment map, so this framework defines its own
deterministic map with the same security contract (unknown discrete log of
the output, fixed-length input):

    for ctr = 0, 1, 2, ...:
        x = (H(msg || ctr || 0), H(msg || ctr || 1)) interpreted in Fp2
        if x^3 + 4(u+1) is a square: y = sqrt, pick lexicographically-even y
        clear the G2 cofactor; if non-infinity, done

The branchy search is deliberately host-side per the build plan (SURVEY.md
§7.2: "hash-to-G2 stays host-side; only curve ops on TPU"); the expensive
cofactor scalar-mul is exactly the part ops/curve.py batches on TPU.
Swapping in the IETF BLS ciphersuite (SSWU + isogeny) is a planned upgrade
and only touches this module.
"""

import hashlib

from . import fields as F
from . import native as NB
from .curve import clear_cofactor_g2, g2
from .params import H2, P

_DST = b"HARMONY-TPU-BLS12381G2-TAI-SHA256-V1"


def _hash_to_fp(msg: bytes, ctr: int, idx: int) -> int:
    """Derive one Fp coordinate from 2 sha256 blocks (uniform enough mod p)."""
    h0 = hashlib.sha256(_DST + msg + bytes([ctr, idx, 0])).digest()
    h1 = hashlib.sha256(_DST + msg + bytes([ctr, idx, 1])).digest()
    return int.from_bytes(h0 + h1, "big") % P


def map_to_twist(msg: bytes):
    """Try-and-increment: find the first counter yielding a twist point.

    Returns an E'(Fp2) point NOT yet in the r-torsion subgroup.
    """
    native = NB.available()
    for ctr in range(256):
        x = (_hash_to_fp(msg, ctr, 0), _hash_to_fp(msg, ctr, 1))
        if native:
            pt = NB.g2_map_tai(x)  # same sqrt + canonical-y conventions
            if pt is not None:
                return pt
            continue
        rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
        y = F.fp2_sqrt(rhs)
        if y is None:
            continue
        # canonical y choice: lexicographically smaller of {y, -y}
        neg = F.fp2_neg(y)
        if (y[1], y[0]) > (neg[1], neg[0]):
            y = neg
        return (x, y)
    raise ValueError("map_to_twist: no point found in 256 tries (p=2^-256)")


def hash_to_g2(msg: bytes):
    """Full hash-to-G2: map to the twist, then clear the cofactor."""
    tw = map_to_twist(msg)
    if NB.available():
        pt = NB.g2_mul(tw, H2)
    else:
        pt = clear_cofactor_g2(tw)
    if pt is None:  # astronomically unlikely (prob 1/r)
        raise ValueError("hash_to_g2 produced infinity")
    return pt
