"""BLS signatures over BLS12-381: the host-side ground-truth API.

Mirrors the herumi surface the reference calls through cgo (SURVEY.md
§2.1): SignHash, Sign.Add, Sign.VerifyHash, PublicKey.Add/Sub, serialize /
deserialize — with pubkeys in G1 (48 B) and signatures in G2 (96 B), i.e.
the BLS_SWAP_G=1 convention (reference: crypto/bls/bls.go:17-20).

Scheme:  sk in [1, r);  pk = sk * G1;  sig = sk * H(msg) in G2;
verify:  e(G1_gen, sig) == e(pk, H(msg)).
Aggregation (same message, the FBFT case — reference:
consensus/quorum/quorum.go:164-196): sum sigs in G2, sum pubkeys in G1,
verify once.
"""

import hashlib
import os

from . import fields as F
from . import native as NB
from .curve import G1_GEN, g1, g2
from .pairing import multi_pairing
from .params import R_ORDER
from .serialize import g1_compress, g1_decompress, g2_compress, g2_decompress

_KEYGEN_DST = b"HARMONY-TPU-BLS-KEYGEN-V1"


def keygen(seed: bytes | None = None) -> int:
    """Derive a secret key: random, or deterministic from a seed."""
    if seed is None:
        seed = os.urandom(48)
    counter = 0
    while True:
        h = hashlib.sha256(_KEYGEN_DST + seed + bytes([counter])).digest()
        h2 = hashlib.sha256(_KEYGEN_DST + h + b"\x01").digest()
        sk = int.from_bytes(h + h2, "big") % R_ORDER
        if sk != 0:
            return sk
        counter += 1


def pubkey(sk: int):
    if NB.available():
        return NB.g1_mul(G1_GEN, sk % R_ORDER)
    return g1.mul(G1_GEN, sk % R_ORDER)


def sign(sk: int, msg_hash: bytes):
    """SignHash analog: sign a (typically 32-byte) message hash."""
    from .hash_to_curve import hash_to_g2

    if NB.available():
        return NB.g2_mul(hash_to_g2(msg_hash), sk % R_ORDER)
    return g2.mul(hash_to_g2(msg_hash), sk % R_ORDER)


def verify(pk, msg_hash: bytes, sig) -> bool:
    """VerifyHash analog: e(G1, sig) == e(pk, H(m)).

    Computed as one product of pairings with a shared final exponentiation:
    e(-G1, sig) * e(pk, H(m)) == 1.
    """
    from .hash_to_curve import hash_to_g2

    if pk is None or sig is None:
        return False
    h = hash_to_g2(msg_hash)
    if NB.available():
        return NB.pairing_check([(g1.neg(G1_GEN), sig), (pk, h)])
    gt = multi_pairing([(g1.neg(G1_GEN), sig), (pk, h)])
    return gt == F.FP12_ONE


def aggregate_sigs(sigs):
    """Sign.Add analog: sum signatures in G2."""
    sigs = list(sigs)
    if NB.available():
        return NB.g2_sum(sigs)
    acc = None
    for s in sigs:
        acc = g2.add(acc, s)
    return acc


def aggregate_pubkeys(pks):
    """PublicKey.Add analog: sum public keys in G1 (mask aggregation)."""
    pks = list(pks)
    if NB.available():
        return NB.g1_sum(pks)
    acc = None
    for p in pks:
        acc = g1.add(acc, p)
    return acc


def verify_hashed(pk, h_point, sig) -> bool:
    """verify() for a message already mapped to G2 (callers that hash
    once and verify many — the engine's batch replay path)."""
    if pk is None or sig is None:
        return False
    if NB.available():
        return NB.pairing_check([(g1.neg(G1_GEN), sig), (pk, h_point)])
    gt = multi_pairing([(g1.neg(G1_GEN), sig), (pk, h_point)])
    return gt == F.FP12_ONE


def verify_aggregate(pks, msg_hash: bytes, agg_sig) -> bool:
    """Aggregate verify for one message: the FBFT quorum check
    (reference: consensus/validator.go:228, internal/chain/engine.go:640)."""
    return verify(aggregate_pubkeys(pks), msg_hash, agg_sig)


# --- serialization convenience --------------------------------------------

def pubkey_to_bytes(pk) -> bytes:
    return g1_compress(pk)


def pubkey_from_bytes(data: bytes):
    return g1_decompress(data)


def sig_to_bytes(sig) -> bytes:
    return g2_compress(sig)


def sig_from_bytes(data: bytes):
    return g2_decompress(data)


def sk_to_bytes(sk: int) -> bytes:
    return (sk % R_ORDER).to_bytes(32, "big")


def sk_from_bytes(data: bytes) -> int:
    return int.from_bytes(data, "big") % R_ORDER
