"""Optimal ate pairing on BLS12-381 over bigints (ground truth).

e(P, Q) for P in G1, Q in G2 is computed as f_{|x|, psi(Q)}(P) raised to
(p^12 - 1)/r, conjugated once because the BLS parameter x is negative.

This implementation optimises for auditability, not speed: the Miller loop
uses affine line functions on the untwisted curve E(Fp12), and the final
exponentiation's hard part is a generic square-and-multiply by the integer
(p^4 - p^2 + 1)/r.  The TPU path (ops/pairing.py) uses projective twist
coordinates, sparse line multiplication and the x-addition-chain hard part,
and is tested to produce identical GT elements to this function.

Replaces the reference's pairing entry points Sign.VerifyHash /
aggregate-verify (reference: consensus/leader.go:173, consensus/
validator.go:228, internal/chain/engine.go:640), which live inside herumi's
C++ mcl library.
"""

from . import fields as F
from .curve import e12, g1_embed, untwist
from .params import P, R_ORDER, X

_ABS_X_BITS = bin(-X)[2:]  # x < 0 for BLS12-381


def _line(t, r_pt, p_pt):
    """Evaluate at p_pt the line through t and r_pt (tangent if t == r_pt).

    All points are affine on E(Fp12).  Vertical lines (r == -t) evaluate as
    x_P - x_T; they appear only at the very last add step when the scalar is
    the group order, which |x| is not, but the case is handled for safety.
    """
    xt, yt = t
    xp, yp = p_pt
    if t == r_pt:
        # tangent: lambda = 3 x^2 / 2 y
        num = e12.fmul(F.fp_to_fp12(3), e12.fmul(xt, xt))
        den = e12.fmul(F.fp_to_fp12(2), yt)
    else:
        xr, yr = r_pt
        if xt == xr:
            return e12.fsub(xp, xt)  # vertical
        num = e12.fsub(yr, yt)
        den = e12.fsub(xr, xt)
    lam = e12.fmul(num, e12.finv(den))
    # l(P) = lambda (x_P - x_T) - (y_P - y_T)
    return e12.fsub(e12.fmul(lam, e12.fsub(xp, xt)), e12.fsub(yp, yt))


def miller_loop(p_pt, q_pt):
    """f_{|x|, Q'}(P') on E(Fp12); returns an Fp12 element (pre-final-exp)."""
    if p_pt is None or q_pt is None:
        return F.FP12_ONE
    pp = g1_embed(p_pt)
    qq = untwist(q_pt)
    f = F.FP12_ONE
    t = qq
    for bit in _ABS_X_BITS[1:]:
        f = F.fp12_mul(F.fp12_sqr(f), _line(t, t, pp))
        t = e12.dbl(t)
        if bit == "1":
            f = F.fp12_mul(f, _line(t, qq, pp))
            t = e12.add(t, qq)
    # x < 0: f_{-|x|} ~ conj(f_{|x|}) up to factors killed by the final exp.
    return F.fp12_conj(f)


def final_exponentiation(f):
    """f^(3 (p^12 - 1) / r) — the framework's canonical pairing power.

    Easy part: f^(p^6 - 1) = conj(f)/f, then ^(p^2 + 1) by generic pow.
    Hard part: generic pow by 3 (p^4 - p^2 + 1)/r.

    The CUBE of the textbook reduced pairing is used throughout (both
    here and the TPU path): the TPU hard part runs the x-addition chain
    3 lambda = (x-1)^2 (x+p)(x^2+p^2-1) + 3 (identity checked in
    tests), and since gcd(3, r) = 1 the cubed pairing is an equally
    valid bilinear non-degenerate pairing — standard practice for BLS12
    final-exponentiation chains.
    """
    f1 = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))  # ^(p^6 - 1)
    f2 = F.fp12_mul(F.fp12_pow(f1, P * P), f1)  # ^(p^2 + 1)
    hard = 3 * ((P**4 - P**2 + 1) // R_ORDER)
    return F.fp12_pow(f2, hard)


def pairing(p_pt, q_pt):
    """Full optimal ate pairing e(P, Q) in GT."""
    return final_exponentiation(miller_loop(p_pt, q_pt))


# --- projective-twist Miller loop (the TPU algorithm, validated here) ------
#
# The TPU kernel (ops/pairing.py) cannot afford per-step inversions, so it
# works on the twist in Jacobian coordinates with denominator-eliminated
# line functions.  Lines are scaled by arbitrary Fp2 factors (killed by the
# final exponentiation) and expressed in the sparse basis {v^2, w, w v}:
#
#   line*v^2 = yp*v^2 - (lambda xp)*(w v) + (lambda x_T - y_T)*w
#
# with, after clearing Jacobian denominators (T = (X, Y, Z), x = X/Z^2):
#   dbl:  c_v2 = 2 Y Z^3 yp,  c_w = 3 X^3 - 2 Y^2,  c_wv = -3 X^2 Z^2 xp
#   add:  c_v2 = yp Z (X - xq Z^2),  c_wv = -xp (Y - yq Z^3),
#         c_w = xq (Y - yq Z^3) - yq Z (X - xq Z^2)
#
# This bigint twin exists so the TPU implementation can be debugged
# step-by-step against exact integers; test_ref_pairing_bls.py checks it
# agrees with the affine miller_loop after final exponentiation.


def _sparse_line_to_fp12(c_v2, c_w, c_wv):
    """Assemble c_v2*v^2 + c_w*w + c_wv*w*v as a full Fp12 element."""
    c0 = (F.FP2_ZERO, F.FP2_ZERO, c_v2)  # 1, v, v^2
    c1 = (c_w, c_wv, F.FP2_ZERO)  # w, w v, w v^2
    return (c0, c1)


def miller_loop_projective(p_pt, q_pt):
    """f_{|x|,Q}(P) with twist-Jacobian steps; equals miller_loop up to
    subfield factors (identical pairing after final exponentiation)."""
    if p_pt is None or q_pt is None:
        return F.FP12_ONE
    xp, yp = p_pt
    xq, yq = q_pt
    x, y, z = xq, yq, F.FP2_ONE  # Jacobian T = Q

    def dbl_step(x, y, z):
        # line coefficients
        zsq = F.fp2_sqr(z)
        z3 = F.fp2_mul(zsq, z)
        xsq = F.fp2_sqr(x)
        ysq = F.fp2_sqr(y)
        c_v2 = F.fp2_scalar(F.fp2_mul(y, z3), 2 * yp % P)
        c_w = F.fp2_sub(
            F.fp2_scalar(F.fp2_mul(xsq, x), 3), F.fp2_scalar(ysq, 2)
        )
        c_wv = F.fp2_neg(F.fp2_scalar(F.fp2_mul(xsq, zsq), 3 * xp % P))
        # dbl-2009-l
        a = xsq
        b = ysq
        c = F.fp2_sqr(b)
        d = F.fp2_scalar(
            F.fp2_sub(F.fp2_sub(F.fp2_sqr(F.fp2_add(x, b)), a), c), 2
        )
        e = F.fp2_scalar(a, 3)
        f_ = F.fp2_sqr(e)
        x3 = F.fp2_sub(f_, F.fp2_scalar(d, 2))
        y3 = F.fp2_sub(F.fp2_mul(e, F.fp2_sub(d, x3)), F.fp2_scalar(c, 8))
        z3_ = F.fp2_scalar(F.fp2_mul(y, z), 2)
        return (x3, y3, z3_), (c_v2, c_w, c_wv)

    def add_step(x, y, z):
        zsq = F.fp2_sqr(z)
        z3 = F.fp2_mul(zsq, z)
        num = F.fp2_sub(y, F.fp2_mul(yq, z3))  # Y - yq Z^3
        den = F.fp2_mul(z, F.fp2_sub(x, F.fp2_mul(xq, zsq)))  # Z(X - xq Z^2)
        c_v2 = F.fp2_scalar(den, yp)
        c_wv = F.fp2_neg(F.fp2_scalar(num, xp))
        c_w = F.fp2_sub(F.fp2_mul(xq, num), F.fp2_mul(yq, den))
        # Jacobian + affine (add-2007-bl with Z2 = 1)
        u2 = F.fp2_mul(xq, zsq)
        s2 = F.fp2_mul(yq, z3)
        h = F.fp2_sub(u2, x)
        r = F.fp2_scalar(F.fp2_sub(s2, y), 2)
        i = F.fp2_sqr(F.fp2_scalar(h, 2))
        j = F.fp2_mul(h, i)
        v = F.fp2_mul(x, i)
        x3 = F.fp2_sub(F.fp2_sub(F.fp2_sqr(r), j), F.fp2_scalar(v, 2))
        y3 = F.fp2_sub(
            F.fp2_mul(r, F.fp2_sub(v, x3)),
            F.fp2_scalar(F.fp2_mul(y, j), 2),
        )
        z3_ = F.fp2_sub(
            F.fp2_sub(F.fp2_sqr(F.fp2_add(z, h)), zsq), F.fp2_sqr(h)
        )
        return (x3, y3, z3_), (c_v2, c_w, c_wv)

    f = F.FP12_ONE
    for bit in _ABS_X_BITS[1:]:
        (x, y, z), (c_v2, c_w, c_wv) = dbl_step(x, y, z)
        f = F.fp12_mul(F.fp12_sqr(f), _sparse_line_to_fp12(c_v2, c_w, c_wv))
        if bit == "1":
            (x, y, z), (c_v2, c_w, c_wv) = add_step(x, y, z)
            f = F.fp12_mul(f, _sparse_line_to_fp12(c_v2, c_w, c_wv))
    return F.fp12_conj(f)  # x < 0


def pairing_projective(p_pt, q_pt):
    return final_exponentiation(miller_loop_projective(p_pt, q_pt))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i): shared final exponentiation over the products of
    Miller loops — the structure the TPU batch-verify kernel exploits."""
    f = F.FP12_ONE
    for p_pt, q_pt in pairs:
        f = F.fp12_mul(f, miller_loop(p_pt, q_pt))
    return final_exponentiation(f)
