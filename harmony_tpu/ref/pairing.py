"""Optimal ate pairing on BLS12-381 over bigints (ground truth).

e(P, Q) for P in G1, Q in G2 is computed as f_{|x|, psi(Q)}(P) raised to
(p^12 - 1)/r, conjugated once because the BLS parameter x is negative.

This implementation optimises for auditability, not speed: the Miller loop
uses affine line functions on the untwisted curve E(Fp12), and the final
exponentiation's hard part is a generic square-and-multiply by the integer
(p^4 - p^2 + 1)/r.  The TPU path (ops/pairing.py) uses projective twist
coordinates, sparse line multiplication and the x-addition-chain hard part,
and is tested to produce identical GT elements to this function.

Replaces the reference's pairing entry points Sign.VerifyHash /
aggregate-verify (reference: consensus/leader.go:173, consensus/
validator.go:228, internal/chain/engine.go:640), which live inside herumi's
C++ mcl library.
"""

from . import fields as F
from .curve import e12, g1_embed, untwist
from .params import P, R_ORDER, X

_ABS_X_BITS = bin(-X)[2:]  # x < 0 for BLS12-381


def _line(t, r_pt, p_pt):
    """Evaluate at p_pt the line through t and r_pt (tangent if t == r_pt).

    All points are affine on E(Fp12).  Vertical lines (r == -t) evaluate as
    x_P - x_T; they appear only at the very last add step when the scalar is
    the group order, which |x| is not, but the case is handled for safety.
    """
    xt, yt = t
    xp, yp = p_pt
    if t == r_pt:
        # tangent: lambda = 3 x^2 / 2 y
        num = e12.fmul(F.fp_to_fp12(3), e12.fmul(xt, xt))
        den = e12.fmul(F.fp_to_fp12(2), yt)
    else:
        xr, yr = r_pt
        if xt == xr:
            return e12.fsub(xp, xt)  # vertical
        num = e12.fsub(yr, yt)
        den = e12.fsub(xr, xt)
    lam = e12.fmul(num, e12.finv(den))
    # l(P) = lambda (x_P - x_T) - (y_P - y_T)
    return e12.fsub(e12.fmul(lam, e12.fsub(xp, xt)), e12.fsub(yp, yt))


def miller_loop(p_pt, q_pt):
    """f_{|x|, Q'}(P') on E(Fp12); returns an Fp12 element (pre-final-exp)."""
    if p_pt is None or q_pt is None:
        return F.FP12_ONE
    pp = g1_embed(p_pt)
    qq = untwist(q_pt)
    f = F.FP12_ONE
    t = qq
    for bit in _ABS_X_BITS[1:]:
        f = F.fp12_mul(F.fp12_sqr(f), _line(t, t, pp))
        t = e12.dbl(t)
        if bit == "1":
            f = F.fp12_mul(f, _line(t, qq, pp))
            t = e12.add(t, qq)
    # x < 0: f_{-|x|} ~ conj(f_{|x|}) up to factors killed by the final exp.
    return F.fp12_conj(f)


def final_exponentiation(f):
    """f^((p^12 - 1) / r).

    Easy part: f^(p^6 - 1) = conj(f)/f, then ^(p^2 + 1) by generic pow.
    Hard part: generic pow by (p^4 - p^2 + 1)/r.
    """
    f1 = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))  # ^(p^6 - 1)
    f2 = F.fp12_mul(F.fp12_pow(f1, P * P), f1)  # ^(p^2 + 1)
    hard = (P**4 - P**2 + 1) // R_ORDER
    return F.fp12_pow(f2, hard)


def pairing(p_pt, q_pt):
    """Full optimal ate pairing e(P, Q) in GT."""
    return final_exponentiation(miller_loop(p_pt, q_pt))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i): shared final exponentiation over the products of
    Miller loops — the structure the TPU batch-verify kernel exploits."""
    f = F.FP12_ONE
    for p_pt, q_pt in pairs:
        f = F.fp12_mul(f, miller_loop(p_pt, q_pt))
    return final_exponentiation(f)
