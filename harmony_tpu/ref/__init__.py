"""Pure-Python bigint reference implementation of BLS12-381.

This subpackage is the ground truth for every TPU kernel in
``harmony_tpu.ops`` and doubles as the host-side CPU fallback — the analog
of the reference chain's herumi/mcl cgo path (reference: crypto/bls/bls.go,
Makefile:68-70).  It is deliberately written for clarity and auditability:
plain Python integers, affine formulas, no Montgomery domain.

Nothing here imports JAX.
"""

from . import params  # noqa: F401
