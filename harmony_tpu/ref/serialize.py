"""Compressed point serialization: G1 pubkeys 48 B, G2 signatures 96 B.

Wire sizes match the reference's BLS_SWAP_G=1 build (reference:
crypto/bls/bls.go:17-20 — pubkeys G1/48B, sigs G2/96B; Makefile:70).
The byte layout is the ZCash/IETF compressed encoding (big-endian field
elements, 3 flag bits in the top byte):

    bit 7 (0x80): compression flag, always set here
    bit 6 (0x40): infinity flag
    bit 5 (0x20): sign flag — y is the lexicographically larger root

G2 serializes x = x0 + x1 u as  x1 || x0  (imaginary limb first), sign from
(y1, y0) lexicographic order.
"""

from . import fields as F
from . import native as NB
from .curve import g1, g2
from .params import P
from .params import R_ORDER as _R_ORDER


def _g1_subgroup_ok(pt) -> bool:
    """r-torsion membership; native when available (the affine bigint
    mul-by-r costs ~40 ms per decompressed point, the native one ~0.2)."""
    if NB.available():
        return NB.g1_in_subgroup(pt)
    return g1.mul(pt, _R_ORDER) is None


def _g2_subgroup_ok(pt) -> bool:
    if NB.available():
        return NB.g2_in_subgroup(pt)
    return g2.mul(pt, _R_ORDER) is None


def _fp_to_bytes(a: int) -> bytes:
    return (a % P).to_bytes(48, "big")


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(47)
    x, y = pt
    out = bytearray(_fp_to_bytes(x))
    out[0] |= 0x80
    if F.fp_is_neg(y):
        out[0] |= 0x20
    return bytes(out)


def g1_decompress(data: bytes, check_subgroup: bool = True):
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G1 infinity")
        return None
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    rhs = (x * x % P * x + g1.b) % P
    y = NB.fp_sqrt(rhs) if NB.available() else F.fp_sqrt(rhs)
    if y is None:
        raise ValueError("G1 x not on curve")
    if F.fp_is_neg(y) != bool(flags & 0x20):
        y = (-y) % P
    pt = (x, y)
    # Rogue-point defense: a curve point need not lie in the r-torsion
    # subgroup (cofactor h1 > 1).  mcl rejects such points on deserialize;
    # so do we (reference behavior: herumi verifyOrder).
    if check_subgroup and not _g1_subgroup_ok(pt):
        raise ValueError("G1 point not in the r-torsion subgroup")
    return pt


def _fp2_is_neg(a) -> bool:
    """Lexicographic sign of an Fp2 element: compare (c1, c0)."""
    if a[1] != 0:
        return F.fp_is_neg(a[1])
    return F.fp_is_neg(a[0])


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([0xC0]) + bytes(95)
    x, y = pt
    out = bytearray(_fp_to_bytes(x[1]) + _fp_to_bytes(x[0]))
    out[0] |= 0x80
    if _fp2_is_neg(y):
        out[0] |= 0x20
    return bytes(out)


def g2_decompress(data: bytes, check_subgroup: bool = True):
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or flags & 0x3F:
            raise ValueError("malformed G2 infinity")
        return None
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
    y = NB.fp2_sqrt(rhs) if NB.available() else F.fp2_sqrt(rhs)
    if y is None:
        raise ValueError("G2 x not on curve")
    if _fp2_is_neg(y) != bool(flags & 0x20):
        y = F.fp2_neg(y)
    pt = (x, y)
    # Rogue-point defense (see g1_decompress): the twist's cofactor is huge;
    # unchecked points enable invalid-curve-style forgeries.
    if check_subgroup and not _g2_subgroup_ok(pt):
        raise ValueError("G2 point not in the r-torsion subgroup")
    return pt
