"""herumi/mcl interop ciphersuite: the reference chain's wire format.

The reference signs and verifies through the herumi bls library built
with BLS_SWAP_G=1 — pubkeys in G1 (48 B), signatures in G2 (96 B)
(reference: crypto/bls/bls.go:17-20, Makefile:70) — using mcl's
*default* (pre-IETF) serialization, NOT the ZCash/IETF encoding that
``ref/serialize.py`` implements.  This module provides the mcl side as
a selectable ciphersuite so keys and committee tables produced by the
real chain load byte-for-byte.

Empirically pinned conventions (validated in tests/test_herumi.py
against data vendored from the reference repo — no herumi code was
available or consulted, only its outputs):

* Field elements serialize LITTLE-endian (Fp: 48 B, Fr: 32 B).
  Validated: all foundational-committee pubkeys in
  reference internal/genesis/foundational.go decode to curve points
  under LE (and none do under BE, which overflows p).
* G1/G2 compressed form: x little-endian with the y-parity flag in the
  MOST significant bit of the final byte (0x80 of byte 47 / 95); the
  all-zero buffer is the point at infinity.  Parity semantics: flag set
  <=> y is odd (mcl convention); our vendored (sk, pk) vector decodes
  with all flag bits clear and an even y, consistent with it.
* G2 x = a + b*u serializes a (real component) first, then b, each
  48 B LE, flag on the global final byte.
* The BLS_SWAP_G G1 base point is NOT the standard BLS12-381 generator.
  HERUMI_G1 below is derived from the reference's test vector pair
  (core/tx_pool_test.go:52-53): G = sk^-1 * pk with sk read LE — the
  unique point satisfying pk = sk*G for that pair.

NOT yet vector-validated (requires herumi-produced signatures, which
neither this image nor the reference repo contains): the SignHash
map-to-G2 — mcl's try-and-increment from the 32-byte message hash —
including its sqrt-root choice and cofactor-clearing method.
``map_to_g2_herumi`` implements the documented mcl "original" shape
(x = hash-as-Fp + 0*u; x += 1 until x^3 + 4(u+1) is square; plain-h2
cofactor clear) with the root choice isolated in ``_choose_root`` so a
single line flips when vectors become available.  Signatures produced
and verified WITHIN this framework using the herumi suite are
self-consistent either way.
"""

from . import fields as F
from .curve import g1, g2
from .params import H2, P, R_ORDER

# The BLS_SWAP_G base point (see module docstring for derivation).
HERUMI_G1 = (
    763293344507811477046371684537583630275805851521468330676434473029673297697877452371442185900362942157156173349093,
    2781315704910118183567811941392363931476590133721789378765638560267023127619616760929191718052242275548019548370600,
)

_ODD_FLAG = 0x80  # MSB of the final byte: y is odd


# ----------------------------------------------------------------------
# scalars
# ----------------------------------------------------------------------


def fr_to_bytes(sk: int) -> bytes:
    return (sk % R_ORDER).to_bytes(32, "little")


def fr_from_bytes(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("herumi Fr must be 32 bytes")
    v = int.from_bytes(data, "little")
    if v >= R_ORDER:
        raise ValueError("herumi Fr out of range")
    return v


# ----------------------------------------------------------------------
# points
# ----------------------------------------------------------------------


def g1_serialize(pt) -> bytes:
    if pt is None:
        return bytes(48)
    x, y = pt
    out = bytearray(x.to_bytes(48, "little"))
    if y & 1:
        out[47] |= _ODD_FLAG
    return bytes(out)


def g1_deserialize(data: bytes, check_subgroup: bool = True):
    if len(data) != 48:
        raise ValueError("herumi G1 must be 48 bytes")
    if not any(data):
        return None
    odd = bool(data[47] & _ODD_FLAG)
    x = int.from_bytes(data[:47] + bytes([data[47] & 0x7F]), "little")
    if x >= P:
        raise ValueError("herumi G1 x out of range")
    y = F.fp_sqrt((x * x % P * x + g1.b) % P)
    if y is None:
        raise ValueError("herumi G1 x not on curve")
    if bool(y & 1) != odd:
        y = (-y) % P
    pt = (x, y)
    # rogue-point defense, as in serialize.py: mcl's verifyOrder
    if check_subgroup and g1.mul(pt, R_ORDER) is not None:
        raise ValueError("herumi G1 point not in the r-torsion subgroup")
    return pt


def _fp2_is_odd(a) -> bool:
    """mcl Fp2 parity: the parity of the real component unless it is
    zero, in which case the imaginary component's (isOdd of a.a or,
    when a.a == 0, of a.b)."""
    return bool((a[0] & 1) if a[0] else (a[1] & 1))


def g2_serialize(pt) -> bytes:
    if pt is None:
        return bytes(96)
    x, y = pt
    out = bytearray(
        x[0].to_bytes(48, "little") + x[1].to_bytes(48, "little")
    )
    if _fp2_is_odd(y):
        out[95] |= _ODD_FLAG
    return bytes(out)


def g2_deserialize(data: bytes, check_subgroup: bool = True):
    if len(data) != 96:
        raise ValueError("herumi G2 must be 96 bytes")
    if not any(data):
        return None
    odd = bool(data[95] & _ODD_FLAG)
    a = int.from_bytes(data[:48], "little")
    b = int.from_bytes(data[48:95] + bytes([data[95] & 0x7F]), "little")
    if a >= P or b >= P:
        raise ValueError("herumi G2 x out of range")
    x = (a, b)
    rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
    y = F.fp2_sqrt(rhs)
    if y is None:
        raise ValueError("herumi G2 x not on curve")
    if _fp2_is_odd(y) != odd:
        y = F.fp2_neg(y)
    pt = (x, y)
    if check_subgroup and g2.mul(pt, R_ORDER) is not None:
        raise ValueError("herumi G2 point not in the r-torsion subgroup")
    return pt


# ----------------------------------------------------------------------
# SignHash-shaped map to G2 (see module docstring: pending vectors)
# ----------------------------------------------------------------------


def _choose_root(y, neg):
    """mcl sqrt root choice — the one unpinned convention.  We take the
    even-parity root (mcl Fp2 parity, see _fp2_is_odd); flip here if
    herumi vectors disagree."""
    return neg if _fp2_is_odd(y) else y


def map_to_g2_herumi(msg_hash: bytes):
    """mcl-original-shaped SignHash map: interpret the hash LE as an Fp
    element t (mcl setArrayMask), start from x = t + 0*u, and increment
    by one until x^3 + 4(u+1) is a square; clear the cofactor by h2.

    Reference call shape: consensus/construct.go:99-114 signs 32-byte
    block hashes via priKey.SignHash."""
    if not msg_hash:
        raise ValueError("empty message hash")
    # setArrayMask: LE interpretation masked below 2^380 (< p)
    t = int.from_bytes(msg_hash, "little")
    t &= (1 << 380) - 1
    t %= P
    x = (t, 0)
    for _ in range(512):
        rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
        y = F.fp2_sqrt(rhs)
        if y is not None:
            y = _choose_root(y, F.fp2_neg(y))
            pt = g2.mul((x, y), H2)
            if pt is not None:
                return pt
        x = (F.fp_add(x[0], 1), x[1])
    raise ValueError("map_to_g2_herumi: no point found (p < 2^-512)")


# ----------------------------------------------------------------------
# BLS over the herumi suite
# ----------------------------------------------------------------------


def pubkey(sk: int):
    return g1.mul(HERUMI_G1, sk % R_ORDER)


def sign_hash(sk: int, msg_hash: bytes):
    return g2.mul(map_to_g2_herumi(msg_hash), sk % R_ORDER)


def verify_hash(pk, msg_hash: bytes, sig) -> bool:
    """e(-G_herumi, sig) * e(pk, H(m)) == 1."""
    from . import pairing as RP
    from .fields import FP12_ONE

    if pk is None or sig is None:
        return False
    h = map_to_g2_herumi(msg_hash)
    gt = RP.multi_pairing([(g1.neg(HERUMI_G1), sig), (pk, h)])
    return gt == FP12_ONE
