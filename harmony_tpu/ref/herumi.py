"""herumi/mcl interop ciphersuite: the reference chain's wire format.

The reference signs and verifies through the herumi bls library built
with BLS_SWAP_G=1 — pubkeys in G1 (48 B), signatures in G2 (96 B)
(reference: crypto/bls/bls.go:17-20, Makefile:70) — using mcl's
*default* (pre-IETF) serialization, NOT the ZCash/IETF encoding that
``ref/serialize.py`` implements.  This module provides the mcl side as
a selectable ciphersuite so keys and committee tables produced by the
real chain load byte-for-byte.

Empirically pinned conventions (validated in tests/test_herumi.py
against data vendored from the reference repo — no herumi code was
available or consulted, only its outputs):

* Field elements serialize LITTLE-endian (Fp: 48 B, Fr: 32 B).
  Validated: all foundational-committee pubkeys in
  reference internal/genesis/foundational.go decode to curve points
  under LE (and none do under BE, which overflows p).
* G1/G2 compressed form: x little-endian with the y-parity flag in the
  MOST significant bit of the final byte (0x80 of byte 47 / 95); the
  all-zero buffer is the point at infinity.  Parity semantics: flag set
  <=> y is odd (mcl convention); our vendored (sk, pk) vector decodes
  with all flag bits clear and an even y, consistent with it.
* G2 x = a + b*u serializes a (real component) first, then b, each
  48 B LE, flag on the global final byte.
* The BLS_SWAP_G G1 base point is NOT the standard BLS12-381 generator.
  HERUMI_G1 below is derived from the reference's test vector pair
  (core/tx_pool_test.go:52-53): G = sk^-1 * pk with sk read LE — the
  unique point satisfying pk = sk*G for that pair.

* 26 more (sk, pk) pairs decrypted from the reference's localnet key
  files (.hmy/*.key, AES-GCM under the empty passphrase — see
  tests/vectors_herumi_localnet.py) all reproduce the herumi pubkey
  bytes exactly under the conventions above.

NOT yet vector-validated (requires herumi-produced signatures; an
exhaustive round-4 mine of the reference tree — every >=190-hex-char
constant, every binary fixture, every *_test.go using SignHash — found
NONE: all reference signatures are generated at runtime from random
keys, and no committed-block fixture carries a lastCommitSignature):
the SignHash map-to-G2 — mcl's try-and-increment from the 32-byte
message hash — specifically its sqrt-root choice and cofactor-clearing
method.  ``map_to_g2_herumi`` implements the documented mcl "original"
shape (x = hash-as-Fp + 0*u; x += 1 until x^3 + 4(u+1) is square) with
BOTH open conventions carried behind ``MAP_CONVENTION`` /
``set_map_convention`` so pinning is a config flip, never a code
change.  Analytic note: with p = 3 mod 4, Tonelli-Shanks in Fp
degenerates to the principal power a^((p+1)/4), and the complex-method
Fp2 sqrt composed from it is fully deterministic with no
canonicalization step; mcl's sqrt is also consumed by point
deserialization where the CALLER fixes parity from the wire flag
afterwards, so the "algorithmic" (uncanonicalized) root is the
best-guess mcl convention.  Signatures produced and verified WITHIN
this framework are self-consistent under every carried convention
(tests/test_herumi.py::test_map_conventions_all_self_consistent).
"""

from . import fields as F
from . import native as NB
from .curve import g1, g2
from .params import H2, P, R_ORDER

# The BLS_SWAP_G base point (see module docstring for derivation).
HERUMI_G1 = (
    763293344507811477046371684537583630275805851521468330676434473029673297697877452371442185900362942157156173349093,
    2781315704910118183567811941392363931476590133721789378765638560267023127619616760929191718052242275548019548370600,
)

_ODD_FLAG = 0x80  # MSB of the final byte: y is odd


# ----------------------------------------------------------------------
# scalars
# ----------------------------------------------------------------------


def fr_to_bytes(sk: int) -> bytes:
    return (sk % R_ORDER).to_bytes(32, "little")


def fr_from_bytes(data: bytes) -> int:
    if len(data) != 32:
        raise ValueError("herumi Fr must be 32 bytes")
    v = int.from_bytes(data, "little")
    if v >= R_ORDER:
        raise ValueError("herumi Fr out of range")
    return v


# ----------------------------------------------------------------------
# points
# ----------------------------------------------------------------------


def g1_serialize(pt) -> bytes:
    if pt is None:
        return bytes(48)
    x, y = pt
    out = bytearray(x.to_bytes(48, "little"))
    if y & 1:
        out[47] |= _ODD_FLAG
    return bytes(out)


def g1_deserialize(data: bytes, check_subgroup: bool = True):
    if len(data) != 48:
        raise ValueError("herumi G1 must be 48 bytes")
    if not any(data):
        return None
    odd = bool(data[47] & _ODD_FLAG)
    x = int.from_bytes(data[:47] + bytes([data[47] & 0x7F]), "little")
    if x >= P:
        raise ValueError("herumi G1 x out of range")
    rhs = (x * x % P * x + g1.b) % P
    y = NB.fp_sqrt(rhs) if NB.available() else F.fp_sqrt(rhs)
    if y is None:
        raise ValueError("herumi G1 x not on curve")
    if bool(y & 1) != odd:
        y = (-y) % P
    pt = (x, y)
    # rogue-point defense, as in serialize.py: mcl's verifyOrder
    from .serialize import _g1_subgroup_ok

    if check_subgroup and not _g1_subgroup_ok(pt):
        raise ValueError("herumi G1 point not in the r-torsion subgroup")
    return pt


def _fp2_is_odd(a) -> bool:
    """mcl Fp2 parity: the parity of the real component unless it is
    zero, in which case the imaginary component's (isOdd of a.a or,
    when a.a == 0, of a.b)."""
    return bool((a[0] & 1) if a[0] else (a[1] & 1))


def g2_serialize(pt) -> bytes:
    if pt is None:
        return bytes(96)
    x, y = pt
    out = bytearray(
        x[0].to_bytes(48, "little") + x[1].to_bytes(48, "little")
    )
    if _fp2_is_odd(y):
        out[95] |= _ODD_FLAG
    return bytes(out)


def g2_deserialize(data: bytes, check_subgroup: bool = True):
    if len(data) != 96:
        raise ValueError("herumi G2 must be 96 bytes")
    if not any(data):
        return None
    odd = bool(data[95] & _ODD_FLAG)
    a = int.from_bytes(data[:48], "little")
    b = int.from_bytes(data[48:95] + bytes([data[95] & 0x7F]), "little")
    if a >= P or b >= P:
        raise ValueError("herumi G2 x out of range")
    x = (a, b)
    rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
    y = NB.fp2_sqrt(rhs) if NB.available() else F.fp2_sqrt(rhs)
    if y is None:
        raise ValueError("herumi G2 x not on curve")
    if _fp2_is_odd(y) != odd:
        y = F.fp2_neg(y)
    pt = (x, y)
    from .serialize import _g2_subgroup_ok

    if check_subgroup and not _g2_subgroup_ok(pt):
        raise ValueError("herumi G2 point not in the r-torsion subgroup")
    return pt


# ----------------------------------------------------------------------
# SignHash-shaped map to G2 (see module docstring: pending vectors)
# ----------------------------------------------------------------------

# The two unpinned mcl conventions, carried as CONFIG so that when a
# herumi-produced signature vector surfaces, pinning is a one-line
# config flip, never a code change (VERDICT r3 #3a).
#
# ``root`` — which square root fp2_sqrt's candidate pair the map keeps:
#   "algorithmic"  the raw complex-method root built from principal Fp
#                  roots a^((p+1)/4) (p = 3 mod 4, so Tonelli-Shanks
#                  degenerates to the direct power — deterministic with
#                  no canonicalization step).  Analytic best guess for
#                  mcl: its Fp2 squareRoot is consumed by deserialization
#                  too, where the caller fixes parity from the wire flag
#                  afterwards — i.e. the sqrt itself has no reason to
#                  canonicalize, and a canonicalizing sqrt would make the
#                  caller's explicit parity fix-up redundant.
#   "even"/"odd"   parity-canonicalized under mcl Fp2 parity
#                  (_fp2_is_odd): keep the root whose parity matches.
#
# ``cofactor`` — how the candidate is pushed into the r-torsion:
#   "h2"    plain multiply by the full G2 cofactor h2.
#   "heff"  multiply by the Budroni-Pintore effective cofactor
#           h_eff (RFC 9380 §8.8.2) — what the psi-based "fast"
#           clearing computes.  Verified in tests: lands in the
#           r-torsion, is NOT the same point as h2*P, and h_eff != 0
#           mod r, so the two methods are genuinely distinct
#           conventions that a signature vector will disambiguate.
# Default = the mcl-source best guess (VERDICT r4 #6): "algorithmic"
# root because mcl's Fp2 sqrt is the raw complex-method composition of
# the principal Fp power with no canonicalization pass (the module
# docstring's analytic argument), and plain-"h2" cofactor because mcl's
# pre-IETF hashAndMapToG2 multiplies by the precomputed cofactor
# constant rather than the psi-based effective-cofactor route it
# reserves for the IETF ciphersuites.  RESIDUAL RISK (PARITY.md): both
# choices are reasoned, not vector-pinned — the moment ANY herumi
# signature vector exists, run tools/pin_herumi.py and it emits the
# definitive pin (env override, no code change).
MAP_CONVENTION = {"root": "algorithmic", "cofactor": "h2"}

# RFC 9380 §8.8.2 effective cofactor for BLS12-381 G2 (Budroni-Pintore
# psi-based clearing as a single scalar).
H2_EFF = int(
    "0xbc69f08f2ee75b3584c6a0ea91b352888e2a8e9145ad7689986ff03150"
    "8ffe1329c2f178731db956d82bf015d1212b02ec0ec69d7477c1ae954cbc"
    "06689f6a359894c0adebbf6b4e8020005aaa95551",
    16,
)


def set_map_convention(root=None, cofactor=None):
    """Select the SignHash map conventions (see MAP_CONVENTION)."""
    if root is not None:
        if root not in ("algorithmic", "even", "odd"):
            raise ValueError(f"unknown root convention {root!r}")
        MAP_CONVENTION["root"] = root
    if cofactor is not None:
        if cofactor not in ("h2", "heff"):
            raise ValueError(f"unknown cofactor convention {cofactor!r}")
        MAP_CONVENTION["cofactor"] = cofactor


# Operational override without a code change (e.g. under a node config
# that must interop with a herumi vector discovered later).
import os as _os  # noqa: E402

if _os.environ.get("HERUMI_MAP_ROOT") or _os.environ.get("HERUMI_MAP_COFACTOR"):
    set_map_convention(
        root=_os.environ.get("HERUMI_MAP_ROOT") or None,
        cofactor=_os.environ.get("HERUMI_MAP_COFACTOR") or None,
    )


def _choose_root(y):
    """Apply the configured root convention to fp2_sqrt's output."""
    conv = MAP_CONVENTION["root"]
    if conv == "algorithmic":
        return y
    odd = _fp2_is_odd(y)
    if (conv == "odd") == odd:
        return y
    return F.fp2_neg(y)


def _clear_cofactor(pt):
    h = H2 if MAP_CONVENTION["cofactor"] == "h2" else H2_EFF
    if NB.available():
        return NB.g2_mul(pt, h)
    return g2.mul(pt, h)


def map_to_g2_herumi(msg_hash: bytes):
    """mcl-original-shaped SignHash map: interpret the hash LE as an Fp
    element t (mcl setArrayMask), start from x = t + 0*u, and increment
    by one until x^3 + 4(u+1) is a square; clear the cofactor.  Root and
    cofactor-clearing conventions per MAP_CONVENTION.

    Reference call shape: consensus/construct.go:99-114 signs 32-byte
    block hashes via priKey.SignHash."""
    if not msg_hash:
        raise ValueError("empty message hash")
    # setArrayMask: LE interpretation masked below 2^380 (< p)
    t = int.from_bytes(msg_hash, "little")
    t &= (1 << 380) - 1
    t %= P
    x = (t, 0)
    native = NB.available()
    for _ in range(512):
        rhs = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), g2.b)
        y = NB.fp2_sqrt(rhs) if native else F.fp2_sqrt(rhs)
        if y is not None:
            pt = _clear_cofactor((x, _choose_root(y)))
            if pt is not None:
                return pt
        x = (F.fp_add(x[0], 1), x[1])
    raise ValueError("map_to_g2_herumi: no point found (p < 2^-512)")


# ----------------------------------------------------------------------
# BLS over the herumi suite
# ----------------------------------------------------------------------


def pubkey(sk: int):
    if NB.available():
        return NB.g1_mul(HERUMI_G1, sk % R_ORDER)
    return g1.mul(HERUMI_G1, sk % R_ORDER)


def sign_hash(sk: int, msg_hash: bytes):
    if NB.available():
        return NB.g2_mul(map_to_g2_herumi(msg_hash), sk % R_ORDER)
    return g2.mul(map_to_g2_herumi(msg_hash), sk % R_ORDER)


def verify_hash(pk, msg_hash: bytes, sig) -> bool:
    """e(-G_herumi, sig) * e(pk, H(m)) == 1."""
    from . import pairing as RP
    from .fields import FP12_ONE

    if pk is None or sig is None:
        return False
    h = map_to_g2_herumi(msg_hash)
    if NB.available():
        return NB.pairing_check([(g1.neg(HERUMI_G1), sig), (pk, h)])
    gt = RP.multi_pairing([(g1.neg(HERUMI_G1), sig), (pk, h)])
    return gt == FP12_ONE
