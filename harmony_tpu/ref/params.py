"""BLS12-381 curve parameters, derived from the single BLS parameter ``x``.

BLS12 curves are parameterised by one integer x (here negative, low Hamming
weight).  Every other constant — the base field prime p, the subgroup order
r, cofactors, trace of Frobenius — is a polynomial in x:

    r(x) = x^4 - x^2 + 1
    p(x) = (x - 1)^2 * r(x) / 3 + x
    t(x) = x + 1                      (trace of Frobenius of E(Fp))
    h1   = (x - 1)^2 / 3              (G1 cofactor)

Deriving instead of hard-coding means the only constant that has to be
trusted is ``X`` itself; everything else is checked by the identities below
and by the test suite (subgroup order annihilates generators, pairing is
bilinear and non-degenerate).

Sizes match the reference's wire format: pubkeys are G1 / 48 B, signatures
are G2 / 96 B, i.e. herumi's BLS_SWAP_G=1 build (reference:
crypto/bls/bls.go:17-20, Makefile:70).
"""

# The BLS parameter. Low Hamming weight (6 set bits) => short Miller loop.
X = -0xD201000000010000

_xa = -X  # |x|

# Subgroup order r = x^4 - x^2 + 1 (255 bits, prime).
R_ORDER = X**4 - X**2 + 1

# Base field prime p = (x-1)^2 * r / 3 + x (381 bits).
P = (X - 1) ** 2 * R_ORDER // 3 + X

# Cross-checks against the published constants (independent transcription).
assert R_ORDER == 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
assert P == int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab",
    16,
)
assert P % 4 == 3  # sqrt in Fp is a single exponentiation
assert P % 6 == 1

# Trace of Frobenius: #E(Fp) = p + 1 - t.
TRACE = X + 1

# G1 cofactor h1 = (x-1)^2 / 3; #E(Fp) = h1 * r.
H1 = (X - 1) ** 2 // 3
assert P + 1 - TRACE == H1 * R_ORDER

# Curve equation: E/Fp : y^2 = x^3 + 4, twist E'/Fp2 : y^2 = x^3 + 4(u+1).
B_G1 = 4
# Fp2 is Fp[u]/(u^2 + 1); the twist constant xi = u + 1 (the M-twist used by
# every BLS12-381 deployment, herumi/mcl included).
XI = (1, 1)  # as an Fp2 element (c0, c1)

# --- G2 cofactor -----------------------------------------------------------
# Derived, not transcribed.  E has CM discriminant D = -3, so
# t^2 - 4p = -3 f^2 for an integer f.  The sextic twists of E(Fp2) have
# orders p^2 + 1 - t' with t' in {t2, -t2, (t2 +/- 3 f2)/2, (-t2 +/- 3 f2)/2}
# where t2 = t^2 - 2p is the trace over Fp2 and t2^2 - 4 p^2 = -3 f2^2.
# Exactly one candidate order is divisible by r; that twist is the one G2
# lives on, and H2 = order / r.  The derivation (and the check that the
# candidate annihilates sample points) lives in tests/test_ref_params.py and
# constants_gen.py; the resulting value is fixed here.


def _derive_h2() -> int:
    import math

    t2 = TRACE * TRACE - 2 * P  # trace of Frobenius over Fp2
    d = 4 * P * P - t2 * t2
    assert d % 3 == 0
    f2sq = d // 3
    f2 = math.isqrt(f2sq)
    assert f2 * f2 == f2sq
    assert (t2 + 3 * f2) % 2 == 0
    candidates = [
        (t2 + 3 * f2) // 2,
        (t2 - 3 * f2) // 2,
        (-t2 + 3 * f2) // 2,
        (-t2 - 3 * f2) // 2,
    ]
    divisible = [
        P * P + 1 - tp for tp in candidates if (P * P + 1 - tp) % R_ORDER == 0
    ]
    assert len(divisible) == 1, divisible
    return divisible[0] // R_ORDER


H2 = _derive_h2()

# --- Generators ------------------------------------------------------------
# The standard generators (IETF / ZCash choice; herumi uses the same points).
# Checked for curve membership and order in the test suite.
G1_X = int(
    "17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb",
    16,
)
G1_Y = int(
    "08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
    "d03cc744a2888ae40caa232946c5e7e1",
    16,
)

G2_X = (
    int(
        "024aa2b2f08f0a91260805272dc51051c6e47ad4fa403b02b4510b647ae3d177"
        "0bac0326a805bbefd48056c8c121bdb8",
        16,
    ),
    int(
        "13e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f5049"
        "334cf11213945d57e5ac7d055d042b7e",
        16,
    ),
)
G2_Y = (
    int(
        "0ce5d527727d6e118cc9cdc6da2e351aadfd9baa8cbdd3a76d429a695160d12c"
        "923ac9cc3baca289e193548608b82801",
        16,
    ),
    int(
        "0606c4a02ea734cc32acd2b02bc28b99cb3e287e85a763af267492ab572e99ab"
        "3f370d275cec1da1aaa9075ff05f79be",
        16,
    ),
)

# Serialized sizes (reference: crypto/bls/bls.go:68-71).
PUBKEY_BYTES = 48  # G1 compressed
SIG_BYTES = 96  # G2 compressed
