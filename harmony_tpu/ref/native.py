"""ctypes binding for the native host BLS12-381 library (native/bls381.cpp).

The reference's hot crypto lives in herumi's C++ mcl (reference:
go.mod:27, Makefile:68-70); this module is the analogous fast host path
for the framework: ~2 ms pairings instead of the bigint twin's ~240 ms.
The twin (ref/fields.py, ref/pairing.py, ref/curve.py) remains the pure
auditable ground truth — this binding exposes the SAME conventions
(identical GT elements, identical sqrt branch choices, identical
hash-map outputs), pinned by tests/test_native_bls381.py.

Interface: reference-style tuples in and out (Fp = int, Fp2 = (c0, c1),
points = affine pairs or None).  Selection knob: HOST_BLS env var —
  auto   (default) use native when the library loads and self-tests
  native require it (raise if unavailable — CI for the native path)
  bigint never use it (pure-twin mode for auditing/debugging)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .params import P, R_ORDER

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "native", "libharmony_bls381.so",
)

_lib = None
_avail: bool | None = None
_lock = threading.Lock()

_R_BYTES = R_ORDER.to_bytes(32, "big")


def _build():
    subprocess.run(
        ["make", "-C", os.path.dirname(_LIB_PATH), "libharmony_bls381.so"],
        check=True, capture_output=True,
    )


def _load():
    global _lib
    if _lib is not None:
        return _lib
    # Always let make decide staleness: a silently stale .so after a
    # bls381.cpp edit would mean parity tests pass against the wrong
    # binary.  Tolerate a failed build only when a prebuilt .so exists
    # (deploy images without a toolchain).
    try:
        _build()
    except Exception:
        if not os.path.exists(_LIB_PATH):
            raise
    lib = ctypes.CDLL(_LIB_PATH)
    lib.hbls_ready.restype = ctypes.c_int
    for name, args, res in [
        ("hbls_g1_mul", [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                         ctypes.c_int, ctypes.c_char_p], ctypes.c_int),
        ("hbls_g2_mul", [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                         ctypes.c_int, ctypes.c_char_p], ctypes.c_int),
        ("hbls_g1_sum", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
                         ctypes.c_char_p], ctypes.c_int),
        ("hbls_g2_sum", [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
                         ctypes.c_char_p], ctypes.c_int),
        ("hbls_g1_in_subgroup", [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_int], ctypes.c_int),
        ("hbls_g2_in_subgroup", [ctypes.c_char_p, ctypes.c_char_p,
                                 ctypes.c_int], ctypes.c_int),
        ("hbls_g2_map_tai", [ctypes.c_char_p, ctypes.c_char_p],
         ctypes.c_int),
        ("hbls_fp2_sqrt", [ctypes.c_char_p, ctypes.c_char_p], ctypes.c_int),
        ("hbls_fp_sqrt", [ctypes.c_char_p, ctypes.c_char_p], ctypes.c_int),
        ("hbls_multi_pairing", [ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_char_p], None),
        ("hbls_pairing_check", [ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_char_p,
                                ctypes.c_int], ctypes.c_int),
    ]:
        fn = getattr(lib, name)
        fn.argtypes = args
        fn.restype = res
    _lib = lib
    return lib


def available() -> bool:
    """True when the fast native path should be used (see HOST_BLS)."""
    global _avail
    mode = os.environ.get("HOST_BLS", "auto")
    if mode == "bigint":
        return False
    if _avail is None:
        with _lock:
            if _avail is None:
                try:
                    _avail = _load().hbls_ready() == 1
                except Exception:  # noqa: BLE001 — no toolchain: twin path
                    _avail = False
    if mode == "native" and not _avail:
        raise RuntimeError("HOST_BLS=native but libharmony_bls381 failed")
    return _avail


# --- packing ---------------------------------------------------------------

def _pack_g1(pt) -> tuple[bytes, int]:
    if pt is None:
        return bytes(96), 1
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big"), 0


def _pack_g2(pt) -> tuple[bytes, int]:
    if pt is None:
        return bytes(192), 1
    x, y = pt
    return (x[0].to_bytes(48, "big") + x[1].to_bytes(48, "big")
            + y[0].to_bytes(48, "big") + y[1].to_bytes(48, "big")), 0


def _unpack_g1(raw: bytes):
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:96], "big"))


def _unpack_g2(raw: bytes):
    return (
        (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:96], "big")),
        (int.from_bytes(raw[96:144], "big"),
         int.from_bytes(raw[144:192], "big")),
    )


def _scalar_bytes(k: int) -> bytes:
    if k == 0:
        return b"\x00"
    return k.to_bytes((k.bit_length() + 7) // 8, "big")


# --- group ops -------------------------------------------------------------

def g1_mul(pt, k: int):
    if pt is None or k == 0:
        return None
    if k < 0:
        pt, k = (pt[0], (-pt[1]) % P), -k
    buf, inf = _pack_g1(pt)
    out = ctypes.create_string_buffer(96)
    sc = _scalar_bytes(k)
    if _lib.hbls_g1_mul(buf, inf, sc, len(sc), out):
        return None
    return _unpack_g1(out.raw)


def g2_mul(pt, k: int):
    if pt is None or k == 0:
        return None
    if k < 0:
        x, y = pt
        pt, k = (x, ((-y[0]) % P, (-y[1]) % P)), -k
    buf, inf = _pack_g2(pt)
    out = ctypes.create_string_buffer(192)
    sc = _scalar_bytes(k)
    if _lib.hbls_g2_mul(buf, inf, sc, len(sc), out):
        return None
    return _unpack_g2(out.raw)


def g1_sum(pts):
    packed, infs = [], []
    for p in pts:
        b, i = _pack_g1(p)
        packed.append(b)
        infs.append(i)
    if not packed:
        return None
    out = ctypes.create_string_buffer(96)
    if _lib.hbls_g1_sum(b"".join(packed), bytes(infs), len(packed), out):
        return None
    return _unpack_g1(out.raw)


def g2_sum(pts):
    packed, infs = [], []
    for p in pts:
        b, i = _pack_g2(p)
        packed.append(b)
        infs.append(i)
    if not packed:
        return None
    out = ctypes.create_string_buffer(192)
    if _lib.hbls_g2_sum(b"".join(packed), bytes(infs), len(packed), out):
        return None
    return _unpack_g2(out.raw)


def g1_in_subgroup(pt) -> bool:
    """On-curve AND r-torsion (rogue-point defense in decompress)."""
    if pt is None:
        return True
    buf, _ = _pack_g1(pt)
    return bool(_lib.hbls_g1_in_subgroup(buf, _R_BYTES, len(_R_BYTES)))


def g2_in_subgroup(pt) -> bool:
    if pt is None:
        return True
    buf, _ = _pack_g2(pt)
    return bool(_lib.hbls_g2_in_subgroup(buf, _R_BYTES, len(_R_BYTES)))


# --- hash-to-curve helpers -------------------------------------------------

def g2_map_tai(x):
    """One try-and-increment step: candidate x in Fp2 -> twist point with
    the canonical (lexicographically smaller) y, or None if x^3 + b is a
    non-square.  Bitwise the twin's map_to_twist body."""
    xb = x[0].to_bytes(48, "big") + x[1].to_bytes(48, "big")
    out = ctypes.create_string_buffer(192)
    if not _lib.hbls_g2_map_tai(xb, out):
        return None
    return _unpack_g2(out.raw)


def fp2_sqrt(a):
    """Deterministic Fp2 sqrt; same root as ref/fields.py::fp2_sqrt."""
    ab = (a[0] % P).to_bytes(48, "big") + (a[1] % P).to_bytes(48, "big")
    out = ctypes.create_string_buffer(96)
    if not _lib.hbls_fp2_sqrt(ab, out):
        return None
    raw = out.raw
    return (int.from_bytes(raw[:48], "big"), int.from_bytes(raw[48:96], "big"))


def fp_sqrt(a):
    ab = (a % P).to_bytes(48, "big")
    out = ctypes.create_string_buffer(48)
    if not _lib.hbls_fp_sqrt(ab, out):
        return None
    return int.from_bytes(out.raw[:48], "big")


# --- pairings --------------------------------------------------------------

def _pack_pairs(pairs):
    g1b, g1i, g2b, g2i = [], [], [], []
    for p, q in pairs:
        b, i = _pack_g1(p)
        g1b.append(b)
        g1i.append(i)
        b, i = _pack_g2(q)
        g2b.append(b)
        g2i.append(i)
    return b"".join(g1b), bytes(g1i), b"".join(g2b), bytes(g2i), len(g1i)


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i) as a ref-tuple Fp12 GT element — bitwise equal
    to ref/pairing.py::multi_pairing (the framework's cubed pairing)."""
    a, b, c, d, n = _pack_pairs(pairs)
    out = ctypes.create_string_buffer(576)
    _lib.hbls_multi_pairing(a, b, c, d, n, out)
    raw = out.raw
    vals = [int.from_bytes(raw[i * 48:(i + 1) * 48], "big")
            for i in range(12)]
    fp2s = [(vals[2 * i], vals[2 * i + 1]) for i in range(6)]
    return ((fp2s[0], fp2s[1], fp2s[2]), (fp2s[3], fp2s[4], fp2s[5]))


def pairing_check(pairs) -> bool:
    """prod_i e(P_i, Q_i) == 1 — the signature-verify decision."""
    a, b, c, d, n = _pack_pairs(pairs)
    return bool(_lib.hbls_pairing_check(a, b, c, d, n))
