"""18-decimal fixed-point arithmetic for vote-power math.

Behavioral equivalent of the reference's cosmos-style ``numeric.Dec``
(reference: numeric/decimal.go:51-114): values are integers scaled by
10^18; Mul/Quo chop back to 18 decimals with banker's rounding
(round-half-to-even, reference: numeric/decimal.go chopPrecisionAndRound);
Truncate variants chop toward zero.

Quorum decisions must be bitwise-deterministic across nodes, so this math
stays on the host in exact integers and is never lowered to TPU floats
(SURVEY.md §2.4 note on numeric).
"""

from __future__ import annotations

PRECISION = 18
_UNIT = 10**PRECISION
_HALF = 5 * 10 ** (PRECISION - 1)


def _chop_round(x: int) -> int:
    """Divide by 10^18 with banker's rounding (round half to even)."""
    if x < 0:
        return -_chop_round(-x)
    quo, rem = divmod(x, _UNIT)
    if rem < _HALF:
        return quo
    if rem > _HALF:
        return quo + 1
    return quo if quo % 2 == 0 else quo + 1


def _chop_trunc(x: int) -> int:
    if x < 0:
        return -(-x // _UNIT)
    return x // _UNIT


class Dec:
    """Immutable fixed-point decimal: value = raw / 10^18."""

    __slots__ = ("raw",)

    def __init__(self, raw: int):
        self.raw = raw

    # --- constructors ---
    @classmethod
    def from_int(cls, i: int) -> "Dec":
        return cls(i * _UNIT)

    @classmethod
    def from_str(cls, s: str) -> "Dec":
        neg = s.startswith("-")
        if neg:
            s = s[1:]
        if "." in s:
            whole, frac = s.split(".", 1)
            if len(frac) > PRECISION:
                raise ValueError("too many decimal places")
            frac = frac.ljust(PRECISION, "0")
        else:
            whole, frac = s, "0" * PRECISION
        raw = int(whole or "0") * _UNIT + int(frac)
        return cls(-raw if neg else raw)

    @classmethod
    def with_prec(cls, i: int, prec: int) -> "Dec":
        if not 0 <= prec <= PRECISION:
            raise ValueError("precision out of range")
        return cls(i * 10 ** (PRECISION - prec))

    # --- arithmetic ---
    def add(self, o: "Dec") -> "Dec":
        return Dec(self.raw + o.raw)

    def sub(self, o: "Dec") -> "Dec":
        return Dec(self.raw - o.raw)

    def mul(self, o: "Dec") -> "Dec":
        return Dec(_chop_round(self.raw * o.raw))

    def mul_truncate(self, o: "Dec") -> "Dec":
        return Dec(_chop_trunc(self.raw * o.raw))

    def mul_int(self, i: int) -> "Dec":
        return Dec(self.raw * i)

    def quo(self, o: "Dec") -> "Dec":
        # multiply precision twice, truncate-divide, then chop+round
        num = self.raw * _UNIT * _UNIT
        q = abs(num) // abs(o.raw)
        if (num < 0) != (o.raw < 0):
            q = -q
        return Dec(_chop_round(q))

    def quo_truncate(self, o: "Dec") -> "Dec":
        num = self.raw * _UNIT * _UNIT
        q = abs(num) // abs(o.raw)
        if (num < 0) != (o.raw < 0):
            q = -q
        return Dec(_chop_trunc(q))

    def neg(self) -> "Dec":
        return Dec(-self.raw)

    # --- comparisons / predicates ---
    def cmp(self, o: "Dec") -> int:
        return (self.raw > o.raw) - (self.raw < o.raw)

    def gt(self, o: "Dec") -> bool:
        return self.raw > o.raw

    def gte(self, o: "Dec") -> bool:
        return self.raw >= o.raw

    def lt(self, o: "Dec") -> bool:
        return self.raw < o.raw

    def lte(self, o: "Dec") -> bool:
        return self.raw <= o.raw

    def equal(self, o: "Dec") -> bool:
        return self.raw == o.raw

    def is_zero(self) -> bool:
        return self.raw == 0

    def is_negative(self) -> bool:
        return self.raw < 0

    # --- conversions ---
    def truncate_int(self) -> int:
        return _chop_trunc(self.raw)

    def round_int(self) -> int:
        return _chop_round(self.raw)

    def __repr__(self) -> str:
        sign = "-" if self.raw < 0 else ""
        whole, frac = divmod(abs(self.raw), _UNIT)
        return f"{sign}{whole}.{str(frac).zfill(PRECISION)}"

    def __eq__(self, o) -> bool:
        return isinstance(o, Dec) and self.raw == o.raw

    def __hash__(self):
        return hash(self.raw)


def zero_dec() -> Dec:
    return Dec(0)


def one_dec() -> Dec:
    return Dec(_UNIT)


def new_dec(i: int) -> Dec:
    return Dec.from_int(i)
