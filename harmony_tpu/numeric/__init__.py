from .dec import Dec, new_dec, one_dec, zero_dec  # noqa: F401
