"""The node binary: config, wiring, service lifecycle.

The role of the reference's cmd/harmony (reference:
cmd/harmony/main.go:106-1107 — config load, chain setup, consensus +
node wiring, service registration, RPC startup; TOML config tree
internal/configs/harmony/harmony.go:18-44).  Stdlib-only: argparse +
tomllib; every subsystem built here exists as a library object, so
this file is wiring, not logic.

Run: python -m harmony_tpu.cli --config node.toml  (or flags only).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is the same parser
    import tomli as tomllib

from .chain.engine import Engine, EpochContext
from .config.chain import ChainConfig
from .core.blockchain import Blockchain
from .core.genesis import Genesis, dev_genesis
from .log import get_logger, init_logging
from .core.kv import FileKV, MemKV
from .core.tx_pool import TxPool
from .hmy import Harmony
from .keystore import load_keys
from .metrics import MetricsServer, Registry as MetricsRegistry
from .multibls import PrivateKeys
from .node.node import Node
from .node.registry import Registry
from .node.services import Manager, Service, ServiceType
from .p2p import TCPHost
from .p2p.stream import SyncClient, SyncServer
from .rpc import RPCServer
from .sync import Downloader

DEFAULTS = {
    "network": "localnet",
    "shard_id": 0,
    "shard_count": 1,  # >1 arms live cross-shard receipt routing
    "datadir": "./harmony_tpu_data",
    "blocks_per_epoch": 32768,
    "rpc_port": 9500,
    "metrics_port": 9900,
    "p2p_port": 9000,
    "sync_port": 9001,
    "peers": [],          # "host:port" gossip peers (static)
    "bootnodes": [],      # "host:port" bootnodes for PEX discovery
    # a BEACON-shard node's sync stream; non-beacon shards follow
    # beacon committee rotation through it (sync/epoch_feed.py)
    "beacon_sync_peer": None,
    "sync_peers": [],     # "host:port" sync stream servers
    "bls_keys": [],       # [{"path": ..., "passphrase_file": ...}]
    # dev-genesis knobs (tools/localnet.py): committee size + which
    # single dev key THIS process holds (None = all of them)
    "dev_keys": None,
    "dev_key_index": None,
    "in_memory": False,
    # storage durability: fsync policy of the shard DB ("none" = OS-
    # buffered, "batch" = fsync every atomic block-commit batch —
    # a committed block survives power loss, "always" = every write)
    "fsync": "batch",
    "log_level": "info",
    "log_path": None,
    # None = auto (TPU ops when an accelerator backend is live);
    # True/False force the verification path
    "device_verify": None,
    # seal verification in the live node (reference nodes always
    # verify; False only for throwaway dev chains)
    "verify_seals": True,
    # quorum-check backend: "in-process" (default) runs the TPU/host
    # paths in this process; "sidecar" ships checks to the
    # verification sidecar at sidecar_addr (SURVEY §7.3; served by
    # harmony_tpu.sidecar.server / native/sidecar_client.cpp)
    "verify_backend": "in-process",
    "sidecar_addr": "127.0.0.1:9600",
    # optional HTTP services (None = disabled; 0 = ephemeral port)
    "explorer_port": None,
    "rosetta_port": None,
    "ws_port": None,  # WebSocket JSON-RPC + eth_subscribe push
    # round tracing + flight recorder (harmony_tpu/trace.py): OFF by
    # default (disabled cost is one comparison per instrumented site);
    # when on, /debug/trace serves the round timelines and anomalies
    # (breaker open, view change, sidecar desync, round > trace_slo)
    # dump correlated snapshots to trace_dir
    "trace": False,
    "trace_sample": 1.0,   # root-span sampling rate [0, 1]
    "trace_slo": None,     # round-latency SLO seconds (None = off)
    "trace_dir": None,     # dump dir ($HARMONY_TPU_TRACE_DIR/<tmp>)
    "span_sink_dir": None,  # durable JSONL span export (implies trace;
    # merge the per-node files with tools/round_forensics.py)
    # startup AOT warmup: precompile every compile-manifest program
    # (GL16's machine-checked shape set) before the node serves, so no
    # serving path ever pays a first-use XLA compile (the PR-15
    # NEWVIEW wedge).  False only for throwaway dev runs that accept
    # first-use compile stalls
    "aot_warmup": True,
}


def load_config(path: str | None, overrides: dict) -> dict:
    cfg = dict(DEFAULTS)
    if path:
        with open(path, "rb") as f:
            cfg.update(tomllib.load(f))
    cfg.update({k: v for k, v in overrides.items() if v is not None})
    return cfg


class _CallbackService(Service):
    def __init__(self, start_fn, stop_fn):
        self._start, self._stop = start_fn, stop_fn

    def start(self):
        self._start()

    def stop(self):
        self._stop()


def _open_genesis(cfg: dict):
    """(genesis, dev_bls_or_None) from config."""
    if cfg.get("genesis") is not None:
        return cfg["genesis"], None  # tests inject a Genesis object
    genesis, _, dev_bls = dev_genesis(
        n_keys=int(cfg.get("dev_keys") or 4),
        shard_id=cfg["shard_id"],
    )
    if cfg.get("dev_key_index") is not None:
        # multi-process localnet: this node votes with a SPAN of
        # consecutive dev keys (span > 1 = a multi-BLS validator,
        # reference: multibls/multibls.go)
        i = int(cfg["dev_key_index"])
        span = int(cfg.get("dev_key_span") or 1)
        if i < 0 or span < 1 or i + span > len(dev_bls):
            raise SystemExit(
                f"dev key span [{i}, {i + span}) out of range for "
                f"{len(dev_bls)} dev keys"
            )
        dev_bls = dev_bls[i:i + span]
    return genesis, dev_bls


def _open_db(cfg: dict):
    if cfg["in_memory"]:
        return MemKV()
    db_path = os.path.join(cfg["datadir"], f"shard{cfg['shard_id']}.db")
    fsync = cfg.get("fsync", "batch")
    if cfg.get("native_kv", True):
        # ANY native failure (missing toolchain, corrupt file ->
        # kv_open nullptr, ...) falls back to the Python twin —
        # same on-disk format, so the fallback opens the same DB
        try:
            from .core.kv_native import NativeKV

            return NativeKV(db_path, fsync=fsync)
        except Exception as e:  # documented above: ANY native failure
            get_logger("cli").warn(
                "native kv unavailable, using FileKV twin",
                path=db_path, error=str(e),
            )
    return FileKV(db_path, fsync=fsync)


def open_chain_for_maintenance(cfg: dict) -> Blockchain:
    """The DB + chain WITHOUT hosts/peers/services — offline tooling
    (--revert-to et al.) must not dial anything or bind ports."""
    os.makedirs(cfg["datadir"], exist_ok=True)
    genesis, _ = _open_genesis(cfg)
    return Blockchain(
        _open_db(cfg), genesis,
        blocks_per_epoch=cfg["blocks_per_epoch"],
    )


def load_node_bls_keys(cfg: dict, dev_bls=None):
    """Resolve the node's BLS signing keys from config (reference:
    internal/blsgen — passphrase file/env/console prompt sources,
    KMS envelopes, --bls.dir multikey directories) or the dev
    genesis keys."""
    # BLS keys: encrypted keyfiles (passphrase from file/env/console),
    # KMS envelopes, a multibls key directory — or dev keys on the dev
    # genesis (reference: internal/blsgen config.go passphrase sources
    # + kms.go + the --bls.dir multikey mode)
    entries = list(cfg["bls_keys"] or [])
    if cfg.get("bls_dir"):
        import glob as _glob

        for path in sorted(
            _glob.glob(os.path.join(cfg["bls_dir"], "*.key"))
        ):
            entries.append({
                "path": path,
                "passphrase_file": cfg.get("bls_dir_passphrase_file"),
                "passphrase_env": cfg.get("bls_dir_passphrase_env"),
            })
    if entries:
        loaded = []
        kms_provider = None
        for entry in entries:
            if entry.get("kms"):
                if kms_provider is None:
                    from .blsgen_kms import LocalKMSProvider

                    master = cfg.get("kms_master_key")
                    if not master:
                        raise ValueError(
                            "kms_master_key required for kms bls keys"
                        )
                    kms_provider = LocalKMSProvider(master)
                from . import bls as _bls
                from .blsgen_kms import load_kms_key

                loaded.append(_bls.PrivateKey.from_bytes(
                    load_kms_key(entry["path"], kms_provider)
                ))
                continue
            if entry.get("passphrase_file"):
                with open(entry["passphrase_file"]) as f:
                    passphrase = f.read().strip()
            elif entry.get("passphrase_env"):
                passphrase = os.environ.get(entry["passphrase_env"])
                if passphrase is None:
                    raise ValueError(
                        f"passphrase env {entry['passphrase_env']!r} "
                        f"unset for {entry['path']}"
                    )
            else:
                # operator console (reference: blsgen prompts when no
                # pass source is configured; non-interactive runs must
                # configure one instead)
                if not sys.stdin.isatty():
                    raise ValueError(
                        f"no passphrase source for {entry['path']} and "
                        "stdin is not a terminal"
                    )
                import getpass

                passphrase = getpass.getpass(
                    f"Enter the BLS key passphrase for {entry['path']}: "
                )
            loaded.extend(load_keys([(entry["path"], passphrase)]))
        keys = PrivateKeys.from_keys(loaded)
    elif dev_bls is not None:
        keys = PrivateKeys.from_keys(dev_bls)
    else:
        raise ValueError(
            "bls_keys required when a custom genesis is supplied"
        )
    return keys


def build_node(cfg: dict):
    """Wire every subsystem; returns (node, services, registry)."""
    os.makedirs(cfg["datadir"], exist_ok=True)

    span_sink = None
    if cfg.get("trace") or cfg.get("span_sink_dir"):
        from . import trace as TR

        # explicit None checks: --trace-sample 0 is a valid rate
        # ("arm the recorder, sample no local roots") and must not be
        # swallowed by a falsy-or into the 1.0 default
        sample = cfg.get("trace_sample")
        TR.configure(
            enabled=True,
            sample_rate=None if sample is None else float(sample),
            round_slo_s=cfg.get("trace_slo"),
            dump_dir=cfg.get("trace_dir"),
        )
        # one real node per process: every span this process creates is
        # attributable when sink files from several nodes merge (the
        # TCPHost naming convention, unique across a localnet)
        node_label = f"shard{cfg['shard_id']}-{os.getpid()}"
        TR.set_node(node_label)
        if cfg.get("span_sink_dir"):
            from .obs import SpanSink

            span_sink = SpanSink(
                cfg["span_sink_dir"], node=node_label
            ).arm()

    genesis, dev_bls = _open_genesis(cfg)
    db = _open_db(cfg)

    # the consensus engine — seal checks + the TPU verification path
    # (VERDICT r1: the shipped binary skipped seal verification; now
    # the node refuses unsigned chains unless verify_seals=False).
    # Late-bound committee provider: reads the chain wired just below.
    chain_cell: list = []
    epoch_chain_cell: list = []

    def _committee_provider(shard_id: int, epoch: int) -> EpochContext:
        chain_ = chain_cell[0]
        keys = None
        if shard_id == chain_.shard_id:
            keys = chain_.committee_for_epoch(epoch)
        else:
            state = chain_.shard_state_for_epoch(epoch)
            com = state.find_committee(shard_id) if state else None
            if com is not None and com.slots:
                keys = com.bls_pubkeys()
            elif epoch_chain_cell:
                # foreign shard: resolve through the beacon epoch light
                # chain (core/epochchain.py — the reference's
                # EpochChain); [] when it hasn't seen the epoch
                keys = epoch_chain_cell[0].committee_for(shard_id, epoch)
            else:
                # no resolvable committee for a FOREIGN shard: fail
                # closed with an empty context (rejects every proof) —
                # falling back to the local genesis committee would
                # verify cross-shard seals against the wrong key set
                # and accept headers sealed by the local keys
                keys = []
        return EpochContext(keys)

    if cfg.get("device_verify") is not None:
        from . import device as DV

        DV.use_device(cfg["device_verify"])
    backend = None
    if cfg.get("verify_backend") == "sidecar":
        from .sidecar.client import SidecarClient

        addr = cfg.get("sidecar_addr", "127.0.0.1:9600")
        if ":" in addr:  # host:port, else a unix socket path
            host_part, _, port_part = addr.rpartition(":")
            backend = SidecarClient(
                (host_part or "127.0.0.1", int(port_part))
            )
        else:
            backend = SidecarClient(addr)
    engine = (
        Engine(_committee_provider, backend=backend)
        if cfg.get("verify_seals", True) else None
    )
    chain = Blockchain(db, genesis, engine=engine,
                       blocks_per_epoch=cfg["blocks_per_epoch"],
                       state_retention=cfg.get("state_retention"))
    chain_cell.append(chain)
    if cfg["shard_id"] != 0:
        # non-beacon shards follow beacon committee rotation through
        # the epoch light chain (core/epochchain.py; populated by the
        # beacon-epoch sync feed)
        from .core.epochchain import EpochChain

        epoch_chain_cell.append(EpochChain(
            db, lambda s: list(chain.genesis.committee), engine=engine,
        ))
        reg_epoch_chain = epoch_chain_cell[0]
    else:
        reg_epoch_chain = None
    pool = TxPool(genesis.config.chain_id, cfg["shard_id"], chain.state)
    if not cfg["in_memory"]:
        # locally submitted txs survive restarts (reference:
        # tx_journal.go; rotated at every commit boundary)
        restored = pool.open_journal(os.path.join(
            cfg["datadir"], f"shard{cfg['shard_id']}.txjournal"
        ))
        if restored:
            log = get_logger("pool", shard=cfg["shard_id"])
            log.info("tx journal replayed", restored=restored)

    keys = load_node_bls_keys(cfg, dev_bls)

    host = TCPHost(name=f"shard{cfg['shard_id']}-{os.getpid()}",
                   listen_port=cfg["p2p_port"])
    for peer in cfg["peers"]:
        addr, _, port = peer.rpartition(":")
        host.connect(int(port), addr or "127.0.0.1")
    discovery = None
    if cfg.get("bootnodes"):
        from .p2p.discovery import Discovery

        discovery = Discovery(host, bootnodes=cfg["bootnodes"]).start()

    reg = Registry(blockchain=chain, txpool=pool, host=host)
    if discovery is not None:
        reg.set("discovery", discovery)
    if reg_epoch_chain is not None:
        reg.set("beaconchain", reg_epoch_chain)
    reg.set("shard_count", int(cfg.get("shard_count") or 1))
    # the metrics registry must exist BEFORE the Node: its constructor
    # wires the per-round latency histogram from registry.get("metrics")
    metrics_reg = MetricsRegistry()
    reg.set("metrics", metrics_reg)
    node = Node(reg, keys, network=cfg["network"])
    hmy = Harmony(chain, pool, node)

    manager = Manager()

    rpc = RPCServer(hmy, port=cfg["rpc_port"])
    manager.register(
        ServiceType.CLIENT_SUPPORT,
        _CallbackService(rpc.start, rpc.stop),
    )

    if cfg.get("ws_port") is not None:
        from .rpc.ws import WSServer

        ws = WSServer(rpc, port=cfg["ws_port"])
        manager.register(
            ServiceType.WEBSOCKET,
            _CallbackService(ws.start, ws.stop),
        )

    metrics = MetricsServer(metrics_reg, port=cfg["metrics_port"])
    manager.register(
        ServiceType.PROMETHEUS,
        _CallbackService(metrics.start, metrics.stop),
    )

    if span_sink is not None:
        # armed eagerly above (boot spans export too); the service
        # slot flushes and unhooks it on shutdown
        manager.register(
            ServiceType.SPAN_SINK,
            _CallbackService(lambda: None, span_sink.close),
        )

    if cfg.get("pprof_port") is not None:
        # reference: api/service/pprof behind cmd/harmony --pprof
        from .pprof import PprofServer

        pprof = PprofServer(port=int(cfg["pprof_port"]))
        manager.register(
            ServiceType.PPROF,
            _CallbackService(pprof.start, pprof.stop),
        )

    sync_srv = SyncServer(chain, listen_port=cfg["sync_port"])
    manager.register(
        ServiceType.SYNCHRONIZE,
        _CallbackService(lambda: None, sync_srv.close),
    )

    if discovery is not None:
        manager.register(
            ServiceType.NETWORK_INFO,
            _CallbackService(lambda: None, discovery.stop),
        )

    if reg_epoch_chain is not None and cfg.get("beacon_sync_peer"):
        import threading as _threading

        from .sync.epoch_feed import EpochFeed

        addr, sep, bport = cfg["beacon_sync_peer"].rpartition(":")
        if not sep or not bport.isdigit():
            raise ValueError(
                f"beacon_sync_peer must be host:port, got "
                f"{cfg['beacon_sync_peer']!r}"
            )
        bport_num = int(bport)
        feed_stop = _threading.Event()
        feed_log = get_logger("epoch-feed")

        def _feed_loop():
            from .p2p.stream import SyncClient as _SC

            client = None
            while not feed_stop.is_set():
                try:
                    if client is None:
                        client = _SC(bport_num, addr or "127.0.0.1")
                    feed = EpochFeed(
                        reg_epoch_chain, client, cfg["blocks_per_epoch"]
                    )
                    feed.feed_once()
                except (OSError, ConnectionError, ValueError) as e:
                    feed_log.warn(
                        "beacon feed retry", peer=cfg["beacon_sync_peer"],
                        err=str(e),
                    )
                    client = None  # beacon peer away: retry next tick
                feed_stop.wait(30.0)

        feed_thread = _threading.Thread(
            target=_feed_loop, daemon=True,
        )  # graftlint: thread-role=serving — devnet feed, /readyz covers it
        manager.register(
            ServiceType.CROSSLINK_SENDING,  # beacon-follow service slot
            _CallbackService(feed_thread.start, feed_stop.set),
        )

    if cfg.get("explorer_port") is not None:
        from .explorer import ExplorerServer

        explorer = ExplorerServer(chain, port=cfg["explorer_port"])
        reg.set("explorer", explorer)
        manager.register(
            ServiceType.SUPPORT_EXPLORER,
            _CallbackService(explorer.start, explorer.stop),
        )

    if cfg.get("rosetta_port") is not None:
        from .rosetta import RosettaServer

        rosetta = RosettaServer(hmy, port=cfg["rosetta_port"])
        reg.set("rosetta", rosetta)
        manager.register(
            ServiceType.ROSETTA,
            _CallbackService(rosetta.start, rosetta.stop),
        )

    if cfg["sync_peers"]:
        clients = []
        for peer in cfg["sync_peers"]:
            addr, _, port = peer.rpartition(":")
            clients.append(SyncClient(int(port), addr or "127.0.0.1"))
        downloader = Downloader(chain, clients,
                                verify_seals=chain.engine is not None)
        downloader.sync_once()  # catch up before consensus starts
        # the node spins this up again if consensus detects it fell
        # behind (node.py _spin_up_sync — consensus/downloader.go analog)
        reg.set("downloader", downloader)

    consensus_thread: list = []
    manager.register(
        ServiceType.CONSENSUS,
        _CallbackService(
            lambda: consensus_thread.append(node.run_forever(
                block_time=float(cfg.get("block_time") or 2.0),
                phase_timeout=cfg.get("phase_timeout"),
            )),
            node.stop,
        ),
    )

    # overload survival (ISSUE 14): a node-wide resource governor
    # sampling RSS / fds / threads / scheduler queue depth / pool fill
    # into the NORMAL->PRESSURED->CRITICAL tiers that drive the
    # tx-pool floor, RPC 429s, scheduler sheds and the sync window;
    # /healthz + /readyz on the MetricsServer report its verdicts.
    # Operator knobs: `governor = false` disarms it, `governor_limits`
    # (a table of governor.Limits field overrides, e.g.
    # rss_pressured_bytes) retunes the thresholds for a node whose
    # healthy steady-state sits above the defaults,
    # `governor_interval` / `governor_ingress_rate` tune the sampling
    # cadence and the PRESSURED-tier per-client admission budget
    if cfg.get("governor", True):
        from . import governor as GV

        limit_overrides = cfg.get("governor_limits") or {}
        gov = GV.ResourceGovernor(
            limits=(GV.Limits(**limit_overrides)
                    if limit_overrides else None),
            interval_s=float(cfg.get("governor_interval", 1.0)),
            pressured_ingress_rate=float(
                cfg.get("governor_ingress_rate", 100.0)
            ),
        )
        gov.attach_pool(pool)

        def _stop_governor():
            gov.stop()
            GV.uninstall()

        manager.register(
            ServiceType.MAINTENANCE,
            _CallbackService(
                lambda: GV.install(gov).start(), _stop_governor,
            ),
        )
    return node, manager, reg, rpc, metrics


def main(argv=None):
    p = argparse.ArgumentParser(prog="harmony-tpu")
    p.add_argument("--config", help="TOML config file")
    p.add_argument("--network")
    p.add_argument("--shard-id", type=int, dest="shard_id")
    p.add_argument("--shard-count", type=int, dest="shard_count")
    p.add_argument("--block-time", type=float, dest="block_time")
    p.add_argument("--phase-timeout", type=float, dest="phase_timeout",
                   help="consensus phase timeout before view change "
                        "(default: the reference's 27 s)")
    p.add_argument("--dev-key-span", type=int, dest="dev_key_span",
                   help="number of consecutive dev keys this node votes "
                        "with (multi-BLS validator)")
    p.add_argument("--datadir")
    p.add_argument("--rpc-port", type=int, dest="rpc_port")
    p.add_argument("--metrics-port", type=int, dest="metrics_port")
    p.add_argument("--pprof-port", type=int, dest="pprof_port",
                   help="serve /debug/pprof profiles on localhost "
                        "(off unless given)")
    p.add_argument("--p2p-port", type=int, dest="p2p_port")
    p.add_argument("--sync-port", type=int, dest="sync_port")
    p.add_argument("--peer", action="append", dest="peers")
    p.add_argument("--bootnode", action="append", dest="bootnodes")
    p.add_argument("--sync-peer", action="append", dest="sync_peers")
    p.add_argument("--beacon-sync-peer", dest="beacon_sync_peer")
    p.add_argument("--dev-keys", type=int, dest="dev_keys")
    p.add_argument("--dev-key-index", type=int, dest="dev_key_index")
    p.add_argument("--verify-backend", dest="verify_backend",
                   choices=["in-process", "sidecar"])
    p.add_argument("--sidecar-addr", dest="sidecar_addr")
    p.add_argument("--no-native-kv", action="store_const", const=False,
                   default=None, dest="native_kv")
    p.add_argument("--fsync", dest="fsync",
                   choices=["none", "batch", "always"],
                   help="shard-DB durability: fsync every atomic "
                        "block-commit batch (default), every write, "
                        "or never (OS-buffered)")
    p.add_argument("--skip-ntp-check", action="store_const", const=False,
                   default=None, dest="ntp_check")
    p.add_argument("--log-level", dest="log_level",
                   choices=["debug", "info", "warn", "error"])
    p.add_argument("--log-path", dest="log_path")
    p.add_argument("--trace", dest="trace", action="store_const",
                   const=True, default=None,
                   help="arm round tracing + the flight recorder "
                        "(/debug/trace on the metrics port)")
    p.add_argument("--trace-sample", type=float, dest="trace_sample",
                   help="root-span sampling rate in [0,1] (default 1)")
    p.add_argument("--trace-slo", type=float, dest="trace_slo",
                   help="round-latency SLO seconds; a slower round "
                        "dumps a flight-recorder snapshot")
    p.add_argument("--trace-dir", dest="trace_dir",
                   help="flight-recorder dump directory")
    p.add_argument("--span-sink-dir", dest="span_sink_dir",
                   help="durable span export: write every finished "
                        "span as JSONL under this directory (implies "
                        "--trace; analyze with tools/round_forensics.py)")
    p.add_argument("--device-verify", dest="device_verify",
                   action="store_const", const=True, default=None,
                   help="force the TPU verification path")
    p.add_argument("--host-verify", dest="device_verify",
                   action="store_const", const=False,
                   help="force the host bigint verification path")
    p.add_argument("--no-verify-seals", dest="verify_seals",
                   action="store_const", const=False, default=None)
    p.add_argument("--revert-to", type=int, dest="revert_to",
                   help="roll the chain back to this block and exit "
                        "(the reference's revert tooling)")
    p.add_argument("--state-retention", type=int, dest="state_retention",
                   help="keep only the last N block states (pruned "
                        "node; default: archive, keep all)")
    p.add_argument("--prune-states", type=int, dest="prune_states",
                   help="offline: delete state blobs older than "
                        "head-N, then exit (blockchain_pruner role)")
    p.add_argument("--snapshot-export", dest="snapshot_export",
                   help="offline: write the head state snapshot to "
                        "this file, then exit")
    p.add_argument("--snapshot-import", dest="snapshot_import",
                   help="offline: install a snapshot file, then exit")
    p.add_argument("--snapshot-trust", action="store_true",
                   dest="snapshot_trust",
                   help="allow --snapshot-import into a chain that "
                        "does not yet have the snapshot's header")
    args = p.parse_args(argv)
    cfg = load_config(args.config, vars(args))
    init_logging(cfg.get("log_level"), cfg.get("log_path"))

    if cfg.get("revert_to") is not None:
        # maintenance mode: open the DB + chain DIRECTLY, roll back,
        # exit — no peers dialed, no ports bound, no sync run
        # (cmd/harmony revert semantics)
        chain = open_chain_for_maintenance(cfg)
        n = chain.revert_to(int(cfg["revert_to"]))
        print(
            f"reverted {n} block(s); head is now {chain.head_number}",
            flush=True,
        )
        return 0

    if (cfg.get("prune_states") is not None
            or cfg.get("snapshot_export") or cfg.get("snapshot_import")):
        # offline state maintenance (core/snapshot.py)
        from .core import snapshot as SN

        chain = open_chain_for_maintenance(cfg)
        if cfg.get("snapshot_import"):
            num = SN.import_snapshot(
                chain, cfg["snapshot_import"],
                trust=bool(cfg.get("snapshot_trust")),
            )
            print(f"snapshot installed at block {num}", flush=True)
        if cfg.get("prune_states") is not None:
            n = SN.prune_states(chain, int(cfg["prune_states"]))
            print(f"pruned {n} historical state(s)", flush=True)
        if cfg.get("snapshot_export"):
            num = SN.export_snapshot(chain, cfg["snapshot_export"])
            print(
                f"snapshot of block {num} -> {cfg['snapshot_export']}",
                flush=True,
            )
        return 0

    # clock sanity before consensus (reference: common/ntp at startup):
    # refuse on MEASURED excessive drift; unreachable NTP only warns
    if cfg.get("ntp_check", True):
        from .ntp import check_clock

        ok, offset = check_clock()
        if not ok:
            print(
                f"FATAL: local clock drifts {offset:+.1f}s from NTP — "
                "a validator this far off misses view windows "
                "(--skip-ntp-check to override)",
                flush=True,
            )
            return 1
        if offset is None:
            print("warning: NTP unreachable, clock check skipped",
                  flush=True)

    # warm the compile surface BEFORE any service thread can reach a
    # device dispatch: after this, every manifest program is a cache
    # hit and the consensus pump never blocks on XLA
    if cfg.get("aot_warmup", True):
        from . import aot

        aot.startup_warmup()

    node, manager, reg, rpc, metrics = build_node(cfg)
    manager.start_services()
    from . import device as DV

    get_logger("node").info(
        "harmony-tpu node up", shard=cfg["shard_id"], rpc=rpc.port,
        metrics=metrics.port, p2p=node.host.port,
        seal_verify=node.chain.engine is not None,
        device_path=DV.device_enabled(),
    )
    print(
        f"harmony-tpu node up: shard {cfg['shard_id']} "
        f"rpc :{rpc.port} metrics :{metrics.port} "
        f"p2p :{node.host.port}",
        flush=True,
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        while not stop.is_set():
            stop.wait(1.0)
    finally:
        manager.stop_services()
    return 0


if __name__ == "__main__":
    sys.exit(main())
