"""Committee data model and election (reference: shard/ +
shard/committee/assignment.go — SURVEY.md §2.2)."""
