"""Committee model + EPoS election.

Behavioral parity with the reference's committee assignment (reference:
shard/shard_state.go:28-49 — Slot/Committee/State model;
shard/committee/assignment.go:319-388 — eposStakedCommittee):

- Harmony-operated slots fill round-robin: shard i gets configured
  accounts at indexes i, i + shardCount, i + 2*shardCount, ...;
- the EPoS auction (staking/effective.py) picks external winners, each
  landing on shard (pubkey-as-big-int mod shardCount);
- a committee's device pubkey table (for the TPU mask/agg-verify path)
  is built once per epoch and cached — the analog of the reference's
  epoch-ctx LRU (reference: internal/chain/engine.go:644-663).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..numeric import Dec
from ..staking import effective


@dataclass
class Slot:
    """reference: shard/shard_state.go:40-49."""

    ecdsa_address: bytes
    bls_pubkey: bytes  # 48-byte serialized form
    effective_stake: Dec | None = None  # None for Harmony-operated slots


@dataclass
class Committee:
    shard_id: int
    slots: list = field(default_factory=list)

    def bls_pubkeys(self):
        return [s.bls_pubkey for s in self.slots]

    def device_pubkey_table(self):
        """(N, 2, 32) affine mont tensor of the committee's pubkeys —
        the epoch-keyed device-resident table of SURVEY.md §7.3."""
        import jax.numpy as jnp

        from ..ops import interop as I
        from ..ref import bls as RB

        pts = [RB.pubkey_from_bytes(k) for k in self.bls_pubkeys()]
        return jnp.asarray(I.g1_batch_affine(pts))


@dataclass
class State:
    """Per-epoch sharding state: one committee per shard."""

    epoch: int
    shards: list = field(default_factory=list)

    def find_committee(self, shard_id: int) -> Committee | None:
        for c in self.shards:
            if c.shard_id == shard_id:
                return c
        return None


def epos_staked_committee(
    epoch: int,
    shard_count: int,
    harmony_accounts: list,  # [(address, bls_pubkey)] in schedule order
    harmony_per_shard: int,
    orders: dict,  # address -> effective.SlotOrder
    external_slots_total: int,
    extended_bound: bool = False,
    exclude_keys=frozenset(),  # slashed keys barred from the auction
) -> State:
    """Build the epoch committee state: Harmony slots round-robin +
    EPoS auction winners sharded by key value."""
    state = State(epoch=epoch)
    for i in range(shard_count):
        com = Committee(shard_id=i)
        for j in range(harmony_per_shard):
            idx = i + j * shard_count
            addr, pub = harmony_accounts[idx]
            com.slots.append(Slot(ecdsa_address=addr, bls_pubkey=pub))
        state.shards.append(com)

    _, winners = effective.apply(
        orders, external_slots_total, extended_bound, exclude_keys
    )
    for w in winners:
        shard_id = int.from_bytes(w.key, "big") % shard_count
        state.shards[shard_id].slots.append(
            Slot(
                ecdsa_address=w.addr,
                bls_pubkey=w.key,
                effective_stake=w.epos_stake,
            )
        )
    return state
