"""Mesh construction and sharded BLS computations (pjit / shard_map).

The reference has no NCCL/MPI analog — its "distributed backend" is
libp2p gossip between hosts (SURVEY.md §2.5); the intra-node scaling story
for the TPU framework is XLA collectives over ICI, expressed here.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops import bls as OB
from ..ops import curve as CV
from ..ops import pairing as OP

BATCH_AXIS = "batch"


def make_mesh(devices=None, axis=BATCH_AXIS) -> Mesh:
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def sharded_verify(mesh: Mesh):
    """Batch-data-parallel verify: inputs sharded over the batch axis.

    Each element is an independent 2-pairing check; XLA partitions the
    whole program with zero collectives.
    """
    spec = NamedSharding(mesh, P(BATCH_AXIS))

    @partial(
        jax.jit,
        in_shardings=(spec, spec, spec),
        out_shardings=spec,
    )
    def fn(pk_aff, h_aff, sig_aff):
        return OB.verify(pk_aff, h_aff, sig_aff)

    return fn


def sharded_masked_sum(mesh: Mesh):
    """Committee-sharded mask aggregation: each device tree-sums its local
    chunk of (pubkey, bit) pairs, partial sums are all_gathered over ICI
    and merged in a log-depth tail on every device (replicated output).

    This is the multi-chip version of Mask.AggregatePublic (reference:
    crypto/bls/mask.go:113-153) for committees too large for one chip.
    """

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=P(),
        # the all_gather + identical merge on every device IS replicated,
        # but the static varying-axes checker cannot infer that
        check_vma=False,
    )
    def fn(pk_jac_chunk, bitmap_chunk):
        local = CV.masked_sum(pk_jac_chunk, bitmap_chunk, CV.FP_OPS)
        partials = jax.lax.all_gather(local, BATCH_AXIS)  # (d, 3, 32)
        total = CV.masked_sum(
            partials,
            jnp.ones(partials.shape[0], dtype=jnp.int32),
            CV.FP_OPS,
        )
        return total

    return fn


def sharded_pairing_product(mesh: Mesh):
    """prod_k e(P_k, Q_k) with the pair axis sharded: local Miller loops
    and local Fp12 products per device, one all_gather, then a replicated
    merge + final exponentiation."""

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=P(),
        check_vma=False,  # replicated by construction (see above)
    )
    def fn(p_chunk, q_chunk):
        fs = OP.miller_loop(p_chunk, q_chunk)
        local = OP.fp12_tree_reduce(fs)
        partials = jax.lax.all_gather(local, BATCH_AXIS)  # (d, fp12)
        return OP.final_exponentiation(OP.fp12_tree_reduce(partials))

    return fn


def sharded_agg_verify(mesh: Mesh):
    """The full multi-chip FBFT quorum check: committee pubkeys + bitmap
    sharded across devices, aggregate built with one all_gather, the
    2-pairing verify replicated (it is latency-bound, not compute-bound,
    at this point)."""
    masked = sharded_masked_sum(mesh)

    @jax.jit
    def fn(pk_jac, bitmap, h_aff, agg_sig_aff):
        agg = masked(pk_jac, bitmap)
        ax, ay = CV.to_affine(agg, CV.FP_OPS)
        pk_aff = jnp.stack([ax, ay])[None]
        return OB.verify(pk_aff, h_aff[None], agg_sig_aff[None])[0]

    return fn
