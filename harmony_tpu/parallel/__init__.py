"""Device-mesh parallelism for the BLS pipeline.

The reference scales consensus crypto over committee size and shard count
(SURVEY.md §2.7); here those axes map onto a JAX device mesh:

- independent verifies (block replay, per-vote checks) shard over the
  batch axis — pure data parallelism via sharding annotations;
- committee aggregation (masked G1 sums over 1000+ validators) shards the
  committee axis via shard_map, with an all_gather of per-device partial
  sums and a log-depth merge — the collective rides ICI;
- products of pairings shard the pair axis, combining per-device Miller
  products before one replicated final exponentiation.
"""
