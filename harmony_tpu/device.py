"""The device-path switch: one knob deciding whether verification
choke points (FBFT proofs, view-change aggregates, engine seal checks)
run on the TPU ops or the host bigint twin.

The reference has no such switch — herumi IS its only path; here the
host bigint layer (ref/) is the portable fallback and the TPU ops
(ops/) are the production path.  Default is AUTO: device when JAX's
default backend is an accelerator, host under the CPU-only test image
(tests/conftest.py pins JAX_PLATFORMS=cpu, so the suite keeps its
cached-executable-friendly host route automatically).

COUNTERS record how many checks executed on device — a localnet run
can ASSERT the flagship path is live (VERDICT r1: the ops were dead
code in the shipped binary).
"""

from __future__ import annotations

import threading
import time

from . import aot
from . import faultinject as FI
from . import prof
from . import trace
from .log import get_logger
from .metrics import Gauge, Histogram, LockedCounters
from .resilience import CircuitBreaker

_log = get_logger("device")

_FORCED: bool | None = None
_AUTO: bool | None = None
_LOCK = threading.Lock()

COUNTERS = LockedCounters(
    "verify", "agg_verify", "batch_verify", "ref_fallback"
)

# Observability singletons (exposed through metrics.Registry alongside
# COUNTERS): per-dispatch latency, host<->device transfer bytes, and
# the jit program-shape cache — was this dispatch's (kernel, bucket)
# shape already compiled in-process, and how long did the compiling
# first dispatch take?  All annotated onto the active trace span too,
# so /debug/trace shows WHY one dispatch in a round cost 100x.
DISPATCH_SECONDS = Histogram(
    "harmony_device_dispatch_seconds",
    "wall time of one breaker-guarded device dispatch",
)
TRANSFER = LockedCounters("h2d", "d2h")
JIT = LockedCounters("hit", "miss")
JIT_COMPILE_SECONDS = Gauge(
    "harmony_device_jit_compile_seconds",
    "wall time of the first (compiling) dispatch per program shape",
)

_SEEN_PROGRAMS: set = set()
_SEEN_LOCK = threading.Lock()


def _program_first_use(program: str) -> bool:
    """True exactly once per program shape per process — the dispatch
    that pays the JIT compile (or the twin's first wire-up)."""
    with _SEEN_LOCK:
        first = program not in _SEEN_PROGRAMS
        if first:
            _SEEN_PROGRAMS.add(program)
    JIT.inc("miss" if first else "hit")
    return first


def mark_warm(program: str) -> None:
    """aot.warmup's hook: record ``program`` as already compiled (or
    twin-wired) so serving-path dispatches account a warm cache instead
    of paying a first-use compile.  No JIT counter movement — warmup is
    neither a hit nor a serving-path miss."""
    with _SEEN_LOCK:
        _SEEN_PROGRAMS.add(program)

# The device-dispatch circuit breaker: a backend that keeps raising (a
# wedged accelerator tunnel, a dying sidecar of the twin kernels, an
# injected chaos fault) trips it OPEN and every check routes straight
# to the reference host path until a half-open probe re-admits the TPU.
# Consensus keeps finalizing on the slow-but-correct path instead of
# stalling — the fail-fast contract the FBFT layer assumes.
BREAKER = CircuitBreaker("device", failure_threshold=5,
                         reset_timeout_s=30.0)

# Optional per-dispatch latency budget (seconds).  None disables the
# check — the CPU test image legitimately takes seconds per eager
# pairing, so only deployments (and chaos tests) arm it.  A dispatch
# that completes but overruns the budget still returns its (correct)
# result; it is counted as a breaker failure so a consistently slow
# backend trips OPEN and later checks skip the wait entirely.
DISPATCH_DEADLINE_S: float | None = None


def set_dispatch_deadline(seconds: float | None) -> None:
    global DISPATCH_DEADLINE_S
    DISPATCH_DEADLINE_S = seconds


def _guarded(kind: str, dispatch, fallback):
    """Run one device dispatch under the breaker.

    Raise -> breaker failure + reference fallback (transparent: the
    caller still gets a correct bool).  Deadline overrun -> breaker
    failure, device result kept.  Breaker OPEN -> fallback without
    touching the device at all.  The whole attempt (fallback included,
    when one runs) is a ``device.dispatch`` trace span nested under
    whatever consensus/sidecar span caused it."""
    if not BREAKER.allow():
        COUNTERS.inc("ref_fallback")
        with trace.span("device.dispatch", component="device",
                        kind=kind, outcome="breaker_open"):
            return fallback()
    t0 = time.monotonic()
    with trace.span("device.dispatch", component="device", kind=kind):
        try:
            FI.fire("device.dispatch")
            out = dispatch()
        except Exception as e:  # noqa: BLE001 — any backend failure
            # degrades to the host path, never up into consensus
            BREAKER.record_failure()
            COUNTERS.inc("ref_fallback")
            _log.warn("device dispatch failed; reference fallback",
                      kind=kind, error=str(e))
            trace.annotate(outcome="ref_fallback", error=str(e))
            DISPATCH_SECONDS.observe(time.monotonic() - t0)
            return fallback()
        elapsed = time.monotonic() - t0
        DISPATCH_SECONDS.observe(elapsed)
        if (DISPATCH_DEADLINE_S is not None
                and elapsed > DISPATCH_DEADLINE_S):
            BREAKER.record_failure()
            _log.warn("device dispatch exceeded deadline", kind=kind,
                      budget_s=DISPATCH_DEADLINE_S)
            trace.annotate(outcome="deadline_overrun")
        else:
            BREAKER.record_success()
        return out

# Committee tables are padded to one of these pinned sizes so every
# epoch/committee shares a small set of compiled programs (pad keys are
# affine (0,0) = infinity, masked off by zero bitmap bits).
COMMITTEE_BUCKETS = (8, 16, 32, 64, 128, 256, 512, 1024)


# graftlint: bucket-fn registry=COMMITTEE_BUCKETS
def committee_bucket(n: int) -> int:
    """Smallest pinned bucket admitting ``n`` committee slots.  Widths
    past the largest bucket raise instead of minting an unbounded
    program-shape family (the old round-up tail was exactly the
    NEWVIEW-wedge class GL15 now rejects): no deployed committee
    exceeds 1024 slots, and admitting one is a REGISTRY change —
    extend COMMITTEE_BUCKETS so the warmup manifest precompiles it."""
    for b in COMMITTEE_BUCKETS:
        if n <= b:
            return b
    raise ValueError(
        f"committee width {n} exceeds the largest pinned bucket "
        f"{COMMITTEE_BUCKETS[-1]}; extend COMMITTEE_BUCKETS (and "
        f"regenerate the compile manifest) to admit it")


class CommitteeTable:
    """A committee's pubkeys as ONE device-resident padded affine tensor
    — the epoch-keyed table of SURVEY §7.3 that lets steady-state quorum
    checks ship only a bitmap + 96-byte signature to the device."""

    def __init__(self, points):
        import numpy as np

        from .ops import interop as I

        self.n = len(points)
        self.size = committee_bucket(max(self.n, 1))
        # the original reference points are kept (cheap: references
        # only) so a failing backend can fall back to the host bigint
        # path without re-deriving them from the device layout
        self.points = list(points)
        arr = np.zeros((self.size, 2, 32), dtype=np.int32)
        if self.n:
            arr[: self.n] = I.g1_batch_affine(points)
        self._np = arr
        self._dev = None

    def device_array(self):
        if kernel_twin_active():
            return self._np  # twins are numpy-native; keep jax unloaded
        import jax.numpy as jnp

        if self._dev is None:
            self._dev = jnp.asarray(self._np)
            # the one table upload this cache exists to amortize —
            # count it so /metrics shows the epoch-boundary spike
            TRANSFER.inc("h2d", self._np.nbytes)
        return self._dev

    def pad_bits(self, bits):
        import numpy as np

        out = np.zeros((self.size,), dtype=np.int32)
        out[: self.n] = np.asarray(bits, dtype=np.int32)[: self.n]
        return out


_TABLE_CACHE: "dict[tuple, CommitteeTable]" = {}
_TABLE_CACHE_CAP = 8
_TABLE_CACHE_LOCK = threading.Lock()


def get_committee_table(serialized_keys, points) -> CommitteeTable:
    """Per-committee table cache: a fresh FBFT Validator is built every
    round, but the committee changes only at epoch boundaries — the
    host->device conversion must amortize across rounds, not re-run
    per block.  Keyed by the serialized key tuple; bounded (a node
    tracks at most its own + a few foreign committees at once).

    Locked: consensus, view-change and replay threads all reach this
    cache; eviction (pop during another thread's insert) must not race.
    The CommitteeTable build itself runs outside the lock — it is the
    expensive host->device conversion, and a duplicate build loses only
    work, not correctness."""
    key = tuple(serialized_keys)
    with _TABLE_CACHE_LOCK:
        tbl = _TABLE_CACHE.get(key)
    if tbl is None:
        tbl = CommitteeTable(points)
        with _TABLE_CACHE_LOCK:
            if (key not in _TABLE_CACHE
                    and len(_TABLE_CACHE) >= _TABLE_CACHE_CAP):
                _TABLE_CACHE.pop(next(iter(_TABLE_CACHE)))
            tbl = _TABLE_CACHE.setdefault(key, tbl)
    return tbl


def use_device(flag: bool | None):
    """Force the path (True/False) or restore AUTO (None)."""
    global _FORCED
    _FORCED = flag


def _probe_backend() -> bool:
    """jax.default_backend() not in ('cpu',) — run OFF-thread with a
    deadline: a wedged accelerator tunnel (the axon TPU transport has
    hung backend init on this image, r1 and r3) must degrade the node
    to the host path, not hang startup forever."""
    result: list = []

    def probe():
        try:
            import jax

            result.append(jax.default_backend() not in ("cpu",))
        except Exception:  # noqa: BLE001 — no jax = host only
            result.append(False)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(float(__import__("os").environ.get("DEVICE_PROBE_S", "20")))
    if not result:
        return False  # probe wedged: host path (thread left to die)
    return result[0]


def device_enabled() -> bool:
    global _AUTO
    if _FORCED is not None:
        return _FORCED
    if _AUTO is None:
        # probe OUTSIDE _LOCK: it joins a worker thread for up to
        # DEVICE_PROBE_S seconds, and the consensus/insert paths reach
        # this under their own locks — holding _LOCK across the join
        # would stall every caller behind one wedged probe (GL06).
        # Racing probes are idempotent; first answer under the lock
        # wins and the others confirm it.
        probed = _probe_backend()
        with _LOCK:
            if _AUTO is None:
                _AUTO = probed
    return _AUTO


_VERIFY_BUCKET = 8
_verify_fn = None
_agg_verify_fn = None
_agg_verify_batch_fn = None


def kernel_twin_active() -> bool:
    """HARMONY_KERNEL_TWIN=1 swaps the XLA kernels for the bigint/
    native-backed twins (ops/twin.py): a LIVE node exercises every
    device-path layer — table padding, bitmap routing, COUNTERS, batch
    chunking — on hosts where XLA:CPU pairing execution is measured in
    minutes.  The kernel math stays covered by the ops parity tier."""
    import os

    return os.environ.get("HARMONY_KERNEL_TWIN") == "1"


def _kernels():
    if kernel_twin_active():
        from .ops import twin as T

        return T
    from .ops import bls as OB

    return OB


# The jit factories hold ONE jitted callable each; per-dispatch program
# selection (warmed AOT executable vs. shipped jaxexport artifact vs.
# plain jit) happens at the call sites through ``aot.resolve(program)``
# — the program NAME computed there is the single source of truth, so
# the compile-surface analysis (GL15) can derive every shape from the
# pinned bucket registries instead of chasing runtime ``.shape[0]``s.


def _get_verify_fn():
    global _verify_fn
    if kernel_twin_active():
        return _kernels().verify
    if _verify_fn is None:
        import jax

        from .ops import bls as OB

        _verify_fn = jax.jit(OB.verify)
    return _verify_fn


def _get_agg_verify_fn():
    global _agg_verify_fn
    if kernel_twin_active():
        return _kernels().agg_verify
    if _agg_verify_fn is None:
        import jax

        from .ops import bls as OB

        _agg_verify_fn = jax.jit(OB.agg_verify)
    return _agg_verify_fn


def _get_agg_verify_batch_fn():
    global _agg_verify_batch_fn
    if kernel_twin_active():
        return _kernels().agg_verify_batch
    if _agg_verify_batch_fn is None:
        import jax

        from .ops import bls as OB

        _agg_verify_batch_fn = jax.jit(OB.agg_verify_batch)
    return _agg_verify_batch_fn


_masked_sum_fn = None


def _get_masked_sum_fn():
    """One jitted masked tree-sum per process (shapes bucketed by the
    committee registry) — the fused path for accelerators.  The CPU
    route keeps the eager ops (same rationale as ``_fused``)."""
    global _masked_sum_fn
    if _masked_sum_fn is None:
        import jax

        from .ops import curve as CV

        _masked_sum_fn = jax.jit(
            lambda pks, bm: CV.masked_sum(pks, bm, CV.FP_OPS))
    return _masked_sum_fn


def _fused() -> bool:
    """One truly-fused jitted agg_verify program on real accelerators.
    On XLA:CPU every distinct jitted pairing-shaped program costs
    minutes of LLVM time (see docs/NOTES_r2.md), so the CPU route runs
    the SAME ops eagerly — op-by-op dispatch reuses small in-process
    kernel caches, the path the ops suite exercises in seconds.  Same
    math, same counters, zero big executables.  Twin kernels take the
    'fused' branch (they are plain python callables either way)."""
    if kernel_twin_active():
        return True
    import jax

    return jax.default_backend() != "cpu"


def _ref_agg_verify(table: CommitteeTable, bits, h_point,
                    sig_point) -> bool:
    """Host bigint twin of the fused quorum check — the fallback when
    the device backend is open-circuited or raised mid-dispatch."""
    from .ref import bls as RB
    from .ref.curve import g1

    agg = None
    for pt, bit in zip(table.points, bits):
        if bit:
            agg = g1.add(agg, pt)
    if agg is None:
        return False
    return RB.verify_hashed(agg, h_point, sig_point)


def agg_verify_on_device(table: CommitteeTable, bits, payload: bytes,
                         sig_point) -> bool:
    """THE fused FBFT quorum check: committee table resident on device,
    bitmap in, bool out — masked G1 tree-sum AND the 2-pairing product
    with no host affine round-trip (reference semantics:
    internal/chain/engine.go:619-642 in one shot).  Breaker-guarded:
    a raising or open-circuited backend degrades transparently to the
    reference host path."""
    from .ref.hash_to_curve import hash_to_g2

    with prof.stage("hash_to_g2"):
        h_point = hash_to_g2(payload)
    return agg_verify_hashed_on_device(table, bits, h_point, sig_point)


def agg_verify_hashed_on_device(table: CommitteeTable, bits, h_point,
                                sig_point) -> bool:
    """``agg_verify_on_device`` with the payload already hashed to G2 —
    the shape the scheduler submits (hash-to-curve runs on the
    submitting thread, never on the shared flush thread)."""
    h = h_point
    COUNTERS.inc("agg_verify")

    def dispatch() -> bool:
        import numpy as np

        from .ops import interop as I

        if kernel_twin_active():
            asarray = np.asarray
            OB = None  # twins only: jax stays unloaded
        else:
            import jax.numpy as jnp

            from .ops import bls as OB

            asarray = jnp.asarray
        fused = _fused()
        fn = _get_agg_verify_fn() if fused else OB.agg_verify
        bm = table.pad_bits(bits)
        hh = np.asarray(I.g2_affine_to_arr(h))
        sg = np.asarray(I.g2_affine_to_arr(sig_point))
        TRANSFER.inc("h2d", bm.nbytes + hh.nbytes + sg.nbytes)
        program = f"agg_verify_b{table.size}"
        if fused and not kernel_twin_active():
            warm = aot.resolve(program)
            if warm is not None:
                fn = warm
        first = _program_first_use(program) if fused else False
        t0 = time.monotonic()
        call_args = (
            table.device_array(), asarray(bm), asarray(hh), asarray(sg)
        )
        ok = fn(*call_args)
        res = np.asarray(ok)
        elapsed = time.monotonic() - t0
        if first:
            JIT_COMPILE_SECONDS.set(elapsed, program=program)
            prof.on_first_dispatch(program, fn, call_args, elapsed)
        else:
            prof.observe_execute(program, elapsed)
        TRANSFER.inc("d2h", res.nbytes)
        trace.annotate(
            program=program, bucket=table.size,
            jit_cache=("miss" if first else "hit") if fused else "eager",
            h2d_bytes=bm.nbytes + hh.nbytes + sg.nbytes,
            d2h_bytes=res.nbytes,
        )
        return bool(res)

    return _guarded("agg_verify", dispatch,
                    lambda: _ref_agg_verify(table, bits, h, sig_point))


def masked_pubkey_sum(points, bits, fallback, cache=None):
    """Masked Jacobian tree-sum of a pubkey list, breaker-guarded.

    The NEWVIEW adoption path aggregates a *candidate* mask's pubkeys
    — a mask that is not this node's own, so the committee-table
    bucket cache doesn't apply.  ``cache`` is an optional one-slot
    list holding the device-resident stacked point tensor across
    calls on the same mask (the CommitteeTable idiom without the
    bucket padding: masks own their width).

    This used to be the one device call outside guarded dispatch (the
    PR-15 pump-wedge class): a raising backend now degrades to the
    host ``fallback`` instead of surfacing into consensus, an OPEN
    breaker skips the device entirely, and the dispatch rides the
    same trace span / deadline accounting as every other kind.
    Callers keep the twin early-out (twins keep jax unloaded), but a
    twin activating between check and dispatch still falls back here
    rather than importing jax.
    """
    if kernel_twin_active():
        return fallback()
    COUNTERS.inc("masked_pubkey_sum")

    def dispatch():
        import jax.numpy as jnp
        import numpy as np

        from .ops import curve as CV
        from .ops import interop as I

        # pad mask and points to the committee bucket: one compiled
        # masked-sum program per PINNED width instead of one per mask
        # width (the PR-15 wedge minted a fresh program at every new
        # committee size).  Pad lanes carry zero bits, so the tree sum
        # selects infinity for them regardless of the pad values.
        width = committee_bucket(len(points))
        pks = cache[0] if cache is not None else None
        if pks is None:
            arr = np.zeros((width, 3, 32), dtype=np.int32)
            if points:
                arr[: len(points)] = np.stack(
                    [I.g1_affine_to_jacobian_arr(p) for p in points])
            pks = jnp.asarray(arr)
            if cache is not None:
                cache[0] = pks
        bm = np.zeros((width,), dtype=np.int32)
        bm[: len(points)] = np.asarray(bits, dtype=np.int32)
        TRANSFER.inc("h2d", bm.nbytes)
        program = f"masked_sum_w{width}"
        fused = _fused()
        fn = None
        if fused and not kernel_twin_active():
            fn = aot.resolve(program)
            if fn is None:
                fn = _get_masked_sum_fn()
        first = _program_first_use(program) if fused else False
        t0 = time.monotonic()
        if fn is not None:
            agg = fn(pks, jnp.asarray(bm))
        else:
            agg = CV.masked_sum(pks, jnp.asarray(bm), CV.FP_OPS)
        res = np.asarray(agg)
        elapsed = time.monotonic() - t0
        if first:
            JIT_COMPILE_SECONDS.set(elapsed, program=program)
        TRANSFER.inc("d2h", res.nbytes)
        trace.annotate(program=program, width=width,
                       jit_cache=("miss" if first else "hit")
                       if fused else "eager",
                       h2d_bytes=bm.nbytes, d2h_bytes=res.nbytes)
        return I.arr_to_g1_affine(res)

    return _guarded("masked_pubkey_sum", dispatch, fallback)


# Pinned batch widths for the replay path (same rationale as the
# committee buckets: a handful of compiled programs covers every batch
# size).  CPU caps at 64 — XLA:CPU's LLVM JIT struggles with the
# 256-wide pairing programs on the test image.
BATCH_BUCKETS_CPU = (8, 64)
BATCH_BUCKETS_TPU = (8, 64, 256)


# graftlint: bucket-fn registry=BATCH_BUCKETS_CPU,BATCH_BUCKETS_TPU
def batch_buckets() -> tuple:
    return BATCH_BUCKETS_TPU if device_enabled() else BATCH_BUCKETS_CPU


# graftlint: bucket-fn registry=BATCH_BUCKETS_CPU,BATCH_BUCKETS_TPU
def batch_bucket(n: int) -> int:
    for b in batch_buckets():
        if n <= b:
            return b
    return batch_buckets()[-1]


def agg_verify_batch_on_device(table: CommitteeTable, bits_list,
                               h_points, sig_points):
    """Replay-path batch: B quorum checks against one committee table,
    chunked to pinned batch widths — each chunk is ONE program (masked
    tree-sums + pairing checks together).  h_points are pre-hashed
    payload points (host hash-to-G2); returns list[bool].  Breaker-
    guarded like the single check: a backend failure anywhere in the
    batch re-runs the whole window on the reference host path."""

    def dispatch():
        import numpy as np

        from .ops import interop as I

        if kernel_twin_active():
            asarray = np.asarray
            OB = None  # twins only: jax stays unloaded
        else:
            import jax.numpy as jnp

            from .ops import bls as OB

            asarray = jnp.asarray
        results = []
        widest = batch_buckets()[-1]
        fused = _fused()
        fn = (_get_agg_verify_batch_fn() if fused
              else OB.agg_verify_batch)
        tbl = table.device_array()
        # dispatch EVERY chunk before syncing ANY result: a per-chunk
        # np.asarray inside this loop forced a device round-trip between
        # programs, serializing the replay pipeline exactly where the
        # batched verification should stream (GL07)
        pending = []  # (ok device array, live lane count)
        h2d = 0
        compiles = []  # (program, first-dispatch seconds)
        for start in range(0, len(bits_list), widest):
            chunk_bits = bits_list[start:start + widest]
            chunk_h = h_points[start:start + widest]
            chunk_s = sig_points[start:start + widest]
            n, padded = len(chunk_bits), batch_bucket(len(chunk_bits))
            sel = list(range(n)) + [0] * (padded - n)  # pad lanes sliced
            bm = np.stack([table.pad_bits(chunk_bits[i]) for i in sel])
            hh = np.asarray(I.g2_batch_affine([chunk_h[i] for i in sel]))
            sg = np.asarray(I.g2_batch_affine([chunk_s[i] for i in sel]))
            h2d += bm.nbytes + hh.nbytes + sg.nbytes
            program = f"agg_verify_batch_b{table.size}x{padded}"
            chunk_fn = fn
            if fused and not kernel_twin_active():
                warm = aot.resolve(program)
                if warm is not None:
                    chunk_fn = warm
            first = _program_first_use(program) if fused else False
            t0 = time.monotonic()
            call_args = (tbl, asarray(bm), asarray(hh), asarray(sg))
            ok = chunk_fn(*call_args)
            if first:
                compiles.append((program, time.monotonic() - t0))
                prof.on_first_dispatch(program, chunk_fn, call_args,
                                       time.monotonic() - t0)
            COUNTERS.inc("batch_verify")
            # a compiling chunk's drain time is compile, not execute —
            # it is recorded by on_first_dispatch, not the exec histo
            pending.append((ok, n, program, None if first else t0))
        TRANSFER.inc("h2d", h2d)
        d2h = 0
        for ok, n, program, t_issue in pending:
            # all programs are in flight; this loop only drains results
            flat = np.asarray(ok)  # graftlint: disable=GL07 reviewed: every chunk dispatched above, this is the drain
            # issue->drain latency per chunk: what "execute" means for
            # a streamed dispatch (includes queueing behind siblings)
            if t_issue is not None:
                prof.observe_execute(program, time.monotonic() - t_issue)
            d2h += flat.nbytes
            results.extend(bool(x) for x in flat[:n])
        TRANSFER.inc("d2h", d2h)
        for program, dur in compiles:
            JIT_COMPILE_SECONDS.set(dur, program=program)
        trace.annotate(
            chunks=len(pending), checks=len(bits_list),
            jit_compiles=len(compiles), h2d_bytes=h2d, d2h_bytes=d2h,
        )
        return results

    def fallback():
        return [
            _ref_agg_verify(table, bits, h, sig)
            for bits, h, sig in zip(bits_list, h_points, sig_points)
        ]

    return _guarded("batch_verify", dispatch, fallback)


def verify_on_device(pk_point, payload: bytes, sig_point) -> bool:
    """One aggregate check e(-G1, sig) e(pk, H(payload)) == 1 on the
    device, through the pinned-bucket batched verify (pads to 8 so the
    compiled program is shared with every other single check).

    pk_point: reference affine G1 point; sig_point: affine G2 point;
    payload: signed bytes (hash-to-G2 stays host-side per SURVEY §7.2).
    Breaker-guarded with a host bigint fallback like the fused paths.
    """
    from .ref.hash_to_curve import hash_to_g2

    with prof.stage("hash_to_g2"):
        h = hash_to_g2(payload)
    COUNTERS.inc("verify")

    def dispatch() -> bool:
        import numpy as np

        from .ops import interop as I

        if kernel_twin_active():
            asarray = np.asarray
            OB = None  # twins only: jax stays unloaded
        else:
            import jax.numpy as jnp

            from .ops import bls as OB

            asarray = jnp.asarray
        # fused: pad to the pinned bucket so one compiled program serves
        # every single check; eager (CPU): width 1, no padding — each
        # lane would re-run the whole pairing op-by-op.  Twin kernels
        # skip the padding: each lane costs a real host check
        fused = _fused()
        width = (_VERIFY_BUCKET
                 if fused and not kernel_twin_active() else 1)
        pk = np.asarray(I.g1_batch_affine([pk_point] * width))
        hh = np.asarray(I.g2_batch_affine([h] * width))
        sg = np.asarray(I.g2_batch_affine([sig_point] * width))
        TRANSFER.inc("h2d", pk.nbytes + hh.nbytes + sg.nbytes)
        program = f"verify_w{width}"
        fn = _get_verify_fn() if fused else OB.verify
        if fused and not kernel_twin_active():
            warm = aot.resolve(program)
            if warm is not None:
                fn = warm
        first = _program_first_use(program) if fused else False
        t0 = time.monotonic()
        call_args = (asarray(pk), asarray(hh), asarray(sg))
        ok = fn(*call_args)
        res = np.asarray(ok)
        elapsed = time.monotonic() - t0
        if first:
            JIT_COMPILE_SECONDS.set(elapsed, program=program)
            prof.on_first_dispatch(program, fn, call_args, elapsed)
        else:
            prof.observe_execute(program, elapsed)
        TRANSFER.inc("d2h", res.nbytes)
        trace.annotate(
            program=program, width=width,
            jit_cache=("miss" if first else "hit") if fused else "eager",
            h2d_bytes=pk.nbytes + hh.nbytes + sg.nbytes,
            d2h_bytes=res.nbytes,
        )
        return bool(res[0])

    def fallback() -> bool:
        from .ref import bls as RB

        return RB.verify_hashed(pk_point, h, sig_point)

    return _guarded("verify", dispatch, fallback)


def verify_many_on_device(pk_points, h_points, sig_points) -> list:
    """N *independent* single checks — distinct keys, distinct payload
    points — fused into pinned-width ``verify`` programs: the
    continuous-batching shape the scheduler feeds with coalesced
    tx-pool / RPC / sender-sig traffic (each of which used to pay a
    full dispatch round-trip alone).  h_points are pre-hashed payload
    G2 points.  Pad lanes are affine infinity (sliced off before
    return).  Breaker-guarded; fallback re-checks each lane on the
    host bigint path."""
    n_total = len(pk_points)
    COUNTERS.inc("verify", n_total)

    def dispatch():
        import numpy as np

        from .ops import interop as I

        if kernel_twin_active():
            asarray = np.asarray
            OB = None  # twins only: jax stays unloaded
        else:
            import jax.numpy as jnp

            from .ops import bls as OB

            asarray = jnp.asarray
        fused = _fused()
        fn = _get_verify_fn() if fused else OB.verify
        widest = batch_buckets()[-1]
        results = []
        # dispatch every chunk before syncing any result (the GL07
        # stream discipline agg_verify_batch_on_device established)
        pending = []  # (ok device array, live lane count)
        h2d = 0
        compiles = []  # (program, first-dispatch seconds)
        for start in range(0, n_total, widest):
            chunk_pk = pk_points[start:start + widest]
            chunk_h = h_points[start:start + widest]
            chunk_s = sig_points[start:start + widest]
            n = len(chunk_pk)
            padded = batch_bucket(n) if fused else n
            pad = padded - n
            pk = np.asarray(I.g1_batch_affine(chunk_pk))
            hh = np.asarray(I.g2_batch_affine(chunk_h))
            sg = np.asarray(I.g2_batch_affine(chunk_s))
            if pad:
                # pad with affine infinity: the twins short-circuit
                # those lanes and the kernels' pad output is sliced off
                pk = np.concatenate(
                    [pk, np.zeros((pad,) + pk.shape[1:], pk.dtype)]
                )
                hh = np.concatenate(
                    [hh, np.zeros((pad,) + hh.shape[1:], hh.dtype)]
                )
                sg = np.concatenate(
                    [sg, np.zeros((pad,) + sg.shape[1:], sg.dtype)]
                )
            h2d += pk.nbytes + hh.nbytes + sg.nbytes
            program = f"verify_w{padded}"
            chunk_fn = fn
            if fused and not kernel_twin_active():
                warm = aot.resolve(program)
                if warm is not None:
                    chunk_fn = warm
            first = _program_first_use(program) if fused else False
            t0 = time.monotonic()
            call_args = (asarray(pk), asarray(hh), asarray(sg))
            ok = chunk_fn(*call_args)
            if first:
                compiles.append((program, time.monotonic() - t0))
                prof.on_first_dispatch(program, chunk_fn, call_args,
                                       time.monotonic() - t0)
            pending.append((ok, n, program, None if first else t0))
        TRANSFER.inc("h2d", h2d)
        d2h = 0
        for ok, n, program, t_issue in pending:
            # all programs are in flight; this loop only drains results
            flat = np.asarray(ok)  # graftlint: disable=GL07 reviewed: every chunk dispatched above, this is the drain
            # issue->drain latency per chunk (see batch path above)
            if t_issue is not None:
                prof.observe_execute(program, time.monotonic() - t_issue)
            d2h += flat.nbytes
            results.extend(bool(x) for x in flat[:n])
        TRANSFER.inc("d2h", d2h)
        for program, dur in compiles:
            JIT_COMPILE_SECONDS.set(dur, program=program)
        trace.annotate(
            chunks=len(pending), checks=n_total,
            jit_compiles=len(compiles), h2d_bytes=h2d, d2h_bytes=d2h,
        )
        return results

    def fallback():
        from .ref import bls as RB

        return [
            RB.verify_hashed(pk, h, sig)
            for pk, h, sig in zip(pk_points, h_points, sig_points)
        ]

    return _guarded("verify_many", dispatch, fallback)
