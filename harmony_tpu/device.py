"""The device-path switch: one knob deciding whether verification
choke points (FBFT proofs, view-change aggregates, engine seal checks)
run on the TPU ops or the host bigint twin.

The reference has no such switch — herumi IS its only path; here the
host bigint layer (ref/) is the portable fallback and the TPU ops
(ops/) are the production path.  Default is AUTO: device when JAX's
default backend is an accelerator, host under the CPU-only test image
(tests/conftest.py pins JAX_PLATFORMS=cpu, so the suite keeps its
cached-executable-friendly host route automatically).

COUNTERS record how many checks executed on device — a localnet run
can ASSERT the flagship path is live (VERDICT r1: the ops were dead
code in the shipped binary).
"""

from __future__ import annotations

import threading

_FORCED: bool | None = None
_AUTO: bool | None = None
_LOCK = threading.Lock()

COUNTERS = {"verify": 0, "agg_verify": 0, "batch_verify": 0}


def use_device(flag: bool | None):
    """Force the path (True/False) or restore AUTO (None)."""
    global _FORCED
    _FORCED = flag


def device_enabled() -> bool:
    global _AUTO
    if _FORCED is not None:
        return _FORCED
    if _AUTO is None:
        with _LOCK:
            if _AUTO is None:
                try:
                    import jax

                    _AUTO = jax.default_backend() not in ("cpu",)
                except Exception:  # noqa: BLE001 — no jax = host only
                    _AUTO = False
    return _AUTO


_VERIFY_BUCKET = 8
_verify_fn = None


def _get_verify_fn():
    global _verify_fn
    if _verify_fn is None:
        import jax

        from .ops import bls as OB

        _verify_fn = jax.jit(OB.verify)
    return _verify_fn


def verify_on_device(pk_point, payload: bytes, sig_point) -> bool:
    """One aggregate check e(-G1, sig) e(pk, H(payload)) == 1 on the
    device, through the pinned-bucket batched verify (pads to 8 so the
    compiled program is shared with every other single check).

    pk_point: reference affine G1 point; sig_point: affine G2 point;
    payload: signed bytes (hash-to-G2 stays host-side per SURVEY §7.2).
    """
    import jax.numpy as jnp
    import numpy as np

    from .ops import interop as I
    from .ref.hash_to_curve import hash_to_g2

    h = hash_to_g2(payload)
    pk = np.asarray(I.g1_batch_affine([pk_point] * _VERIFY_BUCKET))
    hh = np.asarray(I.g2_batch_affine([h] * _VERIFY_BUCKET))
    sg = np.asarray(I.g2_batch_affine([sig_point] * _VERIFY_BUCKET))
    ok = _get_verify_fn()(
        jnp.asarray(pk), jnp.asarray(hh), jnp.asarray(sg)
    )
    COUNTERS["verify"] += 1
    return bool(np.asarray(ok)[0])
