"""Continuous-batching verification scheduler: ONE shared device queue.

Every caller that needs a BLS check — consensus quorum proofs, sync
replay seal batches, tx-pool/RPC single signatures, the sidecar server's
wire requests — used to own its dispatch: the engine padded its own
chunks, consensus verified one aggregate at a time, and every single-sig
check paid a full dispatch round-trip while the device idled between
small bursty batches.  This module is the missing subsystem between
those callers and ``device.py``: an inference-server-style continuous
batcher (Handel, arXiv 1906.05132, restructures *who batches when* the
same way; arXiv 2302.00418 shows verification latency under load — not
peak kernel throughput — gates BFT rounds).

Shape:

- **Requests + futures.**  Callers submit :class:`VerifyRequest`\\s
  (single-sig, masked-aggregate, sidecar-backend) and get a
  :class:`VerifyFuture`; the caller's thread blocks only on its own
  result, never on the device queue.
- **Priority lanes** — consensus > sync > ingress/RPC — with a
  starvation bound: a non-empty lane passed over ``starvation_limit``
  times is served next regardless of priority, and lower lanes also
  ride along as *backfill* in any flush with spare bucket slots.
  FIFO holds within each lane.
- **Deadline-aware admission**: a request whose
  :class:`~harmony_tpu.resilience.Deadline` cannot survive the current
  queue depth (EWMA dispatch cost x batches ahead + flush window) fails
  fast with ``DeadlineExceeded`` instead of stalling a round; a request
  that expires while queued is never dispatched.
- **Backpressure**: bounded per-lane queues; overflow — and any request
  arriving while the PR 3 device breaker is OPEN — is *shed* to the CPU
  reference path on the caller's thread (bitwise-identical result,
  counted in ``harmony_sched_shed_total``).
- **Adaptive flush**: dispatch immediately when the queue is otherwise
  idle (no batching opportunity pending), wait up to ``flush_window_s``
  when requests are streaming in — the classic continuous-batching
  latency/throughput tradeoff.

The scheduler thread holds **no lock across dispatch**: queue pops
happen under ``_cond``, the fused device program runs bare, and metric
/ future completion work runs after the critical section (the same
discipline as ``resilience.CircuitBreaker._note``).  Sidecar-backend
batches are handed to a separate worker thread so a wedged sidecar can
back up *its* lane without stalling device flushes.

Observability: ``sched.enqueue`` spans under the caller's round trace,
``sched.flush`` spans resumed from the oldest request's carried
context, and the ``harmony_sched_*`` metric families exposed through
``metrics.Registry`` (queue depth, per-lane wait, batch fill ratio,
sheds, flushes).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from enum import IntEnum

from .. import faultinject as FI
from .. import trace
from ..log import get_logger
from ..metrics import Counter, Gauge, Histogram, LockedCounters
from ..resilience import Deadline, DeadlineExceeded

_log = get_logger("sched")


class Lane(IntEnum):
    """Priority lanes, lowest value = highest priority."""

    CONSENSUS = 0  # live FBFT quorum proofs / seal checks on the round
    SYNC = 1       # replay / staged-sync header batches
    INGRESS = 2    # tx-pool admission, RPC submits, gossip sender sigs


LANE_NAMES = {Lane.CONSENSUS: "consensus", Lane.SYNC: "sync",
              Lane.INGRESS: "ingress"}


def max_queue_depth() -> float:
    """Deepest lane's queue depth — the governor's pressure signal and
    the soak harness's stationarity series read the SAME number through
    this one accessor so lane renames can't silently diverge them."""
    return max(
        (QUEUE_DEPTH.value(lane=name) for name in LANE_NAMES.values()),
        default=0.0,
    )

# -- metrics singletons (exposed via metrics.Registry.expose) ----------------

QUEUE_DEPTH = Gauge(
    "harmony_sched_queue_depth",
    "verification requests waiting in the scheduler, per lane",
)
SHED = Counter(
    "harmony_sched_shed_total",
    "requests shed out of the queue (breaker_open/queue_full/deadline/"
    "expired), per lane",
)
FLUSHES = Counter(
    "harmony_sched_flushes_total",
    "fused dispatches issued by the scheduler, per request kind",
)
ITEMS = Counter(
    "harmony_sched_items_total",
    "verification requests dispatched through the scheduler, per lane",
)
# batch fill accounting: live items vs padded bucket slots across every
# *batched* dispatch (the lone-aggregate fast path is unpadded and does
# not enter the ratio) — harmony_sched_batch_fill_ratio is items/slots
FILL = LockedCounters("items", "slots")

_WAIT_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 5.0)
WAIT_SECONDS = {
    lane: Histogram(
        "harmony_sched_wait_seconds",
        "enqueue-to-dispatch wait inside the scheduler",
        buckets=_WAIT_BUCKETS, labels={"lane": name},
    )
    for lane, name in LANE_NAMES.items()
}


def expose_metrics() -> str:
    """The scheduler's Prometheus families (metrics.Registry hook)."""
    out = [QUEUE_DEPTH.expose(), SHED.expose(), FLUSHES.expose(),
           ITEMS.expose()]
    hist_lines: list = []
    for i, lane in enumerate(sorted(WAIT_SECONDS)):
        lines = WAIT_SECONDS[lane].expose().splitlines()
        hist_lines.extend(lines if i == 0 else lines[2:])
    out.append("\n".join(hist_lines))
    items, slots = FILL["items"], FILL["slots"]
    ratio = (items / slots) if slots else 0.0
    out.append(
        "# HELP harmony_sched_batch_fill_ratio live items / padded "
        "bucket slots across all batched dispatches\n"
        "# TYPE harmony_sched_batch_fill_ratio gauge\n"
        f"harmony_sched_batch_fill_ratio {ratio:g}"
    )
    return "\n".join(out)


# -- requests / futures ------------------------------------------------------


class VerifyFuture:
    """Completion handle for one submitted verification."""

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._result: bool | None = None
        self._exc: BaseException | None = None

    def _complete(self, result: bool) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> bool:
        """The verification verdict; raises what the scheduler raised
        (DeadlineExceeded on fail-fast admission, the dispatch error on
        a failed backend call)."""
        if not self._event.wait(timeout):
            raise TimeoutError("verification result not ready")
        if self._exc is not None:
            raise self._exc
        return bool(self._result)


class VerifyRequest:
    """One verification wanting a bucket slot.

    kind: ``single`` (pk/h/sig points), ``agg`` (committee table + bits
    + h/sig), or ``backend`` (a sidecar ``agg_verify`` call pipelined
    over the wire).  Hash-to-G2 happens on the *submitting* thread —
    the scheduler thread only batches and dispatches.
    """

    __slots__ = ("kind", "lane", "table", "bits", "pk_point", "h_point",
                 "sig_point", "client", "call_args", "deadline", "future",
                 "enqueued_at", "trace_ctx")

    def __init__(self, kind: str, lane: Lane, *, table=None, bits=None,
                 pk_point=None, h_point=None, sig_point=None, client=None,
                 call_args=None, deadline: Deadline | None = None):
        self.kind = kind
        self.lane = Lane(lane)
        self.table = table
        self.bits = bits
        self.pk_point = pk_point
        self.h_point = h_point
        self.sig_point = sig_point
        self.client = client
        self.call_args = call_args
        self.deadline = deadline
        self.future = VerifyFuture()
        self.enqueued_at = 0.0
        self.trace_ctx = b""

    def group_key(self) -> tuple:
        """Requests sharing a key fuse into one dispatch."""
        if self.kind == "agg":
            return ("agg", id(self.table))
        if self.kind == "backend":
            return ("backend", id(self.client))
        return ("single",)


class VerifyScheduler:
    """The shared continuous batcher in front of ``device.py``.

    ``manual=True`` builds a scheduler with no thread: submissions
    queue, and tests drive ``_flush_once()`` deterministically."""

    def __init__(self, *, max_queue_per_lane: int = 1024,
                 flush_window_s: float = 0.002,
                 starvation_limit: int = 4,
                 max_batch: int | None = None,
                 clock=time.monotonic, manual: bool = False):
        self.max_queue_per_lane = max_queue_per_lane
        self.flush_window_s = flush_window_s
        self.starvation_limit = max(1, starvation_limit)
        self._max_batch = max_batch
        self._clock = clock
        self._manual = manual
        self._cond = threading.Condition()
        self._lanes: dict[Lane, deque] = {lane: deque() for lane in Lane}
        self._skips: dict[Lane, int] = {lane: 0 for lane in Lane}
        self._running = False
        self._thread: threading.Thread | None = None
        # sidecar-backend batches run on their own worker so a slow or
        # dead sidecar never blocks device flushes (its callers still
        # wait only on their own futures)
        self._backend_cond = threading.Condition()
        self._backend_batches: deque = deque()
        self._backend_thread: threading.Thread | None = None
        self._backend_hb = None
        self._ewma_dispatch_s = 0.0
        self._hb = None  # health.Heartbeat once start() registers it

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "VerifyScheduler":
        from .. import health

        with self._cond:
            if self._running or self._manual:
                return self
            self._running = True
        self._thread = threading.Thread(
            # graftlint: thread-role=sched.flush
            target=self._loop, name="sched-flush", daemon=True
        )
        self._thread.start()
        # watchdog registration: the flush thread is CRITICAL (every
        # signature check funnels through it) and restart-SAFE when
        # dead — its queues live on the scheduler object, so a fresh
        # loop resumes exactly where the dead one stopped
        self._hb = health.register(
            "sched.flush", thread=self._thread, critical=True,
            restart=self._revive,
        )
        return self

    def _revive(self) -> bool:
        """Watchdog restart hook: respawn the flush loop if (and only
        if) the scheduler is still running and its thread is dead.  The
        queued requests are untouched — the new loop drains them.
        Returns False when it declines (racing a stop(), or the thread
        is alive after all) so the watchdog does not count a restart
        that never ran."""
        with self._cond:
            if not self._running:
                return False
            t = self._thread
            if t is not None and t.is_alive():
                return False
        thread = threading.Thread(
            # graftlint: thread-role=sched.flush
            target=self._loop, name="sched-flush", daemon=True
        )
        # started BEFORE being published: stop() joins self._thread,
        # and joining a never-started thread raises RuntimeError — a
        # stop() racing this window must find either the old dead
        # thread or a joinable live one.  If stop() wins the race the
        # fresh loop sees _running False and exits by itself.
        thread.start()
        with self._cond:
            if not self._running:
                return False
            self._thread = thread
        if self._hb is not None:
            self._hb.bind(thread)
        return True

    def stop(self) -> None:
        with self._cond:
            self._running = False
            pending: list = []
            for q in self._lanes.values():
                pending.extend(q)
                q.clear()
            self._cond.notify_all()
        with self._backend_cond:
            for batch in self._backend_batches:
                pending.extend(batch)
            self._backend_batches.clear()
            self._backend_cond.notify_all()
        for req in pending:
            req.future._fail(RuntimeError("verification scheduler stopped"))
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._backend_thread is not None:
            self._backend_thread.join(timeout=5.0)
            self._backend_thread = None
        if self._hb is not None:
            self._hb.close()
            self._hb = None

    # -- submission ----------------------------------------------------------

    def submit_single(self, pk_point, h_point, sig_point, *,
                      lane: Lane = Lane.INGRESS,
                      deadline: Deadline | None = None) -> VerifyFuture:
        return self._submit(VerifyRequest(
            "single", lane, pk_point=pk_point, h_point=h_point,
            sig_point=sig_point, deadline=deadline,
        ))

    def submit_agg(self, table, bits, h_point, sig_point, *,
                   lane: Lane = Lane.CONSENSUS,
                   deadline: Deadline | None = None) -> VerifyFuture:
        return self._submit(VerifyRequest(
            "agg", lane, table=table, bits=bits, h_point=h_point,
            sig_point=sig_point, deadline=deadline,
        ))

    def submit_backend(self, client, epoch: int, shard: int,
                       payload: bytes, bitmap: bytes, sig: bytes, *,
                       lane: Lane = Lane.SYNC,
                       deadline: Deadline | None = None) -> VerifyFuture:
        return self._submit(VerifyRequest(
            "backend", lane, client=client,
            call_args=(epoch, shard, payload, bitmap, sig),
            deadline=deadline,
        ))

    def _submit(self, req: VerifyRequest) -> VerifyFuture:
        lane_name = LANE_NAMES[req.lane]
        with trace.span("sched.enqueue", component="sched",
                        lane=lane_name, kind=req.kind):
            req.trace_ctx = trace.traceparent()
            # device breaker OPEN: the queue would only delay the
            # inevitable reference fallback — shed NOW on the caller's
            # thread (bitwise the same result _guarded's fallback gives)
            if req.kind != "backend" and self._breaker_open():
                self._shed(req, "breaker_open")
                return req.future
            # resource-governor degradation: INGRESS sheds from the
            # PRESSURED tier, SYNC from CRITICAL, CONSENSUS never —
            # overload must not buy queue depth ahead of quorum proofs.
            # The shed verdict is the exact CPU-reference fallback on
            # the caller's thread (correct, just not batched).
            if req.lane is not Lane.CONSENSUS:
                from .. import governor as GV

                if GV.should_shed(req.lane):
                    self._shed(req, "governor")
                    return req.future
            # fail-fast admission: if the budget cannot survive the
            # queue already ahead of us, refuse before anyone waits
            if req.deadline is not None:
                rem = req.deadline.remaining()
                if rem is not None and rem < self._est_wait_s(req.lane):
                    SHED.inc(lane=lane_name, reason="deadline")
                    trace.annotate(shed="deadline")
                    req.future._fail(DeadlineExceeded(
                        f"sched {req.kind} cannot meet its deadline: "
                        f"{rem:.3f}s left vs "
                        f"~{self._est_wait_s(req.lane):.3f}s queue wait"
                    ))
                    return req.future
            overflow = False
            depth = 0
            with self._cond:
                alive = self._running or self._manual
                if alive:
                    q = self._lanes[req.lane]
                    if len(q) >= self.max_queue_per_lane:
                        overflow = True
                    else:
                        req.enqueued_at = self._clock()
                        q.append(req)
                        depth = len(q)
                        self._cond.notify()
            if not alive:
                # no scheduler: run the exact unscheduled path inline
                self._run_inline(req)
            elif overflow:
                self._shed(req, "queue_full")
            else:
                QUEUE_DEPTH.set(depth, lane=lane_name)
                trace.annotate(queue_depth=depth)
            return req.future

    # -- admission helpers ---------------------------------------------------

    @staticmethod
    def _breaker_open() -> bool:
        from .. import device as DV

        # .state (not .allow()): reading must neither count a rejection
        # nor consume a half-open probe the real dispatch needs
        return DV.BREAKER.state == "open"

    def _est_wait_s(self, lane: Lane) -> float:
        """Worst-case-ish queue wait for a request entering ``lane``:
        everything at equal-or-higher priority dispatches first, in
        batches of the widest bucket, each costing the EWMA dispatch
        time, plus one adaptive-flush window."""
        ahead = sum(
            len(q) for ln, q in self._lanes.items() if ln <= lane
        )
        batches = ahead // self._target_batch() + 1
        per = max(self._ewma_dispatch_s, 1e-3)
        return self.flush_window_s + batches * per

    def _target_batch(self) -> int:
        from .. import device as DV

        return DV.batch_buckets()[-1]

    # -- shed / inline paths -------------------------------------------------

    def _shed(self, req: VerifyRequest, reason: str) -> None:
        SHED.inc(lane=LANE_NAMES[req.lane], reason=reason)
        trace.annotate(shed=reason)
        try:
            req.future._complete(self._ref_result(req))
        except Exception as e:  # noqa: BLE001 — surfaced via the future
            req.future._fail(e)

    @staticmethod
    def _ref_result(req: VerifyRequest) -> bool:
        """CPU reference verdict for a shed request — the same host
        bigint path device._guarded falls back to."""
        from .. import device as DV

        if req.kind == "agg":
            return DV._ref_agg_verify(
                req.table, req.bits, req.h_point, req.sig_point
            )
        if req.kind == "single":
            from ..ref import bls as RB

            return RB.verify_hashed(
                req.pk_point, req.h_point, req.sig_point
            )
        # backend requests have no local committee to shed onto — the
        # degraded path is the plain synchronous client call
        return req.client.agg_verify(*req.call_args, deadline=req.deadline)

    @staticmethod
    def _run_inline(req: VerifyRequest) -> None:
        """No scheduler running: behave exactly like the pre-scheduler
        call sites (one breaker-guarded dispatch per request)."""
        from .. import device as DV

        try:
            if req.kind == "agg":
                ok = DV.agg_verify_hashed_on_device(
                    req.table, req.bits, req.h_point, req.sig_point
                )
            elif req.kind == "single":
                ok = DV.verify_many_on_device(
                    [req.pk_point], [req.h_point], [req.sig_point]
                )[0]
            else:
                ok = req.client.agg_verify(
                    *req.call_args, deadline=req.deadline
                )
            req.future._complete(ok)
        except Exception as e:  # noqa: BLE001 — surfaced via the future
            req.future._fail(e)

    # -- the scheduler loop --------------------------------------------------

    def _loop(self) -> None:
        while True:
            # re-read each pass: start() registers the heartbeat only
            # AFTER the thread is running
            hb = self._hb
            kind = batch = expired = None
            # the wedged_thread_recovery chaos scenario's kill switch:
            # an armed exc here dies like any unexpected flush-loop
            # error would — outside every per-batch catch — and the
            # health watchdog must detect the dead thread and revive it
            FI.fire("sched.flush")
            if hb is not None:
                hb.beat()
            # the bucket width resolves OUTSIDE _cond: its first call
            # may run the device backend probe (a bounded Thread.join)
            # and nothing blocking belongs under the queue lock (GL06)
            target = self._target_batch()
            with self._cond:
                while self._running and not any(self._lanes.values()):
                    if hb is not None:
                        hb.idle()  # empty queue: parked healthy, not
                        #            wedged — the watchdog skips idle
                    self._cond.wait()
                if hb is not None:
                    hb.beat()
                if not self._running:
                    return
                lane = self._choose_lane()
                q = self._lanes[lane]
                now = self._clock()
                head_age = now - q[0].enqueued_at
                # adaptive flush: full bucket or window elapsed -> go.
                # Below the bucket, the lanes trade differently: a
                # CONSENSUS request waits only when FUSABLE traffic is
                # already pending (a same-group neighbor — unrelated
                # sync replay can never join its batch, so waiting on
                # its account would be pure added latency on the path
                # that gates rounds); sync/ingress traffic —
                # throughput work — waits out the window even alone,
                # because bursts arrive within it and lone 1-of-8
                # dispatches waste the bucket
                head_key = q[0].group_key()
                fusable = (
                    (len(q) > 1 and q[1].group_key() == head_key)
                    or any(
                        self._lanes[ln] and
                        self._lanes[ln][0].group_key() == head_key
                        for ln in Lane if ln is not lane
                    )
                )
                if (len(q) < target
                        and head_age < self.flush_window_s
                        and (fusable or lane is not Lane.CONSENSUS)):
                    self._cond.wait(self.flush_window_s - head_age)
                    continue
                kind, batch, expired, depths = self._collect(
                    lane, now, target
                )
            self._after_collect(depths, expired)
            if batch:
                self._dispatch(kind, batch)

    def _flush_once(self) -> bool:
        """Test hook (manual mode): one synchronous choose/collect/
        dispatch cycle; returns whether anything was processed."""
        target = self._target_batch()  # outside _cond, like _loop
        with self._cond:
            if not any(self._lanes.values()):
                return False
            lane = self._choose_lane()
            kind, batch, expired, depths = self._collect(
                lane, self._clock(), target
            )
        self._after_collect(depths, expired)
        if batch:
            if kind == "backend":
                self._run_backend(batch)
            else:
                self._dispatch(kind, batch)
        return bool(batch or expired)

    def _choose_lane(self) -> Lane:
        # caller holds self._cond
        candidates = [ln for ln in Lane if self._lanes[ln]]
        starved = [ln for ln in candidates
                   if self._skips[ln] >= self.starvation_limit]
        return min(starved) if starved else min(candidates)

    def _collect(self, lane: Lane, now: float, target: int):
        """Pop one fused batch (same group key), primary lane first,
        then backfill from every other lane head-first — per-lane FIFO
        is preserved because only matching *prefixes* are taken.
        Expired requests are dropped, never dispatched.  Caller holds
        ``self._cond`` and resolved ``target`` (the widest bucket)
        outside it; all completion/metric work is returned for the
        caller to run outside the lock."""
        expired: list = []
        cap = self._max_batch or 4 * target

        def pop_expired(q) -> bool:
            r = q[0]
            if r.deadline is not None and r.deadline.expired():
                expired.append(q.popleft())
                return True
            return False

        q = self._lanes[lane]
        key = None
        while q:
            if pop_expired(q):
                continue
            key = q[0].group_key()
            break
        batch: list = []
        contributed = set()
        if key is not None:
            if key[0] == "backend":
                cap = min(cap, 64)
            for ln in sorted(Lane, key=lambda x: (x is not lane, x)):
                qq = self._lanes[ln]
                while qq and len(batch) < cap:
                    if pop_expired(qq):
                        continue
                    if qq[0].group_key() != key:
                        break
                    batch.append(qq.popleft())
                    contributed.add(ln)
                if len(batch) >= cap:
                    break
        for ln in Lane:
            if ln in contributed:
                self._skips[ln] = 0
            elif self._lanes[ln]:
                self._skips[ln] += 1
        depths = {ln: len(self._lanes[ln]) for ln in Lane}
        return (key[0] if key else None), batch, expired, depths

    def _after_collect(self, depths, expired) -> None:
        for ln, depth in depths.items():
            QUEUE_DEPTH.set(depth, lane=LANE_NAMES[ln])
        for req in expired or ():
            SHED.inc(lane=LANE_NAMES[req.lane], reason="expired")
            req.future._fail(DeadlineExceeded(
                f"sched {req.kind} expired while queued"
            ))

    # -- dispatch ------------------------------------------------------------

    def _flush_span(self, batch):
        tc = batch[0].trace_ctx
        if tc:
            return trace.resume(tc, "sched.flush", component="sched")
        return trace.span("sched.flush", component="sched")

    def _observe_waits(self, batch) -> None:
        now = self._clock()
        for req in batch:
            WAIT_SECONDS[req.lane].observe(
                max(0.0, now - req.enqueued_at)
            )
        lanes: dict = {}
        for req in batch:
            lanes[LANE_NAMES[req.lane]] = lanes.get(
                LANE_NAMES[req.lane], 0
            ) + 1
        for name, n in lanes.items():
            ITEMS.inc(n, lane=name)

    def _dispatch(self, kind: str, batch: list) -> None:
        if kind == "backend":
            self._enqueue_backend(batch)
            return
        with self._flush_span(batch):
            self._observe_waits(batch)
            t0 = self._clock()
            try:
                if kind == "single":
                    results, slots = self._run_single(batch)
                else:
                    results, slots = self._run_agg(batch)
            except Exception as e:  # noqa: BLE001 — dispatch failures
                # surface through every future, never kill the loop
                _log.warn("sched dispatch failed", kind=kind,
                          items=len(batch), error=str(e))
                trace.annotate(error=str(e))
                for req in batch:
                    req.future._fail(e)
                return
            dur = self._clock() - t0
            for req, ok in zip(batch, results):
                req.future._complete(bool(ok))
            if slots:
                FILL.inc("items", len(batch))
                FILL.inc("slots", slots)
            FLUSHES.inc(kind=kind)
            self._ewma_dispatch_s = (
                dur if self._ewma_dispatch_s == 0.0
                else 0.2 * dur + 0.8 * self._ewma_dispatch_s
            )
            trace.annotate(
                kind=kind, items=len(batch), slots=slots,
                fill=round(len(batch) / slots, 3) if slots else 1.0,
                dispatch_s=round(dur, 6),
            )

    @staticmethod
    def _padded_slots(n: int) -> int:
        from .. import device as DV

        widest = DV.batch_buckets()[-1]
        slots = 0
        remaining = n
        while remaining > 0:
            chunk = min(remaining, widest)
            slots += DV.batch_bucket(chunk)
            remaining -= chunk
        return slots

    def _run_single(self, batch: list):
        from .. import device as DV

        results = DV.verify_many_on_device(
            [r.pk_point for r in batch],
            [r.h_point for r in batch],
            [r.sig_point for r in batch],
        )
        return results, self._padded_slots(len(batch))

    def _run_agg(self, batch: list):
        from .. import device as DV

        table = batch[0].table
        if len(batch) == 1:
            # lone aggregate: the unpadded fused program (shared with
            # the pre-scheduler single-check path) — no fill accounting,
            # there are no pad lanes to waste
            r = batch[0]
            ok = DV.agg_verify_hashed_on_device(
                table, r.bits, r.h_point, r.sig_point
            )
            return [ok], 0
        results = DV.agg_verify_batch_on_device(
            table,
            [r.bits for r in batch],
            [r.h_point for r in batch],
            [r.sig_point for r in batch],
        )
        return results, self._padded_slots(len(batch))

    # -- the sidecar-backend worker ------------------------------------------

    def _enqueue_backend(self, batch: list) -> None:
        from .. import health

        spawned = None
        with self._backend_cond:
            if (self._backend_thread is None
                    or not self._backend_thread.is_alive()):
                self._backend_thread = threading.Thread(
                    # graftlint: thread-role=sched.flush
                    target=self._backend_loop, name="sched-backend",
                    daemon=True,
                )
                self._backend_thread.start()
                spawned = self._backend_thread
            self._backend_batches.append(batch)
            self._backend_cond.notify()
        if spawned is not None:
            # registered OUTSIDE _backend_cond (health._LOCK nests
            # under no scheduler lock — GL05).  Non-critical, no
            # restart hook: a dead worker is respawned lazily by the
            # next enqueue, but a WEDGED one (stuck in a sidecar call)
            # must show up stale on /healthz instead of silently
            # stalling verify futures
            self._backend_hb = health.register(
                "sched.backend", thread=spawned,
            )

    def _backend_loop(self) -> None:
        while True:
            # re-read each pass: _enqueue_backend registers the
            # heartbeat only AFTER the thread is running
            hb = self._backend_hb
            with self._backend_cond:
                while self._running and not self._backend_batches:
                    if hb is not None:
                        hb.idle()  # parked empty: healthy, not wedged
                    self._backend_cond.wait()
                if not self._backend_batches:
                    if hb is not None:
                        hb.close()
                    return
                batch = self._backend_batches.popleft()
            if hb is not None:
                hb.beat()
            self._run_backend(batch)

    def _run_backend(self, batch: list) -> None:
        """Pipeline a batch of sidecar agg_verify calls: send every
        frame before waiting on any reply (the client's reader thread
        demultiplexes) — a cross-epoch header batch no longer pays one
        round-trip per header."""
        with self._flush_span(batch):
            self._observe_waits(batch)
            t0 = self._clock()
            handles: list = []
            for req in batch:
                try:
                    handles.append((req, req.client.agg_verify_begin(
                        *req.call_args, deadline=req.deadline
                    )))
                except Exception as e:  # noqa: BLE001 — per-request
                    req.future._fail(e)
            for req, handle in handles:
                try:
                    req.future._complete(handle.result())
                except Exception as e:  # noqa: BLE001 — per-request
                    req.future._fail(e)
            FLUSHES.inc(kind="backend")
            trace.annotate(kind="backend", items=len(batch),
                           dispatch_s=round(self._clock() - t0, 6))
