"""Public surface of the verification scheduler (see scheduler.py).

One process owns ONE shared :class:`VerifyScheduler`; every layer —
consensus proof checks, engine replay batches, tx-pool admission, the
sidecar server — submits into it through the convenience wrappers
below, so in-process and sidecar deployments share a single device
queue.  ``HARMONY_SCHED=0`` (or ``configure(enabled=False)``) restores
the pre-scheduler per-caller dispatch exactly.
"""

from __future__ import annotations

import os
import threading

from ..resilience import Deadline, DeadlineExceeded
from .scheduler import (
    LANE_NAMES,
    Lane,
    VerifyFuture,
    VerifyRequest,
    VerifyScheduler,
    expose_metrics,
)

__all__ = [
    "Lane",
    "LANE_NAMES",
    "VerifyFuture",
    "VerifyRequest",
    "VerifyScheduler",
    "Deadline",
    "DeadlineExceeded",
    "agg_verify",
    "agg_verify_many",
    "backend_agg_verify_many",
    "configure",
    "enabled",
    "expose_metrics",
    "reset",
    "scheduler",
    "verify_single",
]

_LOCK = threading.Lock()
_SCHED: VerifyScheduler | None = None
_ENABLED: bool | None = None  # None -> environment default
_OPTS: dict = {}


def enabled() -> bool:
    """Scheduler routing armed?  Default on; HARMONY_SCHED=0 or
    ``configure(enabled=False)`` restores direct dispatch."""
    if _ENABLED is not None:
        return _ENABLED
    return os.environ.get("HARMONY_SCHED", "1") != "0"


def configure(enabled: bool | None = ..., **opts) -> None:
    """Arm/disarm routing and set construction options for the global
    scheduler (``flush_window_s``, ``max_queue_per_lane``,
    ``starvation_limit``, ...).  Options apply to the NEXT global
    scheduler built (call ``reset()`` to rebuild)."""
    global _ENABLED
    if enabled is not ...:
        _ENABLED = enabled
    _OPTS.update(opts)


def scheduler() -> VerifyScheduler:
    """The process-wide scheduler, created and started lazily."""
    global _SCHED
    with _LOCK:
        if _SCHED is None:
            _SCHED = VerifyScheduler(**_OPTS).start()
        return _SCHED


def reset() -> None:
    """Stop and discard the global scheduler + configuration (tests)."""
    global _SCHED, _ENABLED
    with _LOCK:
        sched, _SCHED = _SCHED, None
        _ENABLED = None
        _OPTS.clear()
    if sched is not None:
        sched.stop()


# -- convenience wrappers (what the call sites use) --------------------------


def _await(future: VerifyFuture, deadline: Deadline | None) -> bool:
    """Await a future, bounded by the request's own deadline when one
    was given: admission already vetted the budget, so the cushion only
    guards the caller against a WEDGED dispatch parking it forever
    (the resulting TimeoutError is an OSError like DeadlineExceeded).
    Without a deadline the wait is unbounded — parity with the
    pre-scheduler call sites, which blocked in the dispatch itself."""
    if deadline is None:
        return future.result()
    rem = deadline.remaining()
    if rem is None:
        return future.result()
    return future.result(rem + 5.0)


def verify_single(pk_point, payload: bytes, sig_point, *,
                  lane: Lane = Lane.CONSENSUS,
                  deadline: Deadline | None = None) -> bool:
    """One e(-G1,sig)e(pk,H(payload)) check through the shared queue
    (coalesced with every other pending single check into one fused
    program); the direct device path when routing is disarmed."""
    from .. import device as DV

    if not enabled():
        return DV.verify_on_device(pk_point, payload, sig_point)
    from .. import prof
    from ..ref.hash_to_curve import hash_to_g2

    with prof.stage("hash_to_g2"):
        h_point = hash_to_g2(payload)
    return _await(scheduler().submit_single(
        pk_point, h_point, sig_point,
        lane=lane, deadline=deadline,
    ), deadline)


def agg_verify(table, bits, payload: bytes, sig_point, *,
               lane: Lane = Lane.CONSENSUS,
               deadline: Deadline | None = None) -> bool:
    """One masked-aggregate quorum check through the shared queue."""
    from .. import device as DV

    if not enabled():
        return DV.agg_verify_on_device(table, bits, payload, sig_point)
    from .. import prof
    from ..ref.hash_to_curve import hash_to_g2

    with prof.stage("hash_to_g2"):
        h_point = hash_to_g2(payload)
    return _await(scheduler().submit_agg(
        table, bits, h_point, sig_point,
        lane=lane, deadline=deadline,
    ), deadline)


def agg_verify_many(table, bits_list, h_points, sig_points, *,
                    lane: Lane = Lane.SYNC,
                    deadline: Deadline | None = None) -> list:
    """A replay-shaped batch of quorum checks against one committee
    table: submitted individually so the scheduler can interleave
    higher-priority lanes between chunks, coalesced back into the
    pinned-bucket fused programs on dispatch."""
    from .. import device as DV

    if not enabled():
        return DV.agg_verify_batch_on_device(
            table, bits_list, h_points, sig_points
        )
    sched = scheduler()
    futures = [
        sched.submit_agg(table, bits, h, sig, lane=lane,
                         deadline=deadline)
        for bits, h, sig in zip(bits_list, h_points, sig_points)
    ]
    return [_await(f, deadline) for f in futures]


def backend_agg_verify_many(client, calls: list, *,
                            lane: Lane = Lane.SYNC,
                            deadline: Deadline | None = None) -> list:
    """Pipelined sidecar agg_verify calls: returns the submitted
    futures (callers collect per-item so one failed call can fall back
    without poisoning the rest).  ``calls``: (epoch, shard, payload,
    bitmap, sig) tuples.  Disarmed routing degrades to plain
    synchronous calls on the caller's thread — same future-shaped
    return, no scheduler thread armed behind the kill switch."""
    if not enabled():
        out = []
        for args in calls:
            fut = VerifyFuture()
            try:
                fut._complete(client.agg_verify(*args, deadline=deadline))
            except Exception as e:  # noqa: BLE001 — per-item contract
                fut._fail(e)
            out.append(fut)
        return out
    sched = scheduler()
    return [
        sched.submit_backend(client, *args, lane=lane, deadline=deadline)
        for args in calls
    ]
