"""JSON-RPC 2.0 server over HTTP.

The role of the reference's RPC stack (reference: rpc/harmony/rpc.go:
71-275 — HTTP/WS servers registering hmy/hmyv2/eth namespace APIs with
a method filter and rate limiting; eth/rpc is the forked server
internals).  Stdlib-only: a threading HTTP server dispatching
namespace_method to the hmy facade; hmyv2 returns decimal integers
where hmy/eth return 0x-hex (the reference's v1/v2 distinction).

Method names follow the reference surface: hmy_blockNumber,
hmy_getBalance, hmy_getBlockByNumber, hmy_sendRawTransaction,
hmy_getValidatorInformation, eth_* aliases, net_version, web3_*.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..core.tx_pool import PoolError

JSONRPC_INTERNAL = -32603
JSONRPC_METHOD_NOT_FOUND = -32601
JSONRPC_INVALID_PARAMS = -32602
JSONRPC_PARSE_ERROR = -32700


def _hex(v: int) -> str:
    return hex(v)


def _addr(param: str) -> bytes:
    h = param[2:] if param.startswith("0x") else param
    b = bytes.fromhex(h)
    if len(b) != 20:
        raise ValueError("address must be 20 bytes")
    return b


def _block_num(param, head: int) -> int:
    if isinstance(param, str):
        if param in ("latest", "pending", "finalized", "safe"):
            return head
        if param == "earliest":
            return 0
        return int(param, 16) if param.startswith("0x") else int(param)
    return int(param)


from ..ratelimit import RateLimiter  # noqa: E402 — shared bucket impl


class _Filters:
    """Installed eth filters (reference: eth/filters — polling model:
    newFilter / getFilterChanges / uninstallFilter)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 1
        self._filters: dict = {}  # id -> {"kind", "last_block", criteria}

    def install(self, kind: str, criteria: dict | None = None,
                head: int = 0) -> int:
        with self._lock:
            fid = self._next
            self._next += 1
            self._filters[fid] = {
                "kind": kind, "last_block": head,
                "criteria": criteria or {},
            }
            return fid

    def get(self, fid: int):
        with self._lock:
            return self._filters.get(fid)

    def take_range(self, fid: int, head: int):
        """Atomically advance the filter's cursor to ``head`` and
        return (kind, criteria, since) — concurrent polls under the
        ThreadingHTTPServer must not double- or under-report."""
        with self._lock:
            f = self._filters.get(fid)
            if f is None:
                return None
            since = f["last_block"]
            f["last_block"] = head
            return f["kind"], dict(f["criteria"]), since

    def uninstall(self, fid: int) -> bool:
        with self._lock:
            return self._filters.pop(fid, None) is not None


class RPCServer:
    def __init__(self, hmy, port: int = 0, method_allowlist=None,
                 rate_limiter: RateLimiter | None = None):
        self.hmy = hmy
        self.allow = set(method_allowlist) if method_allowlist else None
        self.limiter = rate_limiter or RateLimiter()
        self.filters = _Filters()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                ip = self.client_address[0]
                if not outer.limiter.allow(ip):
                    self.send_response(429)
                    self.end_headers()
                    return
                # resource-governor admission (ISSUE 14): PRESSURED
                # rate-limits per client, CRITICAL refuses outright —
                # a node past rated capacity serves 429s, not OOM kills
                from .. import governor as GV

                if not GV.admit_ingress(ip, surface="rpc"):
                    self.send_response(429)
                    self.send_header("Retry-After", "1")
                    self.end_headers()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                except (ValueError, KeyError):
                    body = outer._error(None, JSONRPC_PARSE_ERROR,
                                        "parse error")
                    self._reply(body)
                    return
                if isinstance(req, list):  # batch (bounded)
                    body = [outer.dispatch(r) for r in req[:100]]
                else:
                    body = outer.dispatch(req)
                self._reply(body)

            def _reply(self, body):
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        # shutdown() BLOCKS FOREVER if serve_forever never ran — guard
        # so stopping a constructed-but-never-started server is a no-op
        if self._thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()

    # -- dispatch -----------------------------------------------------------

    @staticmethod
    def _error(req_id, code, message):
        return {
            "jsonrpc": "2.0", "id": req_id,
            "error": {"code": code, "message": message},
        }

    def dispatch(self, req) -> dict:
        if not isinstance(req, dict):
            return self._error(None, -32600, "invalid request object")
        req_id = req.get("id")
        method = req.get("method", "")
        params = req.get("params", [])
        if self.allow is not None and method not in self.allow:
            return self._error(req_id, JSONRPC_METHOD_NOT_FOUND,
                               f"method {method} not allowed")
        if "_" not in method:
            return self._error(req_id, JSONRPC_METHOD_NOT_FOUND,
                               f"malformed method {method}")
        namespace, name = method.split("_", 1)
        fn = getattr(self, f"_{name}", None)
        if fn is None or namespace not in (
            "hmy", "hmyv2", "eth", "net", "web3", "debug"
        ):
            return self._error(req_id, JSONRPC_METHOD_NOT_FOUND,
                               f"method {method} not found")
        v2 = namespace == "hmyv2"
        try:
            result = fn(params, v2)
        except (ValueError, KeyError, IndexError, TypeError) as e:
            return self._error(req_id, JSONRPC_INVALID_PARAMS, str(e))
        except PoolError as e:
            return self._error(req_id, JSONRPC_INTERNAL, str(e))
        return {"jsonrpc": "2.0", "id": req_id, "result": result}

    # -- methods (shared across namespaces; v2 = decimal ints) --------------

    def _int(self, v: int, v2: bool):
        return v if v2 else _hex(v)

    def _blockNumber(self, params, v2):
        return self._int(self.hmy.block_number(), v2)

    def _chainId(self, params, v2):
        return self._int(self.hmy.chain_id(), v2)

    def _version(self, params, v2):  # net_version
        return str(self.hmy.chain_id())

    def _clientVersion(self, params, v2):  # web3_clientVersion
        return "harmony-tpu/0.1"

    def _shardID(self, params, v2):
        return self.hmy.shard_id()

    def _getEpoch(self, params, v2):
        return self._int(self.hmy.current_epoch(), v2)

    def _getBalance(self, params, v2):
        addr = _addr(params[0])
        num = None
        if len(params) > 1:
            num = _block_num(params[1], self.hmy.block_number())
        return self._int(self.hmy.get_balance(addr, num), v2)

    def _getTransactionCount(self, params, v2):
        return self._int(self.hmy.get_nonce(_addr(params[0])), v2)

    def _header_dict(self, h, v2):
        return {
            "number": self._int(h.block_num, v2),
            "epoch": self._int(h.epoch, v2),
            "shardID": h.shard_id,
            "viewID": self._int(h.view_id, v2),
            "hash": "0x" + h.hash().hex(),
            "parentHash": "0x" + h.parent_hash.hex(),
            "stateRoot": "0x" + h.root.hex(),
            "transactionsRoot": "0x" + h.tx_root.hex(),
            "timestamp": self._int(h.timestamp, v2),
            "lastCommitSig": "0x" + h.last_commit_sig.hex(),
            "lastCommitBitmap": "0x" + h.last_commit_bitmap.hex(),
        }

    def _tx_dict(self, tx, block_num, idx, v2):
        chain_id = self.hmy.chain_id()
        return {
            "hash": "0x" + tx.hash(chain_id).hex(),
            "nonce": self._int(tx.nonce, v2),
            "from": "0x" + tx.sender(chain_id).hex(),
            "to": ("0x" + tx.to.hex()) if tx.to else None,
            "value": self._int(tx.value, v2),
            "gas": self._int(tx.gas_limit, v2),
            "gasPrice": self._int(tx.gas_price, v2),
            "shardID": tx.shard_id,
            "toShardID": tx.to_shard,
            "blockNumber": self._int(block_num, v2),
            "transactionIndex": self._int(idx, v2),
            "input": "0x" + tx.data.hex(),
        }

    def _getBlockByNumber(self, params, v2):
        num = _block_num(params[0], self.hmy.block_number())
        full = bool(params[1]) if len(params) > 1 else False
        block = self.hmy.block_by_number(num)
        if block is None:
            return None
        out = self._header_dict(block.header, v2)
        chain_id = self.hmy.chain_id()
        if full:
            out["transactions"] = [
                self._tx_dict(tx, num, i, v2)
                for i, tx in enumerate(block.transactions)
            ]
        else:
            out["transactions"] = [
                "0x" + tx.hash(chain_id).hex()
                for tx in block.transactions
            ]
        out["stakingTransactions"] = [
            "0x" + stx.hash(chain_id).hex()
            for stx in block.staking_transactions
        ]
        return out

    def _getBlockByHash(self, params, v2):
        block = self.hmy.block_by_hash(bytes.fromhex(params[0][2:]))
        if block is None:
            return None
        return self._getBlockByNumber([block.block_num, *params[1:]], v2)

    def _getTransactionByHash(self, params, v2):
        found = self.hmy.get_transaction(bytes.fromhex(params[0][2:]))
        if found is None:
            return None
        num, idx, tx = found
        return self._tx_dict(tx, num, idx, v2)

    def _sendRawTransaction(self, params, v2):
        blob = bytes.fromhex(params[0][2:] if params[0].startswith("0x")
                             else params[0])
        return "0x" + self.hmy.send_raw_transaction(blob).hex()

    def _sendRawStakingTransaction(self, params, v2):
        blob = bytes.fromhex(params[0][2:] if params[0].startswith("0x")
                             else params[0])
        return "0x" + self.hmy.send_raw_staking_transaction(blob).hex()

    def _getAllValidatorAddresses(self, params, v2):
        return ["0x" + a.hex() for a in self.hmy.validator_addresses()]

    def _getValidatorInformation(self, params, v2):
        return self.hmy.validator_information(_addr(params[0]))

    def _getTotalStaking(self, params, v2):
        return self._int(self.hmy.total_staking(), v2)

    def _getCommittee(self, params, v2):
        epoch = int(params[0]) if params else None
        return ["0x" + k.hex() for k in self.hmy.committee(epoch)]

    def _getBlockSigners(self, params, v2):
        """Keys that signed block N (from the stored commit bitmap)."""
        from ..staking.availability import block_signers

        num = _block_num(params[0], self.hmy.block_number())
        proof = self.hmy.read_commit_sig(num)
        if proof is None:
            return []
        epoch = self.hmy.chain.epoch_of(num)
        committee = self.hmy.committee(epoch)
        signed, _ = block_signers(proof[96:], committee)
        return ["0x" + k.hex() for k in signed]

    # -- receipts / logs / filters (reference: rpc transaction.go
    # GetTransactionReceipt + eth/filters polling API) -----------------

    def _log_dict(self, num, tx_hash, idx, addr, topics, data, v2):
        return {
            "address": "0x" + addr.hex(),
            "topics": ["0x" + t.hex() for t in topics],
            "data": "0x" + data.hex(),
            "blockNumber": self._int(num, v2),
            "transactionHash": "0x" + tx_hash.hex(),
            "logIndex": self._int(idx, v2),
        }

    def _getTransactionReceipt(self, params, v2):
        found = self.hmy.get_receipt(bytes.fromhex(params[0][2:]))
        if found is None:
            return None
        num, idx, rc = found
        out = {
            "transactionHash": "0x" + rc.tx_hash.hex(),
            "blockNumber": self._int(num, v2),
            "transactionIndex": self._int(idx, v2),
            "status": self._int(rc.status, v2),
            "gasUsed": self._int(rc.gas_used, v2),
            "cumulativeGasUsed": self._int(rc.cumulative_gas, v2),
            "logs": [
                self._log_dict(num, rc.tx_hash, i, a, t, d, v2)
                for i, (a, t, d) in enumerate(rc.logs)
            ],
            "contractAddress": (
                "0x" + rc.contract_address.hex()
                if rc.contract_address else None
            ),
        }
        return out

    def _parse_log_criteria(self, crit):
        head = self.hmy.block_number()
        frm = _block_num(crit.get("fromBlock", "latest"), head)
        to = _block_num(crit.get("toBlock", "latest"), head)
        address = _addr(crit["address"]) if crit.get("address") else None
        topics = None
        if crit.get("topics"):
            topics = [
                bytes.fromhex(t[2:]) if isinstance(t, str) else None
                for t in crit["topics"]
            ]
        return frm, to, address, topics

    def _getLogs(self, params, v2):
        frm, to, address, topics = self._parse_log_criteria(
            params[0] if params else {}
        )
        return [
            self._log_dict(*entry, v2)
            for entry in self.hmy.get_logs(frm, to, address, topics)
        ]

    def _newFilter(self, params, v2):
        fid = self.filters.install(
            "logs", params[0] if params else {}, self.hmy.block_number()
        )
        return self._int(fid, v2)

    def _newBlockFilter(self, params, v2):
        return self._int(
            self.filters.install("blocks", head=self.hmy.block_number()), v2
        )

    def _newPendingTransactionFilter(self, params, v2):
        return self._int(
            self.filters.install("pending", head=self.hmy.block_number()),
            v2,
        )

    def _getFilterChanges(self, params, v2):
        fid = int(params[0], 16) if isinstance(params[0], str) else params[0]
        head = self.hmy.block_number()
        taken = self.filters.take_range(fid, head)
        if taken is None:
            raise ValueError("filter not found")
        kind, criteria, since = taken
        f = {"kind": kind, "criteria": criteria}
        if f["kind"] == "blocks":
            out = []
            for n in range(since + 1, head + 1):
                h = self.hmy.header_by_number(n)
                if h is not None:
                    out.append("0x" + h.hash().hex())
            return out
        if f["kind"] == "pending":
            return []  # pending pool surface: poll blocks instead
        crit = dict(f["criteria"])
        crit.setdefault("fromBlock", since + 1)
        crit.setdefault("toBlock", head)
        frm, to, address, topics = self._parse_log_criteria(crit)
        return [
            self._log_dict(*e, v2)
            for e in self.hmy.get_logs(max(frm, since + 1), to,
                                       address, topics)
        ]

    def _getFilterLogs(self, params, v2):
        fid = int(params[0], 16) if isinstance(params[0], str) else params[0]
        f = self.filters.get(fid)
        if f is None or f["kind"] != "logs":
            raise ValueError("filter not found")
        frm, to, address, topics = self._parse_log_criteria(f["criteria"])
        return [
            self._log_dict(*e, v2)
            for e in self.hmy.get_logs(frm, to, address, topics)
        ]

    def _uninstallFilter(self, params, v2):
        fid = int(params[0], 16) if isinstance(params[0], str) else params[0]
        return self.filters.uninstall(fid)

    # -- EVM reads (reference: rpc contract.go Call/EstimateGas/GetCode) ---

    def _call_args(self, obj):
        frm = _addr(obj["from"]) if obj.get("from") else b"\x00" * 20
        to = _addr(obj["to"]) if obj.get("to") else None
        value = int(obj.get("value", "0x0"), 16) if isinstance(
            obj.get("value", 0), str) else int(obj.get("value", 0))
        data_hex = obj.get("data", obj.get("input", "0x")) or "0x"
        data = bytes.fromhex(data_hex[2:])
        gas = int(obj.get("gas", "0x989680"), 16) if isinstance(
            obj.get("gas", 0), str) else int(obj.get("gas") or 10_000_000)
        return frm, to, value, data, gas

    def _call(self, params, v2):
        frm, to, value, data, gas = self._call_args(params[0])
        ok, _gas_left, out, _ = self.hmy.call(frm, to, value, data, gas)
        if not ok:
            raise ValueError("execution reverted: 0x" + out.hex())
        return "0x" + out.hex()

    def _estimateGas(self, params, v2):
        frm, to, value, data, _ = self._call_args(params[0])
        return self._int(self.hmy.estimate_gas(frm, to, value, data), v2)

    def _getCode(self, params, v2):
        return "0x" + self.hmy.get_code(_addr(params[0])).hex()

    def _getStorageAt(self, params, v2):
        slot_param = params[1]
        slot_int = int(slot_param, 16) if isinstance(slot_param, str) \
            else int(slot_param)
        v = self.hmy.get_storage_at(
            _addr(params[0]), slot_int.to_bytes(32, "big")
        )
        return "0x" + v.to_bytes(32, "big").hex()

    def _gasPrice(self, params, v2):
        return self._int(1_000_000_000, v2)  # min gas price placeholder

    def _pendingTransactions(self, params, v2):
        """hmy_pendingTransactions (reference: rpc/transaction.go
        PendingTransactions): the pool's executable plain txs."""
        pool = getattr(self.hmy, "tx_pool", None)
        if pool is None:
            return []
        out = []
        for tx, is_staking in pool.pending():
            if is_staking:
                continue
            d = self._tx_dict(tx, 0, 0, v2)
            # unmined: null placement, per the reference/eth semantics
            d["blockNumber"] = None
            d["transactionIndex"] = None
            out.append(d)
        return out

    def _pendingStakingTransactions(self, params, v2):
        """hmy_pendingStakingTransactions (reference: the staking
        lane of PendingTransactions)."""
        pool = getattr(self.hmy, "tx_pool", None)
        if pool is None:
            return []
        chain_id = self.hmy.chain_id()
        return [
            {
                "hash": "0x" + tx.hash(chain_id).hex(),
                "nonce": self._int(tx.nonce, v2),
                "from": "0x" + tx.sender(chain_id).hex(),
                "type": tx.directive.name,
                "gas": self._int(tx.gas_limit, v2),
                "gasPrice": self._int(tx.gas_price, v2),
            }
            for tx, is_staking in pool.pending()
            if is_staking
        ]

    def _traceBlockByNumber(self, params, v2):
        """debug_traceBlockByNumber: every tx of a block under the
        selected tracer (reference: eth/tracers API)."""
        num = _block_num(params[0], self.hmy.block_number())
        block = self.hmy.block_by_number(num)
        if block is None:
            return None
        opts = params[1] if len(params) > 1 and params[1] else {}
        chain_id = self.hmy.chain_id()
        # ONE parent state, evolved tx by tx: intra-block dependencies
        # (a tx reading its predecessor's writes) trace as executed
        state = self.hmy.chain.state_at(num - 1).copy()
        out = []
        for tx in block.transactions:
            out.append({
                "txHash": "0x" + tx.hash(chain_id).hex(),
                "result": self._trace_core(tx, num, state, opts),
            })
        return out

    def _getCXReceiptByHash(self, params, v2):
        """hmyv2_getCXReceiptByHash (reference: rpc/transaction.go):
        the cross-shard receipt minted by a source-shard tx."""
        cx = self.hmy.get_cx_receipt_by_hash(
            bytes.fromhex(params[0][2:])
        )
        if cx is None:
            return None
        header = self.hmy.header_by_number(cx.block_num)
        # keys per the reference's rpc CxReceipt json tags
        # (rpc/harmony/v2/types.go:253-262)
        return {
            "blockHash": "0x" + (
                header.hash().hex() if header else "00" * 32
            ),
            "blockNumber": self._int(cx.block_num, v2),
            "hash": "0x" + cx.tx_hash.hex(),
            "from": "0x" + cx.sender.hex(),
            "to": "0x" + cx.to.hex(),
            "shardID": cx.from_shard,
            "toShardID": cx.to_shard,
            "value": self._int(cx.amount, v2),
        }

    def _getProof(self, params, v2):
        """eth_getProof (reference: the go-ethereum GetProof RPC the
        fork carries): Merkle account + storage proofs against the
        MPT state commitment (StateDB.mpt_root) — verifiable with
        core/trie.verify_proof.  Note the account leaf is this chain's
        5-field RLP (nonce, balance, storageRoot, codeHash,
        validatorHash); the extra field carries staking state."""
        from .. import rlp as _rlp

        addr = _addr(params[0])
        slots = [
            (int(s, 16) if isinstance(s, str) else int(s)).to_bytes(
                32, "big"
            )
            for s in (params[1] or [])
        ]
        num = None
        if len(params) > 2 and params[2] is not None:
            num = _block_num(params[2], self.hmy.block_number())
        root, leaf, acct_proof, storage = self.hmy.get_proof(
            addr, slots, num
        )
        from ..core.trie import EMPTY_ROOT
        from ..ref.keccak import keccak256 as _keccak

        nonce, balance = 0, 0
        storage_root, code_hash = EMPTY_ROOT, _keccak(b"")
        if leaf:
            fields = _rlp.decode(leaf)
            nonce = _rlp.decode_int(fields[0])
            balance = _rlp.decode_int(fields[1])
            storage_root, code_hash = fields[2], fields[3]
        return {
            "address": "0x" + addr.hex(),
            "stateRoot": "0x" + root.hex(),
            "balance": self._int(balance, v2),
            "nonce": self._int(nonce, v2),
            "codeHash": "0x" + code_hash.hex(),
            "storageHash": "0x" + storage_root.hex(),
            "accountProof": ["0x" + n.hex() for n in acct_proof],
            "storageProof": [
                {
                    "key": "0x" + slot.hex(),
                    "value": self._int(val, v2),
                    "proof": ["0x" + n.hex() for n in nodes],
                }
                for slot, val, nodes in storage
            ],
        }

    # -- debug namespace (reference: eth/tracers callTracer) ---------------

    def _traceTransaction(self, params, v2):
        """Re-execute a mined transaction under a tracer against its
        parent state (reference: debug_traceTransaction + eth/tracers).
        The tracer option selects callTracer / prestateTracer; with no
        option the geth-default opcode structLogs come back."""
        tx_hash = bytes.fromhex(params[0][2:])
        found = self.hmy.get_transaction(tx_hash)
        if found is None:
            return None
        num, _idx, tx = found
        opts = params[1] if len(params) > 1 and params[1] else {}
        state = self.hmy.chain.state_at(num - 1).copy()
        return self._trace_core(tx, num, state, opts)

    def _trace_core(self, tx, num: int, state, opts: dict):
        """One tx replayed under a tracer ON the given state — the
        state EVOLVES (value moves, storage writes, nonce bump, fee
        debit), so a block-level caller chains txs cumulatively."""
        from ..core.vm import (
            EVM, CallTracer, Env, FourByteTracer, NgramTracer,
            NoopTracer, OpcountTracer, PrestateTracer, StructLogTracer,
        )

        which = opts.get("tracer", "")
        chain_id = self.hmy.chain_id()
        sender = tx.sender(chain_id)
        env = Env(block_num=num, chain_id=chain_id,
                  shard_id=self.hmy.shard_id())
        # the reference serves these by NAME via its JS tracer engine
        # (hmy/tracers); here they are native implementations with the
        # same output shapes.  Arbitrary inline-JS tracers are a
        # deliberate non-goal (PARITY.md): RPC-supplied code execution.
        named = {
            "callTracer": lambda: CallTracer(),
            "prestateTracer": lambda: PrestateTracer(state),
            "noopTracer": NoopTracer,
            "opcountTracer": OpcountTracer,
            "4byteTracer": FourByteTracer,
            "unigramTracer": lambda: NgramTracer(1),
            "bigramTracer": lambda: NgramTracer(2),
            "trigramTracer": lambda: NgramTracer(3),
        }
        if which in named:
            tracer = named[which]()
        elif not which:
            tracer = StructLogTracer(
                with_stack=not (
                    opts.get("disableStack") or opts.get("disable_stack")
                ),
            )
        else:
            raise ValueError(f"unknown tracer {which!r}")
        evm = EVM(state, env, origin=sender, gas_price=tx.gas_price,
                  tracer=tracer)
        # mirror the processor's EIP-2929/2930 warm-up (ADVICE r4:
        # without it traces charge cold 2600/2100 where the canonical
        # run paid warm 100, and near-limit txs trace as out-of-gas)
        if tx.to is not None:
            evm.warm_addrs.add(tx.to)
        for al_addr, al_slots in tx.access_list:
            evm.warm_addrs.add(al_addr)
            for slot in al_slots:
                evm.warm_slots.add((al_addr, slot))
        if which == "prestateTracer":
            # capture the sender BEFORE the replay's nonce bump —
            # enter() only fires inside the call
            tracer.touch(sender)
        state.set_nonce(sender, tx.nonce + 1)
        # replay with the same budget the processor gave the VM:
        # intrinsic gas is charged up front (state_processor.py)
        from ..core.state_processor import intrinsic_gas

        intrinsic = intrinsic_gas(tx)
        budget = max(tx.gas_limit - intrinsic, 0)
        if tx.to is None:
            ok, gas_left, created = evm.create(
                sender, tx.value, tx.data, budget
            )[:3]
            # geth's returnValue for creation is the DEPLOYED code
            out = state.code(created) if ok and created else b""
        else:
            ok, gas_left, out = evm.call(
                sender, tx.to, tx.value, tx.data, budget
            )[:3]
        # fee debit, so a later tx in a cumulative block replay sees
        # the sender's true post-tx balance (the processor does this
        # on the real path)
        state.sub_balance(
            sender, (intrinsic + budget - gas_left) * tx.gas_price
        )
        if which == "callTracer":
            return tracer.root
        if which == "prestateTracer":
            return tracer.accounts
        if which:  # named profiling tracers expose .result
            return tracer.result
        result = {
            "gas": intrinsic + (budget - gas_left),
            "failed": not ok,
            "returnValue": out.hex(),
            "structLogs": tracer.logs,
        }
        if tracer.truncated:
            result["truncated"] = True
        return result

    # -- staking reads (reference: rpc staking.go) --------------------------

    def _getDelegationsByDelegator(self, params, v2):
        return self.hmy.delegations_by_delegator(_addr(params[0]))

    def _getDelegationsByValidator(self, params, v2):
        return self.hmy.delegations_by_validator(_addr(params[0]))

    def _getElectedValidatorAddresses(self, params, v2):
        return [
            "0x" + a.hex()
            for a in self.hmy.elected_validator_addresses()
        ]

    def _getMedianRawStakeSnapshot(self, params, v2):
        return self.hmy.median_raw_stake_snapshot()
