"""WebSocket JSON-RPC: the push half of the RPC surface.

The role of the reference's WS servers (reference: rpc/harmony/rpc.go
startHTTP/startWS pair — every namespace is served over both; plus
eth_subscribe push for newHeads/logs).  Stdlib-only RFC 6455:

* handshake: HTTP/1.1 Upgrade with the Sec-WebSocket-Accept digest;
* frames: FIN+opcode, masked client payloads, text frames only, close
  and ping handled; fragmented and >16 MB frames rejected;
* dispatch: the SAME RPCServer.dispatch as HTTP, plus
  eth_subscribe("newHeads" | "logs") — a per-connection poller thread
  pushes notifications in the eth_subscription envelope.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
import time

_WS_MAGIC = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
MAX_FRAME = 16 * 1024 * 1024


def _accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1(client_key.encode() + _WS_MAGIC).digest()
    ).decode()


def _recv_exact(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock):
    """(opcode, payload) or None on close/EOF/protocol error."""
    hdr = _recv_exact(sock, 2)
    if hdr is None:
        return None
    fin, opcode = hdr[0] & 0x80, hdr[0] & 0x0F
    masked, ln = hdr[1] & 0x80, hdr[1] & 0x7F
    if not fin:
        return None  # fragmentation unsupported: drop the connection
    if ln == 126:
        ext = _recv_exact(sock, 2)
        if ext is None:
            return None
        ln = struct.unpack(">H", ext)[0]
    elif ln == 127:
        ext = _recv_exact(sock, 8)
        if ext is None:
            return None
        ln = struct.unpack(">Q", ext)[0]
    if ln > MAX_FRAME:
        return None
    mask = _recv_exact(sock, 4) if masked else b"\x00" * 4
    if mask is None:
        return None
    payload = _recv_exact(sock, ln)
    if payload is None:
        return None
    if masked:
        payload = bytes(
            b ^ mask[i % 4] for i, b in enumerate(payload)
        )
    return opcode, payload


def write_frame(sock, payload: bytes, opcode: int = 0x1):
    ln = len(payload)
    hdr = bytes([0x80 | opcode])
    if ln < 126:
        hdr += bytes([ln])
    elif ln < 1 << 16:
        hdr += bytes([126]) + struct.pack(">H", ln)
    else:
        hdr += bytes([127]) + struct.pack(">Q", ln)
    sock.sendall(hdr + payload)


class WSServer:
    """WebSocket front over an RPCServer's dispatch + subscriptions."""

    def __init__(self, rpc, port: int = 0, poll_interval: float = 0.25):
        self.rpc = rpc  # RPCServer (dispatch + hmy facade)
        self.poll_interval = poll_interval
        self._closing = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        self._thread = threading.Thread(
            # graftlint: thread-role=serving
            target=self._accept_loop, daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._closing = True
        try:
            self._srv.close()
        except OSError:
            pass

    # -- connection handling ------------------------------------------------

    def _accept_loop(self):
        while not self._closing:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                # graftlint: thread-role=transient — per-connection
                target=self._serve_conn, args=(sock,), daemon=True
            ).start()

    def _handshake(self, sock) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = sock.recv(4096)
            if not chunk or len(data) > 16384:
                return False
            data += chunk
        headers = {}
        for line in data.split(b"\r\n")[1:]:
            if b":" in line:
                k, _, v = line.partition(b":")
                headers[k.strip().lower()] = v.strip()
        key = headers.get(b"sec-websocket-key")
        if key is None:
            return False
        sock.sendall(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: "
            + _accept_key(key.decode()).encode() + b"\r\n\r\n"
        )
        return True

    def _serve_conn(self, sock):
        subs: dict[str, dict] = {}  # sub id -> {"kind", "last_block"}
        lock = threading.Lock()
        # one writer at a time: the request loop and the pusher thread
        # share this socket, and interleaved sendall calls would splice
        # two WS frames together mid-header.  Held only around a single
        # write_frame — never across dispatch or chain reads
        wlock = threading.Lock()
        stop = threading.Event()

        def write(payload: bytes, opcode: int = 0x1):
            with wlock:
                write_frame(sock, payload, opcode)

        def pusher():
            while not stop.is_set() and not self._closing:
                try:
                    self._push_round(subs, lock, write)
                except OSError:
                    return
                stop.wait(self.poll_interval)

        try:
            if not self._handshake(sock):
                return
            threading.Thread(
                target=pusher, daemon=True,  # graftlint: thread-role=transient
            ).start()
            while not self._closing:
                frame = read_frame(sock)
                if frame is None:
                    return
                opcode, payload = frame
                if opcode == 0x8:  # close
                    write(b"", 0x8)
                    return
                if opcode == 0x9:  # ping
                    write(payload, 0xA)
                    continue
                if opcode != 0x1:
                    continue
                try:
                    req = json.loads(payload)
                except ValueError:
                    continue
                out = self._dispatch_ws(req, subs, lock)
                write(json.dumps(out).encode())
        except OSError:
            pass
        finally:
            stop.set()
            try:
                sock.close()
            except OSError:
                pass

    # -- subscription dispatch ----------------------------------------------

    def _dispatch_ws(self, req, subs, lock):
        method = req.get("method", "")
        if method.endswith("_subscribe"):
            params = req.get("params") or []
            kind = params[0] if params else ""
            if kind not in ("newHeads", "logs",
                            "newPendingTransactions"):
                return self.rpc._error(
                    req.get("id"), -32602, f"unsupported: {kind}"
                )
            sub_id = hex(int(time.monotonic_ns()))
            with lock:
                subs[sub_id] = {
                    "kind": kind,
                    "criteria": params[1] if len(params) > 1 else {},
                    "last_block": self.rpc.hmy.block_number(),
                    # pending-tx subs push only txs admitted AFTER the
                    # subscription (geth semantics); the pool's
                    # admission ring catches txs that enter and leave
                    # within one poll interval
                    "seq": self._pool_seq(),
                }
            return {"jsonrpc": "2.0", "id": req.get("id"),
                    "result": sub_id}
        if method.endswith("_unsubscribe"):
            params = req.get("params") or []
            with lock:
                ok = subs.pop(params[0] if params else "", None)
            return {"jsonrpc": "2.0", "id": req.get("id"),
                    "result": ok is not None}
        return self.rpc.dispatch(req)

    def _pool_seq(self) -> int:
        pool = getattr(self.rpc.hmy, "tx_pool", None)
        return pool.add_seq if pool is not None else 0

    def _push_round(self, subs, lock, write):
        with lock:
            items = list(subs.items())
        head = self.rpc.hmy.block_number()
        for sub_id, sub in items:
            if sub["kind"] == "newPendingTransactions":
                pool = getattr(self.rpc.hmy, "tx_pool", None)
                if pool is None:
                    continue
                sub["seq"], hashes = pool.adds_since(sub["seq"])
                for h in hashes:
                    self._notify(write, sub_id, "0x" + h.hex())
                continue
            since = sub["last_block"]
            if head <= since:
                continue
            sub["last_block"] = head
            if sub["kind"] == "newHeads":
                for n in range(since + 1, head + 1):
                    h = self.rpc.hmy.header_by_number(n)
                    if h is None:
                        continue
                    self._notify(
                        write, sub_id, self.rpc._header_dict(h, False)
                    )
            else:  # logs
                crit = dict(sub["criteria"])
                crit.setdefault("fromBlock", since + 1)
                crit.setdefault("toBlock", head)
                frm, to, address, topics = self.rpc._parse_log_criteria(
                    crit
                )
                for entry in self.rpc.hmy.get_logs(
                    max(frm, since + 1), to, address, topics
                ):
                    self._notify(
                        write, sub_id,
                        self.rpc._log_dict(*entry, False),
                    )

    @staticmethod
    def _notify(write, sub_id, result):
        write(json.dumps({
            "jsonrpc": "2.0",
            "method": "eth_subscription",
            "params": {"subscription": sub_id, "result": result},
        }).encode())
