"""JSON-RPC: the external API server."""

from .server import RPCServer

__all__ = ["RPCServer"]
