"""User-facing BLS API: the framework's equivalent of the reference's
crypto/bls wrapper types (reference: crypto/bls/bls.go:23-33 —
PublicKeyWrapper / PrivateKeyWrapper pairing a deserialized object with
its serialized bytes) and the herumi object surface the node code calls.

Single-signature operations run on the host bigint path (they are
latency-trivial); batch and aggregate operations route through the TPU
ops (harmony_tpu.ops.bls) — the boundary the reference crosses via cgo.
"""

from __future__ import annotations

import functools

from .ref import bls as RB
from .ref import curve as RC
from .ref.params import PUBKEY_BYTES, SIG_BYTES


class PublicKey:
    """Wrapper pairing the affine point with its 48-byte serialization."""

    __slots__ = ("point", "_bytes")

    def __init__(self, point, serialized: bytes | None = None):
        self.point = point
        self._bytes = serialized

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        return cls(RB.pubkey_from_bytes(data), bytes(data))

    @property
    def bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = RB.pubkey_to_bytes(self.point)
        return self._bytes

    def add(self, other: "PublicKey") -> "PublicKey":
        return PublicKey(RC.g1.add(self.point, other.point))

    def sub(self, other: "PublicKey") -> "PublicKey":
        return PublicKey(RC.g1.add(self.point, RC.g1.neg(other.point)))

    def __eq__(self, o) -> bool:
        return isinstance(o, PublicKey) and self.bytes == o.bytes

    def __hash__(self):
        return hash(self.bytes)

    def __repr__(self):
        return f"PublicKey({self.bytes[:4].hex()}..)"


class Signature:
    __slots__ = ("point", "_bytes")

    def __init__(self, point, serialized: bytes | None = None):
        self.point = point
        self._bytes = serialized

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        return cls(RB.sig_from_bytes(data), bytes(data))

    @property
    def bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = RB.sig_to_bytes(self.point)
        return self._bytes

    def add(self, other: "Signature") -> "Signature":
        """Aggregate (Sign.Add analog)."""
        return Signature(RC.g2.add(self.point, other.point))

    def verify(self, pub: PublicKey, msg_hash: bytes) -> bool:
        """VerifyHash analog."""
        return RB.verify(pub.point, msg_hash, self.point)

    def __eq__(self, o) -> bool:
        return isinstance(o, Signature) and self.bytes == o.bytes

    def __repr__(self):
        return f"Signature({self.bytes[:4].hex()}..)"


class PrivateKey:
    """Wrapper pairing the scalar with its derived public key (reference:
    crypto/bls/bls.go PrivateKeyWrapper)."""

    __slots__ = ("scalar", "pub")

    def __init__(self, scalar: int):
        self.scalar = scalar % RC.R_ORDER
        self.pub = PublicKey(RB.pubkey(self.scalar))

    @classmethod
    def generate(cls, seed: bytes | None = None) -> "PrivateKey":
        return cls(RB.keygen(seed))

    @classmethod
    def from_bytes(cls, data: bytes) -> "PrivateKey":
        return cls(RB.sk_from_bytes(data))

    @property
    def bytes(self) -> bytes:
        return RB.sk_to_bytes(self.scalar)

    def sign_hash(self, msg_hash: bytes) -> Signature:
        """SignHash analog: sign a (typically 32-byte) hash."""
        return Signature(RB.sign(self.scalar, msg_hash))


def aggregate_sigs(sigs) -> Signature:
    """Sum signatures (AggregateSig — reference: crypto/bls/mask.go:57-64)."""
    return Signature(RB.aggregate_sigs([s.point for s in sigs]))


def verify_point(pk_point, payload: bytes, sig_point, *,
                 lane=None) -> bool:
    """One aggregate-signature check, routed through the verification
    scheduler's shared device queue when the device path is live
    (device.device_enabled()) and to the host bigint twin otherwise —
    THE verification choke point every consensus check funnels
    through.  ``lane`` picks the scheduler priority lane (default:
    consensus — vote/proof checks gate live rounds)."""
    from . import device as DV

    if DV.device_enabled():
        from . import sched

        return sched.verify_single(
            pk_point, payload, sig_point,
            lane=sched.Lane.CONSENSUS if lane is None else lane,
        )
    return RB.verify(pk_point, payload, sig_point)


def verify_aggregate_bytes(
    pubkeys_bytes, payload: bytes, sig_bytes: bytes, *, lane=None
) -> bool:
    """Verify a 96-byte signature against the SUM of serialized pubkeys —
    the shape every multi-key vote check takes (consensus votes,
    view-change votes, slash evidence).  Malformed input returns False,
    never raises."""
    if not pubkeys_bytes:
        return False
    try:
        agg_pk = None
        for pk_bytes in pubkeys_bytes:
            pk = pubkey_from_bytes_cached(pk_bytes)
            agg_pk = pk if agg_pk is None else agg_pk.add(pk)
        sig = Signature.from_bytes(sig_bytes)
    except (ValueError, KeyError):
        return False
    return verify_point(agg_pk.point, payload, sig.point, lane=lane)


def proof_of_possession(priv: "PrivateKey") -> bytes:
    """BLS proof-of-possession: the key signs its own serialized public
    key (the reference's staking_verifier.go VerifyBLSKeys contract) —
    carried in create-validator / add-bls-key staking txs and checked
    at pool admission on the scheduler's ingress lane."""
    return priv.sign_hash(priv.pub.bytes).bytes


def verify_proof_of_possession(pub_bytes: bytes, sig_bytes: bytes, *,
                               lane=None) -> bool:
    """Check one key's proof-of-possession; malformed input returns
    False, never raises."""
    return verify_proofs_of_possession([(pub_bytes, sig_bytes)],
                                       lane=lane)


def verify_proofs_of_possession(pairs, *, lane=None) -> bool:
    """Check many (pubkey bytes, pop signature bytes) pairs: on the
    live device path every check is SUBMITTED to the scheduler before
    the first is awaited, so a multi-key create-validator (or a burst
    of staking submits) coalesces into one fused batch instead of N
    sequential round-trips.  False on any malformed or failing pair,
    never raises."""
    from . import device as DV

    decoded = []
    try:
        for pub_bytes, sig_bytes in pairs:
            pk = pubkey_from_bytes_cached(pub_bytes)
            sig = Signature.from_bytes(sig_bytes)
            if sig.point is None:
                return False
            decoded.append((pk.point, bytes(pub_bytes), sig.point))
    except (ValueError, KeyError):
        return False
    if not decoded:
        return True
    if DV.device_enabled():
        from . import sched

        if sched.enabled():
            from .ref.hash_to_curve import hash_to_g2

            s = sched.scheduler()
            use_lane = sched.Lane.INGRESS if lane is None else lane
            futures = [
                s.submit_single(pk, hash_to_g2(payload), sig,
                                lane=use_lane)
                for pk, payload, sig in decoded
            ]
            try:
                return all(f.result() for f in futures)
            except (RuntimeError, OSError):
                # scheduler stopped / deadline surfaced mid-await: an
                # unverifiable proof is a REJECTED proof — this
                # function never raises into admission paths
                return False
    return all(
        verify_point(pk, payload, sig, lane=lane)
        for pk, payload, sig in decoded
    )


@functools.lru_cache(maxsize=1024)
def _cached_pubkey_from_bytes(data: bytes):
    return RB.pubkey_from_bytes(data)


def pubkey_from_bytes_cached(data: bytes) -> PublicKey:
    """Deserialization with the reference's 1024-entry LRU semantics
    (reference: crypto/bls/mask.go:9-16)."""
    return PublicKey(_cached_pubkey_from_bytes(bytes(data)), bytes(data))


__all__ = [
    "PublicKey",
    "PrivateKey",
    "Signature",
    "aggregate_sigs",
    "proof_of_possession",
    "pubkey_from_bytes_cached",
    "verify_proof_of_possession",
    "PUBKEY_BYTES",
    "SIG_BYTES",
]
