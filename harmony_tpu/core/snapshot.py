"""State snapshots + historical-state pruning.

The chain persists one full serialized StateDB per block (rawdb
``S || root``) — simple and crash-safe, but unbounded: a long-running
node's store grows with every block.  This module is the framework's
analog of the reference's snapshot/pruning pair (reference:
core/state/snapshot/ flat snapshot tree, core/blockchain_pruner.go):

* **Pruning** deletes historical state blobs outside a retention
  window, incrementally on insert (O(1) per block) or in bulk.  Headers,
  bodies, receipts and commit proofs are kept — a pruned node is a full
  header-chain node with recent-state depth, exactly the shape a fast
  (snap) sync produces.
* **Snapshots** export one sealed block's state (header + commit proof +
  accounts) to a single file, and import it back with the SAME binding
  check fast sync uses (config.state_root vs the sealed header root), so
  a snapshot can restore a pruned node or bootstrap a fresh one.

Root sharing: consecutive blocks with identical state (no txs, no
rewards) reuse one ``S || root`` entry; the pruner defers deletion until
the NEXT block's root differs, so a retained block never loses its
state to the pruning of an older twin.
"""

from __future__ import annotations

import os

from .. import prof
from . import rawdb
from .state import StateDB

_MAGIC = b"HTSNAP1\n"

# wire-serving page shape: a page closes at whichever bound hits first.
# Byte-bounded pages keep every frame far under the stream layer's
# response cap even when single accounts are huge (validator wrappers
# with long delegation lists)
SNAPSHOT_PAGE_ACCOUNTS = 512
SNAPSHOT_PAGE_BYTES = 4 * 1024 * 1024


class SnapshotError(ValueError):
    pass


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------

def prune_state_at(chain, num: int) -> bool:
    """Delete block ``num``'s state blob if it is safe: never the
    genesis state, and never a root shared with the NEXT block (the
    retained chain still references it).  Returns True if deleted."""
    if num <= 0:
        return False
    header = rawdb.read_header(chain.db, num)
    if header is None:
        return False
    nxt = rawdb.read_header(chain.db, num + 1)
    if nxt is not None and nxt.root == header.root:
        return False  # shared root: defer to the next block's pruning
    if rawdb.read_state(chain.db, header.root) is None:
        return False
    rawdb.delete_state(chain.db, header.root)
    return True


def prune_states(chain, retain: int) -> int:
    """Bulk prune: drop every state blob older than ``head - retain``
    (reference: core/blockchain_pruner.go's offline prune).  Returns
    how many blobs were deleted."""
    if retain < 1:
        raise SnapshotError("retention must be >= 1")
    deleted = 0
    for num in range(1, chain.head_number - retain + 1):
        if prune_state_at(chain, num):
            deleted += 1
    return deleted


# ---------------------------------------------------------------------------
# snapshot files
# ---------------------------------------------------------------------------

def _enc_blob(b: bytes) -> bytes:
    return len(b).to_bytes(8, "big") + b


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def blob(self) -> bytes:
        n = int.from_bytes(self.d[self.o:self.o + 8], "big")
        self.o += 8
        out = self.d[self.o:self.o + n]
        if len(out) != n:
            raise SnapshotError("truncated snapshot")
        self.o += n
        return out


def paginate_state(blob: bytes,
                   max_accounts: int = SNAPSHOT_PAGE_ACCOUNTS,
                   max_bytes: int = SNAPSHOT_PAGE_BYTES) -> list:
    """Partition a serialized StateDB blob (``[u32 n][(addr, account)
    pairs]``) into wire pages: ``[(start_off, end_off, count), ...]``
    covering the pair region exactly.  Page boundaries always fall on
    account boundaries, so every page is itself a decodable
    ``[u32 count] || pairs`` fragment once the count is prepended, and
    the concatenation of all pages reassembles the original blob
    byte-for-byte (the importer's root check then binds the exact
    bytes).  Raises SnapshotError on a structurally damaged blob — the
    walk is length-arithmetic only, no allocation."""
    total = len(blob)
    n = int.from_bytes(blob[:4], "little")
    if n > total - 4:
        raise SnapshotError("implausible account count in state blob")
    off = 4
    pages = []
    start, count = off, 0
    for _ in range(n):
        ln = int.from_bytes(blob[off:off + 4], "little")
        off += 4 + ln
        if off + 4 > total:
            raise SnapshotError("truncated state blob")
        ln = int.from_bytes(blob[off:off + 4], "little")
        off += 4 + ln
        if off > total:
            raise SnapshotError("truncated state blob")
        count += 1
        if count >= max_accounts or off - start >= max_bytes:
            pages.append((start, off, count))
            start, count = off, 0
    if count:
        pages.append((start, off, count))
    if off != total:
        raise SnapshotError("trailing bytes after state accounts")
    return pages


def export_snapshot(chain, path: str, num: int | None = None) -> int:
    """Write block ``num``'s (default: head) sealed state to ``path``.

    Layout: magic || header || commit-proof || state-accounts.  The
    commit proof ([96B agg sig || bitmap], empty when the store has
    none, e.g. genesis) lets the importer's operator audit the seal.
    """
    with prof.stage("snapshot.export"):
        num = chain.head_number if num is None else num
        header = rawdb.read_header(chain.db, num)
        if header is None:
            raise SnapshotError(f"no header {num}")
        blob = rawdb.read_state(chain.db, header.root)
        if blob is None:
            raise SnapshotError(
                f"no state for block {num} (pruned? export a newer block)"
            )
        proof = rawdb.read_commit_sig(chain.db, num) or b""
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(_enc_blob(rawdb.encode_header(header)))
            f.write(_enc_blob(proof))
            f.write(_enc_blob(blob))
        os.replace(tmp, path)
        return num


def import_snapshot(chain, path: str, trust: bool = False) -> int:
    """Install a snapshot file into ``chain``; returns its block number.

    Binding: the accounts must hash to the snapshot header's sealed
    state root (same check as fast sync's adopt_state).  The header
    itself is trusted EITHER because the chain already has the same
    header at that height (restore-after-prune / resync case) OR
    because the operator passed ``trust=True`` (bootstrapping a fresh
    node from an operator-asserted snapshot, the way a trusted snap
    init works).
    """
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(_MAGIC):
        raise SnapshotError("not a snapshot file")
    r = _Reader(data[len(_MAGIC):])
    header = rawdb.decode_header(r.blob())
    proof = r.blob()
    state_blob = r.blob()
    num = header.block_num

    local = rawdb.read_header(chain.db, num)
    if local is not None:
        if local.hash() != header.hash():
            raise SnapshotError(
                f"snapshot header {num} does not match the local chain"
            )
    elif not trust:
        raise SnapshotError(
            f"chain has no header {num}: import with trust=True only if "
            "the snapshot source is operator-trusted"
        )
    return install_snapshot(chain, header, proof, state_blob)


def install_snapshot(chain, header, proof: bytes,
                     state_blob: bytes) -> int:
    """Atomically install a snapshot whose HEADER the caller has
    already established trust in (local-chain match, operator trust,
    or — the late-join bootstrap — a peer-majority hash agreement).
    The accounts are still bound here: they must hash to the header's
    sealed state root, or nothing is written.  Returns the block
    number."""
    with prof.stage("snapshot.install"):
        num = header.block_num
        state = StateDB.deserialize(state_blob)
        if chain.config.state_root(state, header.epoch) != header.root:
            raise SnapshotError(
                "snapshot accounts do not match the sealed state root"
            )

        with chain._insert_lock:
            # header + proof + state + head move in ONE atomic batch: a
            # crash mid-import must leave the store exactly as damaged
            # as before, never half-restored (same discipline as
            # adopt_state)
            from .kv import WriteBatch, commit_batch

            batch = WriteBatch()
            if rawdb.read_header(chain.db, num) is None:
                batch.put(
                    rawdb._num_key(rawdb._HEADER, num),
                    rawdb.encode_header(header),
                )
                batch.put(rawdb._num_key(rawdb._CANON, num), header.hash())
                batch.put(
                    rawdb._NUM_BY_HASH + header.hash(),
                    num.to_bytes(8, "little"),
                )
            if proof:
                rawdb.write_commit_sig(batch, num, proof)
            rawdb.write_state(batch, header.root, state_blob)
            moves_head = num >= chain.head_number
            if moves_head:
                rawdb.write_head_number(batch, num)
            commit_batch(chain.db, batch)
            if moves_head:
                chain._head_num = num
                chain._state = state
                chain._committee_cache.clear()
        return num
