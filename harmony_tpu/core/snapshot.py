"""State snapshots + historical-state pruning.

The chain persists one full serialized StateDB per block (rawdb
``S || root``) — simple and crash-safe, but unbounded: a long-running
node's store grows with every block.  This module is the framework's
analog of the reference's snapshot/pruning pair (reference:
core/state/snapshot/ flat snapshot tree, core/blockchain_pruner.go):

* **Pruning** deletes historical state blobs outside a retention
  window, incrementally on insert (O(1) per block) or in bulk.  Headers,
  bodies, receipts and commit proofs are kept — a pruned node is a full
  header-chain node with recent-state depth, exactly the shape a fast
  (snap) sync produces.
* **Snapshots** export one sealed block's state (header + commit proof +
  accounts) to a single file, and import it back with the SAME binding
  check fast sync uses (config.state_root vs the sealed header root), so
  a snapshot can restore a pruned node or bootstrap a fresh one.

Root sharing: consecutive blocks with identical state (no txs, no
rewards) reuse one ``S || root`` entry; the pruner defers deletion until
the NEXT block's root differs, so a retained block never loses its
state to the pruning of an older twin.
"""

from __future__ import annotations

import os

from . import rawdb
from .state import StateDB

_MAGIC = b"HTSNAP1\n"


class SnapshotError(ValueError):
    pass


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------

def prune_state_at(chain, num: int) -> bool:
    """Delete block ``num``'s state blob if it is safe: never the
    genesis state, and never a root shared with the NEXT block (the
    retained chain still references it).  Returns True if deleted."""
    if num <= 0:
        return False
    header = rawdb.read_header(chain.db, num)
    if header is None:
        return False
    nxt = rawdb.read_header(chain.db, num + 1)
    if nxt is not None and nxt.root == header.root:
        return False  # shared root: defer to the next block's pruning
    if rawdb.read_state(chain.db, header.root) is None:
        return False
    rawdb.delete_state(chain.db, header.root)
    return True


def prune_states(chain, retain: int) -> int:
    """Bulk prune: drop every state blob older than ``head - retain``
    (reference: core/blockchain_pruner.go's offline prune).  Returns
    how many blobs were deleted."""
    if retain < 1:
        raise SnapshotError("retention must be >= 1")
    deleted = 0
    for num in range(1, chain.head_number - retain + 1):
        if prune_state_at(chain, num):
            deleted += 1
    return deleted


# ---------------------------------------------------------------------------
# snapshot files
# ---------------------------------------------------------------------------

def _enc_blob(b: bytes) -> bytes:
    return len(b).to_bytes(8, "big") + b


class _Reader:
    def __init__(self, data: bytes):
        self.d = data
        self.o = 0

    def blob(self) -> bytes:
        n = int.from_bytes(self.d[self.o:self.o + 8], "big")
        self.o += 8
        out = self.d[self.o:self.o + n]
        if len(out) != n:
            raise SnapshotError("truncated snapshot")
        self.o += n
        return out


def export_snapshot(chain, path: str, num: int | None = None) -> int:
    """Write block ``num``'s (default: head) sealed state to ``path``.

    Layout: magic || header || commit-proof || state-accounts.  The
    commit proof ([96B agg sig || bitmap], empty when the store has
    none, e.g. genesis) lets the importer's operator audit the seal.
    """
    num = chain.head_number if num is None else num
    header = rawdb.read_header(chain.db, num)
    if header is None:
        raise SnapshotError(f"no header {num}")
    blob = rawdb.read_state(chain.db, header.root)
    if blob is None:
        raise SnapshotError(
            f"no state for block {num} (pruned? export a newer block)"
        )
    proof = rawdb.read_commit_sig(chain.db, num) or b""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(_enc_blob(rawdb.encode_header(header)))
        f.write(_enc_blob(proof))
        f.write(_enc_blob(blob))
    os.replace(tmp, path)
    return num


def import_snapshot(chain, path: str, trust: bool = False) -> int:
    """Install a snapshot file into ``chain``; returns its block number.

    Binding: the accounts must hash to the snapshot header's sealed
    state root (same check as fast sync's adopt_state).  The header
    itself is trusted EITHER because the chain already has the same
    header at that height (restore-after-prune / resync case) OR
    because the operator passed ``trust=True`` (bootstrapping a fresh
    node from an operator-asserted snapshot, the way a trusted snap
    init works).
    """
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(_MAGIC):
        raise SnapshotError("not a snapshot file")
    r = _Reader(data[len(_MAGIC):])
    header = rawdb.decode_header(r.blob())
    proof = r.blob()
    state_blob = r.blob()
    num = header.block_num

    local = rawdb.read_header(chain.db, num)
    if local is not None:
        if local.hash() != header.hash():
            raise SnapshotError(
                f"snapshot header {num} does not match the local chain"
            )
    elif not trust:
        raise SnapshotError(
            f"chain has no header {num}: import with trust=True only if "
            "the snapshot source is operator-trusted"
        )

    state = StateDB.deserialize(state_blob)
    if chain.config.state_root(state, header.epoch) != header.root:
        raise SnapshotError(
            "snapshot accounts do not match the sealed state root"
        )

    with chain._insert_lock:
        # header + proof + state + head move in ONE atomic batch: a
        # crash mid-import must leave the store exactly as damaged as
        # before, never half-restored (same discipline as adopt_state)
        from .kv import WriteBatch, commit_batch

        batch = WriteBatch()
        if local is None:
            batch.put(
                rawdb._num_key(rawdb._HEADER, num),
                rawdb.encode_header(header),
            )
            batch.put(rawdb._num_key(rawdb._CANON, num), header.hash())
            batch.put(
                rawdb._NUM_BY_HASH + header.hash(),
                num.to_bytes(8, "little"),
            )
        if proof:
            rawdb.write_commit_sig(batch, num, proof)
        rawdb.write_state(batch, header.root, state_blob)
        moves_head = num >= chain.head_number
        if moves_head:
            rawdb.write_head_number(batch, num)
        commit_batch(chain.db, batch)
        if moves_head:
            chain._head_num = num
            chain._state = state
            chain._committee_cache.clear()
    return num
