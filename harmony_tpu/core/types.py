"""Chain value types: transactions, receipts, blocks.

The signable subset of the reference's core/types + staking/types
(reference: core/types tx model, staking/types/transaction.go,
core/types/cx_receipt.go — SURVEY.md §2.4).  Serialization is the
framework's canonical fixed-width layout (length-prefixed fields,
little-endian ints — the same documented deviation from RLP that
chain/header.py makes); hashes are keccak-256 of that layout.

Transactions are ECDSA-signed (crypto_ecdsa) with the sender recovered
from the signature — there is no "from" field on the wire, exactly as
in the reference's tx model.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

from ..crypto_ecdsa import ECDSAKey, pub_to_address, recover
from ..ref.keccak import keccak256


def _enc_bytes(b: bytes) -> bytes:
    return len(b).to_bytes(4, "little") + b


def _enc_int(v: int, width: int = 8) -> bytes:
    return v.to_bytes(width, "little")


def _enc_big(v: int) -> bytes:
    """Variable-length big int (for balances beyond 2^64)."""
    b = v.to_bytes((v.bit_length() + 7) // 8 or 1, "little")
    return _enc_bytes(b)


class Reader:
    """Cursor over the canonical length-prefixed little-endian layout
    (the single decode counterpart of the _enc_* helpers)."""

    def __init__(self, data: bytes):
        self.view = memoryview(data)
        self.off = 0

    def bytes_(self) -> bytes:
        ln = int.from_bytes(self.view[self.off:self.off + 4], "little")
        self.off += 4
        out = bytes(self.view[self.off:self.off + ln])
        self.off += ln
        return out

    def int_(self, width: int = 8) -> int:
        v = int.from_bytes(self.view[self.off:self.off + width], "little")
        self.off += width
        return v

    def big_(self) -> int:
        return int.from_bytes(self.bytes_(), "little")

    def raw(self, n: int) -> bytes:
        out = bytes(self.view[self.off:self.off + n])
        self.off += n
        return out

    def checked_count(self, width: int = 4) -> int:
        """A length-prefixed element count, REJECTED when it cannot
        fit in the remaining bytes (each element consumes >= 1 byte).
        The Reader slices silently past EOF, so a forged count in a
        wire/crash-fed blob would otherwise spin a garbage-object loop
        bounded only by the prefix width — hostile inputs must cost
        their own size, never 4 G iterations."""
        n = self.int_(width)
        if n > len(self.view) - self.off:
            raise ValueError(
                f"implausible element count {n} with "
                f"{len(self.view) - self.off} bytes left"
            )
        return n

    def eof(self) -> bool:
        return self.off >= len(self.view)


@dataclass
class Transaction:
    """A value-transfer / payload transaction, optionally cross-shard
    (to_shard != shard — the CXReceipt source, reference:
    core/state_processor.go cx handling)."""

    nonce: int
    gas_price: int
    gas_limit: int
    shard_id: int
    to_shard: int
    to: bytes | None  # 20-byte address; None = contract-creation style
    value: int
    data: bytes = b""
    sig: bytes = b""  # 65-byte [R||S||V]
    # EIP-2930 typed transaction (reference: core/types AccessListTx):
    # tx_type 0 = legacy (wire format unchanged), 1 = access-list tx
    # carrying [(address20, [slot32...])]; listed entries are pre-warmed
    # for EIP-2929 and paid for in intrinsic gas (2400/addr, 1900/slot)
    tx_type: int = 0
    access_list: list = field(default_factory=list)

    def signing_bytes(self, chain_id: int) -> bytes:
        out = bytearray()
        out += _enc_int(chain_id)
        out += _enc_int(self.nonce)
        out += _enc_big(self.gas_price)
        out += _enc_int(self.gas_limit)
        out += _enc_int(self.shard_id, 4) + _enc_int(self.to_shard, 4)
        out += _enc_bytes(self.to if self.to is not None else b"")
        out += _enc_big(self.value)
        out += _enc_bytes(self.data)
        if self.tx_type == 1:
            # typed envelope rides BEHIND the legacy fields so type-0
            # signing bytes (and hashes) are byte-stable
            out += _enc_int(1, 1)
            out += _enc_int(len(self.access_list), 2)
            for addr, slots in self.access_list:
                out += _enc_bytes(addr)
                out += _enc_int(len(slots), 2)
                for slot in slots:
                    out += _enc_bytes(slot)
        return bytes(out)

    def signing_hash(self, chain_id: int) -> bytes:
        return keccak256(self.signing_bytes(chain_id))

    def hash(self, chain_id: int = 0) -> bytes:
        return keccak256(self.signing_bytes(chain_id) + _enc_bytes(self.sig))

    def sign(self, key: ECDSAKey, chain_id: int) -> "Transaction":
        self.sig = key.sign(self.signing_hash(chain_id))
        return self

    def sender(self, chain_id: int) -> bytes:
        """Recover the 20-byte sender address (raises on a bad sig)."""
        return pub_to_address(recover(self.signing_hash(chain_id), self.sig))

    def is_cross_shard(self) -> bool:
        return self.to_shard != self.shard_id


class Directive(IntEnum):
    """Staking directive kinds (reference: staking/types/messages.go)."""

    CREATE_VALIDATOR = 0
    EDIT_VALIDATOR = 1
    DELEGATE = 2
    UNDELEGATE = 3
    COLLECT_REWARDS = 4


@dataclass
class StakingTransaction:
    """A staking-directive transaction (reference:
    staking/types/transaction.go): same envelope as Transaction, the
    payload is the directive + its fields."""

    nonce: int
    gas_price: int
    gas_limit: int
    directive: Directive
    fields: dict  # directive-specific; bytes/int/str values
    # the shard this directive executes on, BOUND INTO THE SIGNATURE:
    # without it one signed staking tx would replay on every shard at
    # the same nonce (the reference reaches the same safety by routing
    # all staking txs to shard 0 — staking/types/transaction.go)
    shard_id: int = 0
    sig: bytes = b""

    def _enc_fields(self) -> bytes:
        out = bytearray()
        for k in sorted(self.fields):
            v = self.fields[k]
            out += _enc_bytes(k.encode())
            if isinstance(v, bytes):
                out += b"\x00" + _enc_bytes(v)
            elif isinstance(v, int):
                out += b"\x01" + _enc_big(v)
            elif isinstance(v, str):
                out += b"\x02" + _enc_bytes(v.encode())
            else:
                raise TypeError(f"unsupported staking field type {type(v)}")
        return bytes(out)

    def signing_bytes(self, chain_id: int) -> bytes:
        return (
            _enc_int(chain_id)
            + _enc_int(self.nonce)
            + _enc_big(self.gas_price)
            + _enc_int(self.gas_limit)
            + _enc_int(self.shard_id, 4)
            + _enc_int(int(self.directive), 1)
            + self._enc_fields()
        )

    def signing_hash(self, chain_id: int) -> bytes:
        return keccak256(self.signing_bytes(chain_id))

    def hash(self, chain_id: int = 0) -> bytes:
        return keccak256(self.signing_bytes(chain_id) + _enc_bytes(self.sig))

    def sign(self, key: ECDSAKey, chain_id: int) -> "StakingTransaction":
        self.sig = key.sign(self.signing_hash(chain_id))
        return self

    def sender(self, chain_id: int) -> bytes:
        return pub_to_address(recover(self.signing_hash(chain_id), self.sig))


@dataclass
class Receipt:
    """Execution receipt (reference: core/types receipts)."""

    tx_hash: bytes
    status: int  # 1 ok, 0 failed
    gas_used: int
    cumulative_gas: int
    # EVM event logs: [(address20, [topic32...], data)] — consumed by
    # eth_getLogs / filters (reference: core/types/log.go)
    logs: list = field(default_factory=list)
    contract_address: bytes = b""  # set for successful deployments

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_bytes(self.tx_hash)
        out += _enc_int(self.status, 1)
        out += _enc_int(self.gas_used) + _enc_int(self.cumulative_gas)
        out += _enc_bytes(self.contract_address)
        out += _enc_int(len(self.logs), 4)
        for addr, topics, data in self.logs:
            out += _enc_bytes(addr)
            out += _enc_int(len(topics), 2)
            for t in topics:
                out += _enc_bytes(t)
            out += _enc_bytes(data)
        return bytes(out)

    @classmethod
    def decode(cls, r: "Reader") -> "Receipt":
        tx_hash = r.bytes_()
        status = r.int_(1)
        gas_used = r.int_()
        cumulative = r.int_()
        contract = r.bytes_()
        logs = []
        for _ in range(r.checked_count(4)):
            addr = r.bytes_()
            topics = [r.bytes_() for _ in range(r.checked_count(2))]
            logs.append((addr, topics, r.bytes_()))
        return cls(tx_hash, status, gas_used, cumulative,
                   logs=logs, contract_address=contract)


@dataclass
class CXReceipt:
    """A cross-shard transfer in flight: debited on the source shard,
    credited on the destination when the proof arrives (reference:
    core/types/cx_receipt.go, node/harmony/node_cross_shard.go)."""

    tx_hash: bytes
    sender: bytes
    to: bytes
    amount: int
    from_shard: int
    to_shard: int
    block_num: int = 0

    def encode(self) -> bytes:
        return (
            _enc_bytes(self.tx_hash)
            + _enc_bytes(self.sender)
            + _enc_bytes(self.to)
            + _enc_big(self.amount)
            + _enc_int(self.from_shard, 4)
            + _enc_int(self.to_shard, 4)
            + _enc_int(self.block_num)
        )

    def hash(self) -> bytes:
        return keccak256(self.encode())


def cx_group_root(cxs: list) -> bytes:
    """Commitment over one destination shard's receipt group: keccak of
    the concatenated receipt hashes (the framework's items_root shape;
    the reference uses DeriveSha — core/types/cx_receipt.go)."""
    out = bytearray()
    for cx in cxs:
        out += cx.hash()
    return keccak256(bytes(out)) if out else bytes(32)


def receipts_root(receipts: list) -> bytes:
    """Commitment over a block's execution receipts in persisted order
    (plain then staking): keccak of the concatenated receipt-encoding
    hashes — the framework's ReceiptSha analog (reference: block header
    ReceiptHash via core/types/receipt.go DeriveSha).  Fast sync
    verifies downloaded receipt lists against the sealed header's value
    before persisting them (ADVICE r4: unverified receipts let a sync
    peer forge statuses/logs served later by eth_getTransactionReceipt)."""
    out = bytearray()
    for r in receipts:
        out += keccak256(r.encode())
    return keccak256(bytes(out)) if out else bytes(32)


def group_cx_by_shard(cxs: list) -> dict:
    """Group outgoing receipts by destination shard — THE grouping that
    feeds the consensus-critical out_cx_root commitment (proposer,
    replay, and export must all use this one)."""
    by_shard: dict = {}
    for cx in cxs:
        by_shard.setdefault(cx.to_shard, []).append(cx)
    return by_shard


def out_cx_root(groups: dict) -> bytes:
    """The header's outgoing-receipt commitment: keccak over sorted
    (LE4(to_shard) || group_root) pairs of the NON-EMPTY groups
    (reference: block/header OutgoingReceiptHash built in
    core/blockchain_impl.go CXMerkleProof; empty -> zero hash)."""
    out = bytearray()
    for sid in sorted(groups):
        if not groups[sid]:
            continue
        out += sid.to_bytes(4, "little")
        out += cx_group_root(groups[sid])
    return keccak256(bytes(out)) if out else bytes(32)


@dataclass
class CXReceiptsProof:
    """A destination shard's authenticated receipt batch (reference:
    core/types/cx_receipt.go CXReceiptsProof + CXMerkleProof): the
    receipts, the source-shard header they executed in, that header's
    commit signature + bitmap (its seal), and the sibling group roots
    proving the receipts against the header's out_cx_root."""

    receipts: list  # CXReceipts, all with one to_shard
    header_bytes: bytes  # encoded source header (rawdb.encode_header)
    commit_sig: bytes  # 96-byte aggregate seal over the source header
    commit_bitmap: bytes
    shard_ids: list = field(default_factory=list)  # sorted dest shards
    shard_hashes: list = field(default_factory=list)  # group roots

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_int(len(self.receipts), 4)
        for cx in self.receipts:
            out += _enc_bytes(cx.encode())
        out += _enc_bytes(self.header_bytes)
        out += _enc_bytes(self.commit_sig)
        out += _enc_bytes(self.commit_bitmap)
        out += _enc_int(len(self.shard_ids), 4)
        for sid, h in zip(self.shard_ids, self.shard_hashes):
            out += _enc_int(sid, 4) + _enc_bytes(h)
        return bytes(out)

    def hash(self) -> bytes:
        return keccak256(self.encode())


@dataclass
class Block:
    """Header + body.  The header's ``root`` is the post-state root and
    its ``tx_root`` commits to the body: keccak over the EXECUTION-
    ordered tx hashes plus the incoming receipts — a sealed block's
    body cannot be swapped in transit.

    ``execution_order`` is the interleaving the proposer executed
    (0 = next plain tx, 1 = next staking tx); empty means all plain
    then all staking.  Replay must follow it so a sender mixing tx
    kinds keeps a consistent nonce sequence.
    """

    header: object  # chain.header.Header
    transactions: list = field(default_factory=list)
    staking_transactions: list = field(default_factory=list)
    incoming_receipts: list = field(default_factory=list)  # CXReceiptsProofs
    execution_order: list = field(default_factory=list)  # 0/1 flags

    def hash(self) -> bytes:
        return self.header.hash()

    @property
    def block_num(self) -> int:
        return self.header.block_num

    def ordered_txs(self):
        """(tx, is_staking) in execution order."""
        order = self.execution_order or (
            [0] * len(self.transactions)
            + [1] * len(self.staking_transactions)
        )
        if order.count(0) != len(self.transactions) or order.count(1) != len(
            self.staking_transactions
        ):
            raise ValueError("execution_order does not match body")
        its = [iter(self.transactions), iter(self.staking_transactions)]
        return [(next(its[flag]), bool(flag)) for flag in order]

    @staticmethod
    def items_root(hashes: list) -> bytes:
        out = bytearray()
        for h in hashes:
            out += h
        return keccak256(bytes(out)) if out else bytes(32)

    def tx_root(self, chain_id: int = 0) -> bytes:
        return self.items_root(
            [t.hash(chain_id) for t, _ in self.ordered_txs()]
            + [p.hash() for p in self.incoming_receipts]
        )
