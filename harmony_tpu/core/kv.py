"""Key/value storage: the persistence substrate under rawdb.

The role of the reference's LevelDB layer (reference: core/rawdb over
goleveldb; one DB per shard via internal/shardchain/shardchains.go).
Two implementations behind one tiny interface:

- ``MemKV`` — dict-backed, for tests and ephemeral chains (the
  reference's rawdb.NewMemoryDatabase test pattern);
- ``FileKV`` — a log-structured store: append-only record log with an
  in-memory index, crash-safe reopen by log replay, and explicit
  ``compact()`` that rewrites live records.  Single-writer by design
  (the node owns its shard DB exclusively, as in the reference).

Record format (little-endian): [klen u32][vlen u32 | 0xFFFFFFFF =
tombstone][key][value].
"""

from __future__ import annotations

import os
import struct

_TOMB = 0xFFFFFFFF
_HDR = struct.Struct("<II")


class MemKV:
    """Dict-backed store."""

    def __init__(self):
        self._d: dict[bytes, bytes] = {}

    def get(self, key: bytes):
        return self._d.get(key)

    def put(self, key: bytes, value: bytes):
        self._d[bytes(key)] = bytes(value)

    def delete(self, key: bytes):
        self._d.pop(key, None)

    def has(self, key: bytes) -> bool:
        return key in self._d

    def items(self):
        return list(self._d.items())

    def close(self):
        pass

    def __len__(self):
        return len(self._d)


class FileKV:
    """Append-only log + in-memory index."""

    def __init__(self, path: str):
        self.path = path
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (off, vlen)
        exists = os.path.exists(path)
        self._f = open(path, "r+b" if exists else "w+b")
        if exists:
            self._replay()
        self._f.seek(0, os.SEEK_END)

    def _replay(self):
        f = self._f
        f.seek(0)
        while True:
            pos = f.tell()
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                f.truncate(pos)  # drop a torn tail record
                break
            klen, vlen = _HDR.unpack(hdr)
            key = f.read(klen)
            if len(key) < klen:
                f.truncate(pos)
                break
            if vlen == _TOMB:
                self._index.pop(key, None)
                continue
            voff = f.tell()
            val = f.read(vlen)
            if len(val) < vlen:
                f.truncate(pos)
                break
            self._index[key] = (voff, vlen)

    def get(self, key: bytes):
        loc = self._index.get(key)
        if loc is None:
            return None
        off, vlen = loc
        end = self._f.tell()
        self._f.seek(off)
        val = self._f.read(vlen)
        self._f.seek(end)
        return val

    def put(self, key: bytes, value: bytes):
        key, value = bytes(key), bytes(value)
        self._f.write(_HDR.pack(len(key), len(value)))
        self._f.write(key)
        voff = self._f.tell()
        self._f.write(value)
        self._index[key] = (voff, len(value))

    def delete(self, key: bytes):
        if key in self._index:
            key = bytes(key)
            self._f.write(_HDR.pack(len(key), _TOMB))
            self._f.write(key)
            del self._index[key]

    def has(self, key: bytes) -> bool:
        return key in self._index

    def items(self):
        return [(k, self.get(k)) for k in list(self._index)]

    def flush(self):
        self._f.flush()
        os.fsync(self._f.fileno())

    def compact(self):
        """Rewrite live records; reclaims tombstones + stale puts."""
        tmp = self.path + ".compact"
        live = self.items()
        with open(tmp, "wb") as out:
            for k, v in live:
                out.write(_HDR.pack(len(k), len(v)) + k + v)
            out.flush()
            os.fsync(out.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "r+b")
        self._index.clear()
        self._replay()
        self._f.seek(0, os.SEEK_END)

    def close(self):
        self._f.flush()
        self._f.close()

    def __len__(self):
        return len(self._index)


class ShardedCollection:
    """One DB per shard id (reference: internal/shardchain/
    shardchains.go CollectionImpl)."""

    def __init__(self, factory):
        """factory(shard_id) -> KV store."""
        self._factory = factory
        self._dbs: dict[int, object] = {}

    def shard_db(self, shard_id: int):
        db = self._dbs.get(shard_id)
        if db is None:
            db = self._factory(shard_id)
            self._dbs[shard_id] = db
        return db

    def close_all(self):
        for db in self._dbs.values():
            db.close()
        self._dbs.clear()
