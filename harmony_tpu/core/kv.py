"""Key/value storage: the persistence substrate under rawdb.

The role of the reference's LevelDB layer (reference: core/rawdb over
goleveldb; one DB per shard via internal/shardchain/shardchains.go).
Two implementations behind one tiny interface:

- ``MemKV`` — dict-backed, for tests and ephemeral chains (the
  reference's rawdb.NewMemoryDatabase test pattern);
- ``FileKV`` — a log-structured store: append-only record log with an
  in-memory index, crash-safe reopen by log replay, and explicit
  ``compact()`` that rewrites live records.  Single-writer by design
  (the node owns its shard DB exclusively, as in the reference).

Record format (little-endian): [klen u32][vlen u32 | 0xFFFFFFFF =
tombstone][key][value].

Atomic commit batches (the role of LevelDB's WriteBatch under the
reference's ``rawdb.NewBatch``): a :class:`WriteBatch` stages puts and
deletes, and ``write_batch`` appends them between two marker records

    BEGIN  = [0xFFFFFFFE klen][count vlen]   (no key/value bytes)
    COMMIT = [0xFFFFFFFD klen][count vlen]

Replay applies a batch's records to the index ONLY when its COMMIT
marker (with the matching count) is present — a crash anywhere inside
the batch makes the whole batch invisible on reopen, so rawdb's
multi-record block commits are all-or-nothing.  Real keys can never
collide with the sentinels: a klen ≥ 0xFFFFFFF0 is beyond any
plausible record and is treated as corruption by replay.

Durability knob: ``fsync`` policy ``"none"`` (OS-buffered — default,
test speed), ``"batch"`` (fsync on every batch commit — the deployment
setting: a committed block survives power loss), ``"always"`` (fsync
every write).  IO is UNBUFFERED so crash modeling is honest: every
``write()`` reaches the OS immediately and survives a process kill
(the fsync policy is what covers power loss).

Crash-point injection: the batch commit path fires the
``kv.commit`` faultinject point (key = the store's path) before every
record and marker write — ``tools/crash_sweep.py`` enumerates these
points and kills the write at each one.  A failed batch write (fault
or real IO error) self-heals by truncating back to the batch start,
so a LIVE store never leaves torn bytes ahead of its append position.
"""

from __future__ import annotations

import os
import struct
import threading

from .. import faultinject as FI

_TOMB = 0xFFFFFFFF
_BATCH_BEGIN = 0xFFFFFFFE  # klen sentinel: batch start marker
_BATCH_COMMIT = 0xFFFFFFFD  # klen sentinel: batch commit marker
_KLEN_MAX = 0xFFFFFFF0  # any real klen above this is corruption
_HDR = struct.Struct("<II")

FSYNC_POLICIES = ("none", "batch", "always")


class WriteBatch:
    """Staged puts/deletes applied atomically by ``write_batch``.

    Mirrors the db interface's write half (``put``/``delete``) so every
    rawdb accessor writes into a batch unchanged."""

    def __init__(self):
        self._ops: list[tuple[bytes, bytes | None]] = []

    def put(self, key: bytes, value: bytes):
        self._ops.append((bytes(key), bytes(value)))

    def delete(self, key: bytes):
        self._ops.append((bytes(key), None))

    @property
    def ops(self) -> list:
        return list(self._ops)

    def __len__(self):
        return len(self._ops)


def commit_batch(db, batch: WriteBatch) -> None:
    """Apply ``batch`` to ``db`` atomically where the backend supports
    it (``write_batch``), else sequentially (MemKV-shaped stores are
    process-lifetime anyway)."""
    wb = getattr(db, "write_batch", None)
    if wb is not None:
        wb(batch)
        return
    for key, value in batch.ops:
        if value is None:
            db.delete(key)
        else:
            db.put(key, value)


class MemKV:
    """Dict-backed store."""

    def __init__(self):
        self._d: dict[bytes, bytes] = {}

    def get(self, key: bytes):
        return self._d.get(key)

    def put(self, key: bytes, value: bytes):
        self._d[bytes(key)] = bytes(value)

    def delete(self, key: bytes):
        self._d.pop(key, None)

    def has(self, key: bytes) -> bool:
        return key in self._d

    def items(self):
        return list(self._d.items())

    def write_batch(self, batch: WriteBatch):
        for key, value in batch.ops:
            if value is None:
                self._d.pop(key, None)
            else:
                self._d[key] = value

    def flush(self):
        pass

    def close(self):
        pass

    def __len__(self):
        return len(self._d)


class FileKV:
    """Append-only log + in-memory index."""

    def __init__(self, path: str, fsync: str = "none"):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in "
                             f"{FSYNC_POLICIES}")
        self.path = path
        self.fsync = fsync
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (off, vlen)
        # ONE file position is shared by every reader and the writer:
        # a node is multi-threaded (consensus pump + downloader + RPC
        # + replay), so every file op serializes here — the latent
        # interleaved-seek corruption only ever seen on MemKV-free
        # (durable) topologies
        self._lock = threading.RLock()
        exists = os.path.exists(path)
        # unbuffered: every write() hits the OS immediately, so a
        # process kill loses nothing already written (crash modeling —
        # the fsync policy covers power loss, not buffering luck)
        self._f = open(path, "r+b" if exists else "w+b", buffering=0)
        if exists:
            self._replay()
        self._f.seek(0, os.SEEK_END)

    # -- open/replay --------------------------------------------------------

    def _replay(self):
        """Rebuild the index from the log.  Stops (and truncates) at
        the first torn or implausible record; a batch whose COMMIT
        marker never made it to disk is discarded wholesale."""
        f = self._f
        size = os.fstat(f.fileno()).st_size
        f.seek(0)
        batch_start = None  # file offset of an open batch's BEGIN
        batch_count = 0
        pending: list = []  # (key, voff_or_None, vlen) inside the batch
        while True:
            pos = f.tell()
            hdr = f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                break  # torn tail (or clean EOF)
            klen, vlen = _HDR.unpack(hdr)
            if klen == _BATCH_BEGIN:
                if batch_start is not None:
                    break  # nested BEGIN: corrupt
                batch_start, batch_count, pending = pos, vlen, []
                continue
            if klen == _BATCH_COMMIT:
                if batch_start is None or vlen != len(pending) or (
                    batch_count != len(pending)
                ):
                    break  # marker without its batch, or count mismatch
                for key, voff, vl in pending:
                    if voff is None:
                        self._index.pop(key, None)
                    else:
                        self._index[key] = (voff, vl)
                batch_start, pending = None, []
                continue
            if klen >= _KLEN_MAX:
                break  # implausible key length: corrupt header
            # bounds-check BEFORE reading: a corrupt middle record must
            # not mis-frame (and silently poison) everything after it
            if pos + _HDR.size + klen > size:
                break
            key = f.read(klen)
            if len(key) < klen:
                break
            if vlen == _TOMB:
                if batch_start is not None:
                    pending.append((key, None, 0))
                else:
                    self._index.pop(key, None)
                continue
            voff = f.tell()
            if voff + vlen > size:
                break  # torn / implausible value
            f.seek(vlen, os.SEEK_CUR)
            if batch_start is not None:
                pending.append((key, voff, vlen))
            else:
                self._index[key] = (voff, vlen)
        # drop everything from the failure point — and if the failure
        # is inside an open batch, from the batch's BEGIN marker: the
        # un-committed batch must be invisible to appends too
        cut = pos if batch_start is None else batch_start
        if cut < size:
            f.truncate(cut)
        f.seek(0, os.SEEK_END)

    # -- reads/writes -------------------------------------------------------

    def get(self, key: bytes):
        with self._lock:
            loc = self._index.get(key)
            if loc is None:
                return None
            off, vlen = loc
            end = self._f.tell()
            self._f.seek(off)
            val = self._f.read(vlen)
            self._f.seek(end)
            return val

    def _write_all(self, data: bytes) -> None:
        """Raw-mode (buffering=0) writes may legally be SHORT without
        raising — e.g. a multi-MB state blob on a near-full disk.  A
        silent short write would tear a record while the COMMIT marker
        and fsync still succeed, so every write loops to completion or
        raises."""
        view = memoryview(data)
        while view:
            n = self._f.write(view)
            if not n:
                raise OSError(
                    f"short write to {self.path}: 0 of {len(view)} "
                    "bytes accepted"
                )
            view = view[n:]

    def _append(self, key: bytes, value: bytes | None) -> int | None:
        """One record; returns the value offset (None for tombstones).
        Does NOT touch the index — callers commit index updates."""
        if value is None:
            self._write_all(_HDR.pack(len(key), _TOMB) + key)
            return None
        self._write_all(_HDR.pack(len(key), len(value)) + key)
        voff = self._f.tell()
        self._write_all(value)
        return voff

    def _append_healed(self, key: bytes, value: bytes | None):
        """_append with the same truncate-on-failure self-heal as
        write_batch: a failed single put must not leave torn bytes
        ahead of the append position — replay would truncate there on
        reopen and silently drop every LATER committed batch."""
        start = self._f.tell()
        try:
            return self._append(key, value)
        except BaseException:
            try:
                self._f.truncate(start)
                self._f.seek(0, os.SEEK_END)
            except OSError:
                pass  # reopen replay will discard the torn record
            raise

    def put(self, key: bytes, value: bytes):
        key, value = bytes(key), bytes(value)
        with self._lock:
            voff = self._append_healed(key, value)
            self._index[key] = (voff, len(value))
            if self.fsync == "always":
                os.fsync(self._f.fileno())

    def delete(self, key: bytes):
        with self._lock:
            if key in self._index:
                key = bytes(key)
                self._append_healed(key, None)
                del self._index[key]
                if self.fsync == "always":
                    os.fsync(self._f.fileno())

    def write_batch(self, batch: WriteBatch):
        """Append the whole batch between BEGIN/COMMIT markers; the
        index (and replay) sees all of it or none of it.  On ANY
        failure mid-write — injected crash point or real IO error —
        the log is truncated back to the batch start: a live store
        never carries torn bytes ahead of its append position."""
        ops = batch.ops
        if not ops:
            return
        self._lock.acquire()
        try:
            self._write_batch_locked(ops)
        finally:
            self._lock.release()

    def _write_batch_locked(self, ops):
        start = self._f.tell()
        try:
            FI.fire("kv.commit", key=self.path)
            self._write_all(_HDR.pack(_BATCH_BEGIN, len(ops)))
            locs: list = []
            for key, value in ops:
                FI.fire("kv.commit", key=self.path)
                locs.append(self._append(key, value))
            FI.fire("kv.commit", key=self.path)
            self._write_all(_HDR.pack(_BATCH_COMMIT, len(ops)))
        except BaseException:
            try:
                self._f.truncate(start)
                self._f.seek(0, os.SEEK_END)
            except OSError:
                pass  # reopen replay will discard the torn batch
            raise
        if self.fsync in ("batch", "always"):
            os.fsync(self._f.fileno())
        for (key, value), voff in zip(ops, locs):
            if value is None:
                self._index.pop(key, None)
            else:
                self._index[key] = (voff, len(value))

    def has(self, key: bytes) -> bool:
        with self._lock:
            return key in self._index

    def items(self):
        with self._lock:
            return [(k, self.get(k)) for k in list(self._index)]

    def flush(self):
        with self._lock:
            os.fsync(self._f.fileno())

    def compact(self):
        """Rewrite live records; reclaims tombstones + stale puts."""
        with self._lock:
            tmp = self.path + ".compact"
            live = self.items()
            with open(tmp, "wb") as out:
                for k, v in live:
                    out.write(_HDR.pack(len(k), len(v)) + k + v)
                out.flush()
                os.fsync(out.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "r+b", buffering=0)
            self._index.clear()
            self._replay()
            self._f.seek(0, os.SEEK_END)

    # -- lifecycle ----------------------------------------------------------

    def close(self):
        with self._lock:
            if self._f.closed:
                return
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __len__(self):
        with self._lock:
            return len(self._index)


class ShardedCollection:
    """One DB per shard id (reference: internal/shardchain/
    shardchains.go CollectionImpl)."""

    def __init__(self, factory):
        """factory(shard_id) -> KV store."""
        self._factory = factory
        self._dbs: dict[int, object] = {}

    def shard_db(self, shard_id: int):
        db = self._dbs.get(shard_id)
        if db is None:
            db = self._factory(shard_id)
            self._dbs[shard_id] = db
        return db

    def close_all(self):
        for db in self._dbs.values():
            db.close()
        self._dbs.clear()
