"""EpochChain: the beacon-epoch light chain.

The role of the reference's core/epochchain.go: a chain that stores
ONLY epoch-boundary beacon blocks — each must carry the next epoch's
shard state and a valid committee seal — so shard nodes can follow
beacon committee rotation (cross-shard verification, staking epochs)
without replaying the beacon chain's transactions
(epochchain.go:117-175 InsertChain: IsLastBlockInEpoch + signature
check + writeShardStateBytes + head bookkeeping).

Design differences from the full Blockchain: no state execution, no tx
pool, no receipts — headers + shard states only, keyed by EPOCH.  The
committee provider for foreign shards resolves through this chain
(closing the fail-closed gap in cli._committee_provider with real
data instead of rejection)."""

from __future__ import annotations

import threading

from ..chain.header import Header
from . import rawdb


class EpochChainError(ValueError):
    pass


class EpochChain:
    """Epoch-boundary header chain over its own KV namespace."""

    _HEAD = b"EC:head"        # -> epoch(8)
    _HEADER = b"EC:h"         # EC:h || epoch(8) -> header blob

    def __init__(self, db, genesis_committee_provider, engine=None,
                 config=None):
        """genesis_committee_provider(shard_id) -> serialized keys for
        epoch 0 (bootstraps verification of the first epoch block);
        engine: chain.engine.Engine for seal checks (None = unverified
        inserts, test-only)."""
        self.db = db
        self.engine = engine
        self.config = config
        self._genesis_committee = genesis_committee_provider
        self._lock = threading.RLock()

    # -- reads --------------------------------------------------------------

    def head_epoch(self) -> int | None:
        blob = self.db.get(self._HEAD)
        return int.from_bytes(blob, "little") if blob is not None else None

    def header_for_epoch(self, epoch: int) -> Header | None:
        blob = self.db.get(self._HEADER + epoch.to_bytes(8, "little"))
        return rawdb.decode_header(blob) if blob is not None else None

    def shard_state_for_epoch(self, epoch: int):
        return rawdb.read_shard_state(self.db, epoch)

    def committee_for(self, shard_id: int, epoch: int) -> list:
        """Serialized BLS pubkeys for (shard, epoch), or [] when the
        epoch chain has not seen that epoch (callers fail closed)."""
        state = self.shard_state_for_epoch(epoch)
        if state is not None:
            com = state.find_committee(shard_id)
            if com is not None and com.slots:
                return com.bls_pubkeys()
        if epoch == 0:
            return list(self._genesis_committee(shard_id))
        return []

    # -- inserts ------------------------------------------------------------

    def insert(self, header: Header, shard_state, sig_bytes: bytes = b"",
               bitmap: bytes = b"") -> None:
        """Insert one epoch-boundary header + the NEXT epoch's elected
        shard state, seal-verified against the header's own committee
        (epochchain.go:126-139: last-block-in-epoch gate + signature
        validation before any write)."""
        if shard_state is None:
            raise EpochChainError(
                "not an epoch block: no shard state carried"
            )
        head = self.head_epoch()
        if head is not None and header.epoch <= head:
            return  # idempotent: already followed through here
        # seal verification is the expensive step — pairing programs, a
        # device dispatch, possibly a sidecar RPC over a socket — and it
        # needs nothing this lock guards, so it runs BEFORE acquisition
        # (GL05/GL06: holding the epoch-chain lock across it stalled
        # every concurrent follower and nested the device/native locks
        # under ours).  The head re-check under the lock keeps inserts
        # idempotent when two threads verify the same epoch.
        if self.engine is not None:
            if not self.engine.verify_header_signature(
                header, sig_bytes, bitmap
            ):
                raise EpochChainError(
                    f"bad committee seal on epoch block {header.epoch}"
                )
        with self._lock:
            head = self.head_epoch()
            if head is not None and header.epoch <= head:
                return
            rawdb.write_shard_state(self.db, header.epoch + 1, shard_state)
            self.db.put(
                self._HEADER + header.epoch.to_bytes(8, "little"),
                rawdb.encode_header(header),
            )
            self.db.put(
                self._HEAD, header.epoch.to_bytes(8, "little")
            )
