"""Chain core: storage, state, execution, mempool, and the blockchain.

The framework's equivalent of the reference's core/ cluster (reference:
core/blockchain.go:47, core/rawdb, core/state, core/state_processor.go,
core/tx_pool.go — SURVEY.md §2.4), redesigned for this codebase: a
pluggable key/value store (kv), an explicit rawdb schema (rawdb),
fixed-layout signable types (types), an account-model state DB with a
deterministic root (state), a transfer+staking state processor
(state_processor), a nonce/price-ordered mempool (tx_pool), and the
Blockchain that ties them to the consensus engine (blockchain).
"""

from .blockchain import Blockchain
from .genesis import Genesis
from .kv import FileKV, MemKV
from .state import StateDB
from .tx_pool import TxPool
from .types import Block, CXReceipt, Receipt, StakingTransaction, Transaction

__all__ = [
    "Block",
    "Blockchain",
    "CXReceipt",
    "FileKV",
    "Genesis",
    "MemKV",
    "Receipt",
    "StakingTransaction",
    "StateDB",
    "Transaction",
    "TxPool",
]
