"""Mempool: nonce-ordered per sender, price-ordered across senders.

The role of the reference's core/tx_pool.go (1,732 LoC incl. staking
txs — SURVEY.md §2.4), reduced to the consensus-relevant contract:

- ``add`` validates signature, nonce window, balance cover, and gas
  floor, and replaces same-nonce txs only for a >=10% price bump
  (the reference's price-bump rule);
- ``pending`` yields executable txs: per sender a gapless nonce run
  starting at the state nonce, senders interleaved by gas price;
- ``drop_applied`` prunes txs at block commit.

Plain and staking transactions share the pool with a common queue
discipline (the reference keeps both in one pool as well).
"""

from __future__ import annotations

from dataclasses import dataclass, field

PRICE_BUMP_PCT = 10
DEFAULT_POOL_CAP = 8192


class PoolError(ValueError):
    pass


@dataclass
class _Entry:
    tx: object
    sender: bytes
    is_staking: bool


class TxPool:
    def __init__(self, chain_id: int, shard_id: int, state_view,
                 cap: int = DEFAULT_POOL_CAP):
        """state_view() -> StateDB-like with nonce()/balance()."""
        self.chain_id = chain_id
        self.shard_id = shard_id
        self._state_view = state_view
        self.cap = cap
        # sender -> {nonce -> _Entry}
        self._by_sender: dict[bytes, dict[int, _Entry]] = {}
        self._count = 0

    # -- admission ---------------------------------------------------------

    def _validate(self, tx, is_staking: bool) -> bytes:
        try:
            sender = tx.sender(self.chain_id)
        except ValueError as e:
            raise PoolError(f"bad signature: {e}") from e
        if tx.shard_id != self.shard_id:
            raise PoolError("wrong shard")
        state = self._state_view()
        if tx.nonce < state.nonce(sender):
            raise PoolError("nonce too low")
        if tx.gas_price < 1:
            raise PoolError("gas price below floor")
        if is_staking:
            # delegated/self-staked amount must be covered up front
            moved = int(tx.fields.get("amount", 0))
        else:
            moved = tx.value
        cost = tx.gas_limit * tx.gas_price + moved
        if state.balance(sender) < cost:
            raise PoolError("insufficient balance for max cost")
        return sender

    def add(self, tx, is_staking: bool = False) -> bytes:
        """Admit a tx; returns the recovered sender. Raises PoolError."""
        sender = self._validate(tx, is_staking)
        slots = self._by_sender.setdefault(sender, {})
        old = slots.get(tx.nonce)
        if old is not None:
            bump = old.tx.gas_price * (100 + PRICE_BUMP_PCT) // 100
            if tx.gas_price < max(bump, old.tx.gas_price + 1):
                raise PoolError("replacement underpriced")
            slots[tx.nonce] = _Entry(tx, sender, is_staking)
            return sender
        if self._count >= self.cap:
            raise PoolError("pool full")
        slots[tx.nonce] = _Entry(tx, sender, is_staking)
        self._count += 1
        return sender

    # -- selection ---------------------------------------------------------

    def pending(self, max_txs: int = 0):
        """Executable (tx, is_staking) pairs: gapless nonce runs per
        sender, merged by descending gas price (the proposer's read —
        reference: node/harmony/worker block assembly)."""
        state = self._state_view()
        runs = []
        for sender, slots in self._by_sender.items():
            nonce = state.nonce(sender)
            run = []
            while nonce in slots:
                run.append(slots[nonce])
                nonce += 1
            if run:
                runs.append(run)
        out = []
        cursors = [0] * len(runs)
        while True:
            best, best_i = None, -1
            for i, run in enumerate(runs):
                if cursors[i] < len(run):
                    e = run[cursors[i]]
                    if best is None or e.tx.gas_price > best.tx.gas_price:
                        best, best_i = e, i
            if best is None:
                break
            out.append((best.tx, best.is_staking))
            cursors[best_i] += 1
            if max_txs and len(out) >= max_txs:
                break
        return out

    # -- maintenance -------------------------------------------------------

    def drop_applied(self):
        """Prune txs whose nonce is now below the state nonce (called
        after a block commits)."""
        state = self._state_view()
        for sender in list(self._by_sender):
            slots = self._by_sender[sender]
            floor = state.nonce(sender)
            for nonce in [n for n in slots if n < floor]:
                del slots[nonce]
                self._count -= 1
            if not slots:
                del self._by_sender[sender]

    def __len__(self):
        return self._count
