"""Mempool: executable/queued split, price-ordered, eviction-bounded.

The role of the reference's core/tx_pool.go (SURVEY.md §2.4).  The
reference's pool discipline, re-implemented:

- **pending/queue split** (tx_pool.go's pending vs queue maps): a tx
  is *executable* when its nonce sits in the gapless run starting at
  the sender's state nonce; everything above the gap is *queued*.
  Commits promote queued txs as gaps close (``drop_applied``).
- **admission** validates signature, shard binding, nonce floor,
  balance cover at max cost, and the gas-price floor; same-nonce
  replacement needs a >=10% price bump (PriceBump).
- **bounded slots** (AccountSlots/AccountQueue/GlobalSlots/
  GlobalQueue): per-sender and global caps for both tiers; under
  global pressure the CHEAPEST queued tx is evicted for a
  better-paying newcomer (underpriced newcomers are rejected).
- **lifetime eviction**: queued txs older than ``lifetime`` seconds
  are dropped by ``evict_stale`` (the reference's 3h queue lifetime).

Plain and staking transactions share the pool with a common queue
discipline (the reference keeps both in one pool as well).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

PRICE_BUMP_PCT = 10          # reference: DefaultTxPoolConfig.PriceBump
ACCOUNT_SLOTS = 16           # executable txs per sender
ACCOUNT_QUEUE = 64           # queued txs per sender
GLOBAL_SLOTS = 4096          # executable txs total
GLOBAL_QUEUE = 1024          # queued txs total
QUEUE_LIFETIME = 3 * 3600.0  # seconds (reference: 3h)


class PoolError(ValueError):
    pass


@dataclass
class _Entry:
    tx: object
    sender: bytes
    is_staking: bool
    added_at: float
    local: bool = False  # RPC-submitted (journaled) vs gossip


class TxPool:
    def __init__(self, chain_id: int, shard_id: int, state_view,
                 cap: int | None = None, price_floor: int = 1,
                 lifetime: float = QUEUE_LIFETIME):
        """state_view() -> StateDB-like with nonce()/balance().

        ``cap``: legacy single-number bound; when given it overrides
        GLOBAL_SLOTS + GLOBAL_QUEUE combined."""
        self.chain_id = chain_id
        self.shard_id = shard_id
        self._state_view = state_view
        self.global_slots = cap if cap is not None else GLOBAL_SLOTS
        self.global_queue = 0 if cap is not None else GLOBAL_QUEUE
        self.price_floor = price_floor
        # overload knob (ISSUE 14): the resource governor raises this
        # on PRESSURED/CRITICAL tiers — the effective admission floor
        # is price_floor * _floor_mult, so cheap spam is refused in
        # O(1) while well-paying traffic still admits
        self._floor_mult = 1
        self.lifetime = lifetime
        # sender -> {nonce -> _Entry}
        self._by_sender: dict[bytes, dict[int, _Entry]] = {}
        self._count = 0
        self.evicted = 0
        # the pool is shared between the consensus pump and RPC server
        # threads (sendRawTransaction) — every public method locks
        self._lock = threading.RLock()
        self._journal = None  # open file handle once open_journal runs
        self._journal_path: str | None = None
        # admission ring for push subscribers (rpc/ws.py
        # newPendingTransactions): a tx that enters AND leaves the
        # pool between two polls must still be notified, so pushers
        # read this monotonic log instead of diffing snapshots
        self._add_seq = 0
        self._recent_adds: deque = deque(maxlen=4096)

    # -- tier classification -------------------------------------------------

    def _split_counts(self, state):
        """(executable, queued) totals under the current state."""
        execn = 0
        for sender, slots in self._by_sender.items():
            nonce = state.nonce(sender)
            while nonce in slots:
                execn += 1
                nonce += 1
        return execn, self._count - execn

    def _stats_unlocked(self):
        """(pending, queued) — the reference's Stats()."""
        return self._split_counts(self._state_view())

    def _sender_exec_count(self, state, sender) -> int:
        slots = self._by_sender.get(sender, {})
        nonce = state.nonce(sender)
        n = 0
        while nonce in slots:
            n += 1
            nonce += 1
        return n

    # -- admission ---------------------------------------------------------

    def _recover_sender(self, tx) -> bytes:
        """Signature recovery — the expensive, pure-CPU part of
        admission.  Callers hoist it OUT of the pool lock so gossip
        ingest and RPC submits don't serialize behind each other's
        ECDSA work."""
        try:
            return tx.sender(self.chain_id)
        except ValueError as e:
            raise PoolError(f"bad signature: {e}") from e

    @staticmethod
    def _verify_bls_pop(tx) -> None:
        """BLS proof-of-possession check for staking txs that register
        keys (create-validator's ``bls_key_sigs`` aligned with
        ``bls_keys``; edit-validator's ``add_bls_key_sig``): each key
        must have signed its own serialized bytes (the reference's
        staking_verifier.go VerifyBLSKeys).  Runs OUTSIDE the pool
        lock, submitted on the verification scheduler's INGRESS lane —
        a burst of staking submits coalesces into one fused device
        batch instead of each paying an inline pairing.  Raises
        PoolError on an invalid or mis-aligned proof.  Proof fields
        are OPT-IN on the wire: legacy txs without them still admit
        (the execution layer's rules are unchanged); a tx that carries
        them is held to them.  Txs without key material (delegate,
        undelegate, ...) pass untouched."""
        fields = getattr(tx, "fields", None)
        if not isinstance(fields, dict):
            return
        pairs = []  # (pubkey bytes, pop signature bytes)
        keys = fields.get("bls_keys")
        sigs = fields.get("bls_key_sigs")
        if keys and sigs is not None:
            if isinstance(keys, bytes):  # packed 48-byte keys
                keys = [keys[i:i + 48] for i in range(0, len(keys), 48)]
            if isinstance(sigs, bytes):  # packed 96-byte sigs
                sigs = [sigs[i:i + 96] for i in range(0, len(sigs), 96)]
            if len(sigs) != len(keys):
                raise PoolError("bls_key_sigs/bls_keys length mismatch")
            pairs.extend(zip(keys, sigs))
        added = fields.get("add_bls_key")
        pop = fields.get("add_bls_key_sig")
        if added is not None and pop is not None:
            pairs.append((added, pop))
        if not pairs:
            return
        from .. import bls as B
        from .. import sched

        # all proofs submitted before any is awaited: a multi-key
        # registration coalesces into one fused scheduler batch
        if not B.verify_proofs_of_possession(
            pairs, lane=sched.Lane.INGRESS
        ):
            raise PoolError("bad BLS key proof of possession")

    def _validate(self, tx, is_staking: bool,
                  sender: bytes | None = None) -> bytes:
        if sender is None:
            sender = self._recover_sender(tx)
        if tx.shard_id != self.shard_id:
            raise PoolError("wrong shard")
        state = self._state_view()
        if tx.nonce < state.nonce(sender):
            raise PoolError("nonce too low")
        if tx.gas_price < self.price_floor * self._floor_mult:
            if (self._floor_mult > 1
                    and tx.gas_price >= self.price_floor):
                # refused only by the governor's raised floor: count
                # it as a governed rejection, not ordinary underpricing
                from .. import governor as GV

                GV.count_rejection("txpool")
                raise PoolError(
                    "gas price below overload floor "
                    f"({self.price_floor * self._floor_mult})"
                )
            raise PoolError("gas price below floor")
        if is_staking:
            # delegated/self-staked amount must be covered up front
            moved = int(tx.fields.get("amount", 0))
        else:
            moved = tx.value
        cost = tx.gas_limit * tx.gas_price + moved
        if state.balance(sender) < cost:
            raise PoolError("insufficient balance for max cost")
        return sender

    def _evict_cheapest_queued(self, state, min_price: int) -> bool:
        """Drop the lowest-priced NON-executable tx if it pays less
        than ``min_price`` (the reference's pricedList eviction)."""
        worst = None  # (price, sender, nonce)
        for sender, slots in self._by_sender.items():
            exec_top = state.nonce(sender)
            while exec_top in slots:
                exec_top += 1
            for nonce, e in slots.items():
                if nonce >= exec_top and (
                    worst is None or e.tx.gas_price < worst[0]
                ):
                    worst = (e.tx.gas_price, sender, nonce)
        if worst is None or worst[0] >= min_price:
            return False
        del self._by_sender[worst[1]][worst[2]]
        if not self._by_sender[worst[1]]:
            del self._by_sender[worst[1]]
        self._count -= 1
        self.evicted += 1
        return True

    def _add_unlocked(self, tx, is_staking: bool = False,
                      sender: bytes | None = None) -> bytes:
        """Admit a tx; returns the recovered sender. Raises PoolError."""
        sender = self._validate(tx, is_staking, sender)
        state = self._state_view()
        slots = self._by_sender.setdefault(sender, {})
        old = slots.get(tx.nonce)
        if old is not None:
            bump = old.tx.gas_price * (100 + PRICE_BUMP_PCT) // 100
            if tx.gas_price < max(bump, old.tx.gas_price + 1):
                raise PoolError("replacement underpriced")
            slots[tx.nonce] = _Entry(tx, sender, is_staking,
                                     time.monotonic(),
                                     local=old.local)
            self._record_add(tx, is_staking)
            return sender
        # per-sender caps: executable run vs queued tail
        exec_n = self._sender_exec_count(state, sender)
        sender_total = len(slots)
        executable = tx.nonce <= state.nonce(sender) + exec_n
        if executable and exec_n >= ACCOUNT_SLOTS:
            raise PoolError("sender executable slots full")
        if not executable and (sender_total - exec_n) >= ACCOUNT_QUEUE:
            raise PoolError("sender queue full")
        # global pressure: try evicting a cheaper queued tx first
        limit = self.global_slots + self.global_queue
        if self._count >= limit:
            if not self._evict_cheapest_queued(state, tx.gas_price):
                raise PoolError("pool full (newcomer underpriced)")
        slots[tx.nonce] = _Entry(tx, sender, is_staking, time.monotonic())
        self._count += 1
        self._record_add(tx, is_staking)
        return sender

    # -- selection ---------------------------------------------------------

    def _pending_unlocked(self, max_txs: int = 0):
        """Executable (tx, is_staking) pairs: gapless nonce runs per
        sender, merged by descending gas price (the proposer's read —
        reference: node/harmony/worker block assembly)."""
        state = self._state_view()
        runs = []
        for sender, slots in self._by_sender.items():
            nonce = state.nonce(sender)
            run = []
            while nonce in slots:
                run.append(slots[nonce])
                nonce += 1
            if run:
                runs.append(run)
        out = []
        cursors = [0] * len(runs)
        while True:
            best, best_i = None, -1
            for i, run in enumerate(runs):
                if cursors[i] < len(run):
                    e = run[cursors[i]]
                    if best is None or e.tx.gas_price > best.tx.gas_price:
                        best, best_i = e, i
            if best is None:
                break
            out.append((best.tx, best.is_staking))
            cursors[best_i] += 1
            if max_txs and len(out) >= max_txs:
                break
        return out

    def _queued_unlocked(self):
        """Non-executable (tx, is_staking) pairs (future-nonce tail)."""
        state = self._state_view()
        out = []
        for sender, slots in self._by_sender.items():
            exec_top = state.nonce(sender)
            while exec_top in slots:
                exec_top += 1
            for nonce in sorted(slots):
                if nonce >= exec_top:
                    e = slots[nonce]
                    out.append((e.tx, e.is_staking))
        return out

    # -- maintenance -------------------------------------------------------

    def _drop_applied_unlocked(self) -> int:
        """Prune txs whose nonce is now below the state nonce (called
        after a block commits); queued txs just above the new nonce
        become executable implicitly (promotion is the tier REREAD).
        Returns how many were pruned — drop_applied's journal-rotate
        branch gates on it, and the missing return made that branch
        unreachable (the journal never rotated on the commit path)."""
        state = self._state_view()
        dropped = 0
        for sender in list(self._by_sender):
            slots = self._by_sender[sender]
            floor = state.nonce(sender)
            for nonce in [n for n in slots if n < floor]:
                del slots[nonce]
                self._count -= 1
                dropped += 1
            if not slots:
                del self._by_sender[sender]
        return dropped

    def _evict_stale_unlocked(self, now: float | None = None) -> int:
        """Drop queued txs older than the lifetime (reference: the 3h
        queue eviction loop).  Returns the eviction count — the node's
        maintenance tick logs it."""
        now = time.monotonic() if now is None else now
        state = self._state_view()
        dropped = 0
        for sender in list(self._by_sender):
            slots = self._by_sender[sender]
            exec_top = state.nonce(sender)
            while exec_top in slots:
                exec_top += 1
            for nonce in [
                n for n, e in slots.items()
                if n >= exec_top and now - e.added_at > self.lifetime
            ]:
                del slots[nonce]
                self._count -= 1
                self.evicted += 1
                dropped += 1
            if not slots:
                del self._by_sender[sender]
        return dropped

    def __len__(self):
        return self._count

    # -- governor surface ---------------------------------------------------

    def set_floor_multiplier(self, mult: int) -> None:
        """Dynamic gas-price floor (resource governor knob): the
        effective admission floor becomes price_floor * mult."""
        self._floor_mult = max(1, int(mult))

    def fill_ratio(self) -> float:
        """Pool occupancy 0..1 against the combined global bound — the
        governor's queue-pressure signal for this pool."""
        limit = self.global_slots + self.global_queue
        return (self._count / limit) if limit else 0.0


    # -- locked public surface (see _lock above) ---------------------------

    def stats(self):
        with self._lock:
            return self._stats_unlocked()

    def add(self, tx, is_staking: bool = False,
            local: bool = False, sender: bytes | None = None) -> bytes:
        # recover the signature BEFORE taking the lock: it is the
        # dominant cost of admission and needs no pool state.  Callers
        # that already recovered the sender (gossip pre-filter, load
        # harnesses pacing submission independently of the pure-Python
        # secp256k1 stand-in) pass it in and skip the repeat.
        if sender is None:
            sender = self._recover_sender(tx)
        if is_staking:
            # BLS key-registration proofs verify OUTSIDE the lock too,
            # on the scheduler's ingress lane (PR 2 hoisted the ECDSA
            # recover; these pairings were the remaining inline crypto)
            self._verify_bls_pop(tx)
        with self._lock:
            sender = self._add_unlocked(tx, is_staking, sender)
            if local:
                entry = self._by_sender[sender][tx.nonce]
                entry.local = True
                if self._journal is not None:
                    try:
                        self._journal_append(tx, is_staking)
                        self._journal.flush()
                    except OSError:
                        # the journal is best-effort persistence: a
                        # full disk must not fail an ADMITTED tx
                        pass
            return sender

    def _record_add(self, tx, is_staking: bool):
        # the tx OBJECT rides the ring; its hash is computed lazily in
        # adds_since — the pure-Python keccak was 97% of admission cost
        # (measured r06), paid per ADD for a feed only websocket
        # subscribers read.  Third slot: the hash memo the first
        # reader fills (dropping the tx ref), so N subscribers still
        # cost one keccak per tx and read entries pin no bodies.
        # Large-calldata txs hash eagerly instead: pinning up to 4096
        # big bodies after they leave the pool would dwarf the keccak
        # this path avoids (and their keccak is size-bound anyway).
        self._add_seq += 1
        if len(getattr(tx, "data", b"") or b"") > 1024:
            self._recent_adds.append(
                [self._add_seq, None, tx.hash(self.chain_id)]
            )
        else:
            self._recent_adds.append([self._add_seq, tx, None])

    @property
    def add_seq(self) -> int:
        with self._lock:
            return self._add_seq

    def adds_since(self, seq: int):
        """(latest_seq, [tx hashes admitted after ``seq``]) — the push
        feed for newPendingTransactions subscribers.  Hashing happens
        HERE (outside the lock, on the subscriber's thread), not at
        admission: the keccak per tx belongs to the reader, never to
        the hot add path.  The memo slot makes it once per TX, not
        once per subscriber (the write is a GIL-atomic idempotent
        list-item store; a racing reader at worst recomputes)."""
        with self._lock:
            latest = self._add_seq
            tail = [e for e in self._recent_adds if e[0] > seq]
        hashes = []
        for entry in tail:
            h = entry[2]
            if h is None:
                h = entry[1].hash(self.chain_id)
                entry[2] = h
                entry[1] = None  # memoized: stop pinning the body
            hashes.append(h)
        return latest, hashes

    # -- local tx journal (reference: core/tx_journal.go — locally
    # submitted txs survive a node restart; remote gossip does not) ---------

    _JOURNAL_ROTATE_BYTES = 1 << 20  # rewrite when the file outgrows this

    def open_journal(self, path: str) -> int:
        """Attach a journal file; replays any existing entries into the
        pool first (invalid/stale entries are dropped), then rewrites
        it with the survivors.  Returns how many txs were restored."""
        from . import rawdb

        restored = 0
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            blob = b""
        with self._lock:
            i = 0
            while i + 5 <= len(blob):
                kind = blob[i]
                ln = int.from_bytes(blob[i + 1:i + 5], "little")
                i += 5
                raw = blob[i:i + ln]
                i += ln
                if len(raw) < ln or kind not in (0, 1):
                    break  # torn tail (crash mid-append): discard rest
                try:
                    tx = (rawdb.decode_staking_tx if kind
                          else rawdb.decode_tx)(raw)
                    sender = self._add_unlocked(tx, bool(kind))
                    self._by_sender[sender][tx.nonce].local = True
                    restored += 1
                except (ValueError, IndexError):
                    continue  # applied/stale/corrupt entries drop out
            self._journal_path = path
            self._rotate_journal_unlocked()
        return restored

    def _journal_append(self, tx, is_staking: bool, fh=None):
        from . import rawdb

        enc = (rawdb.encode_staking_tx if is_staking
               else rawdb.encode_tx)(tx, self.chain_id)
        (fh or self._journal).write(
            bytes([1 if is_staking else 0])
            + len(enc).to_bytes(4, "little") + enc
        )

    def _rotate_journal_unlocked(self):
        """Rewrite the journal with only the LOCAL txs still in the
        pool, via tmp + atomic replace: a crash mid-rewrite must not
        lose the previous journal (the reference rotates on demand to
        bound file growth)."""
        import os

        if self._journal_path is None:
            return
        try:
            if self._journal is not None:
                self._journal.close()
            tmp = self._journal_path + ".tmp"
            with open(tmp, "wb") as fh:
                for sender_txs in self._by_sender.values():
                    for entry in sender_txs.values():
                        if entry.local:
                            self._journal_append(
                                entry.tx, entry.is_staking, fh=fh
                            )
            os.replace(tmp, self._journal_path)
            self._journal = open(self._journal_path, "ab")
        except OSError:
            self._journal = None  # best-effort: run without a journal

    def rotate_journal(self):
        with self._lock:
            self._rotate_journal_unlocked()

    def pending(self, max_txs: int = 0):
        with self._lock:
            return self._pending_unlocked(max_txs)

    def queued(self):
        with self._lock:
            return self._queued_unlocked()

    def drop_applied(self):
        with self._lock:
            n = self._drop_applied_unlocked()
            if n and self._journal is not None:
                # rotate only when the file outgrew its cap: a rewrite
                # is O(pool) disk work and this runs on the consensus
                # commit path
                try:
                    oversized = (
                        self._journal.tell() > self._JOURNAL_ROTATE_BYTES
                    )
                except (OSError, ValueError):
                    oversized = True
                if oversized:
                    self._rotate_journal_unlocked()
            return n

    def evict_stale(self, now: float | None = None):
        with self._lock:
            return self._evict_stale_unlocked(now)
