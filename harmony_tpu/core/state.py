"""Account-model state DB with a deterministic root.

The role of the reference's core/state (go-ethereum-style StateDB with
an MPT + snapshot tree, plus ValidatorWrapper storage — SURVEY.md
§2.4), redesigned: a flat account map with copy-on-write block copies
and a root that is SHA3-256 over the sorted canonical serialization of
all accounts.  The flat layout trades MPT inclusion proofs (not
consumed anywhere in the reference's consensus path) for O(1) access
and a root that is linear in the number of TOUCHED accounts:

* ``copy()`` is a shallow map copy; an account is cloned only when a
  mutating accessor reaches for it (copy-on-write), so a block that
  touches k accounts costs O(k), not O(N) — the difference between a
  64-account devnet and a 10^5-account rehearsal genesis.
* Every account caches its encoded (address || blob) fragment; the
  root/serialize paths reuse untouched fragments, so sealing a block
  re-encodes only what the block changed.
* The flat root hashes with ``hashlib.sha3_256`` (native): the
  pure-python keccak-256 kept for reference header vectors costs
  ~7 ms/KB, which turns an O(state-bytes) root into minutes at 10^5
  accounts.  The flat root is an internal commitment with no reference
  vector to match (the reference's committed root is the MPT root,
  which keeps real keccak in ``mpt_root()``).

ValidatorWrapper (reference: staking ValidatorWrapper in state) is a
first-class part of the account record here: description, delegations
(ordered), and signing counters serialize into the root so staking
state is consensus-committed exactly as in the reference.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from .. import prof
from .types import Reader, _enc_big, _enc_bytes, _enc_int


@dataclass
class Delegation:
    delegator: bytes  # 20-byte address
    amount: int
    undelegations: list = field(default_factory=list)  # (amount, epoch)
    reward: int = 0

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_bytes(self.delegator) + _enc_big(self.amount)
        out += _enc_big(self.reward)
        out += _enc_int(len(self.undelegations), 4)
        for amount, epoch in self.undelegations:
            out += _enc_big(amount) + _enc_int(epoch)
        return bytes(out)


@dataclass
class ValidatorWrapper:
    """On-chain validator record (reference: staking/types validator +
    wrapper: keys, commission, delegations, signing counters)."""

    address: bytes
    bls_keys: list = field(default_factory=list)  # 48-byte serialized
    commission_rate: int = 0  # scaled 1e18
    max_commission_rate: int = 10**18
    max_change_rate: int = 10**18
    min_self_delegation: int = 0
    max_total_delegation: int = 0
    delegations: list = field(default_factory=list)  # [Delegation]
    blocks_signed: int = 0
    blocks_to_sign: int = 0
    status: int = 0  # 0 active, 1 inactive, 2 banned
    last_epoch_in_committee: int = 0

    def total_delegation(self) -> int:
        return sum(d.amount for d in self.delegations)

    def self_delegation(self) -> int:
        for d in self.delegations:
            if d.delegator == self.address:
                return d.amount
        return 0

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_bytes(self.address)
        out += _enc_int(len(self.bls_keys), 4)
        for k in self.bls_keys:
            out += _enc_bytes(k)
        for v in (self.commission_rate, self.max_commission_rate,
                  self.max_change_rate, self.min_self_delegation,
                  self.max_total_delegation):
            out += _enc_big(v)
        out += _enc_int(len(self.delegations), 4)
        for d in self.delegations:
            out += d.encode()
        out += _enc_int(self.blocks_signed) + _enc_int(self.blocks_to_sign)
        out += _enc_int(self.status, 1)
        out += _enc_int(self.last_epoch_in_committee)
        return bytes(out)


@dataclass
class Account:
    balance: int = 0
    nonce: int = 0
    validator: ValidatorWrapper | None = None
    code: bytes = b""  # EVM bytecode (contract accounts)
    storage: dict = field(default_factory=dict)  # 32B slot -> int
    # cached (address, encoded-fragment) pair — owned by the StateDB
    # machinery below; cleared whenever a mutable accessor hands the
    # account out.  The address rides along so a fragment can never be
    # replayed under a different key.
    _frag: tuple | None = field(default=None, repr=False, compare=False)

    def encode(self) -> bytes:
        out = _enc_big(self.balance) + _enc_int(self.nonce)
        if self.validator is not None:
            out += b"\x01" + self.validator.encode()
        else:
            out += b"\x00"
        if self.code or self.storage:
            out += b"\x01" + _enc_bytes(self.code)
            live = sorted(
                (k, v) for k, v in self.storage.items() if v
            )
            out += _enc_int(len(live), 4)
            for k, v in live:
                out += _enc_bytes(k) + _enc_big(v)
        else:
            out += b"\x00"
        return out


def _clone_wrapper(v: ValidatorWrapper) -> ValidatorWrapper:
    return ValidatorWrapper(
        v.address, list(v.bls_keys), v.commission_rate,
        v.max_commission_rate, v.max_change_rate,
        v.min_self_delegation, v.max_total_delegation,
        [Delegation(d.delegator, d.amount, list(d.undelegations),
                    d.reward)
         for d in v.delegations],
        v.blocks_signed, v.blocks_to_sign, v.status,
        v.last_epoch_in_committee,
    )


def _clone_account(acct: Account) -> Account:
    v = acct.validator
    return Account(
        acct.balance, acct.nonce,
        _clone_wrapper(v) if v is not None else None,
        acct.code, dict(acct.storage),
    )


class StateDB:
    """Mutable state with snapshot/revert and a deterministic root."""

    def __init__(self, accounts: dict | None = None):
        self._accounts: dict[bytes, Account] = (
            accounts if accounts is not None else {}
        )
        # copy-on-write bookkeeping: an address is in _owned iff its
        # Account object is referenced by THIS StateDB alone and may be
        # mutated in place.  A constructor-passed map is owned outright
        # (this is its sole StateDB); copy() disowns BOTH sides.
        self._owned: set = set(self._accounts)
        self._sorted: list | None = None  # cached sorted address list
        # EVM frame journaling (go-ethereum StateDB journal shape):
        # None = off (zero overhead for non-EVM users); a list = every
        # mutation appends an undo record, revert_to() rolls back.
        self._jrnl: list | None = None

    # -- access ------------------------------------------------------------

    def _own(self, addr: bytes) -> Account:
        """Get-or-create ``addr``'s account as a MUTABLE object: clones
        a shared account before handing it out (copy-on-write) and
        drops its cached fragment, since the caller may mutate it in
        place (finalize's reward credit and the slashing paths do)."""
        acct = self._accounts.get(addr)
        if acct is None:
            acct = Account()
            self._accounts[addr] = acct
            self._owned.add(addr)
            self._sorted = None
            if self._jrnl is not None:
                self._jrnl.append(("new", addr))
        elif addr not in self._owned:
            acct = _clone_account(acct)
            self._accounts[addr] = acct
            self._owned.add(addr)
        acct._frag = None
        return acct

    def account(self, addr: bytes) -> Account:
        return self._own(addr)

    def balance(self, addr: bytes) -> int:
        a = self._accounts.get(addr)
        return a.balance if a else 0

    def nonce(self, addr: bytes) -> int:
        a = self._accounts.get(addr)
        return a.nonce if a else 0

    def add_balance(self, addr: bytes, amount: int):
        acct = self._own(addr)
        if self._jrnl is not None:
            self._jrnl.append(("bal", addr, acct.balance))
        acct.balance += amount

    def sub_balance(self, addr: bytes, amount: int):
        acct = self._own(addr)
        if acct.balance < amount:
            raise ValueError("insufficient balance")
        if self._jrnl is not None:
            self._jrnl.append(("bal", addr, acct.balance))
        acct.balance -= amount

    def set_nonce(self, addr: bytes, nonce: int):
        acct = self._own(addr)
        if self._jrnl is not None:
            self._jrnl.append(("nonce", addr, acct.nonce))
        acct.nonce = nonce

    def validator(self, addr: bytes) -> ValidatorWrapper | None:
        a = self._accounts.get(addr)
        if a is None or a.validator is None:
            return None
        # callers mutate the wrapper in place (signing counters, status,
        # delegation rewards) — hand out an owned clone, never a shared
        # object another StateDB still roots over
        return self._own(addr).validator

    # -- EVM surface (code + storage) --------------------------------------

    def code(self, addr: bytes) -> bytes:
        a = self._accounts.get(addr)
        return a.code if a else b""

    def set_code(self, addr: bytes, code: bytes):
        acct = self._own(addr)
        if self._jrnl is not None:
            self._jrnl.append(("code", addr, acct.code))
        acct.code = code

    def storage_get(self, addr: bytes, slot: bytes) -> int:
        a = self._accounts.get(addr)
        return a.storage.get(slot, 0) if a else 0

    def storage_set(self, addr: bytes, slot: bytes, value: int):
        acct = self._own(addr)
        if self._jrnl is not None:
            self._jrnl.append(("slot", addr, slot, acct.storage.get(slot, 0)))
        if value:
            acct.storage[slot] = value
        else:
            acct.storage.pop(slot, None)

    def set_validator(self, wrapper: ValidatorWrapper):
        acct = self._own(wrapper.address)
        if self._jrnl is not None:
            self._jrnl.append(("val", wrapper.address, acct.validator))
        acct.validator = wrapper

    def validator_addresses(self) -> list:
        return sorted(
            addr for addr, a in self._accounts.items() if a.validator
        )

    # -- snapshots ---------------------------------------------------------

    def copy(self) -> "StateDB":
        """O(map) shallow fork: both sides keep the same Account
        objects and BOTH lose in-place mutation rights — the first
        mutating access on either side clones just that account."""
        new = StateDB.__new__(StateDB)
        new._accounts = dict(self._accounts)
        new._owned = set()
        new._sorted = self._sorted
        new._jrnl = None
        self._owned = set()
        return new

    def absorb(self, work: "StateDB"):
        """Adopt a mutated ``copy()`` of self (the atomic-apply
        pattern: mutate a copy, absorb on success, drop on failure).
        ``work`` MUST be discarded after this call — ownership of its
        cloned accounts transfers back here."""
        self._accounts = work._accounts
        self._owned |= work._owned
        self._sorted = work._sorted

    # -- EVM frame journal -------------------------------------------------
    # Per-call-frame rollback without copying the account map: the EVM
    # takes snapshot() at frame entry and revert_to() on failure; the
    # tx driver calls end_tx() once the outermost frame settles.  Only
    # mutations made through the StateDB methods above are journaled —
    # in-place edits of a ValidatorWrapper obtained via validator() are
    # invisible to it (the staking paths use whole-state copies instead;
    # any EVM-reachable staking mutation must go through set_validator
    # with a fresh wrapper).

    def snapshot(self) -> int:
        if self._jrnl is None:
            self._jrnl = []
        return len(self._jrnl)

    def revert_to(self, mark: int):
        j = self._jrnl
        while j is not None and len(j) > mark:
            e = j.pop()
            kind, addr = e[0], e[1]
            if kind == "new":
                self._accounts.pop(addr, None)
                self._owned.discard(addr)
                self._sorted = None
                continue
            acct = self._accounts.get(addr)
            if acct is None:  # account journal entry preceded by "new"
                continue
            acct._frag = None
            if kind == "bal":
                acct.balance = e[2]
            elif kind == "nonce":
                acct.nonce = e[2]
            elif kind == "code":
                acct.code = e[2]
            elif kind == "slot":
                if e[3]:
                    acct.storage[e[2]] = e[3]
                else:
                    acct.storage.pop(e[2], None)
            elif kind == "val":
                acct.validator = e[2]

    def end_tx(self):
        """Drop the journal once a transaction's outermost frame has
        settled (its effects are final either way)."""
        self._jrnl = None

    # -- root --------------------------------------------------------------

    def _sorted_addrs(self) -> list:
        if self._sorted is None:
            self._sorted = sorted(self._accounts)
        return self._sorted

    def _fragment(self, addr: bytes, acct: Account) -> bytes | None:
        """``enc(addr) || enc(acct.encode())`` — the unit both root()
        and serialize() consume — or None for an empty account (empty
        accounts don't affect the root).  Cached per account; any
        mutable access drops the cache."""
        c = acct._frag
        if c is not None and c[0] == addr:
            return c[1]
        if acct.validator is None and not acct.code and not acct.storage:
            if acct.balance == 0 and acct.nonce == 0:
                return None  # empty accounts don't affect the root
            # inlined encode() for the dominant plain-account shape
            # (balance + nonce, no flags) — at 10^5 accounts the
            # generic path's call overhead is the first root's hot spot
            b = acct.balance
            bb = b.to_bytes((b.bit_length() + 7) // 8 or 1, "little")
            blob = (len(bb).to_bytes(4, "little") + bb
                    + acct.nonce.to_bytes(8, "little") + b"\x00\x00")
        else:
            blob = acct.encode()
        f = (len(addr).to_bytes(4, "little") + addr
             + len(blob).to_bytes(4, "little") + blob)
        acct._frag = (addr, f)
        return f

    def _live_accounts(self):
        for addr in self._sorted_addrs():
            acct = self._accounts[addr]
            if (acct.balance == 0 and acct.nonce == 0
                    and not acct.validator and not acct.code
                    and not acct.storage):
                continue  # empty accounts don't affect the root
            yield addr, acct

    def root(self) -> bytes:
        """SHA3-256 over sorted (address, account) serializations — the
        flat fast path (one pass, cached fragments, no trie
        construction; see the module docstring for why this is sha3 and
        not the pure-python keccak)."""
        with prof.stage("state.root"):
            h = hashlib.sha3_256()
            for addr in self._sorted_addrs():
                f = self._fragment(addr, self._accounts[addr])
                if f is not None:
                    h.update(f)
            return h.digest()

    def mpt_root(self) -> bytes:
        """Ethereum-SHAPED commitment over the same data: a secure MPT
        whose leaves are RLP([nonce, balance, storage_root, code_hash,
        validator_hash]) keyed by keccak(address) — per-account storage
        committed through its own trie (reference: core/state +
        go-ethereum trie; the extra validator_hash field carries the
        staking state the reference keeps in ValidatorWrapper storage).
        Execution stays flat; this root exists for reference-shaped
        interop and inclusion proofs."""
        from .trie import trie_root

        return trie_root(self._mpt_account_items())

    def _mpt_account_items(self) -> dict:
        """keccak(address) -> RLP account leaf: the exact key/value
        set mpt_root commits and account_proof proves against."""
        from ..ref.keccak import keccak256
        from .. import rlp
        from .trie import EMPTY_ROOT, secure_trie_root

        items = {}
        for addr, acct in self._live_accounts():
            if acct.storage:
                storage_root = secure_trie_root({
                    k: rlp.encode(rlp.int_to_bytes(v))
                    for k, v in acct.storage.items() if v
                })
            else:
                storage_root = EMPTY_ROOT
            items[keccak256(addr)] = rlp.encode([
                acct.nonce, acct.balance, storage_root,
                keccak256(acct.code),
                keccak256(
                    acct.validator.encode() if acct.validator else b""
                ),
            ])
        return items

    def account_proof(self, addr: bytes, slots: list | None = None):
        """eth_getProof-shaped Merkle proofs against mpt_root():
        (mpt_root, account_leaf_rlp_or_b'', account_proof_nodes,
        [(slot, value, proof_nodes)...]).  Each trie is built once and
        walked per key.  reference: the go-ethereum GetProof RPC over
        core/state."""
        from ..ref.keccak import keccak256
        from .. import rlp
        from .trie import build_proof_db, prove_from

        items = self._mpt_account_items()
        key = keccak256(addr)
        root, nodes = build_proof_db(items)
        acct_proof = prove_from(root, nodes, key)
        leaf = items.get(key, b"")
        storage_proofs = []
        acct = self._accounts.get(addr)
        if slots:
            storage_items = {
                keccak256(k): rlp.encode(rlp.int_to_bytes(v))
                for k, v in (acct.storage if acct else {}).items() if v
            }
            sroot, snodes = build_proof_db(storage_items)
            for slot in slots:
                val = acct.storage.get(slot, 0) if acct else 0
                storage_proofs.append(
                    (slot, val, prove_from(sroot, snodes, keccak256(slot)))
                )
        return root, leaf, acct_proof, storage_proofs

    # -- persistence -------------------------------------------------------

    def serialize(self) -> bytes:
        with prof.stage("state.serialize"):
            frags = []
            for addr in self._sorted_addrs():
                f = self._fragment(addr, self._accounts[addr])
                if f is not None:
                    frags.append(f)
            return _enc_int(len(frags), 4) + b"".join(frags)

    @classmethod
    def deserialize(cls, data: bytes) -> "StateDB":
        with prof.stage("state.deserialize"):
            buf = data if isinstance(data, bytes) else bytes(data)
            total = len(buf)
            n = int.from_bytes(buf[:4], "little")
            if n > total - 4:
                raise ValueError(
                    f"implausible element count {n} with "
                    f"{total - 4} bytes left"
                )
            off = 4
            accounts = {}
            for _ in range(n):
                ln = int.from_bytes(buf[off:off + 4], "little")
                a0 = off + 4
                addr = buf[a0:a0 + ln]
                off = a0 + ln
                ln = int.from_bytes(buf[off:off + 4], "little")
                b0 = off + 4
                blob = buf[b0:b0 + ln]
                off = b0 + ln
                if off > total:
                    raise ValueError("truncated state blob")
                acct = _decode_account(blob)
                # pre-seed the fragment cache with the exact wire
                # bytes: the import binding check (root vs sealed
                # header root) then hashes what arrived, with no O(N)
                # re-encode — a non-canonical encoding yields a
                # different root and is rejected by that same check
                acct._frag = (addr, buf[a0 - 4:off])
                accounts[addr] = acct
            return cls(accounts)


def _checked_count(r: Reader, width: int) -> int:
    """Bounded count for crash-damaged blobs (recovery-on-open feeds
    them straight into this decoder and must get a ValueError, never a
    billion-iteration wedge) — Reader.checked_count."""
    return r.checked_count(width)


def _decode_account(blob: bytes) -> Account:
    # fast path for the dominant plain shape — [4B LE len][balance LE]
    # [8B LE nonce][\x00 validator flag][\x00 code flag] — exact-length
    # match required, so every other (or damaged) shape falls through
    # to the checked Reader path below
    k = int.from_bytes(blob[:4], "little")
    if len(blob) == k + 14 and not blob[k + 12] and not blob[k + 13]:
        return Account(
            int.from_bytes(blob[4:4 + k], "little"),
            int.from_bytes(blob[4 + k:12 + k], "little"),
        )
    r = Reader(blob)
    balance = r.big_()
    nonce = r.int_()
    has_val = r.int_(1)
    validator = None
    if has_val:
        address = r.bytes_()
        keys = [r.bytes_() for _ in range(_checked_count(r, 4))]
        rates = [r.big_() for _ in range(5)]
        delegations = []
        for _ in range(_checked_count(r, 4)):
            delegator = r.bytes_()
            amount = r.big_()
            reward = r.big_()
            undel = [(r.big_(), r.int_())
                     for _ in range(_checked_count(r, 4))]
            delegations.append(
                Delegation(delegator, amount, undel, reward)
            )
        signed = r.int_()
        to_sign = r.int_()
        status = r.int_(1)
        last_epoch = r.int_()
        validator = ValidatorWrapper(
            address, keys, rates[0], rates[1], rates[2], rates[3],
            rates[4], delegations, signed, to_sign, status, last_epoch,
        )
    code, storage = b"", {}
    if not r.eof() and r.int_(1):
        code = r.bytes_()
        for _ in range(_checked_count(r, 4)):
            slot = r.bytes_()
            storage[slot] = r.big_()
    return Account(balance, nonce, validator, code, storage)
