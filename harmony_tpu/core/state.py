"""Account-model state DB with a deterministic root.

The role of the reference's core/state (go-ethereum-style StateDB with
an MPT + snapshot tree, plus ValidatorWrapper storage — SURVEY.md
§2.4), redesigned: a flat account map with copy-on-commit journaling
and a root that is keccak-256 over the sorted canonical serialization
of all accounts.  The flat layout trades MPT inclusion proofs (not
consumed anywhere in the reference's consensus path) for O(1) access
and a trivially parallelizable root computation.

ValidatorWrapper (reference: staking ValidatorWrapper in state) is a
first-class part of the account record here: description, delegations
(ordered), and signing counters serialize into the root so staking
state is consensus-committed exactly as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..ref.keccak import keccak256
from .types import Reader, _enc_big, _enc_bytes, _enc_int


@dataclass
class Delegation:
    delegator: bytes  # 20-byte address
    amount: int
    undelegations: list = field(default_factory=list)  # (amount, epoch)
    reward: int = 0

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_bytes(self.delegator) + _enc_big(self.amount)
        out += _enc_big(self.reward)
        out += _enc_int(len(self.undelegations), 4)
        for amount, epoch in self.undelegations:
            out += _enc_big(amount) + _enc_int(epoch)
        return bytes(out)


@dataclass
class ValidatorWrapper:
    """On-chain validator record (reference: staking/types validator +
    wrapper: keys, commission, delegations, signing counters)."""

    address: bytes
    bls_keys: list = field(default_factory=list)  # 48-byte serialized
    commission_rate: int = 0  # scaled 1e18
    max_commission_rate: int = 10**18
    max_change_rate: int = 10**18
    min_self_delegation: int = 0
    max_total_delegation: int = 0
    delegations: list = field(default_factory=list)  # [Delegation]
    blocks_signed: int = 0
    blocks_to_sign: int = 0
    status: int = 0  # 0 active, 1 inactive, 2 banned
    last_epoch_in_committee: int = 0

    def total_delegation(self) -> int:
        return sum(d.amount for d in self.delegations)

    def self_delegation(self) -> int:
        for d in self.delegations:
            if d.delegator == self.address:
                return d.amount
        return 0

    def encode(self) -> bytes:
        out = bytearray()
        out += _enc_bytes(self.address)
        out += _enc_int(len(self.bls_keys), 4)
        for k in self.bls_keys:
            out += _enc_bytes(k)
        for v in (self.commission_rate, self.max_commission_rate,
                  self.max_change_rate, self.min_self_delegation,
                  self.max_total_delegation):
            out += _enc_big(v)
        out += _enc_int(len(self.delegations), 4)
        for d in self.delegations:
            out += d.encode()
        out += _enc_int(self.blocks_signed) + _enc_int(self.blocks_to_sign)
        out += _enc_int(self.status, 1)
        out += _enc_int(self.last_epoch_in_committee)
        return bytes(out)


@dataclass
class Account:
    balance: int = 0
    nonce: int = 0
    validator: ValidatorWrapper | None = None
    code: bytes = b""  # EVM bytecode (contract accounts)
    storage: dict = field(default_factory=dict)  # 32B slot -> int

    def encode(self) -> bytes:
        out = _enc_big(self.balance) + _enc_int(self.nonce)
        if self.validator is not None:
            out += b"\x01" + self.validator.encode()
        else:
            out += b"\x00"
        if self.code or self.storage:
            out += b"\x01" + _enc_bytes(self.code)
            live = sorted(
                (k, v) for k, v in self.storage.items() if v
            )
            out += _enc_int(len(live), 4)
            for k, v in live:
                out += _enc_bytes(k) + _enc_big(v)
        else:
            out += b"\x00"
        return out


class StateDB:
    """Mutable state with snapshot/revert and a deterministic root."""

    def __init__(self, accounts: dict | None = None):
        self._accounts: dict[bytes, Account] = accounts or {}
        # EVM frame journaling (go-ethereum StateDB journal shape):
        # None = off (zero overhead for non-EVM users); a list = every
        # mutation appends an undo record, revert_to() rolls back.
        self._jrnl: list | None = None

    # -- access ------------------------------------------------------------

    def account(self, addr: bytes) -> Account:
        acct = self._accounts.get(addr)
        if acct is None:
            acct = Account()
            self._accounts[addr] = acct
            if self._jrnl is not None:
                self._jrnl.append(("new", addr))
        return acct

    def balance(self, addr: bytes) -> int:
        a = self._accounts.get(addr)
        return a.balance if a else 0

    def nonce(self, addr: bytes) -> int:
        a = self._accounts.get(addr)
        return a.nonce if a else 0

    def add_balance(self, addr: bytes, amount: int):
        acct = self.account(addr)
        if self._jrnl is not None:
            self._jrnl.append(("bal", addr, acct.balance))
        acct.balance += amount

    def sub_balance(self, addr: bytes, amount: int):
        acct = self.account(addr)
        if acct.balance < amount:
            raise ValueError("insufficient balance")
        if self._jrnl is not None:
            self._jrnl.append(("bal", addr, acct.balance))
        acct.balance -= amount

    def set_nonce(self, addr: bytes, nonce: int):
        acct = self.account(addr)
        if self._jrnl is not None:
            self._jrnl.append(("nonce", addr, acct.nonce))
        acct.nonce = nonce

    def validator(self, addr: bytes) -> ValidatorWrapper | None:
        a = self._accounts.get(addr)
        return a.validator if a else None

    # -- EVM surface (code + storage) --------------------------------------

    def code(self, addr: bytes) -> bytes:
        a = self._accounts.get(addr)
        return a.code if a else b""

    def set_code(self, addr: bytes, code: bytes):
        acct = self.account(addr)
        if self._jrnl is not None:
            self._jrnl.append(("code", addr, acct.code))
        acct.code = code

    def storage_get(self, addr: bytes, slot: bytes) -> int:
        a = self._accounts.get(addr)
        return a.storage.get(slot, 0) if a else 0

    def storage_set(self, addr: bytes, slot: bytes, value: int):
        acct = self.account(addr)
        if self._jrnl is not None:
            self._jrnl.append(("slot", addr, slot, acct.storage.get(slot, 0)))
        if value:
            acct.storage[slot] = value
        else:
            acct.storage.pop(slot, None)

    def set_validator(self, wrapper: ValidatorWrapper):
        acct = self.account(wrapper.address)
        if self._jrnl is not None:
            self._jrnl.append(("val", wrapper.address, acct.validator))
        acct.validator = wrapper

    def validator_addresses(self) -> list:
        return sorted(
            addr for addr, a in self._accounts.items() if a.validator
        )

    # -- snapshots ---------------------------------------------------------

    def copy(self) -> "StateDB":
        import copy as _copy

        return StateDB(_copy.deepcopy(self._accounts))

    # -- EVM frame journal -------------------------------------------------
    # Per-call-frame rollback without copying the account map: the EVM
    # takes snapshot() at frame entry and revert_to() on failure; the
    # tx driver calls end_tx() once the outermost frame settles.  Only
    # mutations made through the StateDB methods above are journaled —
    # in-place edits of a ValidatorWrapper obtained via validator() are
    # invisible to it (the staking paths use whole-state copies instead;
    # any EVM-reachable staking mutation must go through set_validator
    # with a fresh wrapper).

    def snapshot(self) -> int:
        if self._jrnl is None:
            self._jrnl = []
        return len(self._jrnl)

    def revert_to(self, mark: int):
        j = self._jrnl
        while j is not None and len(j) > mark:
            e = j.pop()
            kind, addr = e[0], e[1]
            if kind == "new":
                self._accounts.pop(addr, None)
                continue
            acct = self._accounts.get(addr)
            if acct is None:  # account journal entry preceded by "new"
                continue
            if kind == "bal":
                acct.balance = e[2]
            elif kind == "nonce":
                acct.nonce = e[2]
            elif kind == "code":
                acct.code = e[2]
            elif kind == "slot":
                if e[3]:
                    acct.storage[e[2]] = e[3]
                else:
                    acct.storage.pop(e[2], None)
            elif kind == "val":
                acct.validator = e[2]

    def end_tx(self):
        """Drop the journal once a transaction's outermost frame has
        settled (its effects are final either way)."""
        self._jrnl = None

    # -- root --------------------------------------------------------------

    def _live_accounts(self):
        for addr in sorted(self._accounts):
            acct = self._accounts[addr]
            if (acct.balance == 0 and acct.nonce == 0
                    and not acct.validator and not acct.code
                    and not acct.storage):
                continue  # empty accounts don't affect the root
            yield addr, acct

    def root(self) -> bytes:
        """keccak over sorted (address, account) serializations — the
        flat fast path (O(n), one pass, no trie construction)."""
        out = bytearray()
        for addr, acct in self._live_accounts():
            out += _enc_bytes(addr) + _enc_bytes(acct.encode())
        return keccak256(bytes(out))

    def mpt_root(self) -> bytes:
        """Ethereum-SHAPED commitment over the same data: a secure MPT
        whose leaves are RLP([nonce, balance, storage_root, code_hash,
        validator_hash]) keyed by keccak(address) — per-account storage
        committed through its own trie (reference: core/state +
        go-ethereum trie; the extra validator_hash field carries the
        staking state the reference keeps in ValidatorWrapper storage).
        Execution stays flat; this root exists for reference-shaped
        interop and inclusion proofs."""
        from .trie import trie_root

        return trie_root(self._mpt_account_items())

    def _mpt_account_items(self) -> dict:
        """keccak(address) -> RLP account leaf: the exact key/value
        set mpt_root commits and account_proof proves against."""
        from ..ref.keccak import keccak256
        from .. import rlp
        from .trie import EMPTY_ROOT, secure_trie_root

        items = {}
        for addr, acct in self._live_accounts():
            if acct.storage:
                storage_root = secure_trie_root({
                    k: rlp.encode(rlp.int_to_bytes(v))
                    for k, v in acct.storage.items() if v
                })
            else:
                storage_root = EMPTY_ROOT
            items[keccak256(addr)] = rlp.encode([
                acct.nonce, acct.balance, storage_root,
                keccak256(acct.code),
                keccak256(
                    acct.validator.encode() if acct.validator else b""
                ),
            ])
        return items

    def account_proof(self, addr: bytes, slots: list | None = None):
        """eth_getProof-shaped Merkle proofs against mpt_root():
        (mpt_root, account_leaf_rlp_or_b'', account_proof_nodes,
        [(slot, value, proof_nodes)...]).  Each trie is built once and
        walked per key.  reference: the go-ethereum GetProof RPC over
        core/state."""
        from ..ref.keccak import keccak256
        from .. import rlp
        from .trie import build_proof_db, prove_from

        items = self._mpt_account_items()
        key = keccak256(addr)
        root, nodes = build_proof_db(items)
        acct_proof = prove_from(root, nodes, key)
        leaf = items.get(key, b"")
        storage_proofs = []
        acct = self._accounts.get(addr)
        if slots:
            storage_items = {
                keccak256(k): rlp.encode(rlp.int_to_bytes(v))
                for k, v in (acct.storage if acct else {}).items() if v
            }
            sroot, snodes = build_proof_db(storage_items)
            for slot in slots:
                val = acct.storage.get(slot, 0) if acct else 0
                storage_proofs.append(
                    (slot, val, prove_from(sroot, snodes, keccak256(slot)))
                )
        return root, leaf, acct_proof, storage_proofs

    # -- persistence -------------------------------------------------------

    def serialize(self) -> bytes:
        out = bytearray()
        live = list(self._live_accounts())
        out += _enc_int(len(live), 4)
        for addr, acct in live:
            out += _enc_bytes(addr) + _enc_bytes(acct.encode())
        return bytes(out)

    @classmethod
    def deserialize(cls, data: bytes) -> "StateDB":
        r = Reader(data)
        n = _checked_count(r, 4)
        accounts = {}
        for _ in range(n):
            addr = r.bytes_()
            blob = r.bytes_()
            accounts[addr] = _decode_account(blob)
        return cls(accounts)


def _checked_count(r: Reader, width: int) -> int:
    """Bounded count for crash-damaged blobs (recovery-on-open feeds
    them straight into this decoder and must get a ValueError, never a
    billion-iteration wedge) — Reader.checked_count."""
    return r.checked_count(width)


def _decode_account(blob: bytes) -> Account:
    r = Reader(blob)
    balance = r.big_()
    nonce = r.int_()
    has_val = r.int_(1)
    validator = None
    if has_val:
        address = r.bytes_()
        keys = [r.bytes_() for _ in range(_checked_count(r, 4))]
        rates = [r.big_() for _ in range(5)]
        delegations = []
        for _ in range(_checked_count(r, 4)):
            delegator = r.bytes_()
            amount = r.big_()
            reward = r.big_()
            undel = [(r.big_(), r.int_())
                     for _ in range(_checked_count(r, 4))]
            delegations.append(
                Delegation(delegator, amount, undel, reward)
            )
        signed = r.int_()
        to_sign = r.int_()
        status = r.int_(1)
        last_epoch = r.int_()
        validator = ValidatorWrapper(
            address, keys, rates[0], rates[1], rates[2], rates[3],
            rates[4], delegations, signed, to_sign, status, last_epoch,
        )
    code, storage = b"", {}
    if not r.eof() and r.int_(1):
        code = r.bytes_()
        for _ in range(_checked_count(r, 4)):
            slot = r.bytes_()
            storage[slot] = r.big_()
    return Account(balance, nonce, validator, code, storage)
