"""The Blockchain: canonical chain + state + commit-sig storage.

The role of the reference's core.BlockChain (reference:
core/blockchain.go:47-360 interface, core/blockchain_impl.go:1666
InsertChain, WriteBlockWithState, ReadCommitSig/WriteCommitSig —
SURVEY.md §2.4): insert verified blocks, execute them against state,
persist everything through the rawdb schema, and expose the read
surface consensus and RPC consume.

Verification on insert mirrors the reference's sync path (SURVEY.md
§3.3): each block's commit proof arrives either in the NEXT header
(``last_commit_sig``) or as the explicitly passed proof for the tip;
signature checks route through the chain Engine (one aggregate pairing
per block, batched across an insert).
"""

from __future__ import annotations

import threading

from ..chain.header import Header
from ..log import get_logger
from ..obs.replay import stage as replay_stage
from .genesis import Genesis
from .kv import WriteBatch, commit_batch
from .state import StateDB
from .state_processor import StateProcessor
from .types import Block
from . import rawdb, types

_log = get_logger("chain")


def verify_cx_proof(proof, dest_shard: int, engine, config) -> bool:
    """Authenticate one cross-shard receipt batch (reference:
    core/block_validator.go:172-236 ValidateCXReceiptsProof):

    (1) the receipts hash to the destination's group root;
    (2) the (shard, group-root) pairs hash to the source header's
        out_cx_root;
    (3) every receipt routes to this shard and claims the source
        header's shard/number;
    (4) the source header's seal verifies against the SOURCE shard's
        committee (engine.verify_header_signature) — skipped only when
        no engine is wired (test chains without consensus).

    Fabricated receipts fail (1)/(2); receipts lifted from another
    shard's group fail (3); a forged source header fails (4).
    """
    try:
        header = rawdb.decode_header(proof.header_bytes)
    except (ValueError, IndexError):
        return False
    if not proof.receipts:
        return False
    for cx in proof.receipts:
        if cx.to_shard != dest_shard:
            return False
        if cx.from_shard != header.shard_id or cx.block_num != header.block_num:
            return False
    if dest_shard not in proof.shard_ids:
        return False
    if len(proof.shard_ids) != len(proof.shard_hashes):
        return False
    group = proof.shard_hashes[proof.shard_ids.index(dest_shard)]
    if types.cx_group_root(proof.receipts) != group:
        return False
    out = bytearray()
    for sid, h in zip(proof.shard_ids, proof.shard_hashes):
        out += sid.to_bytes(4, "little") + h
    from ..ref.keccak import keccak256

    if keccak256(bytes(out)) != header.out_cx_root:
        return False
    if engine is not None:
        if len(proof.commit_sig) != 96:
            return False
        return engine.verify_header_signature(
            header, proof.commit_sig, proof.commit_bitmap,
            config.is_staking(header.epoch),
        )
    return True


class ChainError(ValueError):
    pass


class Blockchain:
    def __init__(self, db, genesis: Genesis, engine=None,
                 blocks_per_epoch: int = 32768, finalizer=None,
                 state_retention: int | None = None,
                 require_commit_sigs: bool | None = None):
        """engine: chain.engine.Engine or None (no seal checks — tests
        and block production before wiring consensus).  finalizer:
        chain.finalize.Finalizer or None (no rewards/election — the
        pre-staking chain shape).  state_retention: keep only the last
        N block states (None = archive node, every state kept).
        require_commit_sigs: recovery-on-open additionally requires a
        stored commit proof at every candidate head (None = derived
        from ``engine is not None`` — consensus-wired nodes always
        persist the proof with the block; proof-less test chains do
        not)."""
        self.db = db
        self.state_retention = state_retention
        self.genesis = genesis
        self.config = genesis.config
        self.shard_id = genesis.shard_id
        self.engine = engine
        self.finalizer = finalizer
        self.blocks_per_epoch = blocks_per_epoch
        self.processor = StateProcessor(self.config.chain_id, self.shard_id)
        self._committee_cache: dict[int, list] = {}
        self.recovered_blocks = 0  # head rollback depth at last open
        self._require_commit_sigs = (
            engine is not None if require_commit_sigs is None
            else require_commit_sigs
        )
        # insert_chain can be reached from two threads at once: the
        # consensus pump (commit path) and the background downloader
        # (node._spin_up_sync) — serialize writers
        self._insert_lock = threading.RLock()
        head = rawdb.read_head_number(db)
        if head is None:
            self._init_genesis()
        else:
            self._head_num, self._state = self._recover_head(head)

    # -- bootstrap ---------------------------------------------------------

    def _init_genesis(self):
        block = self.genesis.build_block()
        state = self.genesis.build_state()
        batch = WriteBatch()
        rawdb.write_block(batch, block, self.config.chain_id)
        rawdb.write_state(batch, block.header.root, state.serialize())
        rawdb.write_head_number(batch, 0)
        commit_batch(self.db, batch)
        self._head_num = 0
        self._state = state

    def _block_complete(self, num: int):
        """The stored Header of block ``num`` if its block records are
        whole — header present, canonical hash matches, commit proof
        present where this chain requires one — else None.  State is
        judged separately: a pruned node legitimately has no state
        below head, and that must NOT read as a torn block."""
        header = rawdb.read_header(self.db, num)
        if header is None:
            return None
        if rawdb.read_canonical_hash(self.db, num) != header.hash():
            return None
        if self._require_commit_sigs and num > 0 and (
            rawdb.read_commit_sig(self.db, num) is None
        ):
            return None
        return header

    def _recover_head(self, head: int):
        """Reopen-time head verification (the role of the reference's
        loadLastState + its SetHead repair, core/blockchain_impl.go):
        serve ``head`` only if its block records are whole and its
        state loads + re-derives the sealed root; roll back across any
        TORN blocks (missing header/canonical/proof, corrupt state
        blob) to the newest whole one.  A whole block whose state blob
        is simply ABSENT is a pruned/snapshot-restorable store, not a
        tear: raise the classic "missing state" instead of destroying
        the block records a snapshot import needs.  With atomic commit
        batches a tear can only come from a pre-batch DB or external
        damage — but a restarted node must NEVER crash on (or silently
        serve) one."""
        for num in range(head, -1, -1):
            header = self._block_complete(num)
            if header is None:
                continue
            blob = rawdb.read_state(self.db, header.root)
            if blob is None:
                raise ChainError(
                    f"missing state for root at block {num}"
                )
            try:
                state = StateDB.deserialize(blob)
            except (ValueError, IndexError, KeyError):
                continue  # corrupt state blob: torn, keep walking
            if self.config.state_root(state, header.epoch) != header.root:
                continue
            if num < head:
                batch = WriteBatch()
                for n in range(head, num, -1):
                    rawdb.delete_canonical(self.db, n, w=batch)
                rawdb.write_head_number(batch, num)
                commit_batch(self.db, batch)
                self.recovered_blocks = head - num
                _log.warn(
                    "torn head rolled back on open", stored_head=head,
                    recovered_head=num, shard=self.shard_id,
                )
            return num, state
        raise ChainError(
            f"no consistent head at or below {head}: storage is "
            "damaged beyond rollback (genesis itself is torn)"
        )

    def _load_state_at(self, num: int) -> StateDB:
        header = rawdb.read_header(self.db, num)
        if header is None:
            raise ChainError(f"missing header {num}")
        blob = rawdb.read_state(self.db, header.root)
        if blob is None:
            raise ChainError(f"missing state for root at block {num}")
        return StateDB.deserialize(blob)

    # -- reads -------------------------------------------------------------

    @property
    def head_number(self) -> int:
        return self._head_num

    def current_header(self) -> Header:
        return rawdb.read_header(self.db, self._head_num)

    def current_block(self) -> Block:
        return rawdb.read_block(self.db, self._head_num)

    def header_by_number(self, num: int) -> Header | None:
        return rawdb.read_header(self.db, num)

    def block_by_number(self, num: int) -> Block | None:
        return rawdb.read_block(self.db, num)

    def block_by_hash(self, block_hash: bytes) -> Block | None:
        num = rawdb.read_block_number(self.db, block_hash)
        return None if num is None else rawdb.read_block(self.db, num)

    def state(self) -> StateDB:
        """The CURRENT state (a live reference; copy() to speculate)."""
        return self._state

    def state_at(self, num: int) -> StateDB:
        return self._load_state_at(num)

    def epoch_of(self, num: int) -> int:
        return num // self.blocks_per_epoch

    def is_epoch_boundary(self, num: int) -> bool:
        return num % self.blocks_per_epoch == 0 and num > 0

    def is_election_block(self, num: int) -> bool:
        """Last block of its epoch: the committee-selection point
        (reference: engine.go:412 IsCommitteeSelectionBlock — the
        block before the epoch turns)."""
        return (num + 1) % self.blocks_per_epoch == 0

    def committee_for_epoch(self, epoch: int) -> list:
        """Serialized BLS pubkeys: the elected shard state if one was
        persisted for this epoch, else the genesis committee.  Cached —
        this sits on the gossip ingress hot path; the cache entry is
        dropped when an election writes that epoch's shard state."""
        cached = self._committee_cache.get(epoch)
        if cached is not None:
            return list(cached)
        keys = list(self.genesis.committee)
        state = rawdb.read_shard_state(self.db, epoch)
        if state is not None:
            com = state.find_committee(self.shard_id)
            if com is not None and com.slots:
                keys = com.bls_pubkeys()
        self._committee_cache[epoch] = keys
        return list(keys)

    def shard_state_for_epoch(self, epoch: int):
        return rawdb.read_shard_state(self.db, epoch)

    def read_commit_sig(self, num: int) -> bytes | None:
        return rawdb.read_commit_sig(self.db, num)

    def write_commit_sig(self, num: int, sig_and_bitmap: bytes):
        rawdb.write_commit_sig(self.db, num, sig_and_bitmap)

    def outgoing_cx(self, to_shard: int, num: int) -> list:
        return rawdb.read_outgoing_cx(self.db, to_shard, num)

    # -- insertion ---------------------------------------------------------

    def _verify_structure(self, block: Block, parent: Header):
        h = block.header
        if h.block_num != parent.block_num + 1:
            raise ChainError(
                f"non-sequential block {h.block_num} on {parent.block_num}"
            )
        if h.parent_hash != parent.hash():
            raise ChainError("parent hash mismatch")
        if h.shard_id != self.shard_id:
            raise ChainError("wrong shard")
        if h.epoch != self.epoch_of(h.block_num):
            raise ChainError("wrong epoch for block number")
        if block.tx_root(self.config.chain_id) != h.tx_root:
            raise ChainError("tx root does not commit to the body")

    def post_process(self, state, block_num: int, epoch: int,
                     prev_bitmap: bytes | None):
        """Everything after tx execution that feeds the sealed state
        root: rewards + availability (per block), undelegation payouts
        + EPoS status + election (at the boundary).  Shared verbatim by
        the proposer (worker) and replay so roots agree.  Returns the
        elected shard state at election blocks (caller persists on
        insert), else None."""
        if self.finalizer is not None:
            # the bitmap being consumed is the PARENT's commit proof,
            # taken over the parent's epoch committee (matters on the
            # first block after an election)
            prev_epoch = self.epoch_of(max(block_num - 1, 0))
            self.finalizer.finalize_block(
                state, self.shard_state_for_epoch(prev_epoch),
                self.shard_id, prev_bitmap,
            )
        if self.is_epoch_boundary(block_num):
            self.processor.payout_undelegations(state, epoch)
        if self.finalizer is not None and self.is_election_block(block_num):
            self.finalizer.compute_epos_status(state, epoch)
            return self.finalizer.elect(state, epoch + 1)
        return None

    # -- slashing (reference: staking/slash/double-sign.go Verify+Apply) ----

    def verify_slash_record(self, record, block_num: int) -> None:
        """Chain-side checks layered over the pure evidence
        verification (the reference's Verify does both: the ballot
        crypto AND the chain-state lookups): the moment must be in this
        chain's past, its committee must be resolvable locally, and the
        double-sign keys must have held slots in THAT epoch.  Raises
        ChainError."""
        from ..staking.slash import SlashVerifyError, verify_record

        ev = record.evidence
        m = ev.moment
        if m.shard_id != self.shard_id:
            raise ChainError("slash record from another shard")
        if m.height >= block_num:
            raise ChainError("slash evidence from the future")
        if m.epoch > self.epoch_of(block_num):
            raise ChainError("slash evidence epoch ahead of the chain")
        if m.epoch != self.epoch_of(m.height):
            raise ChainError("slash moment epoch/height disagree")
        committee = self.committee_for_epoch(m.epoch)
        try:
            verify_record(
                record, committee,
                is_staking=self.config.is_staking(m.epoch),
            )
        except SlashVerifyError as e:
            raise ChainError(f"invalid slash record: {e}") from e

    def apply_slash_records(self, state, records: list,
                            block_num: int, observe: bool = True) -> int:
        """Verify + apply ``records`` to ``state`` — the economics the
        reference runs in Finalize (double-sign.go Apply): slash the
        offender's delegations at the double-sign rate, reward the
        reporter half the slashed amount, BAN the offender (status 2 —
        which also bars its keys from every later election and, because
        a banned offender can never be slashed again, dedups the same
        evidence across blocks).  Deterministic: runs identically on
        the proposer, the pre-vote dry run, and replay, BEFORE the
        state root is sealed/checked.  Returns total atto slashed.
        ``observe=False`` suppresses the harmony_slash_* counters and
        the log line — dry runs (proposer candidate filtering, the
        validator's pre-vote speculation) must not inflate the
        'applied' stage or the atto amounts actually moved."""
        from ..staking import slash as SL

        if not records:
            return 0
        if len(records) > SL.MAX_SLASHES_PER_BLOCK:
            raise ChainError("too many slash records in one block")
        total = 0
        seen: set = set()
        for record in records:
            fp = SL.record_fingerprint(record)
            if fp in seen:
                raise ChainError("duplicate slash record in block")
            seen.add(fp)
            self.verify_slash_record(record, block_num)
            if observe:
                SL.COUNTERS.inc("verified")
            offender = record.evidence.offender
            w = state.validator(offender)
            if w is None:
                raise ChainError("slash offender is not a validator")
            if w.status == 2:
                raise ChainError("slash offender already banned")
            app = SL.apply_slash(w.total_delegation())
            # burn from delegations in order (deterministic; the
            # reference burns self-delegation first — delegations[0]
            # is the self-delegation by construction)
            left = app.total_slashed
            for d in w.delegations:
                take = min(d.amount, left)
                d.amount -= take
                left -= take
                if left == 0:
                    break
            w.status = 2  # double-sign ban (permanent)
            if record.reporter and record.reporter != offender:
                state.add_balance(
                    record.reporter, app.total_beneficiary_reward
                )
                if observe:
                    SL.AMOUNTS.inc(
                        "reward_atto", app.total_beneficiary_reward
                    )
            total += app.total_slashed
            if observe:
                SL.COUNTERS.inc("applied")
                SL.AMOUNTS.inc("slashed_atto", app.total_slashed)
                _log.warn(
                    "slash applied", offender=offender.hex()[:12],
                    slashed=app.total_slashed, block=block_num,
                    shard=self.shard_id,
                )
        return total

    def apply_slashes(self, state, slashes_bytes: bytes,
                      block_num: int, observe: bool = True,
                      version: str = "v3") -> int:
        """Header-bytes entry point (replay + the validator's pre-vote
        dry run): bounded decode, then verify + apply.  ``version`` is
        the carrying header's version: only v3 headers HASH the
        slashes field, so slashes riding any other version are
        unsigned malleable bytes — a relay could splice a valid record
        into an honest proposal without changing its hash and split
        the committee on the derived root.  Reject them outright."""
        from ..staking.slash import decode_records

        if not slashes_bytes:
            return 0
        if version != "v3":
            raise ChainError(
                f"header version {version!r} does not hash its "
                "slashes field; carried slash bytes are unsigned"
            )
        try:
            records = decode_records(slashes_bytes)
        except (ValueError, IndexError) as e:
            raise ChainError(f"bad slash payload: {e}") from e
        return self.apply_slash_records(state, records, block_num,
                                        observe=observe)

    def _execute(self, block: Block):
        state = self._state.copy()
        epoch = block.header.epoch
        result = self.processor.process(state, block, epoch)
        groups = types.group_cx_by_shard(result.outgoing_cx)
        if types.out_cx_root(groups) != block.header.out_cx_root:
            raise ChainError("outgoing receipt root mismatch")
        if types.receipts_root(
            result.receipts + result.staking_receipts
        ) != block.header.receipt_root:
            raise ChainError("receipt root mismatch after execution")
        # included slash records re-verify against the moment's epoch
        # committee and apply BEFORE finalization — the state the
        # header seals includes their effect, so a fabricated record
        # can never survive the root check, and an invalid one rejects
        # the whole block (exactly the reference's Verify-on-inclusion)
        self.apply_slashes(state, block.header.slashes, block.block_num,
                           version=block.header.version)
        elected = self.post_process(
            state, block.block_num, epoch,
            block.header.last_commit_bitmap or None,
        )
        # the header's carried committee must BE the election this
        # replay just computed (reference: VerifyShardState) — the
        # sealed bytes are what fast-syncing nodes will trust
        carried = block.header.shard_state
        want = (rawdb.encode_shard_state(elected)
                if elected is not None else b"")
        if carried != want:
            raise ChainError(
                f"header shard state mismatch at block {block.block_num}"
            )
        if self.config.state_root(state, epoch) != block.header.root:
            raise ChainError("state root mismatch after execution")
        return state, result, elected

    def revert_to(self, num: int) -> int:
        """Roll the chain head back to block ``num`` (reference:
        cmd/harmony's revert tooling / core RevertChain): resets the
        head pointer and live state to the target block and drops the
        canonical entries above it.  Returns how many blocks were
        reverted.  State snapshots/bodies above stay in the KV store
        (log-structured; unreachable entries are harmless), the
        canonical number index is what defines the chain."""
        with self._insert_lock:
            head = self.head_number
            if num >= head:
                return 0
            target = self.header_by_number(num)
            if target is None:
                raise ChainError(f"no canonical block {num} to revert to")
            batch = WriteBatch()
            for n in range(head, num, -1):
                # un-mark cx batches the reverted block consumed —
                # re-syncing the same block must not read as a double
                # spend (the whole point of reverting is to replay)
                block = self.block_by_number(n)
                if block is not None:
                    for proof in block.incoming_receipts:
                        try:
                            src = rawdb.decode_header(proof.header_bytes)
                        except (ValueError, IndexError):
                            continue
                        rawdb.delete_cx_spent(
                            batch, src.shard_id, src.block_num
                        )
                rawdb.delete_canonical(self.db, n, w=batch)
            rawdb.write_head_number(batch, num)
            # the whole revert is ONE atomic commit: a crash mid-revert
            # must not leave the head pointing above deleted blocks
            commit_batch(self.db, batch)
            self._head_num = num
            self._state = self._load_state_at(num)
            self._committee_cache.clear()
            return head - num

    def verify_incoming_receipts(self, block: Block) -> list:
        """Reject unauthenticated / double-spent CX batches (reference:
        core/blockchain_impl.go:441-478 VerifyIncomingReceipts).  Raises
        ChainError; returns the (from_shard, block_num) keys so insert
        can mark them spent without re-decoding."""
        seen: list = []
        for proof in block.incoming_receipts:
            try:
                src = rawdb.decode_header(proof.header_bytes)
            except (ValueError, IndexError) as e:
                raise ChainError(f"bad cx proof header: {e}") from e
            key = (src.shard_id, src.block_num)
            spender = rawdb.cx_spender(self.db, *key)
            if key in seen or (
                spender is not None and spender != block.block_num
            ):
                # spent by a DIFFERENT block = double spend; spent by
                # THIS block num = an idempotent re-insert (a replay
                # sync walking over a fast-synced range)
                raise ChainError("cx receipt batch double spend")
            seen.append(key)
            if not verify_cx_proof(proof, self.shard_id, self.engine,
                                   self.config):
                raise ChainError(
                    f"invalid cx proof from shard {src.shard_id} "
                    f"block {src.block_num}"
                )
        return seen

    def _resolve_and_verify(self, blocks, commit_sigs, parent,
                            verify_seals, lane=None):
        """Shared insert front-half (replay and fast-sync paths):
        structural checks against ``parent``, commit-proof resolution
        (blocks[i+1]'s carried header proof fills a None — the replay
        pattern, sig_verify.go:37-48), and ONE batched seal
        verification across the window.  Returns (blocks, proofs).
        ``lane`` is the verification-scheduler priority lane for the
        seal batch (None = the engine's default, the sync lane).
        """
        if commit_sigs is None:
            commit_sigs = [None] * len(blocks)
        proofs = []
        for i, block in enumerate(blocks):
            self._verify_structure(block, parent)
            proof = commit_sigs[i]
            if proof is None:
                nxt = (blocks[i + 1].header if i + 1 < len(blocks) else None)
                if nxt is not None and nxt.last_commit_sig:
                    proof = nxt.last_commit_sig + nxt.last_commit_bitmap
            proofs.append(proof)
            parent = block.header

        if verify_seals:
            if self.engine is None:
                raise ChainError("no engine wired; verify_seals=True")
            items, flags = [], []
            for block, proof in zip(blocks, proofs):
                if proof is None:
                    raise ChainError(
                        f"no commit proof for block {block.block_num}"
                    )
                sig, bitmap = proof[:96], proof[96:]
                items.append((block.header, sig, bitmap))
                flags.append(self.config.is_staking(block.header.epoch))
            with replay_stage("seal_verify", blocks=len(items)):
                ok = self.engine.verify_headers_batch(
                    items, flags, lane=lane
                )
            for block, good in zip(blocks, ok):
                if not good:
                    raise ChainError(
                        f"bad commit signature on block {block.block_num}"
                    )
        return blocks, proofs

    # -- fast (state) sync --------------------------------------------------

    def insert_headers_fast(self, blocks: list,
                            commit_sigs: list | None = None,
                            verify_seals: bool = True) -> int:
        """State-LESS insert for fast sync (reference:
        api/service/stagedstreamsync — the blockhashes/bodies stages
        persist verified blocks ahead of the states stage): structural
        checks + batched seal verification + block/proof persistence,
        WITHOUT execution and without moving the head.  The head and
        state move together in :meth:`adopt_state` once the account
        range download completes.  The CX spent-set IS reconstructed —
        each downloaded block's carried incoming_receipts name exactly
        the (from_shard, num) batches its committee consumed, and the
        blocks are seal-verified — so a fast-synced node later serving
        as leader cannot re-propose an already-credited batch.
        """
        if not blocks:
            return 0
        with self._insert_lock:
            first = blocks[0].block_num
            parent = self.header_by_number(first - 1)
            if parent is None:
                raise ChainError(f"fast insert with no parent {first - 1}")
            # pre-resolve carried proofs from the FULL window so
            # segmenting below can't lose a block's proof to a
            # boundary (blocks[i+1] holds blocks[i]'s commit proof)
            if commit_sigs is None:
                commit_sigs = [None] * len(blocks)
            commit_sigs = list(commit_sigs)
            for i in range(len(blocks) - 1):
                nxt = blocks[i + 1].header
                if commit_sigs[i] is None and nxt.last_commit_sig:
                    commit_sigs[i] = (
                        nxt.last_commit_sig + nxt.last_commit_bitmap
                    )
            # committees chain forward through the SEALED headers:
            # an election block (non-empty header.shard_state, sealed
            # by the current committee) carries the next epoch's
            # committee, so verify in segments and harvest each
            # boundary before verifying the blocks it elects for.
            # This is what makes fast sync trustless — no committee
            # bytes are ever taken from a sync peer unverified
            # (reference: stagedstreamsync + epochchain.go ShardState)
            start = 0
            for i, block in enumerate(blocks):
                if not (i == len(blocks) - 1
                        or block.header.shard_state):
                    continue
                seg = blocks[start:i + 1]
                seg, proofs = self._resolve_and_verify(
                    seg, commit_sigs[start:i + 1], parent, verify_seals
                )
                for b, proof in zip(seg, proofs):
                    # one atomic batch per fast block: reopen never
                    # sees a block without its proof or spent marks
                    batch = WriteBatch()
                    rawdb.write_block(batch, b, self.config.chain_id)
                    if proof is not None:
                        rawdb.write_commit_sig(batch, b.block_num, proof)
                    for cxp in b.incoming_receipts:
                        try:
                            src = rawdb.decode_header(cxp.header_bytes)
                        except (ValueError, IndexError,
                                UnicodeDecodeError) as e:
                            raise ChainError(
                                f"bad cx proof header in fast block "
                                f"{b.block_num}: {e}"
                            ) from e
                        rawdb.write_cx_spent(
                            batch, src.shard_id, src.block_num,
                            spender=b.block_num,
                        )
                    if b.header.shard_state:
                        rawdb.write_shard_state(
                            batch, b.header.epoch + 1,
                            rawdb.decode_shard_state(b.header.shard_state),
                        )
                    commit_batch(self.db, batch)
                    if b.header.shard_state:
                        self._committee_cache.pop(
                            b.header.epoch + 1, None
                        )
                parent = block.header
                start = i + 1
            return len(blocks)

    def adopt_state(self, num: int, state: StateDB) -> None:
        """Bind a downloaded StateDB to the stored header at ``num`` and
        move the head there — completion of the fast-sync states stage.
        The binding check is the chain's own state commitment
        (config.state_root: flat keccak or the epoch-gated MPT root), so
        a peer cannot serve a forged account set: the header root was
        already sealed by the committee's verified aggregate signature.
        """
        with self._insert_lock:
            header = self.header_by_number(num)
            if header is None:
                raise ChainError(f"adopt_state: no header {num}")
            if self.config.state_root(state, header.epoch) != header.root:
                raise ChainError(
                    "adopt_state: downloaded accounts do not match the "
                    f"sealed state root of block {num}"
                )
            batch = WriteBatch()
            rawdb.write_state(batch, header.root, state.serialize())
            rawdb.write_head_number(batch, num)
            # state + head move TOGETHER: a crash between them would
            # otherwise leave a head with no state to serve
            commit_batch(self.db, batch)
            self._head_num = num
            self._state = state
            self._committee_cache.clear()

    def write_synced_receipts(self, num: int, receipts: list) -> None:
        """Persist receipts fetched by the fast-sync receipts stage for
        a block in the skipped (unexecuted) range."""
        rawdb.write_receipts(self.db, num, receipts)

    def insert_chain(self, blocks: list, commit_sigs: list | None = None,
                     verify_seals: bool = True, lane=None) -> int:
        """Insert consecutive blocks; returns how many were inserted.

        ``commit_sigs[i]`` is the [96B sig || bitmap] proof for
        blocks[i]; where None, the proof is taken from blocks[i+1]'s
        header (the replay pattern — sig_verify.go:37-48).  Seal
        verification is batched across the insert through the engine;
        ``lane`` picks the scheduler lane (the consensus commit path
        passes its CONSENSUS lane, replay/sync take the default).
        """
        if not blocks:
            return 0
        with self._insert_lock:
            return self._insert_chain_locked(
                blocks, commit_sigs, verify_seals, lane
            )

    def _insert_chain_locked(self, blocks, commit_sigs, verify_seals,
                             lane=None):
        if commit_sigs is None:
            commit_sigs = [None] * len(blocks)

        # blocks the OTHER writer already landed are skipped
        # idempotently (a sync pass and a consensus commit can race to
        # the same height); proofs stay aligned with their blocks
        pairs = [
            (b, s) for b, s in zip(blocks, commit_sigs)
            if b.block_num > self.head_number
        ]
        if not pairs:
            return 0
        blocks = [b for b, _ in pairs]
        commit_sigs = [s for _, s in pairs]

        # pre-resolve carried proofs over the FULL window (blocks[i+1]
        # holds blocks[i]'s proof) so the epoch segmentation below
        # can't lose the proof of a segment's last block
        commit_sigs = list(commit_sigs)
        for i in range(len(blocks) - 1):
            nxt = blocks[i + 1].header
            if commit_sigs[i] is None and nxt.last_commit_sig:
                commit_sigs[i] = (
                    nxt.last_commit_sig + nxt.last_commit_bitmap
                )

        # a replay window crossing an election boundary must verify in
        # SEGMENTS: the blocks after an election block (non-empty
        # header.shard_state) are sealed by the committee that election
        # seats, which this chain only learns by EXECUTING the election
        # block.  One up-front batch verified them against the stale
        # committee and rejected every honest post-boundary block (the
        # chaos sweep's election scenario found this — replay across
        # epoch 0 -> 1 failed with "bad commit signature").  Same
        # segmentation as insert_headers_fast.
        inserted = 0
        parent = self.current_header()
        start = 0
        for i, block in enumerate(blocks):
            if not (i == len(blocks) - 1 or block.header.shard_state):
                continue
            seg, seg_proofs = self._resolve_and_verify(
                blocks[start:i + 1], commit_sigs[start:i + 1],
                parent, verify_seals, lane,
            )
            inserted += self._execute_segment(seg, seg_proofs)
            parent = block.header
            start = i + 1
        return inserted

    def _execute_segment(self, blocks, proofs):
        """Execution + persistence pass over verified blocks.

        EVERY per-block write — block, state, receipts, commit proof,
        spent marks, outgoing cx, elected shard state, head pointer —
        stages into ONE WriteBatch committed atomically (the role of
        the reference's WriteBlockWithState batch over LevelDB): a
        crash at any byte of the commit leaves the previous head fully
        intact, never a block without its state or proof."""
        inserted = 0
        for block, proof in zip(blocks, proofs):
            with replay_stage("execute", block=block.block_num):
                spent_keys = self.verify_incoming_receipts(block)
                state, result, elected = self._execute(block)
            with replay_stage("kv_commit", block=block.block_num):
                batch = WriteBatch()
                for from_shard, num in spent_keys:
                    rawdb.write_cx_spent(
                        batch, from_shard, num, spender=block.block_num
                    )
                if elected is not None:
                    rawdb.write_shard_state(
                        batch, elected.epoch, elected
                    )
                rawdb.write_block(batch, block, self.config.chain_id)
                rawdb.write_state(
                    batch, block.header.root, state.serialize()
                )
                rawdb.write_receipts(
                    batch, block.block_num,
                    result.receipts + result.staking_receipts,
                )
                if proof is not None:
                    rawdb.write_commit_sig(
                        batch, block.block_num, proof
                    )
                by_shard: dict[int, list] = {}
                for cx in result.outgoing_cx:
                    by_shard.setdefault(cx.to_shard, []).append(cx)
                for to_shard, cxs in by_shard.items():
                    rawdb.write_outgoing_cx(
                        batch, to_shard, block.block_num, cxs
                    )
                rawdb.write_head_number(batch, block.block_num)
                commit_batch(self.db, batch)
            if elected is not None:
                self._committee_cache.pop(elected.epoch, None)
            if self.state_retention:
                # incremental prune AFTER the commit: the state falling
                # out of the retention window (O(1) per insert;
                # core/snapshot.py).  Losing a prune to a crash costs
                # one extra state blob, never consistency.
                from .snapshot import prune_state_at

                prune_state_at(
                    self, block.block_num - self.state_retention
                )
            self._head_num = block.block_num
            self._state = state
            inserted += 1
        return inserted
