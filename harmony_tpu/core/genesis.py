"""Genesis: the spec that deterministically produces block 0.

The role of the reference's core/genesis.go + genesis_initializer.go +
internal/genesis (hard-coded foundational accounts and BLS keys —
SURVEY.md §2.6): an account allocation, the initial committee, and the
chain config, hashed into a reproducible genesis header.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import prof
from ..chain.header import Header
from ..config.chain import ChainConfig
from .state import Account, StateDB
from .types import Block


@dataclass
class Genesis:
    config: ChainConfig
    shard_id: int
    alloc: dict = field(default_factory=dict)  # address -> balance
    committee: list = field(default_factory=list)  # 48B BLS pubkeys
    timestamp: int = 0
    extra: bytes = b"harmony-tpu-genesis"

    def build_state(self) -> StateDB:
        # bulk-seeded: the per-mutation accessor machinery (journal
        # check, copy-on-write bookkeeping) costs ~10x a direct
        # construction, which at a 10^5-account rehearsal alloc is the
        # difference between a fixture and a coffee break
        with prof.stage("genesis.build_state"):
            return StateDB({
                addr: Account(balance)
                for addr, balance in sorted(self.alloc.items())
            })

    def build_block(self) -> Block:
        state = self.build_state()
        with prof.stage("genesis.seal"):
            root = self.config.state_root(state, 0)
        header = Header(
            shard_id=self.shard_id,
            block_num=0,
            epoch=0,
            view_id=0,
            parent_hash=bytes(32),
            root=root,
            timestamp=self.timestamp,
            extra=self.extra + b"".join(self.committee),
            version=self.config.header_version(0),
        )
        return Block(header)


def mainnet_genesis(shard_id: int = 0) -> Genesis:
    """The mainnet-shaped genesis: the real epoch-gate table
    (config.chain.mainnet_config), the real era-0 committee assembled
    from the reference's foundational account tables with the
    round-robin shard distribution (reference: internal/genesis/
    foundational.go + harmony.go via shard/committee/assignment.go
    preStakingEnabledCommittee), and the herumi-wire BLS pubkeys.

    The account ALLOCATION is left empty: the reference's initial
    token distribution lives in a one-off genesis contract deploy
    (core/genesis.go GenesisSpec) that predates open-sourcing; nodes
    joining mainnet acquire balances through sync, never genesis
    replay.
    """
    from ..config.chain import mainnet_config
    from ..config.genesis_accounts import committee_slots
    from ..config.sharding import MAINNET

    inst = MAINNET.instance_for_epoch(0)
    slots = committee_slots(inst, shard_id)
    return Genesis(
        config=mainnet_config(),
        shard_id=shard_id,
        alloc={},
        committee=[bls for _, bls, _ in slots],
        extra=b"harmony-mainnet-genesis",
    )


_MAX_DEV_KEYS = 64  # real keypairs per dev genesis; the rest of the
# alloc is hash-derived (keygen is ~13 ms/key — a 10^5-account fixture
# cannot afford 10^5 of them, and only tx-senders need a private key)


def dev_genesis(n_accounts: int = 4, n_keys: int = 4,
                shard_id: int = 0,
                flat_root: bool = False) -> tuple[Genesis, list, list]:
    """A deterministic localnet genesis: funded ECDSA accounts + a BLS
    committee (the test/deploy.sh localnet role — SURVEY.md §4).
    Returns (genesis, ecdsa_keys, bls_secret_keys).

    Beyond ``_MAX_DEV_KEYS`` accounts, the extra allocation entries get
    deterministic hash-derived addresses with no private key — large
    fixtures pay for state size, not keygen.  ``flat_root=True`` gates
    the MPT root off (``mpt_root_epoch=None``) so headers commit the
    O(touched)-fast flat root: the only viable shape for a 10^5-account
    chain, where a pure-python secure-trie seal would take minutes per
    block.
    """
    from .. import bls as B
    from ..crypto_ecdsa import ECDSAKey

    ecdsa_keys = [
        ECDSAKey.from_seed(b"harmony-tpu-dev-%d" % i)
        for i in range(min(n_accounts, _MAX_DEV_KEYS))
    ]
    bls_keys = [B.PrivateKey.generate(b"harmony-tpu-dev-bls-%d" % i)
                for i in range(n_keys)]
    committee = [k.pub.bytes for k in bls_keys]
    alloc = {k.address(): 10**24 for k in ecdsa_keys}
    if n_accounts > len(ecdsa_keys):
        import hashlib

        for i in range(len(ecdsa_keys), n_accounts):
            addr = hashlib.sha3_256(
                b"harmony-tpu-dev-acct-%d" % i
            ).digest()[:20]
            alloc[addr] = 10**24
    config = ChainConfig(chain_id=2)
    if flat_root:
        config.mpt_root_epoch = None
    genesis = Genesis(
        config=config,
        shard_id=shard_id,
        alloc=alloc,
        committee=committee,
    )
    return genesis, ecdsa_keys, bls_keys
