"""EVM interpreter + precompiles (reference: core/vm — the go-ethereum
interpreter fork that is the reference's largest functional mass;
SURVEY.md §2.4).

Design: a host-side bytecode interpreter over the flat StateDB (EVM
execution is branchy, serial, and consensus-critical — per SURVEY §7.2
it stays off the accelerator; the TPU owns the crypto lattice, not the
contract ISA).  Word ops are Python ints masked to 256 bits; state
mutation is recorded in the StateDB undo journal so REVERT/failure
unwinds in O(touched entries), not O(state size) (reference:
core/vm/interpreter.go Run + StateDB journaled snapshots).

Gas: Istanbul-shaped constant table + quadratic memory expansion +
EIP-2929 warm/cold access lists (behind the ``berlin`` switch, on by
default: 2600/2100 cold account/slot, 100 warm, access lists reverted
with their frame) + exact EIP-2200 net SSTORE metering (clean/dirty/
no-op transitions against the tx-start original value, clear refunds
added and unwound, restore refunds, the 2300-stipend sentry) with the
Berlin re-pricing (reset 2900, SLOAD-like 100) when 2929 is on.
Refunds capped at gas_used // 2 (Istanbul rule, as the reference's
chain config uses pre-London gas policy).

Precompiles 0x1-0x9: ecrecover, sha256, ripemd160, identity, modexp,
bn256 add/mul/pairing (crypto_bn256.py — the from-scratch alt_bn128
bigint twin) and blake2f.  Address 252
is the Harmony staking precompile (write-capable: Delegate/Undelegate/
CollectRewards from contract code, beacon shard only — reference:
staking/precompile.go, core/vm/contracts_write.go).

Tracing: pass ``tracer=CallTracer()`` to capture the nested call tree
(debug_traceTransaction callTracer shape).
"""

from __future__ import annotations

import hashlib

from ..crypto_ecdsa import pub_to_address, recover
from ..ref.keccak import keccak256
from .. import rlp

WORD = (1 << 256) - 1
SIGN_BIT = 1 << 255
MAX_DEPTH = 1024
MAX_CODE_SIZE = 24576

CREATE_GAS = 32000
CALL_GAS = 700
CALL_VALUE_GAS = 9000
CALL_STIPEND = 2300
NEW_ACCOUNT_GAS = 25000
SSTORE_SET = 20000
SSTORE_UPDATE = 5000
SSTORE_CLEAR_REFUND = 15000
LOG_GAS, LOG_TOPIC_GAS, LOG_DATA_GAS = 375, 375, 8
SHA3_GAS, SHA3_WORD_GAS = 30, 6
COPY_WORD_GAS = 3
MEM_WORD_GAS = 3
EXP_BYTE_GAS = 50
SLOAD_GAS = 800
BALANCE_GAS = 700
EXTCODE_GAS = 700
CODE_DEPOSIT_GAS = 200


class VMError(Exception):
    """Out of gas / stack violation / invalid op — consumes all gas."""


class Revert(Exception):
    def __init__(self, data: bytes):
        self.data = data


class Log:
    __slots__ = ("address", "topics", "data")

    def __init__(self, address, topics, data):
        self.address = address
        self.topics = topics
        self.data = data


class Env:
    """Block-level context (reference: vm.BlockContext)."""

    def __init__(self, block_num=0, timestamp=0, coinbase=b"\x00" * 20,
                 gas_limit=30_000_000, chain_id=1, epoch=0,
                 block_hash_fn=None, shard_id=0):
        self.block_num = block_num
        self.timestamp = timestamp
        self.coinbase = coinbase
        self.gas_limit = gas_limit
        self.chain_id = chain_id
        self.epoch = epoch
        self.shard_id = shard_id
        self.block_hash_fn = block_hash_fn or (lambda n: bytes(32))


def _s256(v: int) -> int:
    return v - (1 << 256) if v & SIGN_BIT else v


def _u256(v: int) -> int:
    return v & WORD


def _addr_word(b: bytes) -> int:
    return int.from_bytes(b, "big")


def _word_addr(v: int) -> bytes:
    return (v & ((1 << 160) - 1)).to_bytes(20, "big")


def _mem_words(n: int) -> int:
    return (n + 31) // 32


class Memory:
    def __init__(self):
        self.data = bytearray()
        self.gas_paid = 0

    def expansion_cost(self, offset: int, size: int) -> int:
        if size == 0:
            return 0
        new_words = _mem_words(offset + size)
        cur_words = _mem_words(len(self.data))
        if new_words <= cur_words:
            return 0
        def cost(w):
            return MEM_WORD_GAS * w + w * w // 512
        return cost(new_words) - cost(cur_words)

    def extend(self, offset: int, size: int):
        if size == 0:
            return
        need = offset + size
        if need > len(self.data):
            self.data.extend(b"\x00" * (need - len(self.data)))

    def read(self, offset: int, size: int) -> bytes:
        if size == 0:
            return b""
        return bytes(self.data[offset:offset + size])

    def write(self, offset: int, blob: bytes):
        self.data[offset:offset + len(blob)] = blob


class Frame:
    """One call frame: stack, memory, pc, gas."""

    def __init__(self, code: bytes, gas: int):
        self.code = code
        self.gas = gas
        self.pc = 0
        self.stack: list[int] = []
        self.mem = Memory()
        self.returndata = b""
        self.jumpdests = _valid_jumpdests(code)

    def use_gas(self, amount: int):
        if amount > self.gas:
            raise VMError("out of gas")
        self.gas -= amount

    def push(self, v: int):
        if len(self.stack) >= 1024:
            raise VMError("stack overflow")
        self.stack.append(v & WORD)

    def pop(self) -> int:
        if not self.stack:
            raise VMError("stack underflow")
        return self.stack.pop()

    def mem_gas(self, offset: int, size: int):
        if size == 0:
            return  # zero-size ops are free no-ops at any offset
        if offset + size > 2 ** 32:
            raise VMError("memory offset too large")
        self.use_gas(self.mem.expansion_cost(offset, size))
        self.mem.extend(offset, size)


def _valid_jumpdests(code: bytes) -> set:
    dests = set()
    i = 0
    while i < len(code):
        op = code[i]
        if op == 0x5B:
            dests.add(i)
        if 0x60 <= op <= 0x7F:
            i += op - 0x5F
        i += 1
    return dests


def create_address(sender: bytes, nonce: int) -> bytes:
    return keccak256(rlp.encode([sender, nonce]))[12:]


def create2_address(sender: bytes, salt: bytes, init_code: bytes) -> bytes:
    return keccak256(
        b"\xff" + sender + salt.rjust(32, b"\x00") + keccak256(init_code)
    )[12:]


# -- precompiles -------------------------------------------------------------

def _pc_ecrecover(data: bytes, gas: int):
    cost = 3000
    if gas < cost:
        raise VMError("precompile oog")
    data = data.ljust(128, b"\x00")[:128]
    h, v = data[:32], int.from_bytes(data[32:64], "big")
    r = data[64:96]
    s = data[96:128]
    if v not in (27, 28):
        return gas - cost, b""
    try:
        pub = recover(h, r + s + bytes([v - 27]))
        return gas - cost, pub_to_address(pub).rjust(32, b"\x00")
    except (ValueError, KeyError):
        return gas - cost, b""


def _pc_sha256(data: bytes, gas: int):
    cost = 60 + 12 * _mem_words(len(data))
    if gas < cost:
        raise VMError("precompile oog")
    return gas - cost, hashlib.sha256(data).digest()


def _pc_ripemd160(data: bytes, gas: int):
    cost = 600 + 120 * _mem_words(len(data))
    if gas < cost:
        raise VMError("precompile oog")
    try:
        h = hashlib.new("ripemd160", data).digest()
    except ValueError as e:  # image without ripemd in OpenSSL
        raise VMError("ripemd160 unavailable") from e
    return gas - cost, h.rjust(32, b"\x00")


def _pc_identity(data: bytes, gas: int):
    cost = 15 + 3 * _mem_words(len(data))
    if gas < cost:
        raise VMError("precompile oog")
    return gas - cost, data


def _pc_modexp(data: bytes, gas: int):
    head = data.ljust(96, b"\x00")
    blen = int.from_bytes(head[:32], "big")
    elen = int.from_bytes(head[32:64], "big")
    mlen = int.from_bytes(head[64:96], "big")
    if blen > 1024 or elen > 1024 or mlen > 1024:
        raise VMError("modexp operand too large")
    body = data[96:].ljust(blen + elen + mlen, b"\x00")
    base = int.from_bytes(body[:blen], "big")
    exp = int.from_bytes(body[blen:blen + elen], "big")
    mod = int.from_bytes(body[blen + elen:blen + elen + mlen], "big")
    words = _mem_words(max(blen, mlen))
    cost = max(200, words * words * max(1, exp.bit_length()) // 3 // 20)
    if gas < cost:
        raise VMError("precompile oog")
    out = b"" if mlen == 0 else (
        (pow(base, exp, mod) if mod else 0).to_bytes(mlen, "big")
    )
    return gas - cost, out


def _bn_g1_from(data: bytes):
    """EIP-196 G1 decode: 64 BE bytes; (0, 0) = infinity; coordinates
    must be < p and on the curve."""
    from .. import crypto_bn256 as BN

    x = int.from_bytes(data[:32], "big")
    y = int.from_bytes(data[32:64], "big")
    if x >= BN.P or y >= BN.P:
        raise VMError("bn256 coordinate out of range")
    if x == 0 and y == 0:
        return None
    pt = (x, y)
    if not BN.g1_on_curve(pt):
        raise VMError("bn256 point not on curve")
    return pt


def _pc_bn256_add(data: bytes, gas: int):
    from .. import crypto_bn256 as BN

    if gas < 150:  # Istanbul (EIP-1108)
        raise VMError("precompile oog")
    data = data.ljust(128, b"\x00")
    out = BN.g1_add(_bn_g1_from(data[:64]), _bn_g1_from(data[64:128]))
    x, y = out if out is not None else (0, 0)
    return gas - 150, x.to_bytes(32, "big") + y.to_bytes(32, "big")


def _pc_bn256_mul(data: bytes, gas: int):
    from .. import crypto_bn256 as BN

    if gas < 6000:
        raise VMError("precompile oog")
    data = data.ljust(96, b"\x00")
    k = int.from_bytes(data[64:96], "big")
    out = BN.g1_mul(_bn_g1_from(data[:64]), k)
    x, y = out if out is not None else (0, 0)
    return gas - 6000, x.to_bytes(32, "big") + y.to_bytes(32, "big")


def _pc_bn256_pairing(data: bytes, gas: int):
    from .. import crypto_bn256 as BN

    if len(data) % 192:
        raise VMError("bn256 pairing input not a multiple of 192")
    k = len(data) // 192
    cost = 45000 + 34000 * k  # Istanbul (EIP-1108)
    if gas < cost:
        raise VMError("precompile oog")
    pairs = []
    for i in range(k):
        chunk = data[i * 192:(i + 1) * 192]
        p = _bn_g1_from(chunk[:64])
        # EIP-197 G2 encoding: x = a*i + b as (a, b), y likewise —
        # imaginary component FIRST
        xi_ = int.from_bytes(chunk[64:96], "big")
        xr = int.from_bytes(chunk[96:128], "big")
        yi = int.from_bytes(chunk[128:160], "big")
        yr = int.from_bytes(chunk[160:192], "big")
        if max(xi_, xr, yi, yr) >= BN.P:
            raise VMError("bn256 coordinate out of range")
        if xi_ == xr == yi == yr == 0:
            q = None
        else:
            q = ((xr, xi_), (yr, yi))
            if not BN.g2_in_subgroup(q):
                raise VMError("bn256 G2 point not in subgroup")
        pairs.append((p, q))
    ok = BN.pairing_check(pairs)
    return gas - cost, (1 if ok else 0).to_bytes(32, "big")


def _pc_blake2f(data: bytes, gas: int):
    import struct

    from ..crypto_bn256 import blake2f

    if len(data) != 213:
        raise VMError("blake2f input must be 213 bytes")
    rounds = int.from_bytes(data[:4], "big")
    if data[212] not in (0, 1):
        raise VMError("blake2f final flag must be 0 or 1")
    if gas < rounds:  # EIP-152: 1 gas per round
        raise VMError("precompile oog")
    h = list(struct.unpack("<8Q", data[4:68]))
    m = list(struct.unpack("<16Q", data[68:196]))
    t = list(struct.unpack("<2Q", data[196:212]))
    out = blake2f(rounds, h, m, t, data[212] == 1)
    return gas - rounds, struct.pack("<8Q", *out)


PRECOMPILES = {
    1: _pc_ecrecover,
    2: _pc_sha256,
    3: _pc_ripemd160,
    4: _pc_identity,
    5: _pc_modexp,
    # alt_bn128 + blake2f (reference: go-ethereum cgo contracts;
    # crypto_bn256.py is the from-scratch bigint twin)
    6: _pc_bn256_add,
    7: _pc_bn256_mul,
    8: _pc_bn256_pairing,
    9: _pc_blake2f,
}


# ----------------------------------------------------------------------
# Harmony staking precompile (write-capable, address 252 — reference:
# staking/precompile.go ParseStakeMsg + core/vm/contracts_write.go
# stakingPrecompile; beacon shard only)
# ----------------------------------------------------------------------

STAKING_PRECOMPILE_ADDR = (252).to_bytes(20, "big")

_SEL_DELEGATE = keccak256(b"Delegate(address,address,uint256)")[:4]
_SEL_UNDELEGATE = keccak256(b"Undelegate(address,address,uint256)")[:4]
_SEL_COLLECT = keccak256(b"CollectRewards(address)")[:4]


def _abi_addr(word: bytes) -> bytes:
    if any(word[:12]):
        raise VMError("malformed ABI address (dirty upper bytes)")
    return word[12:32]


def parse_stake_msg(caller: bytes, data: bytes):
    """Decode the three supported staking methods.  The delegator
    argument MUST equal the calling contract — a contract may only
    stake its own balance (reference: staking/precompile.go:125-131
    ValidateContractAddress)."""
    if len(data) < 4:
        raise VMError("staking precompile: short input")
    sel, body = data[:4], data[4:]
    if sel == _SEL_COLLECT:
        if len(body) != 32:
            raise VMError("staking precompile: bad CollectRewards args")
        delegator = _abi_addr(body[:32])
        if delegator != caller:
            raise VMError("delegator is not the caller")
        return ("collect", delegator, None, 0)
    if sel in (_SEL_DELEGATE, _SEL_UNDELEGATE):
        if len(body) != 96:
            raise VMError("staking precompile: bad (un)delegate args")
        delegator = _abi_addr(body[:32])
        validator = _abi_addr(body[32:64])
        amount = int.from_bytes(body[64:96], "big")
        if delegator != caller:
            raise VMError("delegator is not the caller")
        kind = "delegate" if sel == _SEL_DELEGATE else "undelegate"
        return (kind, delegator, validator, amount)
    raise VMError("staking precompile: unknown selector")


# EIP-2929 access costs (reference: core/vm adopted warm/cold gas;
# applied here behind the ``berlin`` switch)
COLD_ACCOUNT_ACCESS = 2600
COLD_SLOAD = 2100
WARM_ACCESS = 100


class CallTracer:
    """Minimal callTracer-shaped tracer: a nested dict of frames
    (reference: the debug_traceTransaction callTracer of eth/tracers,
    surfaced via rpc).  Attach via EVM(tracer=...); read ``.root``."""

    def __init__(self):
        self.root = None
        self._stack: list[dict] = []

    def enter(self, typ: str, frm: bytes, to: bytes, value: int,
              gas: int, data: bytes):
        node = {
            "type": typ, "from": frm.hex(), "to": to.hex(),
            "value": hex(value), "gas": gas, "input": data.hex(),
            "calls": [],
        }
        if self._stack:
            self._stack[-1]["calls"].append(node)
        else:
            self.root = node
        self._stack.append(node)

    def exit(self, ok: bool, gas_left: int, output: bytes):
        node = self._stack.pop()
        node["gasUsed"] = node["gas"] - gas_left
        node["output"] = output.hex()
        if not ok:
            node["error"] = "execution reverted"


OPCODE_NAMES = {
    0x00: "STOP", 0x01: "ADD", 0x02: "MUL", 0x03: "SUB", 0x04: "DIV",
    0x05: "SDIV", 0x06: "MOD", 0x07: "SMOD", 0x08: "ADDMOD",
    0x09: "MULMOD", 0x0A: "EXP", 0x0B: "SIGNEXTEND", 0x10: "LT",
    0x11: "GT", 0x12: "SLT", 0x13: "SGT", 0x14: "EQ", 0x15: "ISZERO",
    0x16: "AND", 0x17: "OR", 0x18: "XOR", 0x19: "NOT", 0x1A: "BYTE",
    0x1B: "SHL", 0x1C: "SHR", 0x1D: "SAR", 0x20: "SHA3",
    0x30: "ADDRESS", 0x31: "BALANCE", 0x32: "ORIGIN", 0x33: "CALLER",
    0x34: "CALLVALUE", 0x35: "CALLDATALOAD", 0x36: "CALLDATASIZE",
    0x37: "CALLDATACOPY", 0x38: "CODESIZE", 0x39: "CODECOPY",
    0x3A: "GASPRICE", 0x3B: "EXTCODESIZE", 0x3C: "EXTCODECOPY",
    0x3D: "RETURNDATASIZE", 0x3E: "RETURNDATACOPY", 0x3F: "EXTCODEHASH",
    0x40: "BLOCKHASH", 0x41: "COINBASE", 0x42: "TIMESTAMP",
    0x43: "NUMBER", 0x44: "DIFFICULTY", 0x45: "GASLIMIT",
    0x46: "CHAINID", 0x47: "SELFBALANCE", 0x50: "POP", 0x51: "MLOAD",
    0x52: "MSTORE", 0x53: "MSTORE8", 0x54: "SLOAD", 0x55: "SSTORE",
    0x56: "JUMP", 0x57: "JUMPI", 0x58: "PC", 0x59: "MSIZE", 0x5A: "GAS",
    0x5B: "JUMPDEST", 0xF0: "CREATE", 0xF1: "CALL", 0xF2: "CALLCODE",
    0xF3: "RETURN", 0xF4: "DELEGATECALL", 0xF5: "CREATE2",
    0xFA: "STATICCALL", 0xFD: "REVERT", 0xFE: "INVALID",
    0xFF: "SELFDESTRUCT",
    **{0x5F + n: f"PUSH{n}" for n in range(33)},
    **{0x80 + n: f"DUP{n + 1}" for n in range(16)},
    **{0x90 + n: f"SWAP{n + 1}" for n in range(16)},
    **{0xA0 + n: f"LOG{n}" for n in range(5)},
}


class StructLogTracer(CallTracer):
    """The default geth tracer's structLogs (reference: eth/tracers —
    debug_traceTransaction with no tracer option returns opcode-level
    struct logs).  Collects {pc, op, gas, depth, stack} per step, list
    capped so a gas-heavy loop can't OOM the RPC server."""

    def __init__(self, max_steps: int = 50_000, with_stack: bool = True):
        super().__init__()
        self.logs: list[dict] = []
        self.max_steps = max_steps
        self.with_stack = with_stack
        self.truncated = False

    def step(self, pc, op, gas, depth, stack, mem_size):
        if len(self.logs) >= self.max_steps:
            self.truncated = True  # surfaced by the RPC layer: a
            # capped trace must not read as a complete one
            return
        entry = {
            "pc": pc,
            "op": OPCODE_NAMES.get(op, f"opcode 0x{op:02x}"),
            "gas": gas,
            # EVM.depth is incremented before the frame runs, so the
            # top-level call already reads 1 — geth's 1-based depth
            "depth": depth,
            "memSize": mem_size,
        }
        if self.with_stack:
            entry["stack"] = [hex(v) for v in stack]
        self.logs.append(entry)


class NoopTracer(CallTracer):
    """noopTracer: accepts every hook, returns {} — the liveness probe
    tracer (reference: eth/tracers js noop tracer)."""

    @property
    def result(self):
        return {}


class OpcountTracer(CallTracer):
    """opcountTracer: total executed opcode count (reference:
    eth/tracers' opcount JS tracer, served by name)."""

    def __init__(self):
        super().__init__()
        self.count = 0

    def step(self, pc, op, gas, depth, stack, mem_size):
        self.count += 1

    @property
    def result(self):
        return self.count


class FourByteTracer(CallTracer):
    """4byteTracer: function-selector usage — {"0xselector-argsize":
    count} over every call frame carrying >= 4 bytes of input
    (reference: eth/tracers' 4byte tracer output shape)."""

    def __init__(self):
        super().__init__()
        self.ids: dict[str, int] = {}

    def enter(self, typ, frm, to, value, gas, data):
        super().enter(typ, frm, to, value, gas, data)
        if typ != "CREATE" and len(data) >= 4:
            key = f"0x{data[:4].hex()}-{len(data) - 4}"
            self.ids[key] = self.ids.get(key, 0) + 1

    @property
    def result(self):
        return self.ids


class NgramTracer(CallTracer):
    """unigram/bigram/trigramTracer: opcode n-gram histograms
    (reference: eth/tracers' unigram/bigram/trigram JS tracers — the
    profiling family served by name)."""

    def __init__(self, n: int):
        super().__init__()
        self.n = n
        self.hist: dict[str, int] = {}
        self._window: list[str] = []

    def step(self, pc, op, gas, depth, stack, mem_size):
        name = OPCODE_NAMES.get(op, f"0x{op:02x}")
        self._window.append(name)
        if len(self._window) > self.n:
            self._window.pop(0)
        if len(self._window) == self.n:
            key = "-".join(self._window)
            self.hist[key] = self.hist.get(key, 0) + 1

    @property
    def result(self):
        return self.hist


class PrestateTracer(CallTracer):
    """prestateTracer (reference: eth/tracers/native/prestate.go):
    records each touched account's balance/nonce/code and every
    storage slot AS THEY WERE before the transaction — captured on
    first touch via step inspection of state-reading opcodes."""

    def __init__(self, state):
        super().__init__()
        self._state = state
        self.accounts: dict = {}
        self._addr_stack: list[bytes] = []

    def touch(self, addr: bytes):
        """Record an account's pre-tx snapshot on first sight; public
        so the RPC layer can capture the SENDER before the replay's
        nonce bump (enter() only fires after it)."""
        self._touch(addr)

    def _touch(self, addr: bytes):
        key = "0x" + addr.hex()
        if key in self.accounts:
            return
        self.accounts[key] = {
            "balance": hex(self._state.balance(addr)),
            "nonce": self._state.nonce(addr),
            "code": "0x" + self._state.code(addr).hex(),
            "storage": {},
        }

    def _touch_slot(self, addr: bytes, slot: bytes):
        self._touch(addr)
        entry = self.accounts["0x" + addr.hex()]["storage"]
        k = "0x" + slot.hex()
        if k not in entry:
            entry[k] = hex(self._state.storage_get(addr, slot))

    def enter(self, typ, frm, to, value, gas, data):
        super().enter(typ, frm, to, value, gas, data)
        self._touch(frm)
        self._touch(to)
        self._addr_stack.append(to)

    def exit(self, ok, gas_left, output):
        super().exit(ok, gas_left, output)
        self._addr_stack.pop()

    def step(self, pc, op, gas, depth, stack, mem_size):
        if not self._addr_stack or not stack:
            return
        me = self._addr_stack[-1]
        if op in (0x54, 0x55):  # SLOAD/SSTORE: slot on top of stack
            self._touch_slot(me, (stack[-1] % 2**256).to_bytes(32, "big"))
        elif op in (0x31, 0x3B, 0x3C, 0x3F):  # BALANCE/EXTCODE*
            self._touch((stack[-1] % 2**160).to_bytes(20, "big"))
        elif op in (0xF1, 0xF2, 0xF4, 0xFA) and len(stack) >= 2:
            # CALL-family target (2nd from top): covers DELEGATECALL/
            # CALLCODE code accounts, whose frames run under the
            # CALLER's address and so never hit enter()
            self._touch((stack[-2] % 2**160).to_bytes(20, "big"))


class EVM:
    """The interpreter.  One instance per transaction."""

    def __init__(self, state, env: Env, origin: bytes, gas_price: int,
                 berlin: bool = True, tracer: CallTracer | None = None):
        self.state = state
        self.env = env
        self.origin = origin
        self.gas_price = gas_price
        self.logs: list[Log] = []
        self.refund = 0
        self.depth = 0
        self.berlin = berlin
        self.tracer = tracer
        self.stake_msgs: list = []  # applied staking-precompile ops
        # EIP-2200 "original" (tx-start) storage values, captured on
        # first SSTORE touch; tx-scoped, so never reverted with frames
        self._tx_original: dict = {}
        # EIP-2929 access lists: origin + precompiles warm at tx start
        self.warm_addrs: set = {origin} | {
            a.to_bytes(20, "big") for a in PRECOMPILES
        } | {STAKING_PRECOMPILE_ADDR}
        self.warm_slots: set = set()

    # -- EIP-2929 access accounting ----------------------------------------

    def _addr_access_gas(self, addr: bytes) -> int:
        if addr in self.warm_addrs:
            return WARM_ACCESS
        self.warm_addrs.add(addr)
        return COLD_ACCOUNT_ACCESS

    def _slot_access_gas(self, addr: bytes, slot: bytes) -> int:
        key = (addr, slot)
        if key in self.warm_slots:
            return WARM_ACCESS
        self.warm_slots.add(key)
        return COLD_SLOAD

    # -- entry points ------------------------------------------------------

    def call(self, caller: bytes, to: bytes, value: int, data: bytes,
             gas: int, static: bool = False):
        """Message call; returns (ok, gas_left, output)."""
        if self.depth >= MAX_DEPTH:
            return False, gas, b""
        if to == STAKING_PRECOMPILE_ADDR:
            if static:
                return False, 0, b""  # write-capable: no static calls
            snap = self._snapshot()
            if self.tracer:
                self.tracer.enter("CALL", caller, to, value, gas, data)
            # ordinary CALL value semantics apply (the transfer lands
            # on the precompile address and unwinds with the frame)
            if value:
                if self.state.balance(caller) < value:
                    if self.tracer:
                        self.tracer.exit(False, gas, b"")
                    return False, gas, b""
                self.state.sub_balance(caller, value)
                self.state.add_balance(to, value)
            try:
                gas_left, out = self._run_staking_precompile(
                    caller, data, gas
                )
                if self.tracer:
                    self.tracer.exit(True, gas_left, out)
                return True, gas_left, out
            except VMError:
                self._restore(snap)
                if self.tracer:
                    self.tracer.exit(False, 0, b"")
                return False, 0, b""
        fn = PRECOMPILES.get(_addr_word(to))
        if fn is not None:
            snap = self._snapshot()
            if value and not static:
                if self.state.balance(caller) < value:
                    return False, gas, b""
                self.state.sub_balance(caller, value)
                self.state.add_balance(to, value)
            try:
                gas_left, out = fn(data, gas)
                return True, gas_left, out
            except VMError:
                # a failed call has NO state effect — unwind the value
                # transfer too
                self._restore(snap)
                return False, 0, b""
        snap = self._snapshot()
        if self.tracer:
            self.tracer.enter(
                "STATICCALL" if static else "CALL",
                caller, to, value, gas, data,
            )
        if value and not static:
            if self.state.balance(caller) < value:
                if self.tracer:
                    self.tracer.exit(False, gas, b"")
                return False, gas, b""
            self.state.sub_balance(caller, value)
            self.state.add_balance(to, value)
        code = self.state.code(to)
        if not code:
            if self.tracer:
                self.tracer.exit(True, gas, b"")
            return True, gas, b""
        self.depth += 1
        try:
            out, gas_left = self._run(
                code, caller, to, value, data, gas, static
            )
            if self.tracer:
                self.tracer.exit(True, gas_left, out)
            return True, gas_left, out
        except Revert as r:
            self._restore(snap)
            if self.tracer:
                self.tracer.exit(False, r.gas_left, r.data)
            return False, r.gas_left, r.data
        except VMError:
            self._restore(snap)
            if self.tracer:
                self.tracer.exit(False, 0, b"")
            return False, 0, b""
        finally:
            self.depth -= 1

    def create(self, caller: bytes, value: int, init_code: bytes,
               gas: int, salt: bytes | None = None):
        """Contract creation; returns (ok, gas_left, address)."""
        if self.depth >= MAX_DEPTH:
            return False, gas, b""
        if self.state.balance(caller) < value:
            return False, gas, b""
        nonce = self.state.nonce(caller)
        self.state.set_nonce(caller, nonce + 1)
        addr = (
            create2_address(caller, salt, init_code) if salt is not None
            else create_address(caller, nonce)
        )
        if self.state.code(addr) or self.state.nonce(addr):
            return False, 0, b""  # address collision
        snap = self._snapshot()
        if self.tracer:
            self.tracer.enter(
                "CREATE2" if salt is not None else "CREATE",
                caller, addr, value, gas, init_code,
            )
        self.state.sub_balance(caller, value)
        self.state.add_balance(addr, value)
        self.state.set_nonce(addr, 1)
        self.depth += 1
        try:
            code, gas_left = self._run(
                init_code, caller, addr, value, b"", gas, False
            )
            if len(code) > MAX_CODE_SIZE:
                raise VMError("code size limit")
            deposit = CODE_DEPOSIT_GAS * len(code)
            if gas_left < deposit:
                raise VMError("code deposit oog")
            self.state.set_code(addr, code)
            if self.tracer:
                self.tracer.exit(True, gas_left - deposit, code)
            return True, gas_left - deposit, addr
        except Revert as r:
            self._restore(snap)
            if self.tracer:
                self.tracer.exit(False, r.gas_left, r.data)
            return False, r.gas_left, b""
        except VMError:
            self._restore(snap)
            if self.tracer:
                self.tracer.exit(False, 0, b"")
            return False, 0, b""
        finally:
            self.depth -= 1

    # -- staking precompile (write-capable, beacon shard only) -------------

    def _run_staking_precompile(self, caller: bytes, data: bytes,
                                gas: int):
        """Delegate/Undelegate/CollectRewards from contract code
        (reference: core/vm/contracts_write.go RunWriteCapable).  All
        mutations go through journaled StateDB methods — wrappers are
        deep-copied and written back via set_validator so an outer
        REVERT unwinds the staking op too."""
        import copy as _copy

        if self.env.shard_id != 0:
            raise VMError("staking not supported on this shard")
        kind, delegator, validator, amount = parse_stake_msg(caller, data)
        # intrinsic-shaped charge (reference meters IntrinsicGas of the
        # RLP-encoded msg): base tx gas + Istanbul calldata pricing
        cost = 21000 + sum(16 if b else 4 for b in data)
        if gas < cost:
            raise VMError("staking precompile oog")
        gas -= cost
        st = self.state
        if kind == "delegate":
            w = st.validator(validator)
            if w is None:
                raise VMError("no such validator")
            if amount <= 0 or st.balance(delegator) < amount:
                raise VMError("bad delegation amount")
            w = _copy.deepcopy(w)
            if w.max_total_delegation and (
                w.total_delegation() + amount > w.max_total_delegation
            ):
                raise VMError("exceeds max total delegation")
            st.sub_balance(delegator, amount)
            for d in w.delegations:
                if d.delegator == delegator:
                    d.amount += amount
                    break
            else:
                from .state import Delegation

                w.delegations.append(Delegation(delegator, amount))
            st.set_validator(w)
            self.stake_msgs.append((kind, delegator, validator, amount))
        elif kind == "undelegate":
            w = st.validator(validator)
            if w is None:
                raise VMError("no such validator")
            if amount <= 0:
                raise VMError("bad undelegation amount")
            w = _copy.deepcopy(w)
            for d in w.delegations:
                if d.delegator == delegator:
                    if d.amount < amount:
                        raise VMError("undelegate exceeds delegation")
                    d.amount -= amount
                    d.undelegations.append((amount, self.env.epoch))
                    break
            else:
                raise VMError("no delegation to undelegate")
            st.set_validator(w)
            self.stake_msgs.append((kind, delegator, validator, amount))
        else:  # collect
            total = 0
            for addr in st.validator_addresses():
                w = st.validator(addr)
                if not any(
                    d.delegator == delegator and d.reward
                    for d in w.delegations
                ):
                    continue
                w = _copy.deepcopy(w)
                for d in w.delegations:
                    if d.delegator == delegator and d.reward:
                        total += d.reward
                        d.reward = 0
                st.set_validator(w)
            if total == 0:
                raise VMError("no rewards to collect")
            st.add_balance(delegator, total)
            self.stake_msgs.append((kind, delegator, None, total))
        return gas, b""

    # -- state snapshots ---------------------------------------------------

    def _snapshot(self):
        # warm sets are COPIED: EIP-2929 rolls access lists back when a
        # frame reverts
        return (self.state.snapshot(), len(self.logs), self.refund,
                set(self.warm_addrs), set(self.warm_slots))

    def _restore(self, snap):
        mark, n_logs, refund, warm_a, warm_s = snap
        self.state.revert_to(mark)
        del self.logs[n_logs:]
        self.refund = refund
        self.warm_addrs = warm_a
        self.warm_slots = warm_s

    # -- the dispatch loop -------------------------------------------------

    def _run(self, code: bytes, caller: bytes, address: bytes,
             value: int, calldata: bytes, gas: int, static: bool):
        f = Frame(code, gas)
        st, mem = f.stack, f.mem
        # opcode-level tracing is opt-in per tracer (structLog): the
        # attribute probe is hoisted out of the loop — the common
        # CallTracer path must not pay per-opcode overhead
        step = getattr(self.tracer, "step", None)
        while f.pc < len(code):
            op = code[f.pc]
            if step is not None:
                step(f.pc, op, f.gas, self.depth, f.stack,
                     len(f.mem.data))
            f.pc += 1
            # PUSH0..PUSH32
            if 0x5F <= op <= 0x7F:
                n = op - 0x5F
                f.use_gas(2 if n == 0 else 3)
                f.push(int.from_bytes(code[f.pc:f.pc + n], "big"))
                f.pc += n
            elif 0x80 <= op <= 0x8F:  # DUP
                f.use_gas(3)
                n = op - 0x7F
                if len(st) < n:
                    raise VMError("stack underflow")
                f.push(st[-n])
            elif 0x90 <= op <= 0x9F:  # SWAP
                f.use_gas(3)
                n = op - 0x8F
                if len(st) < n + 1:
                    raise VMError("stack underflow")
                st[-1], st[-n - 1] = st[-n - 1], st[-1]
            elif op == 0x01:  # ADD
                f.use_gas(3); f.push(f.pop() + f.pop())
            elif op == 0x02:  # MUL
                f.use_gas(5); f.push(f.pop() * f.pop())
            elif op == 0x03:  # SUB
                f.use_gas(3); a = f.pop(); f.push(a - f.pop())
            elif op == 0x04:  # DIV
                f.use_gas(5); a = f.pop(); b = f.pop()
                f.push(a // b if b else 0)
            elif op == 0x05:  # SDIV
                f.use_gas(5); a = _s256(f.pop()); b = _s256(f.pop())
                f.push(_u256(abs(a) // abs(b) * (1 if (a < 0) == (b < 0) else -1)) if b else 0)
            elif op == 0x06:  # MOD
                f.use_gas(5); a = f.pop(); b = f.pop()
                f.push(a % b if b else 0)
            elif op == 0x07:  # SMOD
                f.use_gas(5); a = _s256(f.pop()); b = _s256(f.pop())
                f.push(_u256(abs(a) % abs(b) * (1 if a >= 0 else -1)) if b else 0)
            elif op == 0x08:  # ADDMOD
                f.use_gas(8); a = f.pop(); b = f.pop(); n = f.pop()
                f.push((a + b) % n if n else 0)
            elif op == 0x09:  # MULMOD
                f.use_gas(8); a = f.pop(); b = f.pop(); n = f.pop()
                f.push((a * b) % n if n else 0)
            elif op == 0x0A:  # EXP
                base = f.pop(); exp = f.pop()
                f.use_gas(10 + EXP_BYTE_GAS * ((exp.bit_length() + 7) // 8))
                f.push(pow(base, exp, 1 << 256))
            elif op == 0x0B:  # SIGNEXTEND
                f.use_gas(5); k = f.pop(); v = f.pop()
                if k < 31:
                    bit = 8 * (k + 1) - 1
                    if v & (1 << bit):
                        v |= WORD ^ ((1 << (bit + 1)) - 1)
                    else:
                        v &= (1 << (bit + 1)) - 1
                f.push(v)
            elif op == 0x10:  # LT
                f.use_gas(3); f.push(1 if f.pop() < f.pop() else 0)
            elif op == 0x11:  # GT
                f.use_gas(3); f.push(1 if f.pop() > f.pop() else 0)
            elif op == 0x12:  # SLT
                f.use_gas(3); f.push(1 if _s256(f.pop()) < _s256(f.pop()) else 0)
            elif op == 0x13:  # SGT
                f.use_gas(3); f.push(1 if _s256(f.pop()) > _s256(f.pop()) else 0)
            elif op == 0x14:  # EQ
                f.use_gas(3); f.push(1 if f.pop() == f.pop() else 0)
            elif op == 0x15:  # ISZERO
                f.use_gas(3); f.push(1 if f.pop() == 0 else 0)
            elif op == 0x16:  # AND
                f.use_gas(3); f.push(f.pop() & f.pop())
            elif op == 0x17:  # OR
                f.use_gas(3); f.push(f.pop() | f.pop())
            elif op == 0x18:  # XOR
                f.use_gas(3); f.push(f.pop() ^ f.pop())
            elif op == 0x19:  # NOT
                f.use_gas(3); f.push(~f.pop())
            elif op == 0x1A:  # BYTE
                f.use_gas(3); i = f.pop(); v = f.pop()
                f.push((v >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
            elif op == 0x1B:  # SHL
                f.use_gas(3); s = f.pop(); v = f.pop()
                f.push(v << s if s < 256 else 0)
            elif op == 0x1C:  # SHR
                f.use_gas(3); s = f.pop(); v = f.pop()
                f.push(v >> s if s < 256 else 0)
            elif op == 0x1D:  # SAR
                f.use_gas(3); s = f.pop(); v = _s256(f.pop())
                f.push(_u256(v >> s if s < 256 else (0 if v >= 0 else -1)))
            elif op == 0x20:  # SHA3
                off = f.pop(); size = f.pop()
                f.use_gas(SHA3_GAS + SHA3_WORD_GAS * _mem_words(size))
                f.mem_gas(off, size)
                f.push(int.from_bytes(keccak256(mem.read(off, size)), "big"))
            elif op == 0x30:  # ADDRESS
                f.use_gas(2); f.push(_addr_word(address))
            elif op == 0x31:  # BALANCE
                a = _word_addr(f.pop())
                f.use_gas(
                    self._addr_access_gas(a) if self.berlin else BALANCE_GAS
                )
                f.push(self.state.balance(a))
            elif op == 0x32:  # ORIGIN
                f.use_gas(2); f.push(_addr_word(self.origin))
            elif op == 0x33:  # CALLER
                f.use_gas(2); f.push(_addr_word(caller))
            elif op == 0x34:  # CALLVALUE
                f.use_gas(2); f.push(value)
            elif op == 0x35:  # CALLDATALOAD
                f.use_gas(3); off = f.pop()
                f.push(int.from_bytes(
                    calldata[off:off + 32].ljust(32, b"\x00"), "big"
                ))
            elif op == 0x36:  # CALLDATASIZE
                f.use_gas(2); f.push(len(calldata))
            elif op == 0x37:  # CALLDATACOPY
                dst = f.pop(); src = f.pop(); size = f.pop()
                f.use_gas(3 + COPY_WORD_GAS * _mem_words(size))
                f.mem_gas(dst, size)
                mem.write(dst, calldata[src:src + size].ljust(size, b"\x00"))
            elif op == 0x38:  # CODESIZE
                f.use_gas(2); f.push(len(code))
            elif op == 0x39:  # CODECOPY
                dst = f.pop(); src = f.pop(); size = f.pop()
                f.use_gas(3 + COPY_WORD_GAS * _mem_words(size))
                f.mem_gas(dst, size)
                mem.write(dst, code[src:src + size].ljust(size, b"\x00"))
            elif op == 0x3A:  # GASPRICE
                f.use_gas(2); f.push(self.gas_price)
            elif op == 0x3B:  # EXTCODESIZE
                a = _word_addr(f.pop())
                f.use_gas(
                    self._addr_access_gas(a) if self.berlin else EXTCODE_GAS
                )
                f.push(len(self.state.code(a)))
            elif op == 0x3C:  # EXTCODECOPY
                addr2 = _word_addr(f.pop())
                dst = f.pop(); src = f.pop(); size = f.pop()
                base = (self._addr_access_gas(addr2) if self.berlin
                        else EXTCODE_GAS)
                f.use_gas(base + COPY_WORD_GAS * _mem_words(size))
                f.mem_gas(dst, size)
                ext = self.state.code(addr2)
                mem.write(dst, ext[src:src + size].ljust(size, b"\x00"))
            elif op == 0x3D:  # RETURNDATASIZE
                f.use_gas(2); f.push(len(f.returndata))
            elif op == 0x3E:  # RETURNDATACOPY
                dst = f.pop(); src = f.pop(); size = f.pop()
                f.use_gas(3 + COPY_WORD_GAS * _mem_words(size))
                if src + size > len(f.returndata):
                    raise VMError("returndata out of bounds")
                f.mem_gas(dst, size)
                mem.write(dst, f.returndata[src:src + size])
            elif op == 0x3F:  # EXTCODEHASH
                a = _word_addr(f.pop())
                f.use_gas(
                    self._addr_access_gas(a) if self.berlin else EXTCODE_GAS
                )
                c = self.state.code(a)
                if not c and not self.state.balance(a) and not self.state.nonce(a):
                    f.push(0)
                else:
                    f.push(int.from_bytes(keccak256(c), "big"))
            elif op == 0x40:  # BLOCKHASH
                f.use_gas(20)
                f.push(int.from_bytes(self.env.block_hash_fn(f.pop()), "big"))
            elif op == 0x41:  # COINBASE
                f.use_gas(2); f.push(_addr_word(self.env.coinbase))
            elif op == 0x42:  # TIMESTAMP
                f.use_gas(2); f.push(self.env.timestamp)
            elif op == 0x43:  # NUMBER
                f.use_gas(2); f.push(self.env.block_num)
            elif op == 0x44:  # DIFFICULTY / PREVRANDAO
                f.use_gas(2); f.push(0)
            elif op == 0x45:  # GASLIMIT
                f.use_gas(2); f.push(self.env.gas_limit)
            elif op == 0x46:  # CHAINID
                f.use_gas(2); f.push(self.env.chain_id)
            elif op == 0x47:  # SELFBALANCE
                f.use_gas(5); f.push(self.state.balance(address))
            elif op == 0x48:  # BASEFEE
                f.use_gas(2); f.push(0)
            elif op == 0x50:  # POP
                f.use_gas(2); f.pop()
            elif op == 0x51:  # MLOAD
                f.use_gas(3); off = f.pop()
                f.mem_gas(off, 32)
                f.push(int.from_bytes(mem.read(off, 32), "big"))
            elif op == 0x52:  # MSTORE
                f.use_gas(3); off = f.pop(); v = f.pop()
                f.mem_gas(off, 32)
                mem.write(off, v.to_bytes(32, "big"))
            elif op == 0x53:  # MSTORE8
                f.use_gas(3); off = f.pop(); v = f.pop()
                f.mem_gas(off, 1)
                mem.write(off, bytes([v & 0xFF]))
            elif op == 0x54:  # SLOAD
                slot = f.pop().to_bytes(32, "big")
                f.use_gas(
                    self._slot_access_gas(address, slot) if self.berlin
                    else SLOAD_GAS
                )
                f.push(self.state.storage_get(address, slot))
            elif op == 0x55:  # SSTORE — exact EIP-2200 net metering
                # (composed with EIP-2929 under berlin, as in the
                # reference's go-ethereum fork: core/vm gas tables)
                if static:
                    raise VMError("SSTORE in static context")
                if f.gas <= CALL_STIPEND:
                    # EIP-2200 sentry: never leave a reentrant call
                    # enough gas to SSTORE out of the stipend
                    raise VMError("SSTORE with gas <= call stipend")
                slot = f.pop().to_bytes(32, "big")
                v = f.pop()
                if self.berlin:
                    if (address, slot) not in self.warm_slots:
                        self.warm_slots.add((address, slot))
                        f.use_gas(COLD_SLOAD)
                key = (address, slot)
                cur = self.state.storage_get(address, slot)
                orig = self._tx_original.setdefault(key, cur)
                # Berlin re-prices the EIP-2200 constants: the
                # SLOAD-like charge becomes the warm access cost and
                # the reset charge drops by the cold surcharge
                sload_like = WARM_ACCESS if self.berlin else SLOAD_GAS
                reset_gas = SSTORE_UPDATE - (
                    COLD_SLOAD if self.berlin else 0
                )
                if v == cur:  # no-op write
                    f.use_gas(sload_like)
                elif cur == orig:  # clean slot: first real write this tx
                    if orig == 0:
                        f.use_gas(SSTORE_SET)
                    else:
                        f.use_gas(reset_gas)
                        if v == 0:
                            self.refund += SSTORE_CLEAR_REFUND
                else:  # dirty slot: rewritten within this tx
                    f.use_gas(sload_like)
                    if orig != 0:
                        if cur == 0:  # resurrecting: undo clear refund
                            self.refund -= SSTORE_CLEAR_REFUND
                        if v == 0:
                            self.refund += SSTORE_CLEAR_REFUND
                    if v == orig:  # restored to tx-start value
                        if orig == 0:
                            self.refund += SSTORE_SET - sload_like
                        else:
                            self.refund += reset_gas - sload_like
                self.state.storage_set(address, slot, v)
            elif op == 0x56:  # JUMP
                f.use_gas(8)
                dest = f.pop()
                if dest not in f.jumpdests:
                    raise VMError("bad jump destination")
                f.pc = dest + 1
            elif op == 0x57:  # JUMPI
                f.use_gas(10)
                dest = f.pop(); cond = f.pop()
                if cond:
                    if dest not in f.jumpdests:
                        raise VMError("bad jump destination")
                    f.pc = dest + 1
            elif op == 0x58:  # PC
                f.use_gas(2); f.push(f.pc - 1)
            elif op == 0x59:  # MSIZE
                f.use_gas(2); f.push(_mem_words(len(mem.data)) * 32)
            elif op == 0x5A:  # GAS
                f.use_gas(2); f.push(f.gas)
            elif op == 0x5B:  # JUMPDEST
                f.use_gas(1)
            elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
                if static:
                    raise VMError("LOG in static context")
                n = op - 0xA0
                off = f.pop(); size = f.pop()
                topics = [f.pop().to_bytes(32, "big") for _ in range(n)]
                f.use_gas(LOG_GAS + LOG_TOPIC_GAS * n + LOG_DATA_GAS * size)
                f.mem_gas(off, size)
                self.logs.append(Log(address, topics, mem.read(off, size)))
            elif op == 0xF0 or op == 0xF5:  # CREATE / CREATE2
                if static:
                    raise VMError("CREATE in static context")
                val = f.pop(); off = f.pop(); size = f.pop()
                salt = f.pop().to_bytes(32, "big") if op == 0xF5 else None
                f.use_gas(CREATE_GAS)
                if op == 0xF5:
                    f.use_gas(SHA3_WORD_GAS * _mem_words(size))
                f.mem_gas(off, size)
                init = mem.read(off, size)
                child_gas = f.gas - f.gas // 64
                f.use_gas(child_gas)
                ok, gas_left, addr2 = self.create(
                    address, val, init, child_gas, salt
                )
                f.gas += gas_left
                f.returndata = b""
                f.push(_addr_word(addr2) if ok else 0)
            elif op in (0xF1, 0xF2, 0xF4, 0xFA):  # CALL family
                gas_req = f.pop()
                to = _word_addr(f.pop())
                if op in (0xF1, 0xF2):
                    val = f.pop()
                else:
                    val = 0
                in_off = f.pop(); in_size = f.pop()
                out_off = f.pop(); out_size = f.pop()
                if static and op == 0xF1 and val:
                    raise VMError("value call in static context")
                f.use_gas(
                    self._addr_access_gas(to) if self.berlin else CALL_GAS
                )
                if val:
                    f.use_gas(CALL_VALUE_GAS)
                    if op == 0xF1 and not (
                        self.state.nonce(to) or self.state.code(to)
                        or self.state.balance(to)
                    ):
                        f.use_gas(NEW_ACCOUNT_GAS)
                f.mem_gas(in_off, in_size)
                f.mem_gas(out_off, out_size)
                avail = f.gas - f.gas // 64
                child_gas = min(gas_req, avail)
                f.use_gas(child_gas)
                if val:
                    child_gas += CALL_STIPEND
                args = mem.read(in_off, in_size)
                if op == 0xF1:  # CALL
                    ok, gas_left, out = self.call(
                        address, to, val, args, child_gas, static
                    )
                elif op == 0xF2:  # CALLCODE: their code, our storage
                    ok, gas_left, out = self._call_with_code(
                        address, address, to, val, args, child_gas, static
                    )
                elif op == 0xF4:  # DELEGATECALL: keep caller AND value
                    ok, gas_left, out = self._call_with_code(
                        caller, address, to, value, args, child_gas,
                        static, transfer=False,
                    )
                else:  # STATICCALL
                    ok, gas_left, out = self.call(
                        address, to, 0, args, child_gas, True
                    )
                f.gas += gas_left
                f.returndata = out
                mem.write(out_off, out[:out_size].ljust(
                    min(out_size, len(out)), b"\x00"
                ))
                f.push(1 if ok else 0)
            elif op == 0xF3:  # RETURN
                off = f.pop(); size = f.pop()
                f.mem_gas(off, size)
                return mem.read(off, size), f.gas
            elif op == 0xFD:  # REVERT
                off = f.pop(); size = f.pop()
                f.mem_gas(off, size)
                r = Revert(mem.read(off, size))
                r.gas_left = f.gas
                raise r
            elif op == 0xFE:  # INVALID
                raise VMError("invalid opcode")
            elif op == 0xFF:  # SELFDESTRUCT
                if static:
                    raise VMError("SELFDESTRUCT in static context")
                f.use_gas(5000)
                heir = _word_addr(f.pop())
                bal = self.state.balance(address)
                if bal:
                    self.state.sub_balance(address, bal)
                    self.state.add_balance(heir, bal)
                self.state.set_code(address, b"")
                return b"", f.gas
            elif op == 0x00:  # STOP
                return b"", f.gas
            else:
                raise VMError(f"unknown opcode 0x{op:02x}")
        return b"", f.gas

    def _call_with_code(self, caller, storage_addr, code_addr, value,
                        data, gas, static, transfer=True):
        """CALLCODE/DELEGATECALL: run code_addr's code in
        storage_addr's context."""
        if self.depth >= MAX_DEPTH:
            return False, gas, b""
        fn = PRECOMPILES.get(_addr_word(code_addr))
        if fn is not None:
            # precompiles are reachable through every call type; there
            # is no value transfer on this path so no snapshot needed
            try:
                gas_left, out = fn(data, gas)
                return True, gas_left, out
            except VMError:
                return False, 0, b""
        snap = self._snapshot()
        code = self.state.code(code_addr)
        if not code:
            return True, gas, b""
        self.depth += 1
        try:
            out, gas_left = self._run(
                code, caller, storage_addr, value, data, gas, static
            )
            return True, gas_left, out
        except Revert as r:
            self._restore(snap)
            return False, r.gas_left, r.data
        except VMError:
            self._restore(snap)
            return False, 0, b""
        finally:
            self.depth -= 1
