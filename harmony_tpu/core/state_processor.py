"""Block execution: transfers, staking directives, cross-shard receipts.

The role of the reference's core/state_processor.go (699 LoC: tx,
staking-tx, and incoming-CXReceipt application) plus the staking
message validation of core/staking_verifier.go (SURVEY.md §2.4).
Contract transactions execute through core/vm.py (the interpreter
replacing the reference's go-ethereum EVM fork): ``to=None`` deploys,
a coded ``to`` runs a message call; EVM failures follow Ethereum
semantics — the tx is included with status 0, the fee is charged, the
nonce advances, the value stays with the sender.

Gas model (the subset consensus needs to be deterministic about):
intrinsic 21_000 per plain tx + 68/non-zero byte + 4/zero byte of
data, plus the EVM's per-opcode metering (core/vm.py); refunds capped
at used//2.  Fees are burned here (reward issuance is the engine's job
at Finalize, as in the reference's reward.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .state import Delegation, StateDB, ValidatorWrapper
from .types import (
    CXReceipt,
    Directive,
    Receipt,
    StakingTransaction,
    Transaction,
)

INTRINSIC_GAS = 21_000
STAKING_GAS = 21_000
DATA_GAS_NONZERO = 68
DATA_GAS_ZERO = 4
# EIP-2930 access-list pricing (reference: core/types AccessListTx +
# go-ethereum params): paid in intrinsic gas, pre-warmed for EIP-2929
ACCESS_LIST_ADDR_GAS = 2_400
ACCESS_LIST_SLOT_GAS = 1_900
UNDELEGATION_LOCK_EPOCHS = 7  # reference: staking undelegation maturity


class ExecutionError(ValueError):
    pass


def intrinsic_gas(tx: Transaction) -> int:
    g = INTRINSIC_GAS
    for b in tx.data:
        g += DATA_GAS_NONZERO if b else DATA_GAS_ZERO
    for addr, slots in tx.access_list:
        g += ACCESS_LIST_ADDR_GAS + ACCESS_LIST_SLOT_GAS * len(slots)
    return g


@dataclass
class ProcessResult:
    receipts: list = field(default_factory=list)
    staking_receipts: list = field(default_factory=list)
    outgoing_cx: list = field(default_factory=list)  # CXReceipts to export
    gas_used: int = 0


class StateProcessor:
    """Applies a block's transactions to a StateDB."""

    def __init__(self, chain_id: int, shard_id: int):
        self.chain_id = chain_id
        self.shard_id = shard_id
        self._env = None  # block-level EVM context, set per process()

    # -- plain transactions ------------------------------------------------

    def apply_transaction(
        self, state: StateDB, tx: Transaction, block_num: int,
        cumulative_gas: int,
    ) -> tuple[Receipt, CXReceipt | None]:
        try:
            sender = tx.sender(self.chain_id)
        except ValueError as e:
            raise ExecutionError(f"bad signature: {e}") from e
        if tx.shard_id != self.shard_id:
            raise ExecutionError("tx for a different shard")
        if tx.nonce != state.nonce(sender):
            raise ExecutionError(
                f"bad nonce: want {state.nonce(sender)} got {tx.nonce}"
            )
        gas = intrinsic_gas(tx)
        if tx.gas_limit < gas:
            raise ExecutionError("gas limit below intrinsic gas")
        if state.balance(sender) < tx.gas_limit * tx.gas_price + tx.value:
            raise ExecutionError("insufficient balance for value + fee")

        cx = None
        status = 1
        used = gas
        logs: list = []
        created = b""
        if tx.is_cross_shard():
            # cross-shard: value-transfer only (the reference routes no
            # contract execution across shards); data charged, ignored
            state.sub_balance(sender, gas * tx.gas_price + tx.value)
            state.set_nonce(sender, tx.nonce + 1)
            cx = CXReceipt(
                tx_hash=tx.hash(self.chain_id),
                sender=sender,
                to=tx.to or b"\x00" * 20,
                amount=tx.value,
                from_shard=tx.shard_id,
                to_shard=tx.to_shard,
                block_num=block_num,
            )
        elif tx.to is None or state.code(tx.to) or (
            tx.data and self._is_precompile(tx.to)
        ):
            # EVM path: deploy (to=None) or message call into code.
            # Fee bought upfront at the gas limit, unused gas refunded
            # after — Ethereum semantics; an EVM failure keeps the tx
            # in the block with status 0, fee charged, nonce advanced.
            from .vm import EVM, Env

            state.sub_balance(sender, tx.gas_limit * tx.gas_price)
            env = self._env if self._env is not None else Env(
                block_num=block_num, chain_id=self.chain_id,
                shard_id=self.shard_id,
            )
            evm = EVM(state, env, origin=sender, gas_price=tx.gas_price)
            if tx.to is not None:
                evm.warm_addrs.add(tx.to)  # EIP-2929: tx target warm
            for al_addr, al_slots in tx.access_list:
                # EIP-2930: listed entries start warm (paid above in
                # intrinsic gas)
                evm.warm_addrs.add(al_addr)
                for slot in al_slots:
                    evm.warm_slots.add((al_addr, slot))
            created = b""
            if tx.to is None:
                # evm.create advances the nonce and derives the address
                # from the pre-increment value (tx.nonce)
                ok, gas_left, _addr = evm.create(
                    sender, tx.value, tx.data, tx.gas_limit - gas
                )
                if ok:
                    created = _addr
            else:
                state.set_nonce(sender, tx.nonce + 1)
                ok, gas_left, _out = evm.call(
                    sender, tx.to, tx.value, tx.data, tx.gas_limit - gas
                )
            status = 1 if ok else 0
            logs = [(lg.address, lg.topics, lg.data) for lg in evm.logs]
            state.end_tx()  # settle the EVM frame journal
            used = tx.gas_limit - gas_left
            refund = min(evm.refund if ok else 0, used // 2)
            used -= refund
            state.add_balance(
                sender, (tx.gas_limit - used) * tx.gas_price
            )
        else:
            state.sub_balance(sender, gas * tx.gas_price + tx.value)
            state.set_nonce(sender, tx.nonce + 1)
            if tx.to is not None:
                state.add_balance(tx.to, tx.value)
        receipt = Receipt(
            tx_hash=tx.hash(self.chain_id),
            status=status,
            gas_used=used,
            cumulative_gas=cumulative_gas + used,
            logs=logs,
            contract_address=created,
        )
        return receipt, cx

    def set_env(self, env):
        """Block-level EVM context.  The PROPOSER must set this before
        speculative execution with the same (block_num, timestamp) it
        seals into the header — replay rebuilds the env from the header
        (process()), and any disagreement (e.g. the NUMBER opcode
        seeing a stale height) would fork the state root."""
        self._env = env

    @staticmethod
    def _is_precompile(addr: bytes | None) -> bool:
        from .vm import PRECOMPILES, STAKING_PRECOMPILE_ADDR

        return addr is not None and (
            int.from_bytes(addr, "big") in PRECOMPILES
            or addr == STAKING_PRECOMPILE_ADDR
        )

    def apply_incoming_receipt(self, state: StateDB, cx: CXReceipt):
        """Credit a cross-shard transfer on its destination shard
        (reference: core/state_processor ApplyIncomingReceipt)."""
        if cx.to_shard != self.shard_id:
            raise ExecutionError("cx receipt for a different shard")
        state.add_balance(cx.to, cx.amount)

    # -- staking directives ------------------------------------------------

    def apply_staking_transaction(
        self, state: StateDB, tx: StakingTransaction, epoch: int,
        cumulative_gas: int,
    ) -> Receipt:
        """Atomic: on any failure ``state`` is left untouched (so a
        proposer can skip a failing tx without poisoning its
        speculative state — the root it seals must match replay)."""
        try:
            sender = tx.sender(self.chain_id)
        except ValueError as e:
            raise ExecutionError(f"bad signature: {e}") from e
        if tx.shard_id != self.shard_id:
            # the shard is inside signing_bytes, so this binds the
            # SIGNATURE to one shard — a delegate/undelegate signed for
            # shard 0 must not replay on shard 1 at the same nonce
            raise ExecutionError("staking tx bound to a different shard")
        if tx.nonce != state.nonce(sender):
            raise ExecutionError(
                f"bad nonce: want {state.nonce(sender)} got {tx.nonce}"
            )
        if tx.gas_limit < STAKING_GAS:
            raise ExecutionError("gas limit below staking intrinsic gas")
        fee = STAKING_GAS * tx.gas_price
        if state.balance(sender) < fee:
            raise ExecutionError("insufficient balance for fee")
        work = state.copy()
        work.sub_balance(sender, fee)
        work.set_nonce(sender, tx.nonce + 1)
        handler = {
            Directive.CREATE_VALIDATOR: self._create_validator,
            Directive.EDIT_VALIDATOR: self._edit_validator,
            Directive.DELEGATE: self._delegate,
            Directive.UNDELEGATE: self._undelegate,
            Directive.COLLECT_REWARDS: self._collect_rewards,
        }[tx.directive]
        try:
            handler(work, sender, tx.fields, epoch)
        except ExecutionError:
            raise
        except (ValueError, KeyError, TypeError) as e:
            raise ExecutionError(f"{tx.directive.name}: {e}") from e
        state.absorb(work)
        return Receipt(
            tx_hash=tx.hash(self.chain_id),
            status=1,
            gas_used=STAKING_GAS,
            cumulative_gas=cumulative_gas + STAKING_GAS,
        )

    # validation rules mirror core/staking_verifier.go (SURVEY.md §2.4)

    def _create_validator(self, state, sender, f, epoch):
        if state.validator(sender) is not None:
            raise ExecutionError("validator already exists")
        amount = int(f.get("amount", 0))
        min_self = int(f.get("min_self_delegation", 0))
        if amount <= 0 or min_self < 0:
            raise ExecutionError("self-delegation must be positive")
        if amount < min_self:
            raise ExecutionError("initial self-delegation below minimum")
        keys = f.get("bls_keys")
        if not keys:
            raise ExecutionError("create-validator needs >=1 BLS key")
        if isinstance(keys, bytes):  # packed 48-byte keys
            keys = [keys[i:i + 48] for i in range(0, len(keys), 48)]
        if state.balance(sender) < amount:
            raise ExecutionError("insufficient balance for self-delegation")
        state.sub_balance(sender, amount)
        wrapper = ValidatorWrapper(
            address=sender,
            bls_keys=list(keys),
            commission_rate=int(f.get("commission_rate", 0)),
            max_commission_rate=int(f.get("max_commission_rate", 10**18)),
            max_change_rate=int(f.get("max_change_rate", 10**18)),
            min_self_delegation=min_self,
            max_total_delegation=int(f.get("max_total_delegation", 0)),
            delegations=[Delegation(sender, amount)],
            last_epoch_in_committee=epoch,
        )
        state.set_validator(wrapper)

    def _edit_validator(self, state, sender, f, epoch):
        w = state.validator(sender)
        if w is None:
            raise ExecutionError("no such validator")
        if "commission_rate" in f:
            new_rate = int(f["commission_rate"])
            if new_rate > w.max_commission_rate:
                raise ExecutionError("commission above max")
            if abs(new_rate - w.commission_rate) > w.max_change_rate:
                raise ExecutionError("commission change above max change")
            w.commission_rate = new_rate
        if "add_bls_key" in f:
            k = f["add_bls_key"]
            if k in w.bls_keys:
                raise ExecutionError("key already registered")
            w.bls_keys.append(k)
        if "remove_bls_key" in f:
            k = f["remove_bls_key"]
            if k not in w.bls_keys:
                raise ExecutionError("key not registered")
            if len(w.bls_keys) == 1:
                raise ExecutionError("cannot remove last BLS key")
            w.bls_keys.remove(k)

    def _delegate(self, state, sender, f, epoch):
        validator = f["validator"]
        amount = int(f["amount"])
        w = state.validator(validator)
        if w is None:
            raise ExecutionError("no such validator")
        if amount <= 0:
            raise ExecutionError("delegation must be positive")
        if w.max_total_delegation and (
            w.total_delegation() + amount > w.max_total_delegation
        ):
            raise ExecutionError("exceeds max total delegation")
        state.sub_balance(sender, amount)
        for d in w.delegations:
            if d.delegator == sender:
                d.amount += amount
                return
        w.delegations.append(Delegation(sender, amount))

    def _undelegate(self, state, sender, f, epoch):
        validator = f["validator"]
        amount = int(f["amount"])
        w = state.validator(validator)
        if w is None:
            raise ExecutionError("no such validator")
        if amount <= 0:
            raise ExecutionError("undelegation must be positive")
        for d in w.delegations:
            if d.delegator == sender:
                if d.amount < amount:
                    raise ExecutionError("undelegate exceeds delegation")
                d.amount -= amount
                d.undelegations.append((amount, epoch))
                if (
                    validator == sender
                    and d.amount < w.min_self_delegation
                ):
                    w.status = 1  # below self-delegation floor: inactive
                return
        raise ExecutionError("no delegation to undelegate")

    def _collect_rewards(self, state, sender, f, epoch):
        total = 0
        for addr in state.validator_addresses():
            w = state.validator(addr)
            for d in w.delegations:
                if d.delegator == sender and d.reward:
                    total += d.reward
                    d.reward = 0
        if total == 0:
            raise ExecutionError("no rewards to collect")
        state.add_balance(sender, total)

    # -- undelegation maturity (epoch boundary) ----------------------------

    def payout_undelegations(self, state: StateDB, epoch: int):
        """Release matured undelegations back to delegators (reference:
        internal/chain/engine.go:359 payoutUndelegations)."""
        for addr in state.validator_addresses():
            w = state.validator(addr)
            for d in w.delegations:
                kept, released = [], 0
                for amount, at_epoch in d.undelegations:
                    if epoch >= at_epoch + UNDELEGATION_LOCK_EPOCHS:
                        released += amount
                    else:
                        kept.append((amount, at_epoch))
                if released:
                    d.undelegations = kept
                    state.add_balance(d.delegator, released)

    # -- whole block -------------------------------------------------------

    def process(
        self, state: StateDB, block, epoch: int
    ) -> ProcessResult:
        """Execute a block against ``state`` (mutates it)."""
        from .vm import Env

        h = block.header
        self._env = Env(
            block_num=h.block_num, timestamp=h.timestamp,
            chain_id=self.chain_id, epoch=epoch,
            shard_id=self.shard_id,
        )
        res = ProcessResult()
        for tx, is_staking in block.ordered_txs():
            if is_staking:
                receipt = self.apply_staking_transaction(
                    state, tx, epoch, res.gas_used
                )
                res.staking_receipts.append(receipt)
            else:
                receipt, cx = self.apply_transaction(
                    state, tx, block.block_num, res.gas_used
                )
                res.receipts.append(receipt)
                if cx is not None:
                    res.outgoing_cx.append(cx)
            res.gas_used += receipt.gas_used
        for proof in block.incoming_receipts:
            for cx in proof.receipts:
                self.apply_incoming_receipt(state, cx)
        return res
