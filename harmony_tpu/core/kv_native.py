"""ctypes binding for the native C++ KV store (native/kvstore.cpp).

Same interface as kv.FileKV and the SAME on-disk format — a chain
written by one opens under the other.  The native store is the
deployment IO path (the role LevelDB's C++ plays under the reference's
core/rawdb); FileKV stays the dependency-free fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "native", "libharmony_kv.so",
)
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        build_native()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_put.restype = ctypes.c_int
    lib.kv_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_get.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.kv_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_has.restype = ctypes.c_int
    lib.kv_has.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_len.restype = ctypes.c_uint64
    lib.kv_len.argtypes = [ctypes.c_void_p]
    lib.kv_write_batch.restype = ctypes.c_int
    lib.kv_write_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_uint32,
    ]
    lib.kv_config.restype = ctypes.c_int
    lib.kv_config.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.kv_flush.restype = ctypes.c_int
    lib.kv_flush.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_close.restype = None
    lib.kv_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def build_native():
    """Compile the shared library (g++ is in the image)."""
    native_dir = os.path.dirname(_LIB_PATH)
    subprocess.run(
        ["make", "-C", native_dir, "libharmony_kv.so"],
        check=True, capture_output=True,
    )


def available() -> bool:
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class NativeKV:
    """Drop-in for kv.FileKV backed by the C++ store."""

    def __init__(self, path: str, fsync: str = "none"):
        from .kv import FSYNC_POLICIES

        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in "
                             f"{FSYNC_POLICIES}")
        import threading

        lib = _load()
        self._lib = lib
        self._h = lib.kv_open(path.encode())
        if not self._h:
            raise OSError(f"kv_open failed for {path}")
        self.path = path
        self.fsync = fsync
        # the C handle shares one FILE* (file position!) and one
        # returned-value buffer: a node is multi-threaded, so every
        # call serializes here — same discipline as FileKV
        self._lock = threading.RLock()
        # the native store fsyncs batch commits itself; the "always"
        # policy additionally flushes per put/delete from this side
        lib.kv_config(self._h, 1 if fsync in ("batch", "always") else 0)

    def get(self, key: bytes):
        with self._lock:
            vlen = ctypes.c_uint32(0)
            ptr = self._lib.kv_get(
                self._h, key, len(key), ctypes.byref(vlen)
            )
            if not ptr:
                return None
            return ctypes.string_at(ptr, vlen.value)

    def put(self, key: bytes, value: bytes):
        with self._lock:
            if self._lib.kv_put(self._h, key, len(key), value,
                                len(value)) != 0:
                raise OSError("kv_put failed")
            if self.fsync == "always":
                self._lib.kv_flush(self._h)  # fflush + fsync

    def delete(self, key: bytes):
        with self._lock:
            if self._lib.kv_delete(self._h, key, len(key)) != 0:
                raise OSError("kv_delete failed")
            if self.fsync == "always":
                self._lib.kv_flush(self._h)

    def has(self, key: bytes) -> bool:
        with self._lock:
            return bool(self._lib.kv_has(self._h, key, len(key)))

    def write_batch(self, batch):
        """Atomic commit of a kv.WriteBatch — the same BEGIN/COMMIT
        marker grammar as FileKV (the two stores replay each other's
        batches).  Fires the ``kv.commit`` crash point once before the
        native call: the C side is a single append, so the per-record
        crash-point matrix is FileKV's to enumerate."""
        import struct as _struct

        from .. import faultinject as FI
        from .kv import _TOMB

        ops = batch.ops
        if not ops:
            return
        FI.fire("kv.commit", key=self.path)
        out = bytearray()
        for key, value in ops:
            if value is None:
                out += _struct.pack("<II", len(key), _TOMB) + key
            else:
                out += _struct.pack("<II", len(key), len(value))
                out += key + value
        with self._lock:
            if self._lib.kv_write_batch(self._h, bytes(out), len(out),
                                        len(ops)) != 0:
                raise OSError("kv_write_batch failed")

    def flush(self):
        with self._lock:
            self._lib.kv_flush(self._h)

    def compact(self):
        with self._lock:
            if self._lib.kv_compact(self._h) != 0:
                raise OSError("kv_compact failed")

    def close(self):
        with self._lock:
            if self._h:
                self._lib.kv_flush(self._h)
                self._lib.kv_close(self._h)
                self._h = None

    @property
    def closed(self) -> bool:
        return not self._h

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __len__(self):
        with self._lock:
            return int(self._lib.kv_len(self._h))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
