"""ctypes binding for the native C++ KV store (native/kvstore.cpp).

Same interface as kv.FileKV and the SAME on-disk format — a chain
written by one opens under the other.  The native store is the
deployment IO path (the role LevelDB's C++ plays under the reference's
core/rawdb); FileKV stays the dependency-free fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    ))),
    "native", "libharmony_kv.so",
)
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        build_native()
    lib = ctypes.CDLL(_LIB_PATH)
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_put.restype = ctypes.c_int
    lib.kv_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_get.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.kv_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_has.restype = ctypes.c_int
    lib.kv_has.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
    ]
    lib.kv_len.restype = ctypes.c_uint64
    lib.kv_len.argtypes = [ctypes.c_void_p]
    lib.kv_flush.restype = ctypes.c_int
    lib.kv_flush.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_close.restype = None
    lib.kv_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def build_native():
    """Compile the shared library (g++ is in the image)."""
    native_dir = os.path.dirname(_LIB_PATH)
    subprocess.run(
        ["make", "-C", native_dir, "libharmony_kv.so"],
        check=True, capture_output=True,
    )


def available() -> bool:
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class NativeKV:
    """Drop-in for kv.FileKV backed by the C++ store."""

    def __init__(self, path: str):
        lib = _load()
        self._lib = lib
        self._h = lib.kv_open(path.encode())
        if not self._h:
            raise OSError(f"kv_open failed for {path}")
        self.path = path

    def get(self, key: bytes):
        vlen = ctypes.c_uint32(0)
        ptr = self._lib.kv_get(
            self._h, key, len(key), ctypes.byref(vlen)
        )
        if not ptr:
            return None
        return ctypes.string_at(ptr, vlen.value)

    def put(self, key: bytes, value: bytes):
        if self._lib.kv_put(self._h, key, len(key), value,
                            len(value)) != 0:
            raise OSError("kv_put failed")

    def delete(self, key: bytes):
        if self._lib.kv_delete(self._h, key, len(key)) != 0:
            raise OSError("kv_delete failed")

    def has(self, key: bytes) -> bool:
        return bool(self._lib.kv_has(self._h, key, len(key)))

    def flush(self):
        self._lib.kv_flush(self._h)

    def compact(self):
        if self._lib.kv_compact(self._h) != 0:
            raise OSError("kv_compact failed")

    def close(self):
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None

    def __len__(self):
        return int(self._lib.kv_len(self._h))

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
