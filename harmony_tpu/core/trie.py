"""Hexary Merkle-Patricia trie (reference: the go-ethereum trie package
under core/state — SURVEY.md §2.4; node encoding per the Ethereum
yellow paper appendix D).

Purpose here: REFERENCE-SHAPED state commitments.  The execution layer
keeps the flat account map (O(1) access, trivially parallel root); this
trie turns the same data into an Ethereum-style root (and can serve
inclusion proofs).  Nodes are RLP; references are keccak256(rlp) when
the encoding is >= 32 bytes, else the encoding inlined — exactly the
yellow-paper rule, so roots match any correct MPT over the same
key/value set.

In-memory builder + optional node sink (``store``) for persistence.
"""

from __future__ import annotations

from ..ref.keccak import keccak256
from .. import rlp

EMPTY_ROOT = keccak256(rlp.encode(b""))  # the canonical empty-trie root


def _to_nibbles(key: bytes) -> list:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return out


def _hp_encode(nibbles: list, leaf: bool) -> bytes:
    """Hex-prefix encoding (yellow paper appendix C)."""
    flag = 2 if leaf else 0
    if len(nibbles) % 2:
        head = [(flag + 1) << 4 | nibbles[0]]
        rest = nibbles[1:]
    else:
        head = [flag << 4]
        rest = nibbles
    out = bytearray(head)
    for i in range(0, len(rest), 2):
        out.append(rest[i] << 4 | rest[i + 1])
    return bytes(out)


def _common_prefix(a: list, b: list) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class Trie:
    """Build from scratch each commit (the state layer hands it the
    full live account set; incremental update is a planned upgrade).

    ``store``: optional callable (hash, encoded_node) for persisting
    nodes (inclusion-proof serving / cold-start from a root).
    """

    def __init__(self, store=None):
        self._items: dict[bytes, bytes] = {}
        self._store = store

    def update(self, key: bytes, value: bytes):
        if value:
            self._items[key] = value
        else:
            self._items.pop(key, None)

    def root(self) -> bytes:
        if not self._items:
            return EMPTY_ROOT
        pairs = sorted(
            (_to_nibbles(k), v) for k, v in self._items.items()
        )
        node = self._build(pairs, 0)
        enc = rlp.encode(node)
        return keccak256(self._emit(enc))

    def _emit(self, enc: bytes) -> bytes:
        if self._store is not None:
            self._store(keccak256(enc), enc)
        return enc

    def _ref(self, node):
        """Yellow-paper node reference: inline if < 32 bytes."""
        enc = rlp.encode(node)
        if len(enc) < 32:
            return node
        self._emit(enc)
        return keccak256(enc)

    def _build(self, pairs: list, depth: int):
        """pairs: sorted (nibble_list, value), all sharing a prefix of
        length ``depth``; returns the structural node (not yet RLP)."""
        if len(pairs) == 1:
            nibs, value = pairs[0]
            return [_hp_encode(nibs[depth:], True), value]
        # longest common prefix below depth
        first = pairs[0][0]
        last = pairs[-1][0]
        common = _common_prefix(first[depth:], last[depth:])
        if common > 0:
            child = self._build(pairs, depth + common)
            return [
                _hp_encode(first[depth:depth + common], False),
                self._ref(child),
            ]
        # branch on nibble at depth
        children = [b""] * 16
        value = b""
        i = 0
        while i < len(pairs):
            nibs, val = pairs[i]
            if len(nibs) == depth:
                value = val  # key terminates exactly here
                i += 1
                continue
            nib = nibs[depth]
            j = i
            while j < len(pairs) and len(pairs[j][0]) > depth and (
                pairs[j][0][depth] == nib
            ):
                j += 1
            children[nib] = self._ref(self._build(pairs[i:j], depth + 1))
            i = j
        return children + [value]


def _hp_decode(data: bytes):
    """Inverse of _hp_encode -> (nibbles, is_leaf)."""
    flag = data[0] >> 4
    nibs = []
    if flag & 1:
        nibs.append(data[0] & 0x0F)
    for b in data[1:]:
        nibs.append(b >> 4)
        nibs.append(b & 0x0F)
    return nibs, bool(flag & 2)


def build_proof_db(items: dict):
    """(root, {hash: encoded node}) for a key/value set — build the
    trie ONCE, then prove_from() walks it per key (an eth_getProof
    request proves several keys against the same trie)."""
    nodes: dict[bytes, bytes] = {}
    t = Trie(store=lambda h, enc: nodes.__setitem__(h, enc))
    for k, v in items.items():
        t.update(k, v)
    return t.root(), nodes


def prove(items: dict, key: bytes) -> list:
    """One-shot convenience over build_proof_db + prove_from."""
    root, nodes = build_proof_db(items)
    return prove_from(root, nodes, key)


def prove_from(root: bytes, nodes: dict, key: bytes) -> list:
    """Merkle inclusion/exclusion proof: the RLP encodings of every
    HASHED node on ``key``'s path, root first (go-ethereum
    Trie.Prove's format — what eth_getProof carries).  Inline (<32 B)
    nodes ride inside their parents, per the yellow-paper reference
    rule, so the list is exactly the resolvable path."""
    if root == EMPTY_ROOT:
        return []
    proof = [nodes[root]]
    node = rlp.decode(nodes[root])
    nibs = _to_nibbles(key)
    while True:
        if len(node) == 2:
            prefix, is_leaf = _hp_decode(node[0])
            if is_leaf or prefix != nibs[:len(prefix)]:
                return proof  # arrived (or proved absent)
            nibs = nibs[len(prefix):]
            ref = node[1]
        elif len(node) == 17:
            if not nibs:
                return proof  # value sits in this branch
            ref, nibs = node[nibs[0]], nibs[1:]
        else:
            raise ValueError("malformed trie node")
        if isinstance(ref, list):
            node = ref  # inline child: part of the parent's encoding
        elif len(ref) == 32 and ref in nodes:
            proof.append(nodes[ref])
            node = rlp.decode(nodes[ref])
        else:
            return proof  # absent key diverged


def verify_proof(root: bytes, key: bytes, proof: list):
    """Walk a Trie.prove-style proof; returns the value at ``key`` (b""
    for a proven absence) or raises ValueError on a broken proof."""
    if not proof:
        if root == EMPTY_ROOT:
            return b""
        raise ValueError("empty proof for non-empty root")
    by_hash = {keccak256(enc): enc for enc in proof}
    if root not in by_hash:
        raise ValueError("proof does not start at the root")
    node = rlp.decode(by_hash[root])
    nibs = _to_nibbles(key)
    while True:
        if len(node) == 2:
            prefix, is_leaf = _hp_decode(node[0])
            if prefix != nibs[:len(prefix)]:
                return b""  # path diverges: proven absent
            nibs = nibs[len(prefix):]
            if is_leaf:
                if nibs:
                    return b""
                return node[1]
            ref = node[1]
        elif len(node) == 17:
            if not nibs:
                return node[16]
            ref, nibs = node[nibs[0]], nibs[1:]
        else:
            raise ValueError("malformed trie node")
        if isinstance(ref, list):
            node = ref
        elif ref == b"":
            return b""  # no child on the path: proven absent
        elif len(ref) == 32:
            enc = by_hash.get(ref)
            if enc is None:
                raise ValueError("proof is missing a path node")
            node = rlp.decode(enc)
        else:
            raise ValueError("malformed node reference")


def trie_root(items: dict) -> bytes:
    """Root of a key->value map (empty values are absent keys)."""
    t = Trie()
    for k, v in items.items():
        t.update(k, v)
    return t.root()


def secure_trie_root(items: dict) -> bytes:
    """go-ethereum SecureTrie: keys are keccak256-hashed first (the
    state trie's account addressing)."""
    return trie_root({keccak256(k): v for k, v in items.items()})
