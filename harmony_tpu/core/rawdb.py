"""rawdb: the key/value schema and block/state codecs.

The role of the reference's core/rawdb (LevelDB schema: canonical
hashes, headers, bodies, head pointers, and the per-block commit
sig+bitmap consumed at consensus/validator.go:367-377 — SURVEY.md
§2.4).  All keys are prefix-tagged; all values use the framework's
canonical little-endian layout.
"""

from __future__ import annotations

from ..chain.header import Header
from .types import (
    Block,
    CXReceipt,
    Reader as _Reader,
    StakingTransaction,
    Transaction,
    _enc_big,
    _enc_bytes,
    _enc_int,
)

# key prefixes
_HEADER = b"h"          # h || num(8) -> header blob
_BODY = b"b"            # b || num(8) -> body blob
_CANON = b"n"           # n || num(8) -> 32-byte hash
_NUM_BY_HASH = b"H"     # H || hash -> num(8)
_COMMIT_SIG = b"s"      # s || num(8) -> [96B sig || bitmap]
_HEAD = b"LastBlock"    # -> num(8)
_STATE = b"S"           # S || root -> serialized StateDB
_RECEIPTS = b"r"        # r || num(8) -> encoded receipt list
_RECEIPT_IDX = b"R"     # R || tx_hash -> num(8) (lookup index)
_CX = b"x"              # x || to_shard(4) || num(8) -> outgoing cx blob
_CX_SPENT = b"X"        # X || from_shard(4) || num(8) -> spent marker
_LAST_SIGNED = b"V"     # V || bls_pubkey -> last-signed vote record
_VC_WATERMARK = b"W"    # W || bls_pubkey -> highest view-change signed


# -- codecs -----------------------------------------------------------------

def _checked_count(r: _Reader, width: int = 4) -> int:
    """Bounded count for the gossip-fed blobs (ANNOUNCE block bytes,
    CX proofs, sync pages, epoch states) — Reader.checked_count."""
    return r.checked_count(width)

_HEADER_FIELDS = (
    # (name, kind) in storage order — every dataclass field, version
    # included, so the store round-trips any header version losslessly
    ("version", "str"), ("shard_id", "int"), ("block_num", "int"),
    ("epoch", "int"), ("view_id", "int"), ("timestamp", "int"),
    ("parent_hash", "bytes"), ("root", "bytes"), ("tx_root", "bytes"),
    ("receipt_root", "bytes"),
    ("out_cx_root", "bytes"), ("last_commit_sig", "bytes"),
    ("last_commit_bitmap", "bytes"), ("extra", "bytes"),
    ("vrf", "bytes"), ("vdf", "bytes"), ("shard_state", "bytes"),
    ("cross_links", "bytes"), ("slashes", "bytes"),
)


def encode_header(h: Header) -> bytes:
    out = bytearray()
    for name, kind in _HEADER_FIELDS:
        v = getattr(h, name)
        if kind == "int":
            out += v.to_bytes(8, "little")
        elif kind == "str":
            out += _enc_bytes(v.encode())
        else:
            out += _enc_bytes(v)
    return bytes(out)


def decode_header(blob: bytes) -> Header:
    r = _Reader(blob)
    kw = {}
    for name, kind in _HEADER_FIELDS:
        if kind == "int":
            kw[name] = r.int_()
        elif kind == "str":
            kw[name] = r.bytes_().decode()
        else:
            kw[name] = r.bytes_()
    return Header(**kw)


def encode_tx(tx: Transaction, chain_id: int) -> bytes:
    return _enc_bytes(tx.signing_bytes(chain_id)) + _enc_bytes(tx.sig)


def decode_tx(blob: bytes) -> Transaction:
    r = _Reader(blob)
    f = _Reader(r.bytes_())
    f.int_()  # chain id (re-derived from config at use sites)
    nonce = f.int_()
    gas_price = f.big_()
    gas_limit = f.int_()
    shard_id = f.int_(4)
    to_shard = f.int_(4)
    to = f.bytes_()
    value = f.big_()
    data = f.bytes_()
    tx_type, access_list = 0, []
    if not f.eof():  # EIP-2930-shaped typed tail (types.py)
        tx_type = f.int_(1)
        if tx_type == 1:
            for _ in range(_checked_count(f, 2)):
                addr = f.bytes_()
                slots = [f.bytes_() for _ in range(_checked_count(f, 2))]
                access_list.append((addr, slots))
    return Transaction(
        nonce=nonce, gas_price=gas_price, gas_limit=gas_limit,
        shard_id=shard_id, to_shard=to_shard,
        to=(to if to else None), value=value, data=data, sig=r.bytes_(),
        tx_type=tx_type, access_list=access_list,
    )


def encode_staking_tx(tx: StakingTransaction, chain_id: int) -> bytes:
    return _enc_bytes(tx.signing_bytes(chain_id)) + _enc_bytes(tx.sig)


def decode_staking_tx(blob: bytes) -> StakingTransaction:
    from .types import Directive

    r = _Reader(blob)
    f = _Reader(r.bytes_())
    f.int_()  # chain id
    nonce = f.int_()
    gas_price = f.big_()
    gas_limit = f.int_()
    shard_id = f.int_(4)
    directive = Directive(f.int_(1))
    fields = {}
    while f.off < len(f.view):
        key = f.bytes_().decode()
        tag = f.int_(1)
        if tag == 0:
            fields[key] = f.bytes_()
        elif tag == 1:
            fields[key] = f.big_()
        else:
            fields[key] = f.bytes_().decode()
    return StakingTransaction(
        nonce=nonce, gas_price=gas_price, gas_limit=gas_limit,
        directive=directive, fields=fields, shard_id=shard_id,
        sig=r.bytes_(),
    )


def encode_cx(cx: CXReceipt) -> bytes:
    return cx.encode()


def encode_cx_proof(p) -> bytes:
    return p.encode()


def decode_cx_proof(blob: bytes):
    from .types import CXReceiptsProof

    r = _Reader(blob)
    receipts = [decode_cx(r.bytes_()) for _ in range(_checked_count(r))]
    header_bytes = r.bytes_()
    commit_sig = r.bytes_()
    commit_bitmap = r.bytes_()
    shard_ids, shard_hashes = [], []
    for _ in range(_checked_count(r)):
        shard_ids.append(r.int_(4))
        shard_hashes.append(r.bytes_())
    return CXReceiptsProof(
        receipts=receipts, header_bytes=header_bytes,
        commit_sig=commit_sig, commit_bitmap=commit_bitmap,
        shard_ids=shard_ids, shard_hashes=shard_hashes,
    )


def decode_cx(blob: bytes) -> CXReceipt:
    r = _Reader(blob)
    return CXReceipt(
        tx_hash=r.bytes_(), sender=r.bytes_(), to=r.bytes_(),
        amount=r.big_(), from_shard=r.int_(4), to_shard=r.int_(4),
        block_num=r.int_(),
    )


def encode_body(block: Block, chain_id: int) -> bytes:
    out = bytearray()
    out += _enc_int(len(block.transactions), 4)
    for tx in block.transactions:
        out += _enc_bytes(encode_tx(tx, chain_id))
    out += _enc_int(len(block.staking_transactions), 4)
    for stx in block.staking_transactions:
        out += _enc_bytes(encode_staking_tx(stx, chain_id))
    out += _enc_int(len(block.incoming_receipts), 4)
    for p in block.incoming_receipts:
        out += _enc_bytes(encode_cx_proof(p))
    out += _enc_int(len(block.execution_order), 4)
    out += bytes(block.execution_order)
    return bytes(out)


def decode_body(blob: bytes):
    r = _Reader(blob)
    txs = [decode_tx(r.bytes_()) for _ in range(_checked_count(r))]
    stxs = [decode_staking_tx(r.bytes_())
            for _ in range(_checked_count(r))]
    cxps = [decode_cx_proof(r.bytes_()) for _ in range(_checked_count(r))]
    order = list(r.raw(_checked_count(r)))
    return txs, stxs, cxps, order


# -- schema accessors -------------------------------------------------------

def _num_key(prefix: bytes, num: int) -> bytes:
    return prefix + num.to_bytes(8, "little")


def write_block(db, block: Block, chain_id: int):
    num = block.block_num
    db.put(_num_key(_HEADER, num), encode_header(block.header))
    db.put(_num_key(_BODY, num), encode_body(block, chain_id))
    db.put(_num_key(_CANON, num), block.hash())
    db.put(_NUM_BY_HASH + block.hash(), num.to_bytes(8, "little"))


def read_block(db, num: int) -> Block | None:
    hdr_blob = db.get(_num_key(_HEADER, num))
    if hdr_blob is None:
        return None
    header = decode_header(hdr_blob)
    body = db.get(_num_key(_BODY, num))
    txs, stxs, cxs, order = (
        decode_body(body) if body else ([], [], [], [])
    )
    return Block(header, txs, stxs, cxs, order)


def read_header(db, num: int) -> Header | None:
    blob = db.get(_num_key(_HEADER, num))
    return decode_header(blob) if blob else None


def read_canonical_hash(db, num: int) -> bytes | None:
    return db.get(_num_key(_CANON, num))


def delete_canonical(db, num: int, w=None):
    """Drop block ``num`` from the canonical chain (revert tooling);
    the hash->number index entry goes with it.  ``w`` is the write
    target (a WriteBatch staging an atomic revert); reads always come
    from ``db``."""
    w = db if w is None else w
    h = db.get(_num_key(_CANON, num))
    if h is not None:
        w.delete(_NUM_BY_HASH + h)
    w.delete(_num_key(_CANON, num))
    w.delete(_num_key(_HEADER, num))
    w.delete(_num_key(_BODY, num))
    w.delete(_num_key(_COMMIT_SIG, num))
    w.delete(_RECEIPTS + _enc_int(num))


def read_block_number(db, block_hash: bytes) -> int | None:
    blob = db.get(_NUM_BY_HASH + block_hash)
    return int.from_bytes(blob, "little") if blob else None


def write_commit_sig(db, num: int, sig_and_bitmap: bytes):
    """reference: BlockChain.WriteCommitSig (consensus/validator.go:
    367-377 reads it back for the last-mile path)."""
    db.put(_num_key(_COMMIT_SIG, num), sig_and_bitmap)


def read_commit_sig(db, num: int) -> bytes | None:
    return db.get(_num_key(_COMMIT_SIG, num))


def write_head_number(db, num: int):
    db.put(_HEAD, num.to_bytes(8, "little"))


def read_head_number(db) -> int | None:
    blob = db.get(_HEAD)
    return int.from_bytes(blob, "little") if blob else None


def write_state(db, root: bytes, state_blob: bytes):
    db.put(_STATE + root, state_blob)


def read_state(db, root: bytes) -> bytes | None:
    return db.get(_STATE + root)


def delete_state(db, root: bytes) -> None:
    """Drop a historical state blob (core/snapshot.py pruning)."""
    db.delete(_STATE + root)


def write_receipts(db, num: int, receipts: list):
    from .types import Receipt  # noqa: F401 — encoded via Receipt.encode

    out = bytearray(_enc_int(len(receipts), 4))
    for rc in receipts:
        out += rc.encode()
        db.put(_RECEIPT_IDX + rc.tx_hash, _enc_int(num))
    db.put(_RECEIPTS + _enc_int(num), bytes(out))


def read_receipt_block_num(db, tx_hash: bytes) -> int | None:
    blob = db.get(_RECEIPT_IDX + tx_hash)
    return int.from_bytes(blob, "little") if blob is not None else None


def read_receipts(db, num: int) -> list:
    from .types import Receipt

    blob = db.get(_RECEIPTS + _enc_int(num))
    if blob is None:
        return []
    r = _Reader(blob)
    return [Receipt.decode(r) for _ in range(_checked_count(r))]


def write_outgoing_cx(db, to_shard: int, num: int, cxs: list):
    out = bytearray(_enc_int(len(cxs), 4))
    for cx in cxs:
        out += _enc_bytes(encode_cx(cx))
    db.put(_CX + to_shard.to_bytes(4, "little") + num.to_bytes(8, "little"),
           bytes(out))


def read_outgoing_cx(db, to_shard: int, num: int) -> list:
    blob = db.get(
        _CX + to_shard.to_bytes(4, "little") + num.to_bytes(8, "little")
    )
    if blob is None:
        return []
    r = _Reader(blob)
    return [decode_cx(r.bytes_()) for _ in range(_checked_count(r))]


def write_cx_spent(db, from_shard: int, num: int, spender: int = 0):
    """Mark a source block's receipt batch consumed on this shard
    (reference: WriteCXReceiptsProofSpent — replaying the same proof in
    a later block must fail as a double spend).  ``spender`` records
    WHICH local block consumed it, so re-inserting that exact block
    (a replay sync over a fast-synced range) stays idempotent."""
    db.put(_CX_SPENT + from_shard.to_bytes(4, "little")
           + num.to_bytes(8, "little"), spender.to_bytes(8, "little"))


def delete_cx_spent(db, from_shard: int, num: int):
    """Un-mark a receipt batch (revert tooling: a reverted block's
    proofs must be acceptable again when the block re-syncs)."""
    db.delete(
        _CX_SPENT + from_shard.to_bytes(4, "little")
        + num.to_bytes(8, "little")
    )


def is_cx_spent(db, from_shard: int, num: int) -> bool:
    return db.get(
        _CX_SPENT + from_shard.to_bytes(4, "little")
        + num.to_bytes(8, "little")
    ) is not None


def cx_spender(db, from_shard: int, num: int) -> int | None:
    """The local block that consumed the batch, or None if unspent
    (legacy b'\\x01' marks read as spender 1 — the localnet DBs that
    predate the field only ever consumed at block 1... treat any
    short value as 'unknown spender', which fails closed)."""
    blob = db.get(
        _CX_SPENT + from_shard.to_bytes(4, "little")
        + num.to_bytes(8, "little")
    )
    if blob is None:
        return None
    if len(blob) != 8:
        return -1  # unknown: never matches a real block num
    return int.from_bytes(blob, "little")


def encode_block(block: Block, chain_id: int) -> bytes:
    """Standalone block blob (gossip ANNOUNCE carries this)."""
    return (
        _enc_bytes(encode_header(block.header))
        + _enc_bytes(encode_body(block, chain_id))
    )


def decode_block(blob: bytes) -> Block:
    r = _Reader(blob)
    header = decode_header(r.bytes_())
    txs, stxs, cxs, order = decode_body(r.bytes_())
    return Block(header, txs, stxs, cxs, order)


# -- shard state (per-epoch committees) -------------------------------------

_SHARD_STATE = b"E"  # E || epoch(8) -> shard state blob


def encode_shard_state(state) -> bytes:
    """shard.committee.State codec (effective stakes carried as raw
    Dec ints; None marks Harmony-operated slots)."""
    out = bytearray()
    out += _enc_int(state.epoch)
    out += _enc_int(len(state.shards), 4)
    for com in state.shards:
        out += _enc_int(com.shard_id, 4)
        out += _enc_int(len(com.slots), 4)
        for s in com.slots:
            out += _enc_bytes(s.ecdsa_address)
            out += _enc_bytes(s.bls_pubkey)
            if s.effective_stake is None:
                out += b"\x00"
            else:
                out += b"\x01" + _enc_big(s.effective_stake.raw)
    return bytes(out)


def decode_shard_state(blob: bytes):
    from ..numeric import Dec
    from ..shard.committee import Committee, Slot, State

    r = _Reader(blob)
    state = State(epoch=r.int_())
    for _ in range(_checked_count(r)):
        com = Committee(shard_id=r.int_(4))
        for _ in range(_checked_count(r)):
            addr = r.bytes_()
            key = r.bytes_()
            has_stake = r.int_(1)
            stake = None
            if has_stake:
                stake = Dec(r.big_())
            com.slots.append(Slot(addr, key, stake))
        state.shards.append(com)
    return state


def write_shard_state(db, epoch: int, state):
    db.put(_num_key(_SHARD_STATE, epoch), encode_shard_state(state))


def read_shard_state(db, epoch: int):
    blob = db.get(_num_key(_SHARD_STATE, epoch))
    return decode_shard_state(blob) if blob else None


# -- durable consensus safety state -----------------------------------------
#
# The last vote each local BLS key signed, written BEFORE the vote
# leaves the node (consensus/safety.py): a restarted validator reloads
# it and can neither double-sign the same (height, view) with a
# different hash nor re-enter a view it already signed past.  The
# reference stores the equivalent in consensus' FBFT log; we keep it in
# the shard DB so kill -9 + reopen recovers it with the chain.

def write_last_signed(db, pubkey: bytes, block_num: int, view_id: int,
                      phase: int, block_hash: bytes):
    db.put(
        _LAST_SIGNED + pubkey,
        block_num.to_bytes(8, "little") + view_id.to_bytes(8, "little")
        + phase.to_bytes(1, "little") + block_hash,
    )


def read_last_signed(db, pubkey: bytes):
    """-> (block_num, view_id, phase, block_hash) or None."""
    blob = db.get(_LAST_SIGNED + pubkey)
    if blob is None or len(blob) < 17:
        return None
    return (
        int.from_bytes(blob[0:8], "little"),
        int.from_bytes(blob[8:16], "little"),
        blob[16],
        blob[17:],
    )


def write_vc_watermark(db, pubkey: bytes, block_num: int, view_id: int):
    """Highest view this key has signed a VIEWCHANGE for (kept apart
    from the vote record: a VC signature must never overwrite the
    memory of WHAT was voted at a view)."""
    db.put(
        _VC_WATERMARK + pubkey,
        block_num.to_bytes(8, "little") + view_id.to_bytes(8, "little"),
    )


def read_vc_watermark(db, pubkey: bytes):
    """-> (block_num, view_id) or None."""
    blob = db.get(_VC_WATERMARK + pubkey)
    if blob is None or len(blob) < 16:
        return None
    return (
        int.from_bytes(blob[0:8], "little"),
        int.from_bytes(blob[8:16], "little"),
    )
