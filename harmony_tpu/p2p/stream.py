"""Sync streams: request/response block download protocol.

The role of the reference's p2p/stream framework (reference:
p2p/stream/protocols/sync/protocol.go:86-177 — protocol id
hmy/sync/<network>/<shard>/<version>; client.go GetBlocksByNumber /
GetBlockHashes; streammanager pooling + requestmanager matching —
SURVEY.md §2.5).  Here a stream is one TCP connection per peer pair;
requests carry ids so responses match out-of-order; the server side
answers from a Blockchain.

Wire: [u32 len][u8 kind][u64 req_id][payload]; kinds are REQ/RESP with
a method byte leading the payload.  Bit 6 of kind marks an optional
trace context: the payload is then prefixed [u8 tc_len][traceparent]
(harmony_tpu.trace binary form) — requests only, responses stay plain.
Untraced clients speak the original wire format unchanged; a traced
client needs a flag-aware server (a pre-flag server drops flagged
requests), so arm tracing fleet-wide, not per node, when mixing
versions.
"""

from __future__ import annotations

import bisect
import socket
import struct
import threading

from .. import faultinject as FI
from .. import trace
from ..core import rawdb
from ..core.types import _enc_bytes, _enc_int
from ..core.types import Reader as _Reader
from ..metrics import Counter

SNAPSHOT_SERVED = Counter(
    "harmony_snapshot_served_total",
    "snapshot responses served to late-joining peers, by method",
)

PROTOCOL_VERSION = 1
_HDR = struct.Struct("<IBQ")
_REQ, _RESP = 1, 2
_TRACE_FLAG = 0x40

METHOD_BLOCK_HASHES = 1    # [u64 start][u32 count] -> [hash...]
METHOD_BLOCKS_BY_NUM = 2   # [u64 start][u32 count] -> [block blob...]
METHOD_HEAD = 3            # [] -> [u64 head][32B hash]
METHOD_EPOCH_STATE = 4     # [u64 epoch] -> [encoded shard state | empty]
METHOD_RECEIPTS = 5        # [u64 start][u32 count] -> per-block receipt blobs
METHOD_ACCOUNT_RANGE = 6   # [u64 block][len-pfx start addr][u32 limit]
#                            -> [u32 n][(addr, account blob)...]
METHOD_SNAPSHOT_META = 7   # [u64 block (0 = latest)] -> empty |
#                            [u64 num][u32 n_pages][u64 state_len]
#                            [len-pfx header][len-pfx commit proof]
METHOD_SNAPSHOT_PAGE = 8   # [u64 block][u32 page] -> empty |
#                            [u32 count][(addr, account blob) pairs]
MAX_BLOCKS_PER_REQUEST = 128   # server-side clamp
MAX_ACCOUNTS_PER_REQUEST = 512  # account-range clamp
MAX_SNAPSHOT_PAGES = 1_000_000   # client-side plausibility bound
MAX_SNAPSHOT_STATE_BYTES = 1 << 30  # client assembles this in memory
# wire plausibility bounds, checked BEFORE any allocation: every
# request is a method byte + a handful of fixed fields (+ one short
# address), and responses are assembled under the soft byte budget
# below — a peer claiming more is feeding garbage and is dropped
MAX_REQUEST_BYTES = 4096
MAX_RESPONSE_BYTES = 32 * 1024 * 1024
RESPONSE_SOFT_BUDGET = 8 * 1024 * 1024  # server stops packing past this


def protocol_id(network: str, shard_id: int) -> str:
    """reference: protocol.go:86 — hmy/sync/<net>/<shard>/<version>."""
    return f"harmony-tpu/sync/{network}/{shard_id}/{PROTOCOL_VERSION}"


def _checked_count(r: _Reader, width: int = 4) -> int:
    """Bounded count for PEER response bodies — Reader.checked_count
    (a forged count must cost its own wire size, never a
    4-billion-iteration decode loop)."""
    return r.checked_count(width)


def decode_snapshot_meta(resp: bytes):
    """Pure decode of a METHOD_SNAPSHOT_META response body (module
    level so the wire-fuzz tier drives it without a socket): ``(num,
    n_pages, state_len, header_blob, proof)``, or None for the empty
    not-serving response.  Both counts are plausibility-bounded BEFORE
    the caller allocates anything against them — a hostile peer's meta
    frame is the root of the whole download budget."""
    if not resp:
        return None
    r = _Reader(resp)
    num = r.int_()
    n_pages = r.int_(4)
    state_len = r.int_()
    if n_pages > MAX_SNAPSHOT_PAGES:
        raise ValueError(
            f"implausible snapshot page count {n_pages}"
        )
    if state_len > MAX_SNAPSHOT_STATE_BYTES:
        raise ValueError(
            f"implausible snapshot state size {state_len}"
        )
    header_blob = r.bytes_()
    proof = r.bytes_()
    return num, n_pages, state_len, header_blob, proof


def decode_snapshot_page(resp: bytes, num: int = 0) -> tuple:
    """Pure decode of a METHOD_SNAPSHOT_PAGE response body:
    ``(account_count, raw pair bytes)``.  The count is bounded by the
    payload the peer actually paid to send; an empty body is the
    protocol's typed not-serving signal (ConnectionError — the
    downloader rotates peers or restarts with fresh meta)."""
    if not resp:
        raise ConnectionError(
            f"peer no longer serves snapshot at block {num}"
        )
    count = int.from_bytes(resp[:4], "little")
    payload = resp[4:]
    if count > len(payload):
        raise ValueError(
            f"implausible snapshot page count {count} with "
            f"{len(payload)} bytes"
        )
    return count, payload


class SyncServer:
    """Serves a chain over the stream protocol.

    Per-connection request rate limiting mirrors the reference's
    stream-layer rate limiter tiers (p2p/stream rate limiting): a
    token bucket refilled at ``rate_per_sec`` with ``burst`` capacity;
    a peer that exceeds it gets throttled, not disconnected (lagging
    nodes catching up are bursty by design)."""

    def __init__(self, chain, listen_port: int = 0,
                 rate_per_sec: float = 200.0, burst: int = 400):
        from ..ratelimit import RateLimiter

        self.chain = chain
        self.limiter = RateLimiter(rate_per_sec, burst)
        # account-range paging cache: one (block num -> sorted account
        # items) entry, so a full state download costs ONE state
        # deserialize + sort instead of one per page (O(N) not
        # O(N^2/limit) in account count)
        self._range_cache: tuple | None = None
        self._range_lock = threading.Lock()
        # snapshot-serving cache: one (num, header blob, proof, state
        # blob, page offsets) entry — the page walk runs once per
        # served block, every page request after that is a slice.
        # Single-entry: concurrent importers at DIFFERENT blocks
        # thrash it (one O(N) rewalk per flip), which is bounded and
        # rare — a late joiner bootstraps once
        self._snap_cache: tuple | None = None
        self._snap_lock = threading.Lock()
        self._closing = False
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", listen_port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, daemon=True,  # graftlint: thread-role=serving
        ).start()

    def _accept_loop(self):
        while not self._closing:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                # graftlint: thread-role=transient — per-connection
                target=self._serve_conn, args=(sock,), daemon=True
            ).start()

    def _serve_conn(self, sock):
        conn_key = str(id(sock))
        try:
            while not self._closing:
                hdr = _recv_exact(sock, _HDR.size)
                if hdr is None:
                    return
                ln, kind, req_id = _HDR.unpack(hdr)
                if ln > MAX_REQUEST_BYTES:
                    return  # implausible request frame: drop the peer
                body = _recv_exact(sock, ln)
                if body is None or (kind & ~_TRACE_FLAG) != _REQ:
                    return
                tc = b""
                if kind & _TRACE_FLAG:
                    if not body or len(body) < 1 + body[0]:
                        return  # truncated trace prefix: drop the conn
                    tc, body = body[1:1 + body[0]], body[1 + body[0]:]
                # back-pressure, not drop: every request consumes a
                # token, waiting for one when the bucket is dry
                self.limiter.wait(conn_key)
                with trace.resume(tc, "p2p.serve", component="p2p",
                                  method=body[0] if body else -1):
                    resp = self._handle(body)
                sock.sendall(_HDR.pack(len(resp), _RESP, req_id) + resp)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _handle(self, body: bytes) -> bytes:
        method = body[0]
        r = _Reader(body[1:])
        if method == METHOD_HEAD:
            head = self.chain.head_number
            return (
                head.to_bytes(8, "little")
                + self.chain.current_header().hash()
            )
        if method == METHOD_EPOCH_STATE:
            epoch = r.int_()
            state = rawdb.read_shard_state(self.chain.db, epoch)
            if state is None:
                return b""
            return rawdb.encode_shard_state(state)
        if method == METHOD_ACCOUNT_RANGE:
            # snap-style state serving (reference: p2p/stream sync
            # client.go GetAccountRange): sorted accounts of the state
            # at a given block, strictly after ``start``, paged by
            # ``limit`` — the fast-sync states stage reads these.
            num = r.int_()
            start_addr = r.bytes_()
            limit = min(r.int_(4), MAX_ACCOUNTS_PER_REQUEST)
            with self._range_lock:
                if self._range_cache and self._range_cache[0] == num:
                    _, keys, everything = self._range_cache
                else:
                    try:
                        state = self.chain.state_at(num)
                    except Exception:  # noqa: BLE001 — peer lacks the
                        # state (e.g. it fast-synced itself); the count
                        # sentinel is distinct from a legitimate empty
                        # page so the client moves on to another peer
                        # instead of adopting nothing
                        return _enc_int(0xFFFFFFFF, 4)
                    everything = [
                        (addr, acct.encode())
                        for addr, acct in state._live_accounts()
                    ]
                    keys = [a for a, _ in everything]
                    self._range_cache = (num, keys, everything)
            lo = bisect.bisect_right(keys, start_addr)
            items = everything[lo:lo + limit]
            body = bytearray()
            n = 0
            for addr, blob in items:
                body += _enc_bytes(addr) + _enc_bytes(blob)
                n += 1
                if len(body) > RESPONSE_SOFT_BUDGET:
                    break  # short page: the client pages onward
            return bytes(_enc_int(n, 4) + body)
        if method == METHOD_SNAPSHOT_META:
            snap = self._snapshot(r.int_())
            if snap is None:
                return b""
            SNAPSHOT_SERVED.inc(method="meta")
            num, header_blob, proof, state_blob, pages = snap
            return (
                num.to_bytes(8, "little")
                + len(pages).to_bytes(4, "little")
                + len(state_blob).to_bytes(8, "little")
                + _enc_bytes(header_blob) + _enc_bytes(proof)
            )
        if method == METHOD_SNAPSHOT_PAGE:
            num = r.int_()
            idx = r.int_(4)
            snap = self._snapshot(num)
            if snap is None or idx >= len(snap[4]):
                return b""  # unknown/stale block or page out of range
            _, _, _, state_blob, pages = snap
            start_off, end_off, n = pages[idx]
            SNAPSHOT_SERVED.inc(method="page")
            return (n.to_bytes(4, "little")
                    + state_blob[start_off:end_off])
        start = r.int_()
        count = min(r.int_(4), MAX_BLOCKS_PER_REQUEST)
        if method == METHOD_BLOCK_HASHES:
            out = bytearray()
            for num in range(start, start + count):
                h = rawdb.read_canonical_hash(self.chain.db, num)
                if h is None:
                    break
                out += h
            return bytes(out)
        if method == METHOD_RECEIPTS:
            # per-block receipt lists (reference: client.go GetReceipts
            # feeding the stagedstreamsync receipts stage)
            blobs = []
            total = 0
            for num in range(start, start + count):
                if num > self.chain.head_number or (
                    total > RESPONSE_SOFT_BUDGET
                ):
                    break
                receipts = rawdb.read_receipts(self.chain.db, num)
                blob = bytearray(_enc_int(len(receipts), 4))
                for rc in receipts:
                    blob += rc.encode()
                blobs.append(bytes(blob))
                total += len(blob)
            out = bytearray(_enc_int(len(blobs), 4))
            for blob in blobs:
                out += _enc_bytes(blob)
            return bytes(out)
        if method == METHOD_BLOCKS_BY_NUM:
            out = bytearray()
            blobs = []
            total = 0
            for num in range(start, start + count):
                block = self.chain.block_by_number(num)
                if block is None or total > RESPONSE_SOFT_BUDGET:
                    break
                blob = (
                    _enc_bytes(rawdb.encode_header(block.header))
                    + _enc_bytes(
                        rawdb.encode_body(block, self.chain.config.chain_id)
                    )
                    + _enc_bytes(self.chain.read_commit_sig(num) or b"")
                )
                blobs.append(blob)
                total += len(blob)
            out += _enc_int(len(blobs), 4)
            for blob in blobs:
                out += _enc_bytes(blob)
            return bytes(out)
        return b""

    def _snapshot(self, num: int) -> tuple | None:
        """The served snapshot at block ``num`` (0 = current head):
        (num, header blob, commit proof, state blob, page offsets), or
        None when the header/state is unknown or pruned.  Pages come
        from core.snapshot.paginate_state over the stored serialized
        state, so serving never deserializes accounts at all."""
        from ..core.snapshot import SnapshotError, paginate_state

        with self._snap_lock:
            if num == 0:
                num = self.chain.head_number
            c = self._snap_cache
            if c is not None and c[0] == num:
                return c
            header = rawdb.read_header(self.chain.db, num)
            if header is None:
                return None
            state_blob = rawdb.read_state(self.chain.db, header.root)
            if state_blob is None:
                return None  # pruned past: client rotates peers
            proof = rawdb.read_commit_sig(self.chain.db, num) or b""
            try:
                pages = paginate_state(state_blob)
            except SnapshotError:
                return None  # damaged local blob: don't serve garbage
            c = (num, rawdb.encode_header(header), proof, state_blob,
                 pages)
            self._snap_cache = c
            return c

    def close(self):
        self._closing = True
        try:
            # wake the blocked accept NOW (a bare close is deferred
            # while another thread sits in accept on this fd)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass


class _PendingReply:
    __slots__ = ("event", "body")

    def __init__(self):
        self.event = threading.Event()
        self.body: bytes | None = None


class SyncClient:
    """One peer's sync stream (reference: sync/client.go).

    Connects LAZILY and reconnects on the next call after a failure:
    peers come up in arbitrary order (a localnet's node 0 boots before
    its neighbour's server exists) and restart across a node's
    lifetime; a sync peer being down is a per-call error for the
    downloader's peer rotation, never a constructor crash.

    Requests are PIPELINED: the protocol already matches responses by
    req_id, so ``_call`` registers a pending slot, sends, and waits on
    its own event while a shared reader thread demultiplexes replies.
    The old design held ``_lock`` across the socket recv (GL06), which
    serialized every concurrent downloader stage behind one in-flight
    request for up to the 30 s timeout — and made ``close`` unable to
    take the lock at all."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 30.0):
        self._addr = (host, port)
        self.peer_key = f"{host}:{port}"  # faultinject/log identity
        self._timeout = timeout
        self._sock: socket.socket | None = None
        self._next_id = 0
        self._lock = threading.Lock()  # connection + id + pending map
        self._send_lock = threading.Lock()  # frame atomicity only
        self._pending: dict[int, _PendingReply] = {}

    def _ensure_connected(self, deadline=None) -> socket.socket:
        """Current socket, dialing lazily — the dial itself (a blocking
        connect with a long timeout) runs with NO lock held; racing
        dialers resolve by the loser closing its spare socket.  The
        caller's deadline bounds the dial too: a peer black-holed at
        connect time costs the request budget, not the stream's full
        default timeout."""
        with self._lock:
            if self._sock is not None:
                return self._sock
        dial_timeout = (self._timeout if deadline is None
                        else deadline.bound(self._timeout))
        if dial_timeout is not None and dial_timeout <= 0:
            raise ConnectionError("sync request deadline exhausted")
        sock = socket.create_connection(self._addr,
                                        timeout=dial_timeout)
        # TCP self-connect quirk: dialing a freed localhost port can
        # land on our own ephemeral port and "succeed" — a dead peer
        # must look dead, not echo our frames back
        if sock.getsockname() == sock.getpeername():
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionError("self-connected socket (peer is down)")
        # blocking mode from here: the reader thread recvs continuously
        # and must survive idle periods; per-call deadlines are enforced
        # by the waiter's event timeout, not the socket
        sock.settimeout(None)
        with self._lock:
            if self._sock is None:
                self._sock = sock
                threading.Thread(
                    # graftlint: thread-role=transient — per-connection
                    target=self._read_loop, args=(sock,), daemon=True
                ).start()
                return sock
            loser, sock = sock, self._sock
        try:
            loser.close()
        except OSError:
            pass
        return sock

    def _read_loop(self, sock):
        """Demultiplex responses to their waiters by req_id."""
        while True:
            hdr = _recv_exact(sock, _HDR.size)
            if hdr is None:
                break
            ln, kind, rid = _HDR.unpack(hdr)
            if ln > MAX_RESPONSE_BYTES:
                break  # implausible frame: drop the stream, fail waiters
            body = _recv_exact(sock, ln)
            if body is None:
                break
            if kind != _RESP:
                continue
            with self._lock:
                slot = self._pending.get(rid)
            if slot is not None:
                slot.body = body
                slot.event.set()
        self._drop(sock)

    def _drop(self, sock):
        """Retire a dead socket and fail every waiter parked on it.
        Only the CURRENT socket's death fails the pending map — a stale
        reader unwinding after a redial must not kill the healthy
        waiters already registered against the new connection."""
        stale: list = []
        with self._lock:
            if self._sock is sock:
                self._sock = None
                stale = list(self._pending.values())
                self._pending.clear()
        for slot in stale:
            slot.event.set()  # body stays None -> waiter raises
        try:
            # shutdown first: a bare close() while the reader thread is
            # blocked in recv is deferred by the kernel (no FIN, reader
            # stays parked); shutdown wakes it with EOF immediately
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _call(self, payload: bytes, deadline=None) -> bytes:
        """One request/response.  ``deadline`` (a resilience.Deadline)
        tightens this call's wait below the stream's default timeout —
        the downloader propagates one budget across a whole stage so a
        black-holed peer costs bounded time, not 30 s per request."""
        FI.fire("p2p.stream", key=self.peer_key)
        with trace.span("p2p.request", component="p2p",
                        peer=self.peer_key,
                        method=payload[0] if payload else -1):
            sock = self._ensure_connected(deadline)
            # the wait budget is re-taken AFTER the dial so a slow
            # connect and the response wait share ONE deadline, not two
            timeout = (self._timeout if deadline is None
                       else deadline.bound(self._timeout))
            if timeout is not None and timeout <= 0:
                raise ConnectionError("sync request deadline exhausted")
            tc = trace.traceparent()
            kind = _REQ | _TRACE_FLAG if tc else _REQ
            wire = (bytes([len(tc)]) + tc + payload) if tc else payload
            with self._lock:
                self._next_id += 1
                req_id = self._next_id
                slot = _PendingReply()
                self._pending[req_id] = slot
            try:
                try:
                    # _send_lock only keeps concurrent frames from
                    # interleaving; the response wait below happens with
                    # NO lock held, so calls overlap on the wire
                    with self._send_lock:
                        sock.sendall(  # graftlint: disable=GL06 frame-atomicity lock, held per send, never across the response wait
                            _HDR.pack(len(wire), kind, req_id) + wire
                        )
                except OSError:
                    self._drop(sock)
                    raise
                if not slot.event.wait(timeout):
                    self._drop(sock)  # wedged peer: fail all, redial
                    raise ConnectionError("sync request timed out")
                if slot.body is None:
                    raise ConnectionError("sync stream closed")
                return slot.body
            finally:
                with self._lock:
                    self._pending.pop(req_id, None)

    def get_head(self, deadline=None) -> tuple[int, bytes]:
        resp = self._call(bytes([METHOD_HEAD]), deadline)
        return int.from_bytes(resp[:8], "little"), resp[8:40]

    def get_block_hashes(self, start: int, count: int,
                         deadline=None) -> list:
        resp = self._call(
            bytes([METHOD_BLOCK_HASHES])
            + start.to_bytes(8, "little") + count.to_bytes(4, "little"),
            deadline,
        )
        return [resp[i:i + 32] for i in range(0, len(resp), 32)]

    def get_blocks_by_number(self, start: int, count: int,
                             deadline=None) -> list:
        """[(Block, commit_sig_or_None)] — the replay feed."""
        resp = self._call(
            bytes([METHOD_BLOCKS_BY_NUM])
            + start.to_bytes(8, "little") + count.to_bytes(4, "little"),
            deadline,
        )
        r = _Reader(resp)
        out = []
        for _ in range(_checked_count(r)):
            item = _Reader(r.bytes_())
            header = rawdb.decode_header(item.bytes_())
            txs, stxs, cxs, order = rawdb.decode_body(item.bytes_())
            sig = item.bytes_()
            from ..core.types import Block

            out.append(
                (Block(header, txs, stxs, cxs, order), sig or None)
            )
        return out

    def get_receipts(self, start: int, count: int, deadline=None) -> list:
        """[[Receipt]] — one list per block from ``start``."""
        from ..core.types import Receipt

        resp = self._call(
            bytes([METHOD_RECEIPTS])
            + start.to_bytes(8, "little") + count.to_bytes(4, "little"),
            deadline,
        )
        r = _Reader(resp)
        out = []
        for _ in range(_checked_count(r)):
            item = _Reader(r.bytes_())
            out.append([Receipt.decode(item)
                        for _ in range(_checked_count(item))])
        return out

    def get_account_range(self, num: int, start_addr: bytes = b"",
                          limit: int = MAX_ACCOUNTS_PER_REQUEST,
                          deadline=None) -> list:
        """[(addr, account blob)] of the remote state at block ``num``,
        strictly after ``start_addr``; page until a short page."""
        resp = self._call(
            bytes([METHOD_ACCOUNT_RANGE]) + num.to_bytes(8, "little")
            + _enc_bytes(start_addr) + limit.to_bytes(4, "little"),
            deadline,
        )
        r = _Reader(resp)
        n = r.int_(4)
        if n == 0xFFFFFFFF:
            raise ConnectionError(f"peer has no state at block {num}")
        if n > len(r.view) - r.off:
            raise ValueError(
                f"implausible account count {n} in sync response"
            )  # same bound as checked_count; n was already consumed
        return [(r.bytes_(), r.bytes_()) for _ in range(n)]

    def get_snapshot_meta(self, num: int = 0, deadline=None):
        """The peer's served snapshot at block ``num`` (0 = its head):
        ``(num, n_pages, state_len, header_blob, proof)`` or None when
        the peer has nothing to serve.  Every count is plausibility-
        bounded BEFORE the caller allocates anything against it — the
        meta frame is the root of the whole download budget."""
        resp = self._call(
            bytes([METHOD_SNAPSHOT_META]) + num.to_bytes(8, "little"),
            deadline,
        )
        return decode_snapshot_meta(resp)

    def get_snapshot_page(self, num: int, idx: int,
                          deadline=None) -> tuple[int, bytes]:
        """Page ``idx`` of the snapshot at block ``num``:
        ``(account_count, raw pair bytes)``.  Raises ConnectionError
        when the peer no longer serves that block (head moved, pruned)
        so the downloader rotates or restarts with fresh meta."""
        resp = self._call(
            bytes([METHOD_SNAPSHOT_PAGE]) + num.to_bytes(8, "little")
            + idx.to_bytes(4, "little"),
            deadline,
        )
        return decode_snapshot_page(resp, num)

    def get_epoch_state(self, epoch: int, deadline=None):
        """The elected shard State recorded for ``epoch`` on the remote
        chain, or None (feeds the beacon EpochChain)."""
        resp = self._call(
            bytes([METHOD_EPOCH_STATE]) + epoch.to_bytes(8, "little"),
            deadline,
        )
        if not resp:
            return None
        return rawdb.decode_shard_state(resp)

    def close(self):
        # retire the socket NOW (null the slot, fail waiters, close the
        # fd) rather than waiting for the reader thread to notice — the
        # very next call must redial, not trip over a dead descriptor
        with self._lock:
            s = self._sock
        if s is not None:
            self._drop(s)


def _recv_exact(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf
