"""Gossip hosts: publish/subscribe over topics.

The role of the reference's p2p.Host (reference: p2p/host.go:59-80 —
AddStreamProtocol, SendMessageToGroups, subscription with per-topic
validators; gossipsub under the hood).  Two implementations:

- ``InProcessNetwork`` + its hosts — a shared hub delivering messages
  synchronously between hosts in one process: the localnet-in-one-
  process test pattern (the reference's consensus tests likewise run
  real hosts on localhost — SURVEY.md §4).
- ``TCPHost`` — flood gossip over TCP with message-id dedup: each
  frame is [u32 len][u8 kind][payload]; PUBLISH payloads carry
  (topic, msg-id, body) and are re-flooded to every peer except the
  arrival peer until the id is seen.  Validators run before re-flood,
  mirroring gossipsub's validate-then-propagate contract
  (p2p/host.go:92-97 registers 8192-concurrency validators).

Message size cap mirrors the reference's 2 MB (p2p/host.go:98-99).
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from collections import OrderedDict

from ..log import get_logger
from ..metrics import Counter, LockedCounters
from ..ref.keccak import keccak256
from .gating import Gater

_log = get_logger("p2p")

MAX_MESSAGE_BYTES = 2 * 1024 * 1024  # reference: p2p/host.go:98-99

# hostile-wire observability (exposed as harmony_p2p_* via
# metrics.Registry): invalid-message verdicts per transport, the
# throttle/drop/ban ladder, and the worst per-peer score ever
# observed per host (a low-water mark — it does not recover when the
# offending peer disconnects or decays back)
P2P_COUNTERS = LockedCounters(
    "invalid_inproc", "invalid_tcp", "throttled", "conns_dropped",
    "ips_banned", "peers_muted",
)
_WORST_LOCK = threading.Lock()
_WORST_SCORE: dict[str, float] = {}  # host name -> worst live peer score

# consensus-bearing inbound accounting (both transports route every
# subscribed delivery through Host._deliver): how many vote-shaped
# messages each node ingests per phase — THE quantity the Handel
# aggregation overlay exists to shrink at the leader (O(log N)
# aggregates vs N ballots).  Labelled family for /metrics; the
# per-host dict feeds the chaos runner's leader_inbound_msgs_per_round
INBOUND_VOTES = Counter(
    "harmony_consensus_inbound_votes_total",
    "consensus vote-bearing messages delivered, by phase and kind",
)

# CONSENSUS-category envelope types (node.ingress MsgType values; the
# envelope layout [category u8][type u8][payload] is peeked here —
# importing node.ingress would cycle, p2p must stay below node)
_CONSENSUS_KINDS = {
    0: ("prepare", "proposal"),   # ANNOUNCE
    1: ("prepare", "ballot"),     # PREPARE
    2: ("prepare", "proof"),      # PREPARED
    3: ("commit", "ballot"),      # COMMIT
    4: ("commit", "proof"),       # COMMITTED
    5: ("viewchange", "vote"),    # VIEWCHANGE
    6: ("viewchange", "proof"),   # NEWVIEW
}
_AGG_PHASES = {1: "prepare", 2: "commit"}


def _classify_inbound(topic: str, payload: bytes):
    """(phase, kind) of a consensus-bearing delivery, else None."""
    if len(payload) < 3:
        return None
    if topic.endswith("/consensus"):
        if payload[0] != 0x00:  # MessageCategory.CONSENSUS
            return None
        return _CONSENSUS_KINDS.get(payload[1])
    if "/aggregation/" in topic:
        if payload[0] != 0x01 or payload[1] != 0x11:  # NODE / AGG
            return None
        # aggregation body leads with its phase discriminant
        return _AGG_PHASES.get(payload[2], "unknown"), "aggregate"
    return None


def _note_score(host_name: str, score: float):
    with _WORST_LOCK:
        cur = _WORST_SCORE.get(host_name, 0.0)
        _WORST_SCORE[host_name] = min(cur, score)
        if len(_WORST_SCORE) > 256:  # cardinality bound
            _WORST_SCORE.pop(next(iter(_WORST_SCORE)))


def worst_peer_scores() -> dict:
    """Snapshot for metrics exposition (harmony_p2p_peer_score)."""
    with _WORST_LOCK:
        return dict(_WORST_SCORE)
_FRAME = struct.Struct("<IB")
_KIND_PUBLISH = 1
_KIND_HELLO = 2
# peer exchange (the reference's discovery rides libp2p's DHT —
# p2p/discovery/discovery.go:41-79 Advertise/FindPeers; this transport
# carries the same contract as explicit frames: each peer ADVERTs its
# dialable address, and PEERS_REQ/RESP gossip known addresses around)
_KIND_ADVERT = 3      # payload: "ip:port" this peer is dialable at
_KIND_PEERS_REQ = 4   # payload: empty, or a 32B routing target (Kad)
_KIND_PEERS_RESP = 5  # payload: "\n"-joined "ip:port" list
# mesh gossip control frames (gossipsub's GRAFT/PRUNE/IHAVE/IWANT
# roles — reference: p2p/host.go:73-99 rides libp2p gossipsub; this
# transport carries the same degree-bounded mesh + lazy pull protocol
# explicitly)
_KIND_SUBS = 6        # payload: "\n"-joined topic list (full set)
_KIND_GRAFT = 7       # payload: topic — add me to your mesh
_KIND_PRUNE = 8       # payload: topic — drop me from your mesh
_KIND_IHAVE = 9       # payload: [u8 tlen][topic][32B mid]*
_KIND_IWANT = 10      # payload: [32B mid]*

# validator verdicts (gossipsub semantics)
ACCEPT = 0
REJECT = 1   # drop and do not propagate
IGNORE = 2   # drop silently (still counts as seen)


class _SeenCache:
    """Bounded message-id dedup."""

    def __init__(self, cap: int = 65536):
        self._d: OrderedDict[bytes, bool] = OrderedDict()
        self.cap = cap
        self._lock = threading.Lock()

    def seen(self, mid: bytes) -> bool:
        """True if already present; marks it present."""
        with self._lock:
            if mid in self._d:
                self._d.move_to_end(mid)
                return True
            self._d[mid] = True
            while len(self._d) > self.cap:
                self._d.popitem(last=False)
            return False

    def forget(self, mid: bytes):
        """Un-mark a message (shed at an overflow, not processed):
        a later re-flood by another peer must still be ingestible."""
        with self._lock:
            self._d.pop(mid, None)

    def has(self, mid: bytes) -> bool:
        """Non-marking membership probe (IHAVE digest filtering)."""
        with self._lock:
            return mid in self._d


class _MsgCache:
    """Recent full messages by id (gossipsub's mcache): serves IWANT
    pulls and feeds the heartbeat's IHAVE digests.  Bounded by count
    and age."""

    def __init__(self, cap: int = 2048, ttl: float = 60.0):
        self._d: OrderedDict[bytes, tuple] = OrderedDict()  # mid->(topic,body,t)
        self.cap = cap
        self.ttl = ttl
        self._lock = threading.Lock()

    def put(self, mid: bytes, topic: str, body: bytes):
        now = time.monotonic()
        with self._lock:
            self._d[mid] = (topic, body, now)
            self._d.move_to_end(mid)
            while len(self._d) > self.cap:
                self._d.popitem(last=False)

    def get(self, mid: bytes) -> bytes | None:
        with self._lock:
            ent = self._d.get(mid)
        if ent is None or time.monotonic() - ent[2] > self.ttl:
            return None
        return ent[1]

    def recent_ids(self, topic: str, window: float = 6.0) -> list:
        """Message ids for ``topic`` seen within the gossip window."""
        cutoff = time.monotonic() - window
        with self._lock:
            return [mid for mid, (t, _, at) in self._d.items()
                    if t == topic and at >= cutoff]

    def recent_topics(self, window: float = 6.0) -> list:
        """Topics with messages inside the gossip window — includes
        topics this host only PUBLISHES to (gossipsub's fanout): a
        publisher that is not itself subscribed must still advertise
        ids, or a message published before the peer's SUBS announcement
        lands is lost forever."""
        cutoff = time.monotonic() - window
        with self._lock:
            return sorted({t for (t, _, at) in self._d.values()
                           if at >= cutoff})


class Host:
    """Common topic/validator bookkeeping for both transports."""

    def __init__(self, name: str = ""):
        self.name = name
        self._handlers: dict[str, list] = {}
        self._validators: dict[str, list] = {}
        # NOTE: message dedup (_SeenCache) lives on TCPHost only — the
        # in-process hub is single-hop, so every delivery is already
        # exactly-once per publish and re-publishes are deliberately
        # fresh messages (the consensus sender's retry semantics)
        self._lock = threading.Lock()
        # (phase, kind) -> count of consensus-bearing deliveries THIS
        # host actually handled (see _classify_inbound)
        self.inbound_votes: dict[tuple, int] = {}
        # target slot -> count of aggregation contributions delivered
        # to that slot's directed topic: a localnet host multiplexes
        # many committee slots, so per-HOST totals bundle rung traffic
        # a real deployment spreads over one machine per slot — the
        # per-slot split is what lets the chaos runner read off the
        # leader slot's (the ladder's hottest target) actual ingest
        self.inbound_agg_slots: dict[int, int] = {}

    # -- subscription API (reference: host.go:66-71) ------------------------

    def subscribe(self, topic: str, handler):
        """handler(topic, payload, from_name)."""
        with self._lock:
            self._handlers.setdefault(topic, []).append(handler)

    def add_validator(self, topic: str, validator):
        """validator(payload, from_name) -> ACCEPT/REJECT/IGNORE."""
        with self._lock:
            self._validators.setdefault(topic, []).append(validator)

    def topics(self) -> list:
        with self._lock:
            return sorted(set(self._handlers) | set(self._validators))

    def _validate(self, topic: str, payload: bytes, frm: str) -> int:
        with self._lock:
            validators = list(self._validators.get(topic, ()))
        for v in validators:
            verdict = v(payload, frm)
            if verdict != ACCEPT:
                return verdict
        return ACCEPT

    def _deliver(self, topic: str, payload: bytes, frm: str):
        with self._lock:
            handlers = list(self._handlers.get(topic, ()))
        if not handlers:
            return  # the in-process hub delivers to every host; only
            #         a SUBSCRIBED host's ingest counts as inbound
        cls = _classify_inbound(topic, payload)
        if cls is not None:
            with self._lock:
                self.inbound_votes[cls] = self.inbound_votes.get(cls, 0) + 1
                if cls[1] == "aggregate":
                    slot = int(topic.rsplit("/", 1)[1])
                    self.inbound_agg_slots[slot] = (
                        self.inbound_agg_slots.get(slot, 0) + 1
                    )
            INBOUND_VOTES.inc(phase=cls[0], kind=cls[1])
        for h in handlers:
            h(topic, payload, frm)

    # -- to implement -------------------------------------------------------

    def publish(self, topic: str, payload: bytes):
        raise NotImplementedError

    def publish_to_groups(self, topics: list, payload: bytes):
        """reference: p2p/host.go:73 SendMessageToGroups."""
        for t in topics:
            self.publish(t, payload)

    def close(self):
        pass


class InProcessNetwork:
    """Hub connecting InProcess hosts (deterministic, synchronous).

    Carries the same invalid-message scoring ladder as TCPHost (the
    gossipsub score function's role) so in-process Byzantine scenarios
    exercise the REAL defense: every REJECT verdict scores the sender
    down; past ``THROTTLE_FLOOR`` only every other message is routed;
    past ``MUTE_FLOOR`` the sender is muted off the hub entirely."""

    THROTTLE_FLOOR = -24.0
    MUTE_FLOOR = -60.0

    def __init__(self):
        self._hosts: list = []
        self._lock = threading.Lock()
        self.partitioned: set = set()  # names cut off (failure injection)
        self.muted: set = set()        # names dropped for spam
        self.scores: dict[str, float] = {}
        self._throttle_ctr: dict[str, int] = {}
        self.invalid_total = 0         # REJECT verdicts observed
        # optional per-directed-link conditioner
        # (chaostest.netem.NetEm): latency/jitter/loss/dup/reorder/
        # rate per (from, to) host pair — None costs one attribute
        # check on the delivery path
        self.netem = None

    def host(self, name: str) -> "_InProcessHost":
        h = _InProcessHost(name, self)
        with self._lock:
            self._hosts.append(h)
        return h

    def remove(self, host) -> None:
        """Detach a host from the hub (a killed node's process would
        take its sockets with it; the in-process analog must stop
        delivering to — and accepting validation verdicts from — the
        dead node's object, or a restart under the same name would
        leave two receivers)."""
        with self._lock:
            self._hosts = [h for h in self._hosts if h is not host]

    def route(self, topic: str, payload: bytes, frm: str):
        if len(payload) > MAX_MESSAGE_BYTES:
            raise ValueError("message exceeds 2 MB cap")
        if frm in self.partitioned:
            return
        with self._lock:
            if frm in self.muted:
                return  # dropped for spam: nothing propagates
            if self.scores.get(frm, 0.0) <= self.THROTTLE_FLOOR:
                # rate-limit tier: a misbehaving-but-not-yet-dropped
                # sender gets every other message routed
                n = self._throttle_ctr.get(frm, 0) + 1
                self._throttle_ctr[frm] = n
                if n % 2:
                    P2P_COUNTERS.inc("throttled")
                    return
            hosts = list(self._hosts)
        # no dedup on the hub: it is single-hop (each publish visits
        # each host exactly once, no multipath to suppress), and
        # content-hash dedup here marked REJECTED messages seen
        # FOREVER — the consensus sender's retry re-publishes (the
        # mechanism that recovers a transiently IGNOREd NEWVIEW) were
        # dead on arrival for ~50 s until cache eviction.  libp2p ids
        # are (sender, seqno): every publish is a fresh message —
        # TCPHost stamps the same semantics into its PUBLISH bodies.
        nm = self.netem
        if nm is not None and not nm.armed:
            nm = None  # disarmed conditioner: skip closures entirely
        for h in hosts:
            if h.name == frm or h.name in self.partitioned:
                continue
            if nm is not None and nm.send(
                frm, h.name, len(payload),
                lambda h=h: self._deliver_one(
                    topic, payload, frm, h, recheck=True
                ),
            ):
                continue  # conditioned: dropped or scheduled
            self._deliver_one(topic, payload, frm, h)

    def _deliver_one(self, topic: str, payload: bytes, frm: str, h,
                     recheck: bool = False):
        """Validate + deliver to ONE host — the hub's per-link
        delivery chokepoint.  ``recheck`` is set by netem-DELAYED
        deliveries only: a message that spent time in flight must
        re-check partition state and host liveness (its destination
        may have been partitioned or killed meanwhile); the inline
        path already checked all of that in ``route`` and keeps its
        lock-free cost."""
        if recheck:
            if frm in self.partitioned or h.name in self.partitioned:
                return
            with self._lock:
                if frm in self.muted or not any(
                    x is h for x in self._hosts
                ):
                    return
        verdict = h._validate(topic, payload, frm)
        if verdict == ACCEPT:
            h._deliver(topic, payload, frm)
        elif verdict == REJECT:
            self._punish(frm, 1)

    def _punish(self, frm: str, rejects: int):
        """Score a sender down for REJECT verdicts (malformed/bogus
        bytes — IGNORE stays free, exactly the TCPHost contract)."""
        P2P_COUNTERS.inc("invalid_inproc", rejects)
        with self._lock:
            self.invalid_total += rejects
            score = self.scores.get(frm, 0.0) - float(rejects)
            self.scores[frm] = score
            if len(self.scores) > 1024:
                self.scores.pop(next(iter(self.scores)))
            mute = score <= self.MUTE_FLOOR and frm not in self.muted
            if mute:
                self.muted.add(frm)
        _note_score(f"hub:{frm}", score)
        if mute:
            P2P_COUNTERS.inc("peers_muted")
            _log.warn("hub peer muted for spam", peer=frm,
                      score=round(score, 1))


class _InProcessHost(Host):
    def __init__(self, name: str, net: InProcessNetwork):
        super().__init__(name)
        self._net = net

    def publish(self, topic: str, payload: bytes):
        self._net.route(topic, payload, self.name)


class TCPHost(Host):
    """Flood gossip over TCP.

    Peers are symmetric: either side connects (``connect``), both ends
    then exchange HELLO (name) and flood PUBLISH frames.  Validation,
    delivery, and re-flood run on a BOUNDED worker pool, decoupled from
    the per-peer reader threads (reference: p2p/host.go:92-99 — the
    8192-slot validate pool; readers must keep draining sockets while a
    validator does pairing work, and a message flood must translate
    into dropped messages + a counter, not unbounded thread growth).

    Peer scoring (the role of gossipsub's score function): every
    validator IGNORE decrements the sender's score; below the floor
    the peer is dropped and its IP banned through the gater.
    """

    VALIDATE_QUEUE_CAP = 8192  # reference: p2p/host.go maxSize
    VALIDATE_WORKERS = 4
    SCORE_FLOOR = -20.0
    THROTTLE_FLOOR = -10.0  # rate-limit tier BEFORE the drop: half of
    #                         a misbehaving peer's messages shed at
    #                         ingress while its score still decays back
    SCORE_DECAY_PER_S = 0.5  # forgiveness rate for honest mistakes
    # mesh degree bounds (gossipsub's D/D_lo/D_hi): eager push goes to
    # at most MESH_D_HI peers per topic; everyone else gets lazy IHAVE
    # digests on the heartbeat — per-node egress stays bounded as the
    # peer set grows (VERDICT r4 #5: the flood hub was O(peers))
    MESH_D = 6
    MESH_D_LO = 4
    MESH_D_HI = 8
    GOSSIP_LAZY = 6          # IHAVE targets per topic per heartbeat
    HEARTBEAT_S = 1.0
    IWANT_MAX = 32           # served per IWANT frame (anti-amplification)
    IHAVE_MAX = 120          # ids per IHAVE digest (fits the 4 KB frame
    #                          cap; a burst bigger than one digest
    #                          drains over successive heartbeats)

    def __init__(self, name: str = "", listen_port: int = 0,
                 gater: Gater | None = None,
                 msg_rate: float = 500.0, msg_burst: int = 1000):
        from ..ratelimit import RateLimiter

        super().__init__(name)
        self.gater = gater or Gater()
        # per-peer ingress rate limit, ahead of the validate pool
        # (reference: the stream-layer limiter tiers; gossipsub's
        # per-peer throttling role): one chatty peer must not own the
        # shared validation queue.  Generous defaults — an N-validator
        # committee's worst honest burst is ~N msgs per phase + the
        # sender retry tails
        self._msg_limiter = RateLimiter(msg_rate, msg_burst)
        self.dropped_rate_limited = 0
        self._peers: dict[object, str] = {}  # socket -> peer name
        self._peer_lock = threading.Lock()
        self._closing = False
        # peer-exchange state: addresses this host knows to be dialable
        # (its own + those ADVERTed by / learned from peers)
        self.known_addrs: dict[str, float] = {}  # "ip:port" -> learned-at
        self._peer_addr: dict[object, str] = {}  # socket -> advertised
        # bounded validation pool + scoring
        self._send_locks: dict[int, threading.Lock] = {}
        self._val_queue: queue.Queue = queue.Queue(self.VALIDATE_QUEUE_CAP)
        self.dropped_overflow = 0  # messages shed at the full queue
        self._score_lock = threading.Lock()
        self._scores: dict[int, tuple[float, float]] = {}  # sockid->(s,at)
        self._throttle_ctr: dict[int, int] = {}  # sockid -> msg counter
        self._ip_strikes: dict[str, int] = {}  # floor hits per address
        # mesh state (under _peer_lock): per-topic eager-push peer sets,
        # per-peer announced topic sets (None until first SUBS =
        # wildcard: eligible everywhere, the bootstrap posture)
        self._mesh: dict[str, set] = {}
        self._peer_topics: dict[object, set | None] = {}
        self._graft_backoff: dict[tuple, float] = {}  # (sockid,topic)->t
        self._mcache = _MsgCache()
        self._seen = _SeenCache()  # flood-dedup: TCP re-floods multipath
        # optional per-directed-link conditioner on the publish path
        # (chaostest.netem.NetEm), keyed (self.name -> peer HELLO name)
        self.netem = None
        # per-publish id salt+counter (stamped into PUBLISH bodies by
        # _pack_publish; salt makes ids unique ACROSS hosts publishing
        # identical payloads)
        import os as _os

        self._pub_salt = _os.urandom(4)
        self._pub_seq = 0
        self._pub_seq_lock = threading.Lock()
        self._iwant_asked: dict[bytes, float] = {}  # mid -> asked-at
        self.sent_publish_frames = 0  # egress accounting (tests/metrics)
        self.sent_ihave_frames = 0
        self.served_iwant = 0
        # liveness watchdog registration (ISSUE 14): the validate pool
        # and the mesh heartbeat are the host's long-lived threads — a
        # wedged validate worker silently eats a share of all gossip
        from .. import health

        self._hbs = []
        for i in range(self.VALIDATE_WORKERS):
            hb = health.register(f"p2p.validate[{name}#{i}]")
            t = threading.Thread(
                # graftlint: thread-role=serving
                target=self._validate_worker, args=(hb,), daemon=True,
                name=f"p2p-validate-{name}-{i}",
            )
            t.start()
            hb.bind(t)
            self._hbs.append(hb)
        mesh_hb = health.register(f"p2p.mesh[{name}]")
        t = threading.Thread(
            # graftlint: thread-role=serving
            target=self._heartbeat_loop, args=(mesh_hb,), daemon=True,
            name=f"p2p-heartbeat-{name}",
        )
        t.start()
        mesh_hb.bind(t)
        self._hbs.append(mesh_hb)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", listen_port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, daemon=True,  # graftlint: thread-role=serving
        ).start()

    # -- wire ---------------------------------------------------------------

    def _send_frame(self, sock, kind: int, payload: bytes):
        # one frame at a time per socket: floods now run on several
        # validate workers, and interleaved sendall would corrupt the
        # length-prefixed framing
        lock = self._send_locks.setdefault(id(sock), threading.Lock())
        with lock:
            sock.sendall(_FRAME.pack(len(payload), kind) + payload)

    @staticmethod
    def _recv_exact(sock, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _accept_loop(self):
        while not self._closing:
            try:
                sock, addr = self._srv.accept()
            except OSError:
                return
            if not self.gater.allow(addr[0]):
                sock.close()
                continue
            threading.Thread(
                # graftlint: thread-role=transient — per-connection
                target=self._peer_loop, args=(sock, addr[0]), daemon=True
            ).start()

    def connect(self, port: int, host: str = "127.0.0.1"):
        sock = socket.create_connection((host, port), timeout=10)
        if not self.gater.allow(host):
            sock.close()
            raise ConnectionError("gater refused outbound peer")
        threading.Thread(
            # graftlint: thread-role=transient — per-connection
            target=self._peer_loop, args=(sock, host), daemon=True
        ).start()

    def _peer_loop(self, sock, ip: str):
        try:
            self._send_frame(sock, _KIND_HELLO, self.name.encode())
            hdr = self._recv_exact(sock, _FRAME.size)
            if hdr is None:
                return
            ln, kind = _FRAME.unpack(hdr)
            if kind != _KIND_HELLO or ln > 256:
                return
            peer_name = (self._recv_exact(sock, ln) or b"").decode()
            with self._peer_lock:
                self._peers[sock] = peer_name
            _log.info(
                "peer connected", me=self.name, peer=peer_name, ip=ip
            )
            # advertise our own dialable address for peer exchange,
            # then announce subscribed topics (mesh eligibility)
            self._send_frame(
                sock, _KIND_ADVERT, f"127.0.0.1:{self.port}".encode()
            )
            self._send_frame(
                sock, _KIND_SUBS, "\n".join(self.topics()).encode()
            )
            while not self._closing:
                hdr = self._recv_exact(sock, _FRAME.size)
                if hdr is None:
                    return
                ln, kind = _FRAME.unpack(hdr)
                if ln > MAX_MESSAGE_BYTES + 4096:
                    return  # oversized: drop the peer
                body = self._recv_exact(sock, ln)
                if body is None:
                    return
                if kind == _KIND_PUBLISH:
                    self._on_publish(body, sock, peer_name, ip)
                elif kind == _KIND_ADVERT and ln <= 64:
                    addr = body.decode(errors="replace")
                    with self._peer_lock:
                        self._peer_addr[sock] = addr
                        self._remember_addr(addr, time.monotonic())
                elif kind == _KIND_PEERS_REQ:
                    with self._peer_lock:
                        known = list(self.known_addrs)
                    known.append(f"127.0.0.1:{self.port}")
                    if ln == 32:
                        # routed lookup (the Kad FIND_NODE contract):
                        # serve the K known addresses CLOSEST to the
                        # target by XOR distance of keccak(addr)
                        target = int.from_bytes(body, "big")
                        known.sort(key=lambda a: int.from_bytes(
                            keccak256(a.encode()), "big") ^ target)
                        addrs = known[:16]
                    else:
                        addrs = known[:32]
                    self._send_frame(
                        sock, _KIND_PEERS_RESP, "\n".join(addrs).encode()
                    )
                elif kind == _KIND_PEERS_RESP and ln <= 4096:
                    now = time.monotonic()
                    with self._peer_lock:
                        for addr in body.decode(errors="replace").split("\n"):
                            if addr and addr.count(":") == 1:
                                self._remember_addr(addr, now)
                elif kind == _KIND_SUBS and ln <= 4096:
                    topics = set(
                        t for t in body.decode(errors="replace").split("\n")
                        if t
                    )
                    with self._peer_lock:
                        self._peer_topics[sock] = topics
                        # a peer that unsubscribed leaves those meshes
                        for t, mesh in self._mesh.items():
                            if t not in topics:
                                mesh.discard(sock)
                elif kind == _KIND_GRAFT and ln <= 256:
                    self._on_graft(sock, body.decode(errors="replace"))
                elif kind == _KIND_PRUNE and ln <= 256:
                    with self._peer_lock:
                        self._mesh.get(
                            body.decode(errors="replace"), set()
                        ).discard(sock)
                        self._graft_backoff[
                            (id(sock), body.decode(errors="replace"))
                        ] = time.monotonic() + 30.0
                elif kind == _KIND_IHAVE and ln <= 4096:
                    self._on_ihave(sock, body)
                elif kind == _KIND_IWANT and ln <= 4096:
                    self._on_iwant(sock, body)
        except OSError:
            pass
        finally:
            with self._peer_lock:
                dropped = self._peers.pop(sock, None)
                self._peer_addr.pop(sock, None)
                self._peer_topics.pop(sock, None)
                for mesh in self._mesh.values():
                    mesh.discard(sock)
                live = {id(s) for s in self._peers}
            self._send_locks.pop(id(sock), None)
            self._msg_limiter.drop(str(id(sock)))
            with self._score_lock:
                self._scores.pop(id(sock), None)
                self._throttle_ctr.pop(id(sock), None)
            # an in-flight flood can setdefault a lock back after the
            # pop above; prune stale ids when churn accumulates them
            if len(self._send_locks) > 2 * len(live) + 16:
                for sid in list(self._send_locks):
                    if sid not in live:
                        self._send_locks.pop(sid, None)
            if dropped is not None and not self._closing:
                _log.info("peer disconnected", me=self.name, peer=dropped)
            self.gater.release(ip)
            try:
                sock.close()
            except OSError:
                pass

    # -- gossip -------------------------------------------------------------

    def _pack_publish(self, topic: str, payload: bytes) -> bytes:
        """[8B publish id][u8 tlen][topic][payload].  The publish id
        (4B per-host salt + 4B counter) is stamped at ORIGIN and rides
        the body through every re-flood, so the derived message id
        keccak256(body) stays identical network-wide (loop prevention
        intact) while a RE-PUBLISH of the same payload — the consensus
        sender's retry, the mechanism that recovers a transiently
        IGNOREd NEWVIEW — gets a fresh id instead of dying forever in
        every peer's seen-cache (libp2p's (sender, seqno) message-id
        semantics; the in-process hub got the same fix)."""
        t = topic.encode()
        with self._pub_seq_lock:
            self._pub_seq += 1
            seq = self._pub_seq
        return (self._pub_salt + (seq & 0xFFFFFFFF).to_bytes(4, "big")
                + bytes([len(t)]) + t + payload)

    def _on_publish(self, body: bytes, src_sock, frm: str, ip: str):
        # keyed on CONNECTION identity, like the scores: a spoofed
        # HELLO name must not drain an honest peer's bucket
        if not self._msg_limiter.allow(str(id(src_sock))):
            with self._score_lock:
                self.dropped_rate_limited += 1
            return  # NOT marked seen: another (slower) peer may relay
        now = time.monotonic()
        with self._score_lock:
            throttled = False
            ent = self._scores.get(id(src_sock))
            if ent is not None:
                # apply the forgiveness decay on the READ path too —
                # a peer that stopped misbehaving must throttle out of
                # the tier by time alone, not by misbehaving again
                score, at = ent
                score = min(
                    0.0, score + (now - at) * self.SCORE_DECAY_PER_S
                )
                self._scores[id(src_sock)] = (score, now)
                if score <= self.THROTTLE_FLOOR:
                    # throttle tier: a peer feeding garbage loses half
                    # its ingress before the score floor drops it
                    n = self._throttle_ctr.get(id(src_sock), 0) + 1
                    self._throttle_ctr[id(src_sock)] = n
                    throttled = bool(n % 2)
        if throttled:
            P2P_COUNTERS.inc("throttled")
            return
        mid = keccak256(body)
        if self._seen.seen(mid):
            return
        try:
            self._val_queue.put_nowait((body, src_sock, frm, ip, mid))
        except queue.Full:
            # DoS economy: shed load here, count it, keep reading —
            # and un-mark the id so another peer's re-flood of the
            # same message stays ingestible after the burst
            self._seen.forget(mid)
            with self._score_lock:
                self.dropped_overflow += 1

    def _validate_worker(self, hb):
        while not self._closing:
            hb.beat()
            try:
                body, src_sock, frm, ip, mid = self._val_queue.get(
                    timeout=0.5
                )
            except queue.Empty:
                continue
            try:
                # [8B publish id][u8 tlen][topic][payload]
                tlen = body[8]
                topic = body[9:9 + tlen].decode()
                payload = body[9 + tlen:]
                verdict = self._validate(topic, payload, frm)
            except Exception:  # noqa: BLE001 — malformed frame
                verdict = REJECT
            if verdict == REJECT:
                # gossipsub semantics: only REJECT (malformed/bogus
                # bytes) is punishable; IGNORE is routine filtering
                # (role-bound types, stale views) and must cost the
                # sender nothing
                self._punish(ip, src_sock)
                continue
            if verdict != ACCEPT:
                continue
            try:
                if topic in self._handlers:
                    self._deliver(topic, payload, frm)
                # validate-then-propagate: eager push to the topic mesh
                # only; everyone else learns the id from the heartbeat's
                # IHAVE digest and pulls on demand
                self._mcache.put(mid, topic, body)
                self._mesh_push(topic, body, exclude=src_sock)
            except Exception:  # noqa: BLE001 — a raising subscriber
                # must not kill the pool (4 such and the host goes
                # permanently deaf); surface it and move on
                _log.error(
                    "gossip handler raised", me=self.name, topic=topic,
                )

    # distinct connections from one IP that must hit the score floor
    # before the IP itself is gater-banned (ADVICE r4: a single bad
    # connection must not collaterally ban every honest peer behind a
    # shared address — the localnet's 127.0.0.1, NAT'd topologies)
    IP_BAN_STRIKES = 3

    def _punish(self, ip: str, sock):
        """Score the CONNECTION down for a rejected message; at the
        floor, drop THAT connection (the per-peer ban — gossipsub
        scoring's role, on the flood topology).  The IP-level gater ban
        is reserved for repeated offenses across distinct connections,
        and never applied to loopback, so shared-IP peers aren't
        collaterally refused."""
        now = time.monotonic()
        P2P_COUNTERS.inc("invalid_tcp")
        with self._score_lock:
            score, at = self._scores.get(id(sock), (0.0, now))
            score = min(
                0.0, score + (now - at) * self.SCORE_DECAY_PER_S
            ) - 1.0
            self._scores[id(sock)] = (score, now)
        _note_score(self.name or "tcp", score)
        if score <= self.SCORE_FLOOR:
            with self._score_lock:
                self._scores.pop(id(sock), None)
                self._throttle_ctr.pop(id(sock), None)
                strikes = self._ip_strikes.get(ip, 0) + 1
                self._ip_strikes[ip] = strikes
            P2P_COUNTERS.inc("conns_dropped")
            loopback = ip.startswith("127.") or ip in ("::1", "localhost")
            if strikes >= self.IP_BAN_STRIKES and not loopback:
                _log.warn(
                    "ip banned for repeated spam", me=self.name, ip=ip,
                    strikes=strikes,
                )
                P2P_COUNTERS.inc("ips_banned")
                self.gater.ban(ip)
            else:
                _log.warn(
                    "peer connection dropped for spam", me=self.name,
                    ip=ip, score=round(score, 1), strikes=strikes,
                )
            try:
                sock.close()  # reader thread unwinds and releases
            except OSError:
                pass

    # -- mesh ---------------------------------------------------------------

    def subscribe(self, topic: str, handler):
        """Subscribe + announce the topic to every peer (mesh
        eligibility rides SUBS announcements)."""
        super().subscribe(topic, handler)
        self._announce_subs()

    def add_validator(self, topic: str, validator):
        super().add_validator(topic, validator)
        self._announce_subs()

    def _announce_subs(self):
        subs = "\n".join(self.topics()).encode()
        with self._peer_lock:
            socks = list(self._peers)
        for s in socks:
            try:
                self._send_frame(s, _KIND_SUBS, subs)
            except OSError:
                pass

    def _eligible(self, topic: str, sock) -> bool:
        """Caller holds _peer_lock: peer announced the topic, or has
        not announced anything yet (wildcard bootstrap posture)."""
        topics = self._peer_topics.get(sock)
        return topics is None or topic in topics

    def _mesh_peers(self, topic: str) -> list:
        """Current mesh for ``topic``, built on first use from eligible
        peers (caller does NOT hold _peer_lock)."""
        with self._peer_lock:
            mesh = self._mesh.setdefault(topic, set())
            mesh.intersection_update(self._peers)
            if not mesh:
                import random

                cands = [s for s in self._peers
                         if self._eligible(topic, s)]
                random.shuffle(cands)
                mesh.update(cands[: self.MESH_D])
            return list(mesh)

    def _mesh_push(self, topic: str, body: bytes, exclude=None):
        """The TCPHost publish path — netem-conditioned per directed
        (self -> peer) link when a conditioner is installed (publish
        AND re-flood both funnel through here; IWANT repair serves
        from the mcache unconditioned, like a retransmit)."""
        nm = self.netem
        if nm is not None and not nm.armed:
            nm = None  # disarmed conditioner: skip closures entirely
        peers = self._mesh_peers(topic)
        names = {}
        if nm is not None:
            with self._peer_lock:
                # a mesh peer whose HELLO name is somehow unknown
                # (drop racing this snapshot) conditions as "?": a
                # wildcard rule — a total partition — still applies;
                # only name-specific rules need the identity
                names = {id(s): self._peers.get(s) or "?"
                         for s in peers}
        for s in peers:
            if s is exclude:
                continue
            if nm is not None and nm.send(
                self.name, names.get(id(s), "?"), len(body),
                lambda s=s: self._send_publish(s, body),
            ):
                continue  # conditioned: dropped or scheduled
            self._send_publish(s, body)

    def _send_publish(self, s, body: bytes):
        try:
            self._send_frame(s, _KIND_PUBLISH, body)
            self.sent_publish_frames += 1
        except OSError:
            pass

    def _on_graft(self, sock, topic: str):
        with self._peer_lock:
            if sock not in self._peers or not self._eligible(topic, sock):
                return
            mesh = self._mesh.setdefault(topic, set())
            if sock in mesh:
                return
            if len(mesh) >= self.MESH_D_HI:
                over = True
            else:
                mesh.add(sock)
                over = False
        if over:
            try:
                self._send_frame(sock, _KIND_PRUNE, topic.encode())
            except OSError:
                pass

    def _on_ihave(self, sock, body: bytes):
        """Lazy pull: request messages we have not seen.  ``_seen`` is
        NOT marked — the full message arrives as a normal PUBLISH."""
        if not body:
            return
        tlen = body[0]
        mids_raw = body[1 + tlen:]
        now = time.monotonic()
        want = []
        for i in range(0, len(mids_raw) - 31, 32):
            mid = mids_raw[i:i + 32]
            asked = self._iwant_asked.get(mid, 0.0)
            if now - asked < 2.0:
                continue  # an earlier IWANT is in flight
            if not self._seen.has(mid):
                want.append(mid)
            if len(want) >= self.IWANT_MAX:
                break  # the rest re-appears in the next digest
        # only the ids actually REQUESTED get the in-flight stamp —
        # stamping the overflow too would back it off for 2 s without
        # any request in flight, stretching burst recovery
        for mid in want:
            self._iwant_asked[mid] = now
        if len(self._iwant_asked) > 4096:
            cutoff = now - 10.0
            self._iwant_asked = {
                m: t for m, t in self._iwant_asked.items() if t > cutoff
            }
        if want:
            try:
                self._send_frame(sock, _KIND_IWANT, b"".join(want))
            except OSError:
                pass

    def _on_iwant(self, sock, body: bytes):
        served = 0
        for i in range(0, len(body) - 31, 32):
            if served >= self.IWANT_MAX:
                break
            cached = self._mcache.get(body[i:i + 32])
            if cached is None:
                continue
            try:
                self._send_frame(sock, _KIND_PUBLISH, cached)
                self.sent_publish_frames += 1
                self.served_iwant += 1
                served += 1
            except OSError:
                return

    def _heartbeat_loop(self, hb):
        import random

        while not self._closing:
            hb.beat()
            time.sleep(self.HEARTBEAT_S)
            try:
                self._heartbeat(random)
            except Exception:  # noqa: BLE001 — keep the mesh alive
                _log.error("heartbeat failed", me=self.name)

    def _heartbeat(self, random):
        """Mesh maintenance + lazy gossip (gossipsub heartbeat): keep
        every subscribed topic's mesh within [D_LO, D_HI], and send
        IHAVE digests of recent messages to a few non-mesh peers.

        Digests cover subscribed topics AND fanout topics (recently
        published, not subscribed): a proposer publishing into a topic
        it does not consume must still heal peers that missed the eager
        push — e.g. when the publish raced the peer's SUBS announcement
        and the mesh view was still empty."""
        now = time.monotonic()
        # snapshot subscriptions and the message cache BEFORE taking
        # _peer_lock: topics() and recent_ids() take their own locks,
        # and nesting them under _peer_lock put undeclared edges in the
        # whole-program lock-order graph (GL05) for zero benefit — both
        # reads are advisory for this round
        subscribed = self.topics()
        gossip_topics = sorted(
            set(subscribed) | set(self._mcache.recent_topics())
        )
        recent = {t: self._mcache.recent_ids(t) for t in gossip_topics}
        grafts, prunes, gossip = [], [], []
        with self._peer_lock:
            for topic in subscribed:
                mesh = self._mesh.setdefault(topic, set())
                mesh.intersection_update(self._peers)
                cands = [
                    s for s in self._peers
                    if s not in mesh and self._eligible(topic, s)
                    and self._graft_backoff.get((id(s), topic), 0) < now
                ]
                if len(mesh) < self.MESH_D_LO and cands:
                    random.shuffle(cands)
                    add = cands[: self.MESH_D - len(mesh)]
                    mesh.update(add)
                    grafts += [(s, topic) for s in add]
                elif len(mesh) > self.MESH_D_HI:
                    drop = random.sample(
                        sorted(mesh, key=id), len(mesh) - self.MESH_D
                    )
                    for s in drop:
                        mesh.discard(s)
                    prunes += [(s, topic) for s in drop]
            for topic in gossip_topics:
                mids = recent.get(topic) or []
                if mids:
                    # IHAVE digests go to a random sample of ALL
                    # eligible peers — mesh members included, so a
                    # freshly-grafted peer (a partition bridge) still
                    # learns ids it missed; digests are tiny and
                    # already-seen ids cost the receiver nothing
                    targets = [s for s in self._peers
                               if self._eligible(topic, s)]
                    random.shuffle(targets)
                    t = topic.encode()
                    frame = (bytes([len(t)]) + t
                             + b"".join(mids[-self.IHAVE_MAX:]))
                    gossip += [
                        (s, frame) for s in targets[: self.GOSSIP_LAZY]
                    ]
            if len(self._graft_backoff) > 4096:
                self._graft_backoff = {
                    k: t for k, t in self._graft_backoff.items() if t > now
                }
        for s, topic in grafts:
            try:
                self._send_frame(s, _KIND_GRAFT, topic.encode())
            except OSError:
                pass
        for s, topic in prunes:
            try:
                self._send_frame(s, _KIND_PRUNE, topic.encode())
            except OSError:
                pass
        for s, frame in gossip:
            try:
                self._send_frame(s, _KIND_IHAVE, frame)
                self.sent_ihave_frames += 1
            except OSError:
                pass

    def publish(self, topic: str, payload: bytes):
        if len(payload) > MAX_MESSAGE_BYTES:
            raise ValueError("message exceeds 2 MB cap")
        body = self._pack_publish(topic, payload)
        mid = keccak256(body)
        self._seen.seen(mid)  # don't re-deliver to self
        self._mcache.put(mid, topic, body)
        self._mesh_push(topic, body)

    _KNOWN_ADDRS_CAP = 256

    def _remember_addr(self, addr: str, now: float):
        """Bounded peer-address store (caller holds _peer_lock): a
        hostile peer flooding fabricated addresses must not grow
        memory — oldest entries rotate out."""
        if addr in self.known_addrs:
            return
        while len(self.known_addrs) >= self._KNOWN_ADDRS_CAP:
            self.known_addrs.pop(next(iter(self.known_addrs)))
        self.known_addrs[addr] = now

    def request_peers(self, target: bytes = b""):
        """Ask every connected peer for known addresses (PEX pull).
        With a 32-byte ``target``, peers answer with their closest-K
        by XOR distance instead (the Kad FIND_NODE contract) —
        iterative lookups converge on any region of the id space.
        Responses land asynchronously in ``known_addrs``."""
        with self._peer_lock:
            socks = list(self._peers)
        for s in socks:
            try:
                self._send_frame(s, _KIND_PEERS_REQ, target)
            except OSError:
                pass

    def connected_addrs(self) -> set:
        """Advertised addresses of currently-connected peers."""
        with self._peer_lock:
            return set(self._peer_addr.values())

    def peer_count(self) -> int:
        with self._peer_lock:
            return len(self._peers)

    def wait_for_peers(self, n: int, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.peer_count() >= n:
                return True
            time.sleep(0.01)
        return False

    def close(self):
        self._closing = True
        for hb in getattr(self, "_hbs", ()):
            hb.close()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._peer_lock:
            socks = list(self._peers)
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
