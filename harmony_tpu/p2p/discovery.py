"""Peer discovery: PEX maintenance loop + bootnode entry point.

The reference's discovery service wraps libp2p's Kademlia DHT —
Advertise() announces the node under its shard topic and FindPeers()
streams candidates back (reference: p2p/discovery/discovery.go:41-79),
with bootnodes as the DHT's entry points (cmd/bootnode/main.go).  This
transport keeps the same contract with an explicit peer-exchange
protocol on the TCP flood host (p2p/host.py ADVERT/PEERS_REQ frames):

* every connection ADVERTs its dialable address;
* ``Discovery`` periodically pulls peer lists (PEX) and dials unknown
  addresses until ``target_peers`` connections are live;
* a bootnode is just a Discovery-running host with no consensus stack —
  it learns every ADVERT and answers every PEERS_REQ, seeding the mesh.

All dials go through the host's Gater (p2p/gating.py), so banned /
rate-limited addresses stay unreachable exactly as for inbound peers.
"""

from __future__ import annotations

import threading
import time

from ..log import get_logger
from .host import TCPHost

_log = get_logger("discovery")


class Discovery:
    """PEX maintenance loop for one host."""

    def __init__(self, host: TCPHost, bootnodes: list | None = None,
                 target_peers: int = 8, interval: float = 2.0):
        self.host = host
        self.bootnodes = list(bootnodes or [])
        self.target_peers = target_peers
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.dials = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Discovery":
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    # -- the loop -----------------------------------------------------------

    def _my_addr(self) -> str:
        return f"127.0.0.1:{self.host.port}"

    def _dial(self, addr: str) -> bool:
        host_part, _, port_part = addr.rpartition(":")
        try:
            self.host.connect(int(port_part), host_part or "127.0.0.1")
            self.dials += 1
            return True
        except (OSError, ValueError, ConnectionError):
            return False

    def step(self):
        """One maintenance round (callable directly from tests)."""
        if self.host.peer_count() == 0 and self.bootnodes:
            for b in self.bootnodes:
                self._dial(b)
        if self.host.peer_count() >= self.target_peers:
            return
        # pull fresh addresses, then dial the ones we are not holding a
        # connection to (self excluded)
        self.host.request_peers()
        connected = self.host.connected_addrs()
        me = self._my_addr()
        for addr in list(self.host.known_addrs):
            if self.host.peer_count() >= self.target_peers:
                break
            if addr == me or addr in connected or addr in self.bootnodes:
                continue
            if self._dial(addr):
                _log.info("pex dial", me=me, peer=addr)
                # one dial per step per address; connection handshake
                # (HELLO+ADVERT) lands asynchronously
                connected.add(addr)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — keep discovering
                _log.warn("discovery step failed", err=str(e))
            self._stop.wait(self.interval)


def run_bootnode(port: int = 9876, name: str = "bootnode") -> TCPHost:
    """The bootnode entry point (reference: cmd/bootnode/main.go): a
    bare host whose only job is to accumulate ADVERTs and answer
    PEERS_REQs.  Returns the listening host."""
    host = TCPHost(name=name, listen_port=port)
    _log.info("bootnode listening", port=host.port)
    return host


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harmony-tpu bootnode")
    p.add_argument("--port", type=int, default=9876)
    args = p.parse_args(argv)
    host = run_bootnode(args.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        host.close()


if __name__ == "__main__":
    main()
