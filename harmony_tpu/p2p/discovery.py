"""Peer discovery: PEX maintenance loop + bootnode entry point.

The reference's discovery service wraps libp2p's Kademlia DHT —
Advertise() announces the node under its shard topic and FindPeers()
streams candidates back (reference: p2p/discovery/discovery.go:41-79),
with bootnodes as the DHT's entry points (cmd/bootnode/main.go).  This
transport keeps the same contract with an explicit peer-exchange
protocol on the TCP flood host (p2p/host.py ADVERT/PEERS_REQ frames):

* every connection ADVERTs its dialable address;
* ``Discovery`` periodically pulls peer lists (PEX) and dials unknown
  addresses until ``target_peers`` connections are live;
* a bootnode is just a Discovery-running host with no consensus stack —
  it learns every ADVERT and answers every PEERS_REQ, seeding the mesh.

All dials go through the host's Gater (p2p/gating.py), so banned /
rate-limited addresses stay unreachable exactly as for inbound peers.
"""

from __future__ import annotations

import os
import threading
import time

from ..log import get_logger
from ..ref.keccak import keccak256
from .host import TCPHost

_log = get_logger("discovery")


class RoutingTable:
    """Kademlia-style k-buckets over peer ADDRESSES (node id =
    keccak(addr)): known peers sorted into 256 buckets by XOR-distance
    prefix from our own id, k entries per bucket.  Guarantees the
    stored view spans the WHOLE id space instead of clustering around
    whoever answered PEX first — the property that makes iterative
    closest-first lookups converge in O(log N) steps (the role of
    libp2p's dht routing table under reference:
    p2p/discovery/discovery.go:41-79)."""

    K = 16

    def __init__(self, my_addr: str):
        self.my_id = int.from_bytes(keccak256(my_addr.encode()), "big")
        self._buckets: list[list] = [[] for _ in range(256)]
        self._lock = threading.Lock()

    @staticmethod
    def _id(addr: str) -> int:
        return int.from_bytes(keccak256(addr.encode()), "big")

    def _bucket_of(self, addr: str) -> int:
        d = self._id(addr) ^ self.my_id
        return d.bit_length() - 1 if d else 0

    def add(self, addr: str) -> bool:
        """Insert (LRU within the bucket); full buckets evict the
        oldest entry (no liveness ping on this transport — PEX entries
        are refreshed every pull)."""
        with self._lock:
            b = self._buckets[self._bucket_of(addr)]
            if addr in b:
                b.remove(addr)
            elif len(b) >= self.K:
                b.pop(0)
            b.append(addr)
            return True

    def remove(self, addr: str):
        with self._lock:
            b = self._buckets[self._bucket_of(addr)]
            if addr in b:
                b.remove(addr)

    def closest(self, target: bytes, k: int = K) -> list:
        t = int.from_bytes(target, "big")
        with self._lock:
            allv = [a for b in self._buckets for a in b]
        allv.sort(key=lambda a: self._id(a) ^ t)
        return allv[:k]

    def random_target(self) -> bytes:
        """A uniformly random id — refresh lookups probe the sparse
        regions the PEX gossip never reaches organically."""
        return os.urandom(32)

    def __len__(self):
        with self._lock:
            return sum(len(b) for b in self._buckets)


class Discovery:
    """Routed discovery: k-bucket table + PEX pulls + iterative
    random-target lookups, dialing toward ``target_peers``."""

    def __init__(self, host: TCPHost, bootnodes: list | None = None,
                 target_peers: int = 8, interval: float = 2.0):
        self.host = host
        self.bootnodes = list(bootnodes or [])
        self.target_peers = target_peers
        self.interval = interval
        self.table = RoutingTable(f"127.0.0.1:{host.port}")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.dials = 0
        self._rounds = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Discovery":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
        )  # graftlint: thread-role=serving
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    # -- the loop -----------------------------------------------------------

    def _my_addr(self) -> str:
        return f"127.0.0.1:{self.host.port}"

    def _dial(self, addr: str) -> bool:
        host_part, _, port_part = addr.rpartition(":")
        try:
            self.host.connect(int(port_part), host_part or "127.0.0.1")
            self.dials += 1
            return True
        except (OSError, ValueError, ConnectionError):
            return False

    def step(self):
        """One maintenance round (callable directly from tests)."""
        me = self._my_addr()
        if self.host.peer_count() == 0 and self.bootnodes:
            for b in self.bootnodes:
                self._dial(b)
        # fold everything the host has learned into the k-buckets
        for addr in list(self.host.known_addrs):
            if addr != me:
                self.table.add(addr)
        if self.host.peer_count() >= self.target_peers:
            # table refresh only: a routed lookup toward a random
            # region every few rounds keeps bucket coverage broad
            self._rounds += 1
            if self._rounds % 4 == 0:
                self.host.request_peers(self.table.random_target())
            return
        # below target: plain PEX pull + a routed lookup toward our own
        # id (closest-first fills our nearest buckets — the peers best
        # placed to answer future lookups for us)
        self.host.request_peers()
        self.host.request_peers(
            keccak256(me.encode())
        )
        connected = self.host.connected_addrs()
        # dial closest-first from the routing table: deterministic
        # convergence instead of whatever order PEX happened to learn
        for addr in self.table.closest(keccak256(me.encode()), k=64):
            if self.host.peer_count() >= self.target_peers:
                break
            if addr == me or addr in connected or addr in self.bootnodes:
                continue
            if self._dial(addr):
                _log.info("pex dial", me=me, peer=addr)
                # one dial per step per address; connection handshake
                # (HELLO+ADVERT) lands asynchronously
                connected.add(addr)
            else:
                self.table.remove(addr)  # dead address: drop the entry

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — keep discovering
                _log.warn("discovery step failed", err=str(e))
            self._stop.wait(self.interval)


def run_bootnode(port: int = 9876, name: str = "bootnode") -> TCPHost:
    """The bootnode entry point (reference: cmd/bootnode/main.go): a
    bare host whose only job is to accumulate ADVERTs and answer
    PEERS_REQs.  Returns the listening host."""
    host = TCPHost(name=name, listen_port=port)
    _log.info("bootnode listening", port=host.port)
    return host


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="harmony-tpu bootnode")
    p.add_argument("--port", type=int, default=9876)
    args = p.parse_args(argv)
    host = run_bootnode(args.port)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        host.close()


if __name__ == "__main__":
    main()
