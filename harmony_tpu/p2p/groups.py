"""Gossip topic naming.

Behavioral parity with the reference's group ids (reference:
internal/configs/node/group.go — per-(network, shard, purpose) topic
strings; p2p/host.go:73 SendMessageToGroups publishes to them): one
topic per shard for consensus-bound traffic, one for client/node
traffic, a global one for cross-shard links on the beacon.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GroupID:
    network: str  # "mainnet", "testnet", "localnet", ...
    shard_id: int
    purpose: str  # "consensus" | "node" | "client" | "crosslink"

    def topic(self) -> str:
        return f"harmony-tpu/{self.network}/{self.shard_id}/{self.purpose}"


def consensus_topic(network: str, shard_id: int) -> str:
    return GroupID(network, shard_id, "consensus").topic()


def node_topic(network: str, shard_id: int) -> str:
    return GroupID(network, shard_id, "node").topic()


def client_topic(network: str, shard_id: int) -> str:
    return GroupID(network, shard_id, "client").topic()


def crosslink_topic(network: str) -> str:
    """Beacon-chain bound (shard 0) cross-link submissions."""
    return GroupID(network, 0, "crosslink").topic()


def aggregation_topic(network: str, shard_id: int, slot: int) -> str:
    """Per-SLOT directed topic for the Handel vote-aggregation overlay
    (consensus.aggregation): a node subscribes only to the topics of
    slots it holds keys for, so publishing a partial aggregate to a
    slot's topic reaches exactly that slot's owner on both transports
    — the overlay's point-to-point edges over gossip plumbing."""
    return GroupID(network, shard_id, f"aggregation/{slot}").topic()


def slash_topic(network: str, shard_id: int) -> str:
    """Double-sign evidence gossip (the reference publishes slashing
    candidates so non-leader observers aren't silenced; records dedup
    by evidence fingerprint on receipt)."""
    return GroupID(network, shard_id, "slash").topic()
