"""Connection gating: who may connect, and how many.

The role of the reference's p2p/gating + p2p/security (reference:
p2p/gating/gater.go connection gater, p2p/security/security.go
max-conn-per-IP and peer blocking — SURVEY.md §2.5).
"""

from __future__ import annotations

import threading
import time


class Gater:
    def __init__(self, max_peers: int = 64, max_per_ip: int = 8,
                 ban_seconds: float = 600.0):
        self.max_peers = max_peers
        self.max_per_ip = max_per_ip
        self.ban_seconds = ban_seconds
        self._lock = threading.Lock()
        self._per_ip: dict[str, int] = {}
        self._total = 0
        self._banned: dict[str, float] = {}  # ip -> ban expiry

    def ban(self, ip: str):
        with self._lock:
            self._banned[ip] = time.monotonic() + self.ban_seconds

    def unban(self, ip: str):
        with self._lock:
            self._banned.pop(ip, None)

    def allow(self, ip: str) -> bool:
        """Called before accepting; reserves a slot when True."""
        with self._lock:
            expiry = self._banned.get(ip)
            if expiry is not None:
                if time.monotonic() < expiry:
                    return False
                del self._banned[ip]
            if self._total >= self.max_peers:
                return False
            if self._per_ip.get(ip, 0) >= self.max_per_ip:
                return False
            self._per_ip[ip] = self._per_ip.get(ip, 0) + 1
            self._total += 1
            return True

    def release(self, ip: str):
        with self._lock:
            n = self._per_ip.get(ip, 0)
            if n <= 1:
                self._per_ip.pop(ip, None)
            else:
                self._per_ip[ip] = n - 1
            if self._total > 0:
                self._total -= 1
