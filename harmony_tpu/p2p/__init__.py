"""Host-side networking: gossip, discovery-lite, gating, sync streams.

The role of the reference's libp2p stack (reference: p2p/host.go:59-80
Host interface, gossipsub topics, p2p/gating + p2p/security peer
control, p2p/stream request/response sync — SURVEY.md §2.5), rebuilt
on the standard library: the WAN gossip layer is host CPU work by
nature (SURVEY.md §2.5 "TPU-relevant note") — the TPU boundary is the
crypto batch, not the socket.

- groups:   topic naming per (network, shard, purpose);
- host:     Host API with an in-process hub (tests/localnet-in-one-
            process) and a TCP flood-gossip implementation;
- gating:   connection limits and blocklists;
- stream:   length-prefixed request/response sync protocol.
"""

from .gating import Gater
from .groups import GroupID, aggregation_topic, consensus_topic, node_topic, slash_topic
from .host import Host, InProcessNetwork, TCPHost

__all__ = [
    "Gater",
    "GroupID",
    "Host",
    "InProcessNetwork",
    "TCPHost",
    "aggregation_topic",
    "consensus_topic",
    "node_topic",
    "slash_topic",
]
