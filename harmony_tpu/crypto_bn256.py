"""alt_bn128 (BN254): the EVM's pairing curve, plus the blake2 F core.

The reference serves precompiles 0x6-0x9 through go-ethereum's cgo
crypto (core/vm/contracts.go bn256Add/ScalarMul/Pairing + blake2F).
This is a from-scratch bigint implementation in the same style as
harmony_tpu/ref's BLS12-381 twin:

* G1 over Fp (y^2 = x^3 + 3), G2 over Fp2 on the D-type sextic twist
  (b' = 3/(9+u));
* optimal Ate pairing: Miller loop over 6z+2 (z the BN parameter),
  the two Frobenius line corrections, BN final exponentiation;
* EIP-196/197 semantics: subgroup/field validation and the big-endian
  32-byte coordinate wire format handled by the precompile layer in
  core/vm.py;
* EIP-152 blake2 F compression function.

Pairing checks here are consensus-critical host work, like the EVM
interpreter itself (SURVEY §7.2): contract gas prices them, the TPU
lattice stays dedicated to BLS12-381.
"""

from __future__ import annotations

# BN254 parameters
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
N = 21888242871839275222246405745257275088548364400416034343698204186575808495617
Z = 4965661367192848881  # the BN parameter (Miller loop over 6z+2)
B = 3

# Fp2 = Fp[u]/(u^2 + 1); the twist divides by xi = 9 + u
XI = (9, 1)


def _inv(a: int) -> int:
    return pow(a, -1, P)


# -- Fp2 ---------------------------------------------------------------------


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u), u^2 = -1
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    return ((t0 - t1) % P, ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % P)


def f2_sqr(a):
    return f2_mul(a, a)


def f2_inv(a):
    d = _inv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, (-a[1]) * d % P)


def f2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
B2 = f2_mul((B, 0), f2_inv(XI))  # twist b' = 3/(9+u)

# -- Fp12 as pairs of Fp6, Fp6 as triples of Fp2 (v^3 = xi, w^2 = v) --------


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def _mul_xi(a):
    return f2_mul(a, XI)


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0, t1, t2 = f2_mul(a0, b0), f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_add(t0, _mul_xi(f2_sub(
        f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2)
    )))
    c1 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), f2_add(t0, t1)),
        _mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), f2_add(t0, t2)),
        t1,
    )
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), _mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(_mul_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_inv(f2_add(
        f2_mul(a0, c0),
        f2_add(_mul_xi(f2_mul(a2, c1)), _mul_xi(f2_mul(a1, c2))),
    ))
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    # w^2 = v: (t1 shifted by v)
    shifted = (_mul_xi(t1[2]), t1[0], t1[1])
    c0 = f6_add(t0, shifted)
    c1 = f6_sub(
        f6_mul(f6_add(a0, a1), f6_add(b0, b1)), f6_add(t0, t1)
    )
    return (c0, c1)


def f12_sqr(a):
    return f12_mul(a, a)


def f12_inv(a):
    a0, a1 = a
    sq = f6_sqr(a1)
    shifted = (_mul_xi(sq[2]), sq[0], sq[1])
    t = f6_inv(f6_sub(f6_sqr(a0), shifted))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_pow(a, e: int):
    result = F12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


F12_ONE = (F6_ONE, F6_ZERO)

# Frobenius coefficients: gamma1[i] = xi^((p-1) * i / 6)
_G1FROB = [pow((9 * 9 + 1) % P, 0, P)]  # placeholder, computed below


def _f2_pow(a, e: int):
    r = F2_ONE
    b = a
    while e > 0:
        if e & 1:
            r = f2_mul(r, b)
        b = f2_sqr(b)
        e >>= 1
    return r


_XI_P_SIXTH = _f2_pow(XI, (P - 1) // 6)
_FROB_GAMMA = [_f2_pow(XI, (P - 1) * i // 6) for i in range(6)]


def f2_frob(a):
    """a^p in Fp2: conjugation."""
    return (a[0], (-a[1]) % P)


def f6_frob(a):
    return (
        f2_frob(a[0]),
        f2_mul(f2_frob(a[1]), _FROB_GAMMA[2]),
        f2_mul(f2_frob(a[2]), _FROB_GAMMA[4]),
    )


def f12_frob(a):
    """(b0 + b1 w)^p = b0^p + (b1^p * gamma1) w — b^p within Fp6 is
    f6_frob (which carries the v-power coefficients); the w-part then
    takes ONE uniform factor gamma1 = xi^((p-1)/6) from w^p."""
    a0, a1 = a
    b1 = f6_frob(a1)
    return (
        f6_frob(a0),
        (
            f2_mul(b1[0], _FROB_GAMMA[1]),
            f2_mul(b1[1], _FROB_GAMMA[1]),
            f2_mul(b1[2], _FROB_GAMMA[1]),
        ),
    )


# -- G1 ----------------------------------------------------------------------


def g1_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B) % P == 0


def g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * _inv(2 * y1) % P
    else:
        lam = (y2 - y1) * _inv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def g1_mul(pt, k: int):
    k %= N
    out = None
    while k:
        if k & 1:
            out = g1_add(out, pt)
        pt = g1_add(pt, pt)
        k >>= 1
    return out


# -- G2 (on the twist, Fp2 coordinates) -------------------------------------


def g2_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(f2_sqr(x), x), B2)) == F2_ZERO


def g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if f2_add(y1, y2) == F2_ZERO:
            return None
        lam = f2_mul(
            f2_scalar(f2_sqr(x1), 3), f2_inv(f2_scalar(y1, 2))
        )
    else:
        lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
    x3 = f2_sub(f2_sqr(lam), f2_add(x1, x2))
    return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))


def g2_mul(pt, k: int):
    k %= N
    out = None
    while k:
        if k & 1:
            out = g2_add(out, pt)
        pt = g2_add(pt, pt)
        k >>= 1
    return out


def g2_neg(pt):
    return None if pt is None else (pt[0], f2_neg(pt[1]))


def g2_in_subgroup(pt) -> bool:
    return g2_on_curve(pt) and g2_mul(pt, N) is None


G1_GEN = (1, 2)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)


# -- optimal Ate pairing -----------------------------------------------------
#
# Formulation: UNTWIST both points into E(Fp12) and run the textbook
# Miller loop with the general affine line function over Fp12 (the
# py_ecc-style arrangement — slower than sparse twist-coefficient
# tricks, but unambiguous; tests pin bilinearity + EIP-197 identities).
# With the tower Fp12 = Fp6[w]/(w^2 - v), v^3 = xi: w^6 = xi, so the
# D-twist untwist is psi(x', y') = (x' w^2, y' w^3).


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_neg(a):
    return (f6_neg(a[0]), f6_neg(a[1]))


F12_ZERO = (F6_ZERO, F6_ZERO)


def _embed_fp(x: int):
    """Fp -> Fp12."""
    return (((x % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _untwist_g2(q):
    """Twist point (Fp2 coords) -> E(Fp12): (x' v, y' v w)."""
    x2, y2 = q
    return (
        ((F2_ZERO, x2, F2_ZERO), F6_ZERO),       # x' * w^2 = x' * v
        (F6_ZERO, (F2_ZERO, y2, F2_ZERO)),       # y' * w^3 = y' * v * w
    )


def _embed_g1(p):
    return (_embed_fp(p[0]), _embed_fp(p[1]))


def _e12_add(p1, p2):
    """Affine addition on E(Fp12): y^2 = x^3 + 3."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if f12_add(y1, y2) == F12_ZERO:
            return None
        lam = f12_mul(
            f12_mul(_embed_fp(3), f12_sqr(x1)),
            f12_inv(f12_add(y1, y1)),
        )
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    x3 = f12_sub(f12_sqr(lam), f12_add(x1, x2))
    return (x3, f12_sub(f12_mul(lam, f12_sub(x1, x3)), y1))


def _linefunc(p1, p2, t):
    """Line through p1, p2 evaluated at t (all on E(Fp12))."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
        return f12_sub(
            f12_mul(lam, f12_sub(xt, x1)), f12_sub(yt, y1)
        )
    if y1 == y2:
        lam = f12_mul(
            f12_mul(_embed_fp(3), f12_sqr(x1)),
            f12_inv(f12_add(y1, y1)),
        )
        return f12_sub(
            f12_mul(lam, f12_sub(xt, x1)), f12_sub(yt, y1)
        )
    return f12_sub(xt, x1)  # vertical


def _frob_point(pt):
    """Coordinate-wise x -> x^p on E(Fp12)."""
    return (f12_frob(pt[0]), f12_frob(pt[1]))


ATE_LOOP_COUNT = 6 * Z + 2


def miller_loop(q, p):
    """f_{6z+2, Q}(P) with the two Frobenius correction steps."""
    if q is None or p is None:
        return F12_ONE
    qe = _untwist_g2(q)
    pe = _embed_g1(p)
    f = F12_ONE
    r = qe
    for bit in bin(ATE_LOOP_COUNT)[3:]:
        f = f12_mul(f12_sqr(f), _linefunc(r, r, pe))
        r = _e12_add(r, r)
        if bit == "1":
            f = f12_mul(f, _linefunc(r, qe, pe))
            r = _e12_add(r, qe)
    q1 = _frob_point(qe)
    nq2 = _frob_point(q1)
    nq2 = (nq2[0], f12_neg(nq2[1]))
    f = f12_mul(f, _linefunc(r, q1, pe))
    r = _e12_add(r, q1)
    f = f12_mul(f, _linefunc(r, nq2, pe))
    return f


def final_exponentiation(f):
    """f^((p^12 - 1) / n) — easy part via conjugation/inversion, hard
    part by plain exponentiation of the cofactor (slow but simple and
    obviously correct; contract gas prices the call, not us)."""
    # easy: f^(p^6 - 1) * ... ; do the whole exponent directly but use
    # the easy part to shrink the base first
    f = f12_mul(f12_conj(f), f12_inv(f))          # f^(p^6 - 1)
    f = f12_mul(f12_frob(f12_frob(f)), f)         # ^(p^2 + 1)
    e = (P ** 4 - P ** 2 + 1) // N
    return f12_pow(f, e)


def pairing(p, q):
    """e(P, Q) for P in G1, Q in G2 (twist coords)."""
    return final_exponentiation(miller_loop(q, p))


def pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 (the 0x8 precompile's question)."""
    f = F12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        f = f12_mul(f, miller_loop(q, p))
    return final_exponentiation(f) == F12_ONE


# -- EIP-152: blake2 F compression ------------------------------------------

_BLAKE2B_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_SIGMA = [
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3],
    [11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4],
    [7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8],
    [9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13],
    [2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9],
    [12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11],
    [13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10],
    [6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5],
    [10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0],
]

_M64 = (1 << 64) - 1


def _rotr(x, n):
    return ((x >> n) | (x << (64 - n))) & _M64


def blake2f(rounds: int, h: list, m: list, t: list, flag: bool) -> list:
    """The blake2b F function (RFC 7693 sec 3.2), EIP-152 semantics."""
    v = h[:] + _BLAKE2B_IV[:]
    v[12] ^= t[0] & _M64
    v[13] ^= t[1] & _M64
    if flag:
        v[14] ^= _M64

    def g(a, b, c, d, x, y):
        v[a] = (v[a] + v[b] + x) & _M64
        v[d] = _rotr(v[d] ^ v[a], 32)
        v[c] = (v[c] + v[d]) & _M64
        v[b] = _rotr(v[b] ^ v[c], 24)
        v[a] = (v[a] + v[b] + y) & _M64
        v[d] = _rotr(v[d] ^ v[a], 16)
        v[c] = (v[c] + v[d]) & _M64
        v[b] = _rotr(v[b] ^ v[c], 63)

    for r in range(rounds):
        s = _SIGMA[r % 10]
        g(0, 4, 8, 12, m[s[0]], m[s[1]])
        g(1, 5, 9, 13, m[s[2]], m[s[3]])
        g(2, 6, 10, 14, m[s[4]], m[s[5]])
        g(3, 7, 11, 15, m[s[6]], m[s[7]])
        g(0, 5, 10, 15, m[s[8]], m[s[9]])
        g(1, 6, 11, 12, m[s[10]], m[s[11]])
        g(2, 7, 8, 13, m[s[12]], m[s[13]])
        g(3, 4, 9, 14, m[s[14]], m[s[15]])

    return [h[i] ^ v[i] ^ v[i + 8] for i in range(8)]
