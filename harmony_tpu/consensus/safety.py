"""Durable consensus safety state: the last vote each key signed.

The double-sign hazard this closes (ISSUE 12): FBFT keeps its
"have I already voted this round" state in memory
(``Node._announce_voted``), so a validator hard-killed after casting a
prepare vote and restarted from disk remembers NOTHING — an
equivocating (or merely re-proposing) leader could then extract a
second signature for a DIFFERENT block at the same (height, view), the
exact evidence ``Node._check_double_sign`` slashes others for
(reference: consensus/double_sign.go — equivocation IS same
height+view, different hash).

:class:`SafetyStore` persists two durable records per local BLS key
through the node's shard DB, written BEFORE the signature leaves the
node and reloaded on restart:

* the **vote record** (``rawdb V || pubkey``): the last
  (block_num, view_id, phase, block_hash) PREPARE/COMMIT signed.
  The rules (``may_sign``): never sign below the recorded height
  (only an operator revert regresses the head — conservative refuse),
  and at the exact recorded (height, view) only ever re-sign the SAME
  block hash.  Votes at OTHER views of the same height are allowed —
  that is ordinary FBFT view churn, not equivocation, and refusing it
  wedges liveness (a NEWVIEW quorum can legitimately form at a lower
  view than a node's last escalated view-change vote; the rolling-
  restart chaos scenario found exactly that wedge: every validator
  withheld its vote in every adopted view and the committee never
  committed again).
* the **view-change watermark** (``rawdb W || pubkey``): the highest
  view a VIEWCHANGE was signed for at the height.  Never gates votes;
  it exists so a RESTARTED node fast-forwards its first round to
  where it had already escalated (``min_view``, applied once at node
  construction) instead of re-entering the storm from view 1.

Durability: records flush through ``db.flush()`` when the backing
store's fsync policy says batches are durable — on the in-process
chaos topology (kill = thread stop, OS page cache survives) the
unbuffered write alone already survives the kill.
"""

from __future__ import annotations

import threading

from ..core import rawdb

PHASE_PREPARE = 1
PHASE_COMMIT = 2
PHASE_VIEWCHANGE = 3


class SafetyStore:
    def __init__(self, db):
        self.db = db
        self._votes: dict[bytes, tuple] = {}
        self._marks: dict[bytes, tuple] = {}  # vc watermark per key
        self._lock = threading.Lock()
        # flush per record only when the store is configured durable
        # (FileKV/NativeKV fsync="batch"/"always"); MemKV and
        # fsync="none" stores skip the syscall
        self._durable = getattr(db, "fsync", "none") != "none"
        self.refused = 0  # votes withheld by the safety rules

    def last(self, pubkey: bytes):
        """Last signed vote (block_num, view_id, phase, block_hash)
        for ``pubkey``, memory-cached over the durable record."""
        with self._lock:
            rec = self._votes.get(pubkey)
        if rec is None:
            rec = rawdb.read_last_signed(self.db, pubkey)
            if rec is not None:
                with self._lock:
                    self._votes[pubkey] = rec
        return rec

    def watermark(self, pubkey: bytes):
        """Highest (block_num, view_id) a VIEWCHANGE was signed for."""
        with self._lock:
            mark = self._marks.get(pubkey)
        if mark is None:
            mark = rawdb.read_vc_watermark(self.db, pubkey)
            if mark is not None:
                with self._lock:
                    self._marks[pubkey] = mark
        return mark

    def min_view(self, block_num: int) -> int:
        """The highest view any of this node's keys actually VOTED at
        ``block_num``.  ``Node._new_round`` keeps its round view
        STRICTLY above this (voted view + 1): a view is never
        re-entered after voting in it, so the only way to meet "same
        (height, view), different hash" is genuine equivocation within
        one round visit.  The store keeps only the LAST vote per key,
        so re-entering an older view is inherently unsafe to allow —
        the memory of what was signed there may already be gone.

        Deliberately EXCLUDES the view-change watermark: VC votes
        escalate far ahead of any adopted view during a storm, and
        flooring on them strands nodes above every view where a
        NEWVIEW quorum can actually form."""
        floor = 0
        with self._lock:
            records = list(self._votes.values())
        for rec in records:
            if rec[0] == block_num:
                floor = max(floor, rec[1])
        return floor

    def restart_floor(self, block_num: int) -> int:
        """The view a RESTARTED node rejoins ``block_num`` at:
        strictly above its last vote, and at least its view-change
        watermark (rejoin the storm where it left off instead of from
        view 1).  Applied once at Node construction."""
        voted = self.min_view(block_num)
        floor = voted + 1 if voted else 0
        with self._lock:
            marks = list(self._marks.values())
        for mark in marks:
            if mark[0] == block_num:
                floor = max(floor, mark[1])
        return floor

    def load_keys(self, pubkeys) -> None:
        """Prime the cache from disk for this node's keys (restart
        path: ``min_view`` must see the durable records immediately,
        not after the first ``last()`` miss per key)."""
        for pk in pubkeys:
            self.last(pk)
            self.watermark(pk)

    def may_sign(self, pubkey: bytes, block_num: int, view_id: int,
                 phase: int, block_hash: bytes) -> bool:
        if phase == PHASE_VIEWCHANGE:
            return True  # VC signatures never equivocate on a block
        rec = self.last(pubkey)
        if rec is None:
            return True
        lb, lv, _lp, lh = rec
        if block_num != lb:
            return block_num > lb
        if view_id != lv:
            return True  # view churn at the same height is not
            # equivocation (and refusing it wedges NEWVIEW quorums
            # that form below this key's last escalated view)
        return block_hash == lh

    def record(self, pubkeys, block_num: int, view_id: int, phase: int,
               block_hash: bytes) -> bool:
        """Gate + persist one outgoing signature for ALL of this
        node's round keys.  Returns False (and persists nothing) if
        ANY key's rules refuse — the node withholds the whole vote.
        On True, every key's record is durably updated BEFORE the
        caller broadcasts."""
        pubkeys = list(pubkeys)
        if not all(
            self.may_sign(pk, block_num, view_id, phase, block_hash)
            for pk in pubkeys
        ):
            self.refused += 1
            return False
        if phase == PHASE_VIEWCHANGE:
            for pk in pubkeys:
                mark = self.watermark(pk)
                if mark is None or (block_num, view_id) > mark:
                    rawdb.write_vc_watermark(
                        self.db, pk, block_num, view_id
                    )
                    with self._lock:
                        self._marks[pk] = (block_num, view_id)
        else:
            for pk in pubkeys:
                rawdb.write_last_signed(
                    self.db, pk, block_num, view_id, phase, block_hash
                )
            with self._lock:
                for pk in pubkeys:
                    self._votes[pk] = (
                        block_num, view_id, phase, block_hash
                    )
        if self._durable:
            self.db.flush()
        return True
