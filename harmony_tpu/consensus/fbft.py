"""In-process FBFT round: leader + validator state machines over the TPU
crypto path.

This is the framework's executable model of the reference's hot loop
(reference call stack SURVEY.md §3.2): announce -> prepare votes ->
prepared (agg sig + bitmap) -> commit votes -> committed.  It drives the
same crypto sequence the Go node drives through cgo, but with the
verify/aggregate steps batched on TPU:

- leader.on_prepare / on_commit: per-vote signature verification
  (reference: consensus/leader.go:156-197) — batchable across validators;
- quorum transition: aggregate votes + build [sig || bitmap] proof
  (reference: consensus/threshold.go:14-69);
- validator.on_prepared / on_committed: bitmap quorum check + ONE
  aggregate-signature pairing verify (reference:
  consensus/validator.go:217-236, 336-353).

Transport is pluggable (in-process lists here; libp2p in deployment).
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import bls as B
from ..multibls import PrivateKeys
from ..ref import bls as RB
from .mask import Mask
from .messages import (
    FBFTLog,
    FBFTMessage,
    MsgType,
    decode_sig_and_bitmap,
    encode_sig_and_bitmap,
    sign_message,
)
from .quorum import Ballot, Decider, Phase
from .signature import construct_commit_payload, prepare_payload


@dataclass
class RoundConfig:
    committee: list  # ordered serialized pubkeys (the epoch committee)
    block_num: int
    view_id: int  # message routing view
    is_staking: bool = True
    # the view id bound into commit payloads: the BLOCK HEADER's view.
    # Equal to view_id in normal rounds; after a view change re-proposes
    # a prepared block, it stays the ORIGINAL proposal view so commit
    # votes cast across views bind the same payload (PBFT safety: the
    # re-proposed block must be THE SAME block, hash included) and the
    # engine's replay check (which derives the payload from the header,
    # engine.py _commit_payload) agrees with live consensus.
    payload_view_id: int | None = None

    @property
    def commit_view_id(self) -> int:
        return (
            self.view_id if self.payload_view_id is None
            else self.payload_view_id
        )


class _Node:
    def __init__(self, keys: PrivateKeys, cfg: RoundConfig, decider: Decider):
        self.keys = keys
        self.cfg = cfg
        self.decider = decider
        self.log = FBFTLog()
        self.committee_points = [
            B.PublicKey.from_bytes(k).point for k in cfg.committee
        ]

    def _commit_payload(self, block_hash: bytes) -> bytes:
        return construct_commit_payload(
            block_hash, self.cfg.block_num, self.cfg.commit_view_id,
            self.cfg.is_staking,
        )


class Leader(_Node):
    """Collects votes, verifies each, aggregates at quorum (reference:
    consensus/leader.go + threshold.go)."""

    def __init__(self, keys, cfg, decider):
        super().__init__(keys, cfg, decider)
        self.prepare_sigs: dict = {}
        self.commit_sigs: dict = {}
        self.current_block_hash: bytes | None = None

    def announce(self, block_hash: bytes, block_bytes: bytes) -> FBFTMessage:
        msg = sign_message(FBFTMessage(
            msg_type=MsgType.ANNOUNCE,
            view_id=self.cfg.view_id,
            block_num=self.cfg.block_num,
            block_hash=block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            block=block_bytes,
        ), self.keys)
        self.log.add_message(msg)
        self.log.add_block(block_hash, block_bytes)
        self.current_block_hash = block_hash
        # the leader's own prepare vote counts toward quorum at announce
        # time (the reference's leader signs the block hash with all its
        # keys alongside the announce — leader.go:20 + construct.go:124).
        # Cast directly — no pairing check on a signature produced one
        # line earlier; a stale committee is a hard wiring error.
        own = [k.pub.bytes for k in self.keys]
        committee = set(self.cfg.committee)
        missing = [pk for pk in own if pk not in committee]
        if missing:
            raise ValueError(
                f"leader key(s) not in committee: {len(missing)} of "
                f"{len(own)}"
            )
        sig = self.keys.sign_hash_aggregated(prepare_payload(block_hash))
        for pk in own:
            self.decider.submit_vote(
                Phase.PREPARE,
                Ballot(pk, block_hash, sig.bytes,
                       self.cfg.block_num, self.cfg.view_id),
            )
        self.prepare_sigs[tuple(own)] = sig
        return msg

    def _on_vote(self, msg, phase, payload, store):
        """Shared hot loop: verify the vote sig (possibly multi-key
        aggregated by the sender) against the sum of its sender keys
        (reference: consensus/leader.go:156-197).  Votes for a different
        block hash, from non-committee keys, overlapping an already-voted
        key, or malformed are dropped — never raised — matching the
        reference's tolerant message loop."""
        if (
            self.current_block_hash is None
            or msg.block_hash != self.current_block_hash
            or not msg.sender_pubkeys
        ):
            return False
        committee = set(self.cfg.committee)
        if any(pk not in committee for pk in msg.sender_pubkeys):
            return False
        # per-KEY dedup: a key-set overlapping any prior vote would put a
        # key's signature into the aggregate twice while the bitmap marks
        # it once, breaking the quorum proof
        if any(
            self.decider.has_voted(phase, pk) for pk in msg.sender_pubkeys
        ):
            return False
        if not B.verify_aggregate_bytes(
            msg.sender_pubkeys, payload, msg.payload
        ):
            return False
        for pk_bytes in msg.sender_pubkeys:
            self.decider.submit_vote(
                phase,
                Ballot(pk_bytes, msg.block_hash, msg.payload,
                       msg.block_num, msg.view_id),
            )
        store[tuple(msg.sender_pubkeys)] = B.Signature.from_bytes(msg.payload)
        return True

    def on_prepare(self, msg: FBFTMessage) -> bool:
        return self._on_vote(
            msg, Phase.PREPARE, prepare_payload(msg.block_hash),
            self.prepare_sigs,
        )

    def on_commit(self, msg: FBFTMessage) -> bool:
        return self._on_vote(
            msg, Phase.COMMIT, self._commit_payload(msg.block_hash),
            self.commit_sigs,
        )

    def _quorum_proof(self, phase, store) -> bytes:
        """Aggregate stored vote sigs + bitmap (reference:
        consensus/quorum/quorum.go:164-196 AggregateVotes)."""
        agg = B.aggregate_sigs(list(store.values()))
        mask = Mask(self.committee_points)
        voted = {b.signer_key for b in self.decider.ballots(phase)}
        for i, key in enumerate(self.cfg.committee):
            if key in voted:
                mask.set_bit(i, True)
        return encode_sig_and_bitmap(agg.bytes, mask.mask_bytes())

    def try_prepared(self, block_hash: bytes):
        """At prepare quorum: broadcast PREPARED with the proof
        (reference: consensus/threshold.go:14-52).  Only the round's
        announced block may be proven — a caller passing any other hash
        (e.g. lifted from a rejected vote) gets None."""
        if block_hash != self.current_block_hash:
            return None
        if not self.decider.is_quorum_achieved(Phase.PREPARE):
            return None
        return sign_message(FBFTMessage(
            msg_type=MsgType.PREPARED,
            view_id=self.cfg.view_id,
            block_num=self.cfg.block_num,
            block_hash=block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=self._quorum_proof(Phase.PREPARE, self.prepare_sigs),
            block=self.log.get_block(block_hash) or b"",
        ), self.keys)

    def try_committed(self, block_hash: bytes):
        if block_hash != self.current_block_hash:
            return None
        if not self.decider.is_quorum_achieved(Phase.COMMIT):
            return None
        return sign_message(FBFTMessage(
            msg_type=MsgType.COMMITTED,
            view_id=self.cfg.view_id,
            block_num=self.cfg.block_num,
            block_hash=block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=self._quorum_proof(Phase.COMMIT, self.commit_sigs),
        ), self.keys)

    def prepared_from_proof(self, block_hash: bytes, proof: bytes):
        """PREPARED built from an externally-assembled quorum proof —
        the aggregation overlay's path (consensus.aggregation): every
        piece of the aggregate was pairing-verified before merging and
        the caller checked quorum-by-mask, so the ballot store is
        bypassed.  Same message shape ``try_prepared`` emits, same
        announced-hash guard."""
        if block_hash != self.current_block_hash:
            return None
        return sign_message(FBFTMessage(
            msg_type=MsgType.PREPARED,
            view_id=self.cfg.view_id,
            block_num=self.cfg.block_num,
            block_hash=block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=proof,
            block=self.log.get_block(block_hash) or b"",
        ), self.keys)

    def committed_from_proof(self, block_hash: bytes, proof: bytes):
        """COMMITTED from an overlay-assembled proof (see
        :meth:`prepared_from_proof`)."""
        if block_hash != self.current_block_hash:
            return None
        return sign_message(FBFTMessage(
            msg_type=MsgType.COMMITTED,
            view_id=self.cfg.view_id,
            block_num=self.cfg.block_num,
            block_hash=block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=proof,
        ), self.keys)


class Validator(_Node):
    """Signs votes; verifies aggregate proofs (reference:
    consensus/validator.go)."""

    def on_announce(self, msg: FBFTMessage) -> FBFTMessage:
        """Sign the block hash with every local key, locally aggregated
        (reference: consensus/validator.go:144-165 + construct.go:99-105)."""
        self.log.add_message(msg)
        sig = self.keys.sign_hash_aggregated(prepare_payload(msg.block_hash))
        return sign_message(FBFTMessage(
            msg_type=MsgType.PREPARE,
            view_id=msg.view_id,
            block_num=msg.block_num,
            block_hash=msg.block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=sig.bytes,
        ), self.keys)

    def _verify_proof(self, msg: FBFTMessage, payload: bytes) -> bool:
        """Decode [sig || bitmap], check quorum-by-mask, verify the
        aggregate signature — the reference's validator-side check
        (validator.go:217-236; engine.go:619-642 uses the same shape).
        Malformed payloads return False, never raise.

        Device path: the committee lives as one device-resident table
        and the masked aggregation + pairing check run FUSED as a
        single program (ops/bls.agg_verify) — submitted through the
        verification scheduler's CONSENSUS lane, so a proof check
        rides the shared device queue ahead of sync/ingress traffic
        (and coalesces with any concurrent same-committee checks)."""
        from .. import device as DV

        try:
            mask = Mask(self.committee_points)
            sig_bytes, bitmap = decode_sig_and_bitmap(
                msg.payload, mask.bytes_len()
            )
            mask.set_mask(bitmap)
            if not self.decider.is_quorum_achieved_by_mask(mask.bit_vector()):
                return False
            sig = B.Signature.from_bytes(sig_bytes)
        except ValueError:
            return False
        if DV.device_enabled():
            from .. import sched

            table = DV.get_committee_table(
                self.cfg.committee, self.committee_points
            )
            return sched.agg_verify(
                table, mask.bit_vector(), payload, sig.point,
                lane=sched.Lane.CONSENSUS,
            )
        agg_pk = mask.aggregate_public(device=False)
        if agg_pk is None:
            return False
        return RB.verify(agg_pk, payload, sig.point)

    def on_prepared(self, msg: FBFTMessage):
        """Verify the prepare proof; if valid, send the commit vote
        signed over the commit payload (validator.go:196-260)."""
        if not self._verify_proof(msg, prepare_payload(msg.block_hash)):
            return None
        sig = self.keys.sign_hash_aggregated(
            self._commit_payload(msg.block_hash)
        )
        return sign_message(FBFTMessage(
            msg_type=MsgType.COMMIT,
            view_id=msg.view_id,
            block_num=msg.block_num,
            block_hash=msg.block_hash,
            sender_pubkeys=[k.pub.bytes for k in self.keys],
            payload=sig.bytes,
        ), self.keys)

    def on_committed(self, msg: FBFTMessage) -> bool:
        """Final check before accepting the block (validator.go:299-377)."""
        return self._verify_proof(msg, self._commit_payload(msg.block_hash))
