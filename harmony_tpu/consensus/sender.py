"""Message sender with per-type retry.

The role of the reference's MessageSender (reference:
consensus/consensus_msg_sender.go — SendWithRetry keeps re-publishing
a consensus message until the chain advances past its block number or
the retry budget runs out; SendWithoutRetry is fire-and-forget).
"""

from __future__ import annotations

import threading


class MessageSender:
    # 10 fast re-publishes, then a slow tail: a proposal must outlive
    # mesh FORMATION (a fresh localnet's PEX rounds take tens of
    # seconds), not just a dropped packet.  ~70 s of coverage total;
    # stop_retry / supersession bound the traffic as before.
    RETRY_INTERVAL = 1.0   # seconds between the first re-publishes
    SLOW_INTERVAL = 5.0    # tail interval after the fast burst
    FAST_RETRIES = 10
    MAX_RETRIES = 22

    def __init__(self, host, topics: list):
        self.host = host
        self.topics = list(topics)
        self._active: dict = {}  # msg_type -> (block_num, cancel Event)
        self._lock = threading.Lock()

    def send_without_retry(self, payload: bytes):
        self.host.publish_to_groups(self.topics, payload)

    def send_with_retry(self, block_num: int, msg_type, payload: bytes):
        """Publish now; keep re-publishing in the background until
        ``stop_retry`` reports the chain moved past block_num."""
        cancel = threading.Event()
        with self._lock:
            old = self._active.get(msg_type)
            if old is not None:
                old[1].set()  # newer message supersedes the retry loop
            self._active[msg_type] = (block_num, cancel)
        self.host.publish_to_groups(self.topics, payload)

        def loop():
            for i in range(self.MAX_RETRIES):
                wait = (self.RETRY_INTERVAL if i < self.FAST_RETRIES
                        else self.SLOW_INTERVAL)
                if cancel.wait(wait):
                    return
                self.host.publish_to_groups(self.topics, payload)

        threading.Thread(target=loop, daemon=True).start()

    def stop_retry(self, committed_block_num: int):
        """Cancel retries for messages at or below the committed height
        (reference: StopRetry on block commit)."""
        with self._lock:
            for msg_type, (num, cancel) in list(self._active.items()):
                if num <= committed_block_num:
                    cancel.set()
                    del self._active[msg_type]

    def stop_all(self):
        """Cancel EVERY retry loop (node shutdown): a stopped node must
        leave no thread re-publishing into the network — retry threads
        outliving the node by their ~70 s budget kept running gossip
        and native hashing into interpreter teardown (shutdown aborts
        in the chaos suite)."""
        with self._lock:
            for _, cancel in self._active.values():
                cancel.set()
            self._active.clear()
