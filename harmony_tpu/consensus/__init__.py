"""Host-side FBFT consensus support: the framework pieces around the TPU
crypto kernels that must stay deterministic and branchy on the host —
bitmap masks, signable payload construction, vote-power rosters, quorum
policies (reference: consensus/ + crypto/bls/mask.go; SURVEY.md §2.2)."""
