"""Participation bitmap over a committee of BLS public keys.

Behavioral parity with the reference's cosigning Mask (reference:
crypto/bls/mask.go:67-196): little-endian bit order (bit i of the bitmap
is bit i&7 of byte i>>3), length-checked SetMask, per-bit enable/disable,
signer extraction.

TPU-first redesign: the reference maintains AggregatePublic incrementally
with a G1 Add/Sub per bit flip across the cgo boundary (mask.go:113-153).
Here the committee lives as ONE device-resident tensor (the epoch-keyed
pubkey table of SURVEY.md §7.3) and the aggregate is a single batched
masked tree-sum on TPU — O(log N) depth instead of N sequential cgo
calls, recomputed on demand (bit flips are cheap bookkeeping).
"""

from __future__ import annotations

import numpy as np

from ..ref import bls as RB
from ..ref import curve as RC


def bits_from_bytes(bitmap: bytes, n: int):
    """Unpack a little-endian participation bitmap to a 0/1 list — THE
    bit-order convention of the whole protocol (bit i = bit i&7 of byte
    i>>3; reference: crypto/bls/mask.go:112-120).  A bitmap too short
    for n raises ValueError (never IndexError — callers catch
    ValueError on untrusted input)."""
    if len(bitmap) < (n + 7) >> 3:
        raise ValueError(
            f"bitmap of {len(bitmap)} bytes cannot cover {n} bits"
        )
    return [(bitmap[i >> 3] >> (i & 7)) & 1 for i in range(n)]


class Mask:
    """Committee bitmap with device-backed aggregation.

    ``publics`` is a list of affine G1 pubkeys (reference tuples).  The
    device tensor is built lazily on first aggregate call and cached.
    """

    def __init__(self, publics):
        self.publics = list(publics)
        self.bitmap = bytearray(self.bytes_len())
        self._device_pks = [None]  # one-slot device-tensor cache
        self._index = {}
        for i, pk in enumerate(self.publics):
            key = RB.pubkey_to_bytes(pk)
            self._index.setdefault(key, i)

    # --- shape ---
    def __len__(self) -> int:
        return len(self.publics)

    def bytes_len(self) -> int:
        return (len(self.publics) + 7) >> 3

    # --- bit ops (little-endian order, mask.go:112-153) ---
    def _check(self, i: int):
        if not 0 <= i < len(self.publics):
            raise IndexError("mask index out of range")

    def bit(self, i: int) -> bool:
        self._check(i)
        return bool(self.bitmap[i >> 3] & (1 << (i & 7)))

    def set_bit(self, i: int, enable: bool):
        self._check(i)
        byte, bit = i >> 3, 1 << (i & 7)
        if enable:
            self.bitmap[byte] |= bit
        else:
            self.bitmap[byte] &= ~bit

    def set_key(self, pubkey_bytes: bytes, enable: bool):
        """Enable/disable by serialized pubkey (mask.go SetKey)."""
        if pubkey_bytes not in self._index:
            raise KeyError("pubkey not in committee")
        self.set_bit(self._index[pubkey_bytes], enable)

    def set_mask(self, mask_bytes: bytes):
        """Replace the bitmap; length must match exactly (mask.go:113-120)."""
        if len(mask_bytes) != self.bytes_len():
            raise ValueError(
                f"mismatching bitmap lengths: expected {self.bytes_len()}, "
                f"got {len(mask_bytes)}"
            )
        self.bitmap = bytearray(mask_bytes)

    def clear(self):
        self.bitmap = bytearray(self.bytes_len())

    def mask_bytes(self) -> bytes:
        return bytes(self.bitmap)

    def count_enabled(self) -> int:
        return sum(self.bit(i) for i in range(len(self.publics)))

    def index_enabled(self):
        return [i for i in range(len(self.publics)) if self.bit(i)]

    def get_signed_pubkeys(self):
        """Enabled pubkeys (mask.go GetSignedPubKeysFromBitmap)."""
        return [self.publics[i] for i in self.index_enabled()]

    def bit_vector(self) -> np.ndarray:
        return np.array(
            [1 if self.bit(i) else 0 for i in range(len(self.publics))],
            dtype=np.int32,
        )

    # --- aggregation ---
    def aggregate_public(self, device: bool = True):
        """The masked aggregate public key, as a reference affine point.

        device=True runs the batched TPU tree-sum; False uses host
        bigints (both bitwise-identical, tested).  Twin mode
        (``device.kernel_twin_active``) forces the host path even when
        a caller asks for the device: twins keep jax UNLOADED by
        contract.  The device path goes through
        ``device.masked_pubkey_sum`` — breaker-guarded dispatch, like
        every other device call.  It used to be the one device call
        OUTSIDE guarded dispatch: the NEWVIEW verify path compiled a
        fresh XLA masked-sum ON THE CONSENSUS PUMP THREAD the first
        time a committee width appeared, wedging every validator's
        pump for the length of an XLA:CPU compile (~90 s at width 7;
        found by the minority_partition_heal chaos scenario, whose
        view changes are the first to exercise NEWVIEW adoption at
        unusual committee widths — and now caught statically by
        graftlint GL12)."""
        from .. import device as DV

        if (not device or DV.kernel_twin_active()
                or len(self.publics) == 0):
            # native Jacobian sum when available, affine bigint otherwise
            return RB.aggregate_pubkeys(self.get_signed_pubkeys())
        return DV.masked_pubkey_sum(
            self.publics, self.bit_vector(),
            lambda: RB.aggregate_pubkeys(self.get_signed_pubkeys()),
            cache=self._device_pks,
        )
